"""Cross-host sharded serving: seed-ownership routing over the
`HostRankTable` exchange.

The single-host `ServeEngine` (rounds 8-9) turns a request stream into
efficient fixed-shape device work, but its QPS ceiling is one chip's
sample+forward throughput and one host's feature tier. The training side
already scales past one host by PARTITIONING the data and moving requests
to their owners (`HostRankTable` / `DistFeature` / `TpuComm.exchange` —
the reference's ``PartitionInfo``+``DistFeature`` multi-host layer); this
module applies the same owner-compute-then-exchange shape to serving, the
pattern the PyTorch-Direct / GPU-initiated-access line uses to keep
feature fetch off the slow path: **move the request to the data, not the
rows to the request.**

Topology of a request:

1. A front-end **router** (`DistServeEngine`) accepts single-node
   requests, dedupes/coalesces them within a flush window, and applies the
   same max_batch / max_delay_ms flush policy as the single-host engine.
2. Each router flush **splits its (deduped) seed batch by owner**
   (``global2host[seed]``, `HostRankTable` host ids) and forwards the
   per-owner sub-batches through the serve-shaped exchange
   (`TpuComm.exchange_serve`: seed ids ship out over the same all_to_all
   the feature exchange rides; LOGITS rows come back instead of feature
   rows).
3. Each **owner** runs its local pipelined `ServeEngine` — micro-batching,
   bucketed shapes, embedding cache, bounded ``max_in_flight`` window —
   against only its shard of topology + features. Aggregate QPS scales
   with hosts because each shard samples/forwards a batch ~1/H as wide,
   and per-host HBM holds ~1/H of the tables (exact 1/H when the
   partition is k-hop closed, e.g. community partitions; the halo the
   closure adds on other partitions is reported, never hidden — see
   `shard_topology_by_owner`). Under the default
   ``feature_residency="closure"`` each owner materializes its closure's
   feature rows at build time (`ClosureFeature`) so the whole shard
   dispatch is the FUSED one-program serve step — one execute call per
   owner flush; ``"exchange"`` keeps the round-10 per-flush on-demand
   feature exchange (`DistFeature`) and the split dispatch.
4. Results **scatter back by request id** and re-interleave into the
   router's dispatch-log order.

Bit-parity contract (the round-8/9 contract, extended): every served
logits row is bit-identical to the offline `inference.batch_logits` replay
of the OWNING shard's dispatch log — through a sampler over the FULL graph
(`replay_shard_oracle`), because a shard's halo-closed topology produces
draws bit-equal to the full graph's for owned seeds. At ``hosts=1`` the
engine degenerates to the single-host `ServeEngine` bit-for-bit (same
dispatch log, same key stream, same logits) at any ``max_in_flight``.

Execution modes:

- ``exchange="collective"``: sub-batches and logits ride the real
  `_a2a_ids_jit`/`_a2a_rows_jit` collectives over an H-device mesh (the
  hermetic CPU-mesh simulation of an H-host pod; on a real pod each
  process drives its own shard — `TpuComm.exchange_serve` multi-process
  mode, exercised by tests/dist_worker.py's lockstep serve mode).
- ``exchange="host"``: the router calls owner engines directly (and the
  shard features exchange through a host-side loopback). Value-identical;
  for environments without H devices.

Round 16 — the fleet is ELASTIC: ``scale(hosts=H±k)`` / ``rebalance()``
migrate seed ownership live, one bounded contiguous range at a time
(`plan_migration_ranges` x ``migrate_batch_seeds``). Per range: the
destination's halo-closure shard and feature rows build OUTSIDE any
fence (`closure_masks` is incremental — k-hop closures are
union-homomorphic, so the destination's new masks are old-OR-range)
while the old owner keeps serving; then a per-range fence (the
`update_params` drain, held only for the pointer flip) swaps the
destination engine, flips ``global2host[lo:hi]``, bumps
``ownership_epoch``, and invalidates exactly the migrated seeds'
router-cache/old-owner-cache entries. Replaced engines retire with
their dispatch logs and `replay_fleet_oracle` replays them like live
owners, so completed rows stay bit-identical to offline replay across
every epoch. `FaultSpec(at="migration")` kills mid-handoff: a dead
destination rolls the range back, a dead source rolls it forward —
deterministically. ``stop(drain=True)`` settles an open range before
the drain deadline starts. See docs/api.md "Elastic fleet".
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import comm as comm_mod
from ..comm import HostRankTable, TpuComm, round_up_pow2
from ..feature import DistFeature, Feature, PartitionInfo
from ..trace import (
    NULL_JOURNAL,
    EventJournal,
    HitRateCounter,
    LatencyHistogram,
    MetricsRegistry,
    SpanRecorder,
    WorkloadConfig,
    WorkloadMonitor,
    export_chrome_trace as _export_chrome_trace,
    register_hit_rate,
)
from ..utils import CSRTopo
from .cache import EmbeddingCache
from .faults import OwnerFault
from .engine import (
    DEFAULT_TENANT,
    ResultBatch,
    ServeConfig,
    ServeEngine,
    ServeResult,
    ServeStats,
    ShedError,
    _PendingStripes,
    _Slot,
    _admit_batch_vector,
    _admit_chunk_fast,
    _batch_uniq,
    _resolve_block,
    abandon_undrained,
    register_tenant_latency,
    resolve_tenants,
    shed_decision,
    weighted_drain_keys,
)

# pseudo-owner id for the local hot-set replica in a routed flush's owner
# split / dispatch log: seeds routed here are answered on the router's own
# host and never enter the serve exchange (round 15, ROADMAP item 3a)
REPLICA_HOST = -2

# bound on the hedge/shed policy logs (ring semantics, newest win): the
# conditions that fill them — sustained overload, a long-dead owner — are
# exactly when an unbounded list would leak until OOM
POLICY_LOG_CAP = 65536


class OwnerTimeout(RuntimeError):
    """A routed owner sub-batch missed its ``hedge_deadline_ms`` — the
    hedge machinery re-routes the sub-batch; the slow owner's eventual
    answer is discarded."""


def contiguous_partition(n_nodes: int, hosts: int) -> np.ndarray:
    """Balanced contiguous ``global2host`` map: host h owns rows
    ``[h*ceil(N/H), ...)`` (the same contiguous-range convention the
    row-sharded topology uses). int32 [N]."""
    if hosts < 1 or n_nodes < 1:
        raise ValueError("need hosts >= 1 and n_nodes >= 1")
    per = -(-n_nodes // hosts)
    return np.minimum(np.arange(n_nodes, dtype=np.int64) // per, hosts - 1).astype(
        np.int32
    )


def plan_migration_ranges(
    current: np.ndarray, target: np.ndarray, batch_seeds: int
) -> List[Tuple[int, int, int, int]]:
    """Cut the ownership delta ``current != target`` into the round-16
    migration units: ``[(lo, hi, src, dst)]`` contiguous id ranges, each
    with ONE (src, dst) pair and at most ``batch_seeds`` seeds — the
    bounded batches `DistServeEngine.rebalance` hands off one fenced
    flip at a time. Deterministic (ascending id order) so two runs of
    the same plan migrate identical batches in identical order."""
    current = np.asarray(current)
    target = np.asarray(target)
    if current.shape != target.shape:
        raise ValueError("current/target ownership shapes differ")
    batch_seeds = max(int(batch_seeds), 1)
    diff = np.nonzero(current != target)[0]
    ranges: List[Tuple[int, int, int, int]] = []
    if diff.size == 0:
        return ranges
    start = 0
    for i in range(1, diff.size + 1):
        at_boundary = (
            i == diff.size
            or diff[i] != diff[i - 1] + 1
            or current[diff[i]] != current[diff[start]]
            or target[diff[i]] != target[diff[start]]
        )
        if at_boundary:
            lo, hi = int(diff[start]), int(diff[i - 1]) + 1
            src, dst = int(current[lo]), int(target[lo])
            for b in range(lo, hi, batch_seeds):
                ranges.append((b, min(b + batch_seeds, hi), src, dst))
            start = i
    return ranges


def shard_topology_by_owner(
    csr_topo: CSRTopo,
    global2host: np.ndarray,
    host: int,
    hops: int,
    return_closure: bool = False,
    closure_hops: Optional[int] = None,
):
    """Host ``host``'s serving topology shard: the full-id-space CSR with
    adjacency kept ONLY for the ``hops``-hop closure of its owned nodes
    (every other row reads degree 0).

    ``hops`` is the number of EXPANSION hops whose adjacency the shard's
    sampler reads — ``len(sizes) - 1`` for an L-layer sampler, because the
    final hop's frontier is feature-gathered but never expanded. Keeping
    the closure rows bit-identical to the full graph is what makes a shard
    engine's draws for owned seeds bit-equal to a full-graph sampler on
    the same key stream (the parity contract `replay_shard_oracle` tests);
    rows outside the closure are unreachable from owned seeds, so zeroing
    them changes nothing.

    The id space stays GLOBAL (indptr keeps all N+1 rows — ~8 bytes/node,
    small next to edges and features); only the EDGE table shrinks. On a
    k-hop-closed partition (e.g. community partitions, where serving
    shards naturally align with communities) the closure adds nothing and
    each shard holds exactly its 1/H of the edges; on other partitions the
    halo is real replication and ``edge_frac`` reports it honestly.

    Returns ``(shard_topo, stats)`` with stats keys ``owned_nodes``,
    ``closure_nodes``, ``edges_kept``, ``edges_total``, ``edge_frac``;
    with ``return_closure=True``, ``(shard_topo, stats, closure_ids)`` —
    the sorted global ids of the ``closure_hops``-hop closure (default:
    ``hops``). `ClosureFeature` wants ``closure_hops = hops + 1``: the
    final hop's LEAF frontier is feature-gathered but never expanded, so
    leaves live one hop beyond the adjacency closure — that deeper set is
    exactly every node a shard engine can ever gather a row for.
    """
    indptr = np.asarray(csr_topo.indptr, np.int64)
    indices = np.asarray(csr_topo.indices, np.int64)
    g2h = np.asarray(global2host)
    n = indptr.shape[0] - 1
    if g2h.shape[0] != n:
        raise ValueError(f"global2host has {g2h.shape[0]} rows, graph has {n}")
    owned = np.nonzero(g2h == host)[0]
    seed_mask = np.zeros(n, bool)
    seed_mask[owned] = True
    hops = max(int(hops), 0)
    feat_hops = hops if closure_hops is None else max(int(closure_hops), hops)
    topo_closure, closure = closure_masks(
        indptr, indices, seed_mask, hops, feat_hops
    )
    shard, edge_stats = shard_from_mask(csr_topo, topo_closure)
    stats = {
        "owned_nodes": int(owned.shape[0]),
        "closure_nodes": int(topo_closure.sum()),
        "feature_closure_nodes": int(closure.sum()),
        **edge_stats,
    }
    if return_closure:
        return shard, stats, np.nonzero(closure)[0]
    return shard, stats


def closure_masks(
    indptr: np.ndarray,
    indices: np.ndarray,
    seed_mask: np.ndarray,
    hops: int,
    feat_hops: int,
    src_per_edge: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """The closure BFS shared by `shard_topology_by_owner` and the
    round-16 INCREMENTAL migration path: ``(topo_mask, feat_mask)`` bool
    [N] — the ``hops``-hop adjacency closure and the ``feat_hops``-hop
    feature closure of ``seed_mask``. Edge-parallel and vectorized (a
    per-frontier-node python loop is O(minutes) at products scale): src
    id per CSR slot built once (pass ``src_per_edge`` to amortize it
    across calls — the migration loop does), each hop masks the
    frontier's edges and uniques their endpoints.

    k-hop reachability is union-homomorphic — ``closure(A | B) ==
    closure(A) | closure(B)`` at any fixed depth — which is exactly what
    makes a RANGE handoff incremental: the destination's new masks are
    its old masks OR'd with the migrated range's, no BFS over the rows
    it already held."""
    n = indptr.shape[0] - 1
    if src_per_edge is None:
        src_per_edge = np.repeat(
            np.arange(n, dtype=np.int64), (indptr[1:] - indptr[:-1])
        )
    closure = seed_mask.copy()
    frontier_mask = closure.copy()
    topo_closure = closure.copy() if hops == 0 else None
    for hop in range(feat_hops):
        if not frontier_mask.any():
            break
        nxt = np.unique(indices[frontier_mask[src_per_edge]])
        nxt = nxt[~closure[nxt]]
        if nxt.size == 0:
            break
        closure[nxt] = True
        frontier_mask = np.zeros(n, bool)
        frontier_mask[nxt] = True
        if hop + 1 == hops:
            topo_closure = closure.copy()
    if topo_closure is None:  # BFS exhausted the graph before `hops`
        topo_closure = closure.copy()
    return topo_closure, closure


def shard_from_mask(
    csr_topo: CSRTopo, topo_mask: np.ndarray,
    src_per_edge: Optional[np.ndarray] = None,
) -> Tuple[CSRTopo, Dict[str, float]]:
    """Materialize the global-id-space shard CSR keeping adjacency only
    for rows in ``topo_mask`` (every other row reads degree 0) — the
    build half of `shard_topology_by_owner`, shared with the migration
    path so an extended owner shard is constructed by the byte-for-byte
    same code as a built one. Pass ``src_per_edge`` to amortize the
    O(E) repeat across calls, exactly like `closure_masks`."""
    indptr = np.asarray(csr_topo.indptr, np.int64)
    indices = np.asarray(csr_topo.indices, np.int64)
    n = indptr.shape[0] - 1
    if src_per_edge is None:
        src_per_edge = np.repeat(
            np.arange(n, dtype=np.int64), (indptr[1:] - indptr[:-1])
        )
    deg = np.where(topo_mask, indptr[1:] - indptr[:-1], 0)
    new_indptr = np.zeros(n + 1, np.int64)
    np.cumsum(deg, out=new_indptr[1:])
    keep_edge = topo_mask[src_per_edge]
    new_indices = indices[keep_edge]
    new_weights = (
        None
        if csr_topo.edge_weights is None
        else np.asarray(csr_topo.edge_weights, np.float32)[keep_edge]
    )
    shard = CSRTopo(indptr=new_indptr, indices=new_indices, edge_weights=new_weights)
    stats = {
        "edges_kept": int(new_indices.shape[0]),
        "edges_total": int(indices.shape[0]),
        "edge_frac": (
            float(new_indices.shape[0]) / float(max(indices.shape[0], 1))
        ),
    }
    return shard, stats


def shard_topology_for_seeds(
    csr_topo: CSRTopo,
    seed_ids: np.ndarray,
    hops: int,
    closure_hops: Optional[int] = None,
):
    """`shard_topology_by_owner` for an EXPLICIT seed set instead of an
    ownership map: the hops-hop halo-closure topology of ``seed_ids``
    (every other row reads degree 0), in the GLOBAL id space. This is the
    hot-set replica's topology (round 15): a sampler over it draws
    bit-identically to a full-graph sampler for the replicated seeds —
    the same closure argument the owner shards ride. Returns
    ``(shard_topo, stats, closure_ids)``."""
    n = csr_topo.indptr.shape[0] - 1
    seed_ids = np.asarray(seed_ids, np.int64)
    if seed_ids.size and (seed_ids.min() < 0 or seed_ids.max() >= n):
        raise ValueError(f"seed ids outside [0, {n})")
    mask = np.ones(n, np.int32)  # host 1 = everyone else
    mask[seed_ids] = 0           # host 0 = the replicated set
    return shard_topology_by_owner(
        csr_topo, mask, 0, hops, return_closure=True,
        closure_hops=closure_hops,
    )


class LoopbackComm:
    """Host-side stand-in for `TpuComm` in ``exchange="host"`` mode: the
    same `register_local_table` / `exchange` surface, answered by direct
    numpy indexing instead of collectives. Value-identical to the wire
    path (the collectives move bytes, they never transform them), so shard
    features built over it serve bit-identical rows — it just measures
    nothing about the interconnect."""

    def __init__(self, hosts: int):
        self.table = HostRankTable(hosts, 1)
        self._blocks: Dict[int, np.ndarray] = {}

    def register_local_table(self, host: int, rows: np.ndarray) -> None:
        self._blocks[host] = np.asarray(rows, np.float32)

    def exchange(self, host2ids, budget=None):
        res = []
        for j, ids in enumerate(host2ids):
            ids = np.asarray(ids, np.int64)
            res.append(self._blocks[j][ids] if ids.size else None)
        return res


class _ShardFeature:
    """The shard engine's feature view: clip global ids like the raw-table
    `inference.lookup_features` path (sampled ``n_id`` may carry padding
    lanes), then answer owned rows from the local 1/H block and halo rows
    through the feature exchange (`DistFeature`). The clip is what keeps a
    shard engine's forward bit-identical to a raw-full-table engine's on
    the same sample."""

    def __init__(self, dist: DistFeature, n_nodes: int):
        self._dist = dist
        self._n = n_nodes

    @property
    def shape(self):
        return (self._n, self._dist.feature.dim)

    @property
    def dim(self) -> int:
        return self._dist.feature.dim

    @property
    def tier_counter(self):
        """Delegate the observe-only tier tap to the LOCAL feature shard
        (round 14): the owner engine's workload monitor then attributes
        the owned-rows gather per tier — hbm/host/disk of the shard's
        own store; exchanged halo rows are the peer's tiers to count."""
        return self._dist.feature.tier_counter

    @tier_counter.setter
    def tier_counter(self, counter) -> None:
        self._dist.feature.tier_counter = counter

    @property
    def row_tap(self):
        return self._dist.feature.row_tap

    @row_tap.setter
    def row_tap(self, tap) -> None:
        self._dist.feature.row_tap = tap

    def __getitem__(self, n_id):
        ids = np.clip(np.asarray(n_id), 0, self._n - 1)
        return self._dist[ids]


class ClosureFeature:
    """Owner-resident serve features over GLOBAL ids — the fusable shard
    feature (``feature_residency="closure"``).

    Holds the feature rows of the shard's whole ``hops``-hop closure
    (owned + halo — exactly the rows the per-flush `DistFeature` exchange
    would have fetched, materialized ONCE at build time) plus an ``[N]``
    int32 global→row map, so the owner's gather is a pure in-jit
    take-of-take and the FUSED one-dispatch serve program applies
    (`inference.feature_gather_spec` reads `jit_gather_spec`). On a
    k-hop-closed partition the closure adds nothing and residency is
    exactly 1/H of the table; elsewhere the halo is real replication,
    reported in ``shard_topo_stats`` (``closure_nodes`` vs ``owned_nodes``)
    — never hidden.

    Out-of-closure ids map to -1 and clip to row 0: such lanes are
    unreachable from owned seeds (the closure IS the sampler's reachable
    set), so they only ever occur in masked pad lanes the model's
    aggregation zeroes out — the same guarantee every padded pipeline here
    rides. Host ``__getitem__`` runs the identical clip/map/clip/take
    arithmetic, so split-path dispatches and parity replays are
    value-identical to the fused gather.

    ``reserve_rows`` (round-17 streaming graphs) appends zeroed slack
    rows so `install_rows` can land feature rows for nodes that ENTER the
    closure under a graph delta without changing the table's shape —
    sealed AOT executables take the table as an argument, so same-shape
    swaps never recompile. Exhausting the reserve raises
    `stream.StreamCapacityError` (capacity is planned, never silently
    grown)."""

    def __init__(self, rows: np.ndarray, local_map: np.ndarray,
                 reserve_rows: int = 0):
        rows = np.asarray(rows, np.float32)
        if rows.ndim != 2:
            raise ValueError("ClosureFeature wants rows [C, D] and map [N]")
        self._used = rows.shape[0]
        if reserve_rows:
            rows = np.concatenate(
                [rows, np.zeros((int(reserve_rows), rows.shape[1]),
                                np.float32)]
            )
        self._rows = np.ascontiguousarray(rows)
        self._map = np.asarray(local_map, np.int32)
        if self._map.ndim != 1:
            raise ValueError("ClosureFeature wants rows [C, D] and map [N]")
        # hosts=1 (closure == everything): the map is the identity, so the
        # fused gather collapses to the plain-table program — the hosts=1
        # engine then runs the EXACT executable the single-host engine
        # runs (bitwise degeneration by construction, and one fewer
        # compiled program shape)
        self._identity = self._map.shape[0] == self._rows.shape[0] and bool(
            np.array_equal(self._map, np.arange(self._map.shape[0], dtype=np.int32))
        )
        self._dev: Optional[Tuple] = None

    @property
    def shape(self):
        return (self._map.shape[0], self._rows.shape[1])

    @property
    def dim(self) -> int:
        return self._rows.shape[1]

    @property
    def resident_rows(self) -> int:
        """Rows holding real feature data (reserve slack excluded)."""
        return self._used

    @property
    def capacity_rows(self) -> int:
        return self._rows.shape[0]

    def preflight_install(self, node_ids) -> int:
        """Reserve-capacity check for a batch of `install_rows` ids
        WITHOUT mutating: raises the same `StreamCapacityError` an
        install would, so multi-consumer commits (the dist router's
        fleet-wide `update_graph`) can validate every owner before
        mutating any. Returns the fresh slots the batch would take."""
        from ..stream import StreamCapacityError

        node_ids = np.asarray(node_ids, np.int64).reshape(-1)
        if node_ids.size == 0:
            return 0
        fresh = int(np.count_nonzero(
            self._map[np.unique(node_ids)] < 0
        ))
        if self._used + fresh > self._rows.shape[0]:
            raise StreamCapacityError(
                f"ClosureFeature reserve exhausted: batch installs "
                f"{fresh} new rows, {self._rows.shape[0] - self._used} "
                f"free of {self._rows.shape[0]} — rebuild with a larger "
                "reserve_rows"
            )
        return fresh

    def install_rows(self, node_ids, rows) -> int:
        """Land feature rows for nodes newly entering the closure (the
        round-17 incremental extension): each node takes the next free
        reserve slot (a node already mapped is overwritten in place —
        feature rows are static under topology deltas, so this only
        happens on a re-install). ATOMIC: capacity is preflighted before
        any slot moves, so a raising install leaves map, rows, and
        device state untouched. Device state updates as a batched
        same-shape row scatter, exactly like the tile swaps. Callers
        must hold the owning engine's fence (the serve engines do)."""
        from ..stream import _bucketed, _swap_rows

        node_ids = np.asarray(node_ids, np.int64).reshape(-1)
        rows = np.asarray(rows, np.float32)
        if rows.shape[0] != node_ids.shape[0] or rows.shape[1] != self.dim:
            raise ValueError(
                f"install rows {rows.shape} do not match "
                f"{node_ids.shape[0]} nodes x dim {self.dim}"
            )
        if node_ids.size == 0:
            return 0
        self.preflight_install(node_ids)
        slots = np.empty(node_ids.shape[0], np.int64)
        for i, node in enumerate(node_ids):
            node = int(node)
            slot = int(self._map[node])
            if slot < 0:
                slot = self._used
                self._used += 1
                self._map[node] = slot
            slots[i] = slot
            self._rows[slot] = rows[i]
        if self._dev is not None:
            import jax.numpy as jnp

            dev_rows, dev_map = self._dev
            pos, vals = _bucketed(slots, rows, self._rows.shape[0])
            dev_rows = _swap_rows(dev_rows, jnp.asarray(pos),
                                  jnp.asarray(vals))
            if dev_map is not None:
                pos, vals = _bucketed(
                    node_ids, self._map[node_ids], self._map.shape[0]
                )
                dev_map = _swap_rows(dev_map, jnp.asarray(pos),
                                     jnp.asarray(vals))
            self._dev = (dev_rows, dev_map)
        return int(node_ids.size)

    def jit_gather_spec(self):
        import jax.numpy as jnp

        if self._dev is None:
            self._dev = (
                jnp.asarray(self._rows),
                None if self._identity else jnp.asarray(self._map),
            )
        return self._dev

    def __getitem__(self, n_id):
        import jax.numpy as jnp

        ids = np.clip(np.asarray(n_id), 0, self._map.shape[0] - 1)
        loc = np.clip(self._map[ids], 0, self._rows.shape[0] - 1)
        return jnp.asarray(self._rows[loc])


def _feat_reserve(config, n_closure: int) -> int:
    """`ClosureFeature` reserve rows for a closure shard of ``n_closure``
    nodes: room for rows ENTERING the closure under streaming deltas
    (sized like the tile reserve, off the same knob; 0 = frozen graph).
    One formula for every shard build site — initial owners and
    migration engines must agree or a migrated-in owner would exhaust
    its reserve earlier than the fleet it joined."""
    if not config.streaming:
        return 0
    return max(64, int(config.stream_reserve_frac * n_closure))


@dataclass
class DistServeConfig:
    """Router knobs (per-shard engine knobs ride ``shard_config``).

    hosts          : number of serving shards (HostRankTable hosts).
    max_batch      : router flush width — unique seeds drained per flush,
                     BEFORE the owner split (per-shard sub-batches are
                     ~max_batch/hosts on uniform traffic; the probe's
                     width-shrink acceptance reads this).
    max_delay_ms   : router flush-age policy, same semantics as
                     `ServeConfig.max_delay_ms`.
    max_in_flight  : router in-flight window (concurrent routed flushes).
    exchange       : "collective" (ids/logits ride the mesh all_to_all),
                     "host" (direct owner calls + loopback feature
                     exchange), or "auto" (collective when the backend has
                     >= hosts devices).
    budget         : per-owner seed-id budget of the serve exchange (static
                     collective shape); default pow2(max_batch) — a whole
                     router flush to one owner always fits.
    shard_config   : template `ServeConfig` for the per-shard engines
                     (default: the router's max_batch/max_in_flight with
                     the delay policy irrelevant — the router drives shard
                     flushes synchronously). ``record_dispatches`` on the
                     shard engines is what the parity replay reads.
    cache_entries  : per-shard embedding-cache rows at the OWNERS (so the
                     backing cache splits by ownership).
    router_cache_entries : front-end result-cache rows (default: same as
                     ``cache_entries``; 0 disables). Repeat requests for a
                     node already served under the current params version
                     are answered AT THE ROUTER — no routing, no exchange
                     bytes, no owner work. Same get-at-submit /
                     put-at-resolve / invalidate-on-update sequencing as
                     `ServeEngine`'s cache, which is what makes the
                     ``hosts=1`` engine bit-identical to the single-host
                     engine INCLUDING cache behavior (identical LRU
                     evolution -> identical flush composition -> identical
                     key stream) — PROVIDED the cache never evicts (working
                     set <= capacity). Under eviction pressure the router
                     and owner caches can diverge in LRU state (the owner
                     cache only sees router misses), so an owner may answer
                     a router-missed repeat from ITS cache where the
                     single-host engine would re-dispatch — flush
                     composition then differs. Served rows stay bit-equal
                     to the owning shard's replay oracle either way (a
                     cached row was computed by a logged dispatch).
    clock          : injectable monotonic clock shared with shard engines.
    record_dispatches : keep the router's (seeds, per-owner split) log.
    feature_residency : "closure" (default) materializes each owner's
                     feature rows for its whole k-hop closure at BUILD time
                     (`ClosureFeature`: the rows the per-flush DistFeature
                     exchange would have fetched, fetched once), making the
                     owner gather in-jit so shard engines run the FUSED
                     one-dispatch serve program; "exchange" keeps the
                     round-10 on-demand feature exchange (owned rows local,
                     halo rows over the wire per flush — shard engines then
                     serve on the split path). Value-identical; residency
                     trades halo-row memory for per-flush exchange work.
    late_admission : admit late-arriving seeds into a routed flush that is
                     assembled but still waiting for a window slot (up to
                     ``max_batch``), mirroring `ServeConfig.late_admission`.
    journal_events : router-side `trace.EventJournal` capacity (0 =
                     disabled). The default shard config inherits it, so
                     every owner engine journals too; `fleet_snapshot` /
                     `export_chrome_trace` merge the owner journals
                     deterministically (sorted host, dispatch-index order
                     within — the same discipline as the stats merges).
                     Observe-only, same contract as
                     `ServeConfig.journal_events`.
    workload       : a `trace.WorkloadConfig` enables round-13 workload
                     telemetry at the ROUTER (access-frequency sketches
                     over every submitted seed, per-owner routed
                     sub-batch widths + flush/exchange latency quantiles,
                     imbalance + straggler stats) and — via the default
                     shard config — at every owner engine (owner-side
                     sketches, cache taps, tier attribution).
                     `workload_report()` / `fleet_registry()` are the
                     read side. Observe-only, replay-deterministic decay
                     ticks on the router's dispatch index, same contract
                     as `ServeConfig.workload`.
    """

    hosts: int = 2
    max_batch: int = 64
    max_delay_ms: float = 2.0
    max_in_flight: int = 2
    exchange: str = "auto"
    budget: Optional[int] = None
    shard_config: Optional[ServeConfig] = None
    cache_entries: int = 100_000
    router_cache_entries: Optional[int] = None
    clock: Callable[[], float] = time.monotonic
    flush_poll_ms: float = 0.2
    record_dispatches: bool = False
    feature_residency: str = "closure"
    late_admission: bool = True
    journal_events: int = 0
    workload: Optional[WorkloadConfig] = None
    # -- round-15 fleet policies (ROADMAP item 3; docs/api.md "Fleet
    # serving") -----------------------------------------------------------
    # replicate_top_k: hot-set replication head size — `refresh_replicas()`
    # mirrors the k hottest seeds (router workload sketch; k priced by
    # scaling.skew_table) onto the router's own host, so head traffic is
    # answered locally and never enters comm.exchange_serve. 0 = off.
    replicate_top_k: int = 0
    # hedge_deadline_ms: per-owner deadline on routed sub-batches
    # (exchange="host" mode, where owner legs are individually
    # addressable). A leg that misses it re-routes to the full-graph
    # fallback / the replica; the slow owner's answer is discarded.
    # 0 = no deadline (errors still fail over when a target exists).
    hedge_deadline_ms: float = 0.0
    # full_graph_fallback: build() keeps one full-topology/full-feature
    # engine on the router's host as the degraded-mode hedge target — any
    # seed can fail over to it (the replica covers only the hot head).
    full_graph_fallback: bool = False
    # eject_after / eject_backoff_flushes: an owner failing this many
    # CONSECUTIVE sub-batches is ejected (routed straight to the hedge
    # target, no deadline burned) until this many router dispatch indices
    # pass — then it is probed again (half-open). Flush-indexed, never
    # wall time, so ejection decisions replay deterministically.
    eject_after: int = 2
    eject_backoff_flushes: int = 16
    # fault_injector: a `serve.faults.FaultInjector` exercising the
    # host-mode owner legs — deterministic (owner, dispatch-index) keyed
    # kill/error/stall, the proof harness for everything above.
    fault_injector: Optional[object] = None
    # per-tenant admission (same semantics as the ServeConfig fields;
    # applied at the ROUTER — the fleet's admission point)
    tenant_weights: Optional[Dict[str, float]] = None
    max_queue_depth: int = 0
    drain_deadline_s: float = 30.0
    # round-14 adaptive tier knobs, inherited by every owner engine via
    # the default shard config (same semantics as the ServeConfig
    # fields); `DistServeEngine.adapt_tiers` drives one fenced pass per
    # owner, `start()` runs it fleet-wide when tier_adapt_every_s > 0
    tier_promote_batch: int = 64
    tier_promote_min: float = 2.0
    tier_hysteresis: float = 1.25
    tier_adapt_every_s: float = 0.0
    # round-18 flush-ahead prefetch (same semantics as the ServeConfig
    # fields, inherited by the default shard config). The ROUTER
    # additionally prefetches per owner off the routed sub-batches at
    # its own seal — one window EARLIER than the owner's assemble; the
    # staging buffer dedups, so router + owner double-issue is free.
    tier_prefetch: bool = False
    tier_prefetch_hops: Optional[int] = None
    tier_prefetch_max_rows: int = 4096
    # -- round-16 elastic fleet (ROADMAP item 2; docs/api.md "Elastic
    # fleet") --------------------------------------------------------------
    # migrate_batch_seeds: the BOUNDED migration unit — a range handoff
    # moves at most this many seeds per fenced flip. The expensive work
    # (range closure BFS, feature materialization, AOT warmup) runs
    # OUTSIDE the fence with the old owner still serving; only the
    # routing flip + range-scoped cache invalidation sit under it, so a
    # migration batch never stalls serving for longer than a weight swap.
    migrate_batch_seeds: int = 256
    # rebalance_imbalance: OwnerLoadStats max/mean routed-load ratio at
    # which `maybe_rebalance()` migrates ranges off the hottest owner
    # (requires workload telemetry). rebalance_max_seeds bounds one
    # pass; rebalance_every_s > 0 runs the check on a background timer.
    rebalance_imbalance: float = 1.5
    rebalance_max_seeds: int = 1024
    rebalance_every_s: float = 0.0
    # replica_refresh_every_s: the r15 remaining-leverage note — a
    # background timer re-runs `refresh_replicas()` when the router
    # sketch's hot set has drifted more than replica_drift_frac away
    # from what the live replica holds (WorkloadMonitor.hot_set_drift).
    # Fenced and observe-parity pinned exactly like the manual path;
    # 0 = manual refreshes only.
    replica_refresh_every_s: float = 0.0
    replica_drift_frac: float = 0.5
    # -- round-17 streaming graphs (ROADMAP item 1; docs/api.md
    # "Streaming graphs") -------------------------------------------------
    # streaming: build() binds every owner shard (and the full-graph
    # fallback) to a `stream.StreamingTiledGraph` so
    # `update_graph(delta)` can commit live edge appends — in-place
    # pad-lane tile writes + batched device tile swaps, the owner shards'
    # halo closures extended INCREMENTALLY (never resharded). Requires
    # feature_residency="closure" (owner feature rows install into the
    # ClosureFeature reserve; the exchange residency's DistFeature
    # partition already spans the full id space but its owners gather
    # host-side — stream them by rebuilding). False = the frozen-graph
    # engine, byte-for-byte round 16.
    streaming: bool = False
    # stream_reserve_frac: slack planned per owner at build, as a
    # fraction of the built size — tile rows for spills/installs AND
    # ClosureFeature rows for closure growth. Exhaustion raises
    # stream.StreamCapacityError (plan capacity like sampler caps;
    # shapes are frozen so sealed executables never recompile).
    stream_reserve_frac: float = 0.5
    # stream_invalidate_hops: reverse-closure depth of the delta cache
    # invalidation (None = len(sizes) - 1, the expansion-hop count —
    # see ServeConfig.stream_invalidate_hops).
    stream_invalidate_hops: Optional[int] = None
    # stream_replica_rebuild: when a delta's closure touches the live
    # hot-set replica, the replica is DROPPED under the commit fence
    # (its shard topology went stale — serving from it would draw from
    # the pre-delta graph); True rebuilds it over the updated graph
    # right after the fence, False leaves replication off until the
    # next manual/drift refresh.
    stream_replica_rebuild: bool = True
    # -- round-23 concurrent owner fan-out (docs/api.md "Concurrent owner
    # fan-out") ------------------------------------------------------------
    # sequential_legs: run host-mode dispatch legs one after another on
    # the flushing thread — the pre-round-23 router, kept verbatim as
    # the bit-parity twin of the concurrent fan-out (exactly like
    # `_scalar_resolve`). False = fan the legs out on per-flush worker
    # threads (owner `predict` blocks in XLA with the GIL released, so
    # the overlap is real even on one core) and JOIN IN SPLIT ORDER,
    # applying every leg's side effects at join — logits, dispatch
    # logs, `hedge_events()`, owner health, and the journal stay
    # bit-identical to the sequential pass; only wall time changes
    # (max(legs) + merge instead of sum(legs)). Collective mode is
    # untouched either way: one launch under the collective lock —
    # concurrent collective launches deadlock XLA's rendezvous.
    sequential_legs: bool = False
    # leg_fanout: bound on CONCURRENTLY RUNNING legs per routed flush
    # (0 = all at once). Legs start in split order and join in split
    # order regardless, so the bound changes scheduling, never results
    # — leg_fanout=1 is the sequential pass on a worker thread.
    leg_fanout: int = 0
    # -- round-24 zero-stall commits (see ServeConfig.fenced_commits) ------
    # False (default) = fleet update_graph plans/preflights outside the
    # router fence, owner engines run their own zero-stall commits, and
    # the router-grain flip (graph_version bump + replica retire) runs
    # under the router _seq only. True = the drain-ordered round-17..23
    # fence, bit-identical, propagated to every owner engine.
    fenced_commits: bool = False

    def resolved_shard_config(self) -> ServeConfig:
        if self.shard_config is not None:
            return self.shard_config
        return ServeConfig(
            max_batch=self.max_batch,
            max_delay_ms=self.max_delay_ms,
            max_in_flight=self.max_in_flight,
            cache_entries=self.cache_entries,
            clock=self.clock,
            record_dispatches=self.record_dispatches,
            late_admission=self.late_admission,
            journal_events=self.journal_events,
            workload=self.workload,
            tier_promote_batch=self.tier_promote_batch,
            tier_promote_min=self.tier_promote_min,
            tier_hysteresis=self.tier_hysteresis,
            tier_prefetch=self.tier_prefetch,
            tier_prefetch_hops=self.tier_prefetch_hops,
            tier_prefetch_max_rows=self.tier_prefetch_max_rows,
            # round-16 owner-side tenant scheduling: the router forwards
            # each sub-batch's submitting tenants, and owner engines
            # apply the SAME weighted flush quotas — a tenant's share
            # holds end-to-end, not just at router admission. None (no
            # QoS) leaves owner engines byte-identical to round 15.
            tenant_weights=self.tenant_weights,
            fenced_commits=self.fenced_commits,
        )


@dataclass
class DistServeStats:
    """Router-side counters; `DistServeEngine.aggregate_stats` merges the
    per-shard `ServeStats` on top (via the ``merge`` family in
    `quiver_tpu.trace`). ``exchange_id_bytes``/``exchange_logit_bytes``
    count the GLOBAL collective payloads (H*H*L ids, H*H*L*C logits per
    routed flush in collective mode) — the wire term
    `scaling.serve_table(hosts=...)` prices."""

    requests: int = 0
    coalesced: int = 0
    router_dispatches: int = 0
    routed_seeds: int = 0
    late_admitted: int = 0
    # round-15 fleet-policy counters: replica_hits counts seeds answered
    # by the local hot-set replica (never entered the exchange); hedges /
    # hedged_seeds count owner sub-batches (and their seeds) re-routed to
    # a failover target, split by cause (deadline miss vs owner error vs
    # routed-while-ejected); owner_ejections counts backoff entries;
    # shed / request_errors / undrained mirror the ServeStats fields.
    replica_hits: int = 0
    hedges: int = 0
    hedged_seeds: int = 0
    hedge_timeouts: int = 0
    hedge_errors: int = 0
    hedge_ejected: int = 0
    hedge_failed: int = 0       # failovers with no (working) target
    owner_ejections: int = 0
    shed: int = 0
    request_errors: int = 0
    undrained: int = 0
    # round-16 elastic-fleet counters: migration_batches counts fenced
    # range flips COMMITTED (roll-forwards included — the range landed),
    # migration_rollbacks the ranges that stayed with their old owner
    # after a destination died mid-handoff; migrated_seeds sums committed
    # range widths; replica_refreshes counts background drift-triggered
    # replica rebuilds (manual refresh_replicas calls ride
    # replica_version, not this).
    migration_batches: int = 0
    migration_rollbacks: int = 0
    migration_rollforwards: int = 0
    migrated_seeds: int = 0
    replica_refreshes: int = 0
    # round-17 streaming-graph counters: graph_deltas counts fenced
    # update_graph commits, delta_edges the edges they appended,
    # delta_cache_invalidated the closure-touched ROUTER cache drops,
    # delta_closure_installs the owner-shard rows (topology installs)
    # landed by incremental halo extension, replica_delta_invalidations
    # the hot-set replicas dropped because a delta touched their closure
    graph_deltas: int = 0
    delta_edges: int = 0
    delta_cache_invalidated: int = 0
    delta_closure_installs: int = 0
    replica_delta_invalidations: int = 0
    # round-21 lifecycle: removals committed fleet-wide (expiry and
    # compaction are per-owner-engine — they ride the merged ServeStats)
    edges_deleted: int = 0
    inflight_peak: int = 0
    sub_batches: Dict[int, int] = field(default_factory=dict)
    sub_batch_seeds: Dict[int, int] = field(default_factory=dict)
    exchange_id_bytes: int = 0
    exchange_logit_bytes: int = 0
    router_cache: HitRateCounter = field(default_factory=HitRateCounter)
    latency: LatencyHistogram = field(default_factory=LatencyHistogram)
    tenant_latency: Dict[str, LatencyHistogram] = field(default_factory=dict)
    spans: SpanRecorder = field(default_factory=SpanRecorder)
    # round-24: per-commit routed-serving stall in MICROSECONDS (the
    # histogram is unit-agnostic; µs keeps sub-ms flips resolvable).
    # Fenced: the whole drain+apply hold; zero-stall: the _seq flip.
    commit_stall: LatencyHistogram = field(
        default_factory=lambda: LatencyHistogram(min_ms=1e-2, max_ms=1e9)
    )

    def tenant_hist(self, tenant: str) -> LatencyHistogram:
        from .engine import tenant_latency_hist

        return tenant_latency_hist(self.tenant_latency, tenant)

    def mean_sub_batch_width(self) -> Dict[int, float]:
        return {
            h: self.sub_batch_seeds[h] / n
            for h, n in self.sub_batches.items()
            if n
        }

    def snapshot(self) -> Dict[str, object]:
        return {
            "requests": self.requests,
            "coalesced": self.coalesced,
            "router_dispatches": self.router_dispatches,
            "routed_seeds": self.routed_seeds,
            "late_admitted": self.late_admitted,
            "replica_hits": self.replica_hits,
            "hedges": self.hedges,
            "hedged_seeds": self.hedged_seeds,
            "hedge_timeouts": self.hedge_timeouts,
            "hedge_errors": self.hedge_errors,
            "hedge_ejected": self.hedge_ejected,
            "hedge_failed": self.hedge_failed,
            "owner_ejections": self.owner_ejections,
            "shed": self.shed,
            "request_errors": self.request_errors,
            "undrained": self.undrained,
            "migration_batches": self.migration_batches,
            "migration_rollbacks": self.migration_rollbacks,
            "migration_rollforwards": self.migration_rollforwards,
            "migrated_seeds": self.migrated_seeds,
            "replica_refreshes": self.replica_refreshes,
            "graph_deltas": self.graph_deltas,
            "delta_edges": self.delta_edges,
            "delta_cache_invalidated": self.delta_cache_invalidated,
            "delta_closure_installs": self.delta_closure_installs,
            "replica_delta_invalidations": self.replica_delta_invalidations,
            "edges_deleted": self.edges_deleted,
            "inflight_peak": self.inflight_peak,
            "sub_batches": dict(self.sub_batches),
            "mean_sub_batch_width": self.mean_sub_batch_width(),
            "exchange_id_bytes": self.exchange_id_bytes,
            "exchange_logit_bytes": self.exchange_logit_bytes,
            "router_cache": self.router_cache.snapshot(),
            "latency": self.latency.snapshot(),
            "commit_stall_us": self.commit_stall.snapshot(),
            "tenant_latency": {
                t: self.tenant_latency[t].snapshot()
                for t in sorted(self.tenant_latency)
            },
            "overlap": self.spans.overlap_summary(),
        }


class _RoutedFlush:
    """Per-flush router state between assemble and resolve. ``bucket`` is
    the admission cap (the router pads nothing, so its "pad slack" is the
    drained width up to ``max_batch``); the owner split is computed at SEAL
    time so late-admitted seeds route with their flush.

    ``error`` poisons the WHOLE flush (assemble/seal failures, a
    collective-exchange abort); ``slot_errors`` maps key POSITIONS to
    per-request exceptions — the round-15 isolation contract: a failed
    owner sub-batch resolves only its own slots with the error, every
    other slot resolves normally, and `flush()` does not re-raise."""

    __slots__ = ("keys", "slots", "split", "bucket", "error", "slot_errors",
                 "fid", "tenants", "extra", "ids", "rids", "tenant_ix",
                 "graph_version")

    def __init__(self, keys, slots, split):
        self.keys = keys
        self.slots = slots
        self.split = split  # [(host, ids ndarray, positions ndarray)]
        # ROUTER graph epoch this flush sealed against (round 24): stamped
        # under _seq at seal, so a zero-stall fleet commit flipping the
        # router version mid-flight never mixes epochs within one flush.
        # Cache writebacks carry it as their floor-gate stamp.
        self.graph_version = 0
        # array-native slot views (round 20, sealed — see _Flush): seed
        # ids (int64), journal rids (int64, -1 = journal off) and wire
        # tenant indices (int32, the collective's registry; -1 =
        # unregistered tenant), aligned with ``slots``
        self.ids = None
        self.rids = None
        self.tenant_ix = None
        self.bucket = 0
        self.error: Optional[BaseException] = None
        self.slot_errors: Dict[int, BaseException] = {}
        self.fid = -1  # journal flush id (router dispatch-log index)
        # per-key submitting tenant (filled at seal, aligned with keys):
        # owner legs forward these so owner-side quotas hold end-to-end
        self.tenants: List[str] = []
        # extra per-key dispatch payload aligned with keys (round 19:
        # the temporal router's query-time vector); None on the plain
        # router
        self.extra = None


class _LegRun:
    """One host-mode dispatch leg in flight (round 23). The worker half
    fills ``box`` only — {"rows", "err", "dt"}; never ``out``, never
    stats — so an abandoned (timed-out) worker can finish whenever it
    likes without touching anything the joiner already settled. The
    joiner half applies every side effect in split order."""

    __slots__ = ("h", "ids", "pos", "tenants", "ejected", "thread",
                 "t_start", "box")

    def __init__(self, h, ids, pos, tenants):
        self.h = h
        self.ids = ids
        self.pos = pos
        self.tenants = tenants
        self.ejected = False
        self.thread: Optional[threading.Thread] = None
        self.t_start = 0.0
        self.box: Dict[str, object] = {}


def _bounded_leg_schedule(runs, cap, start_leg):
    """Start fan-out legs STRICTLY IN SPLIT ORDER with at most ``cap``
    running at once, yielding each run in order for its join — the
    joiner runs between yields, so starts interleave with joins and the
    pipeline stays full up to the bound. ``start_leg(run)`` returns
    True when it spawned a thread (ejected/wedged legs never spawn and
    never count). The bound changes scheduling, never results: joins
    happen in split order regardless."""
    started = 0
    active = 0
    for r in runs:
        while started < len(runs) and active < cap:
            nxt = runs[started]
            started += 1
            if start_leg(nxt):
                active += 1
        yield r
        if r.thread is not None:
            active -= 1


class _HotReplica:
    """The router-local hot-set replica (round 15): a full `ServeEngine`
    over the replicated seeds' halo-closure topology + feature rows —
    the mirror of Quiver's ``p2p_clique_replicate`` hot-prefix applied to
    serving. ``ids`` is the sorted replicated seed set; ``id_set`` the
    O(1) membership view the hedge path consults."""

    __slots__ = ("engine", "ids", "id_set", "version", "stats")

    def __init__(self, engine: ServeEngine, ids: np.ndarray, version: int,
                 stats: Dict[str, float]):
        self.engine = engine
        self.ids = np.asarray(ids, np.int64)
        self.id_set = frozenset(int(x) for x in self.ids)
        self.version = version
        self.stats = stats


class DistServeEngine:
    """Seed-ownership-sharded serving front end (module docstring has the
    design; docs/api.md "Distributed serving" the contract). Typical use::

        dist = DistServeEngine.build(
            model, params, csr_topo, feat, sizes=[8, 8], hosts=2,
            config=DistServeConfig(max_batch=32),
        )
        dist.warmup()
        out = dist.predict(node_ids)     # routed, owner-served, re-merged

    The constructor takes prebuilt shard engines keyed by host (`build`
    does the partitioning); multi-process deployments construct with only
    their own host's engine and a `TpuComm` whose serve answerer is
    registered, then drive lockstep flushes (tests/dist_worker.py serve
    mode)."""

    def __init__(
        self,
        engines: Dict[int, ServeEngine],
        global2host: np.ndarray,
        out_dim: int,
        config: Optional[DistServeConfig] = None,
        comm: Optional[TpuComm] = None,
        shard_topo_stats: Optional[Dict[int, Dict[str, float]]] = None,
    ):
        self.config = config or DistServeConfig()
        if self.config.max_in_flight < 1:
            raise ValueError("max_in_flight must be >= 1")
        mode = self.config.exchange
        if mode not in ("auto", "collective", "host"):
            raise ValueError(f"unknown exchange mode {mode!r}")
        if mode == "auto":
            mode = "collective" if comm is not None else "host"
        if mode == "collective" and comm is None:
            raise ValueError("exchange='collective' needs a TpuComm")
        if self.config.fault_injector is not None and mode != "host":
            raise ValueError(
                "fault_injector exercises the per-owner host-mode dispatch "
                "legs (the collective is one launch and cannot fail "
                "per-owner); build with exchange='host'"
            )
        self.exchange_mode = mode
        self.engines = dict(engines)
        self.hosts = self.config.hosts
        # a COPY: scale()/rebalance() mutate ownership in place under the
        # per-range fence, and the caller's array must not move under it
        self.global2host = np.array(global2host, np.int32, copy=True)
        self.out_dim = int(out_dim)
        self.comm = comm
        self.shard_topo_stats = shard_topo_stats or {}
        self._budget = self.config.budget or round_up_pow2(self.config.max_batch)
        self._clock = self.config.clock
        self.stats = DistServeStats()
        self.journal = (
            EventJournal(self.config.journal_events, clock=self._clock)
            if self.config.journal_events > 0
            else NULL_JOURNAL
        )
        self._next_rid = 0     # journal request ids (guarded by _lock)
        self._flush_index = 0  # router dispatch-log index (guarded by _seq)
        self.tier_adapt_errors = 0  # failed fleet tier-adaptation passes
        # round-13 router-side workload telemetry (observe-only): the
        # router sees EVERY submitted seed, so its sketch is the fleet's
        # access-frequency view; per-owner load/latency land here too
        self.workload = (
            WorkloadMonitor(self.config.workload, clock=self._clock)
            if self.config.workload is not None
            else None
        )
        rc = self.config.router_cache_entries
        self.cache = EmbeddingCache(
            self.config.cache_entries if rc is None else rc,
            counters=self.stats.router_cache,
        )
        if self.workload is not None:
            self.cache.workload = self.workload
        self.params_version = 0
        self.dispatch_log: List[Tuple[np.ndarray, List[Tuple[int, np.ndarray]]]] = []
        # ROUTER graph epoch per dispatch-log entry (round 24), a parallel
        # aligned list (the log's tuple shape is pinned by tests and the
        # round-21 CI smoke): dispatch_graph_versions[i] is the router
        # graph_version entry i sealed against — the epoch filter
        # `replay_fleet_oracle(graph_version=...)` selects rows by
        self.dispatch_graph_versions: List[int] = []
        # per-OWNER pending queues (round 20): the stripe hint is the
        # BUILD-TIME ownership snapshot, deliberately NOT the live
        # global2host — scale()/rebalance() mutate placement in place, and
        # a key whose stripe moved mid-flight would dodge its own coalesce
        # probe / pop. Routing always reads the live array at seal; the
        # stripe is only a lock-contention partition, so staleness is free.
        g2h_build = self.global2host.copy()
        n_ids = g2h_build.shape[0]

        def _stripe_hint(k, _g2h=g2h_build, _n=n_ids):
            # temporal routers key by (node, t_bucket): stripe by the node
            node = k[0] if type(k) is tuple else k
            return int(_g2h[node]) if 0 <= node < _n else hash(k)

        self._pending = _PendingStripes(self.hosts, stripe_key=_stripe_hint)
        self._inflight: Dict[int, _Slot] = {}
        import collections

        # round-15 fleet-policy state -------------------------------------
        # per-tenant admission rides the striped store's per-stripe counts
        # (mirrors ServeEngine). Policy logs are BOUNDED rings (newest
        # win) — sustained overload or a long-dead owner is exactly when
        # they fill, and an unbounded list there would leak until OOM
        self.shed_log = collections.deque(maxlen=POLICY_LOG_CAP)
        # hot-set replica (swapped only under the update_params fence) +
        # the full-graph failover engine (built by `build` on request)
        self.replica: Optional[_HotReplica] = None
        self.replica_version = 0
        # retired replica engines keep their dispatch logs so the fleet
        # replay oracle can still vouch for rows they served pre-refresh
        self._retired_replicas: List[ServeEngine] = []
        self.fallback: Optional[ServeEngine] = None
        self._params = None                # tracked for replica rebuilds
        self._replica_materials: Optional[Dict[str, object]] = None
        # -- round-16 elastic-fleet state ---------------------------------
        # owner engines replaced by a range handoff (and engines of
        # shrunk-away hosts) keep their dispatch logs for the replay
        # oracle, exactly like retired replicas. Engines retired WITHOUT
        # dispatch recording are dropped (a production fleet must not
        # accumulate dead device state), but their counters fold into
        # _retired_stats first so the merged fleet view never goes
        # backwards across a range flip.
        self._retired_engines: List[ServeEngine] = []
        self._retired_stats = ServeStats()
        # per-owner (adjacency-closure mask, feature-closure mask) over
        # the GLOBAL id space — the incremental-extension state: a range
        # handoff ORs the migrated range's closure into the destination's
        # masks instead of re-BFS-ing its whole owned set
        self._owner_masks: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        self._src_per_edge: Optional[np.ndarray] = None  # BFS amortizer
        # ownership_epoch bumps once per COMMITTED range flip; the
        # migration log [(mig, epoch, lo, hi, src, dst, n, outcome)] is
        # the deterministic routing-epoch history replay comparisons read
        self.ownership_epoch = 0
        self.migration_log: List[Tuple[int, int, int, int, int, int, int,
                                       str]] = []
        self._mig_index = 0          # monotonic handoff-batch counter
        # -- round-17 streaming-graph state -------------------------------
        # graph_version counts fenced delta commits at the ROUTER grain;
        # pending_delta accumulates staged arrivals (stage_edges);
        # _stream_adj is the host-side full-graph adjacency view (base
        # CSR + appended edges — closures and materialization, no device
        # bytes); _owner_streams/_owner_feats hold each owner's
        # StreamingTiledGraph / ClosureFeature for the in-place apply;
        # _materials_stale marks the build() materials' csr_topo as
        # behind the stream (re-materialized lazily by
        # `_current_full_topo` before a replica rebuild / migration
        # build — NEVER on the serving path).
        self.graph_version = 0
        self.pending_delta = None
        self._stream_adj = None
        self._owner_streams: Dict[int, object] = {}
        self._owner_feats: Dict[int, ClosureFeature] = {}
        self._materials_stale = False
        # serializes _stream_adj WRITES (update_graph's add/rollback)
        # against the lazy re-materialize — replica/migration builds run
        # OUTSIDE the router fence by design (AOT warmup costs seconds),
        # so without this a background build could iterate the adjacency
        # dicts mid-mutation or capture a mid-rollback graph. Ordering:
        # router fence lock -> _mat_lock, never the reverse.
        self._mat_lock = threading.Lock()
        # zero-stall commits (round 24): serializes WHOLE fleet commits
        # (plan + preflight + owner flips) against each other without
        # fencing traffic — the flip itself happens under _seq only.
        # Ordering: _commit_lock -> _mat_lock and _commit_lock -> _seq;
        # never taken while holding _seq.
        self._commit_lock = threading.RLock()
        # per-commit counter samples for the Chrome-trace counter lane
        # (graph_version staircase + commit_stall_us), observe-only
        self._commit_samples = collections.deque(maxlen=4096)
        # one range handoff is atomic under this lock; stop() takes it
        # before draining, so an open range always completes or rolls
        # back first and no seed is ever stranded ownerless
        self._migration_lock = threading.Lock()
        self._draining = False       # rebalance loops stop between batches
        self.replica_refresh_errors = 0  # failed background refresh passes
        self.rebalance_errors = 0        # failed background rebalance passes
        # owner-side tenant scheduling: tenant name <-> wire index (the
        # collective ships int32 indices; every host derives the same
        # registry from the sorted QoS config keys)
        tw = self.config.tenant_weights
        self._tenant_names: List[str] = sorted(tw) if tw else []
        self._tenant_index: Dict[str, int] = {
            t: i for i, t in enumerate(self._tenant_names)
        }
        # per-owner health for hedged dispatch: consecutive failures +
        # the dispatch index an ejection started at (-1 = serving);
        # flush-indexed backoff keeps the state machine replayable
        self._owner_health: Dict[int, Dict[str, int]] = {}
        # deterministic hedge log [(fid, owner, reason, target)] — append
        # order may interleave across in-flight flushes, read the sorted
        # `hedge_events()` view for replay comparison; bounded like
        # shed_log (a dead owner with no failover appends per flush)
        self.hedge_log = collections.deque(maxlen=POLICY_LOG_CAP)
        # abandoned (deadline-missed) leg threads per owner, guarded by
        # _lock: while any is still alive the owner is treated as wedged
        # and no new leg is spawned — growth is bounded by max_in_flight
        # per wedge episode, never the life of the router
        self._abandoned_legs: Dict[int, List[threading.Thread]] = {}
        self.faults = self.config.fault_injector
        self._open: Optional[_RoutedFlush] = None
        self._lock = threading.Lock()
        self._fence = threading.Condition(self._lock)
        self._seq = threading.Lock()
        self._window = threading.BoundedSemaphore(self.config.max_in_flight)
        self._inflight_flushes = 0
        # parity escape hatch (round 22): True forces the per-slot
        # resolve loop the block resolution is pinned against
        self._scalar_resolve = False
        self._threads: List[threading.Thread] = []
        self._running = False
        if mode == "collective":
            # the serve exchange's static shape: every host must agree
            self.comm.static_budget = self._budget
            for h, eng in self.engines.items():
                self.comm.register_serve_answerer(h, self._make_answerer(h))

    # -- construction ------------------------------------------------------

    @classmethod
    def build(
        cls,
        model,
        params,
        csr_topo: CSRTopo,
        feat: np.ndarray,
        sizes: Sequence[int],
        *,
        hosts: int,
        config: Optional[DistServeConfig] = None,
        global2host: Optional[np.ndarray] = None,
        sampler_seed: int = 0,
        sampler_mode: str = "TPU",
        sampler_kw: Optional[dict] = None,
        out_dim: Optional[int] = None,
        mesh=None,
        feature_kw: Optional[dict] = None,
    ) -> "DistServeEngine":
        """Partition ``csr_topo``/``feat`` by seed ownership and assemble
        the router + H shard engines in one process (the hermetic pod
        simulation). Every shard sampler is born with the SAME
        ``sampler_seed`` — each shard's key stream then matches a freshly
        born single-host sampler's, which is what lets the parity oracle
        replay any shard's dispatch log through a full-graph sampler."""
        import jax

        from ..pyg.sage_sampler import GraphSageSampler

        config = config or DistServeConfig(hosts=hosts)
        if config.hosts != hosts:
            raise ValueError(f"config.hosts={config.hosts} != hosts={hosts}")
        feat = np.asarray(feat, np.float32)
        n = csr_topo.indptr.shape[0] - 1
        if global2host is None:
            global2host = contiguous_partition(n, hosts)
        out_dim = out_dim if out_dim is not None else getattr(model, "out_dim", None)
        if out_dim is None:
            raise ValueError("pass out_dim= (model has no out_dim attribute)")
        mode = config.exchange
        if mode == "auto":
            mode = "collective" if len(jax.devices()) >= hosts else "host"
        comm = None
        feat_comms: List[object] = []
        if mode == "collective":
            if mesh is None:
                from jax.sharding import Mesh

                devs = jax.devices()
                if len(devs) < hosts:
                    raise ValueError(
                        f"exchange='collective' needs >= {hosts} devices "
                        f"(got {len(devs)}); use exchange='host'"
                    )
                mesh = Mesh(np.array(devs[:hosts]), ("serve_host",))
            comm = TpuComm(
                rank=0, world_size=hosts, hosts=hosts, mesh=mesh, axis="serve_host"
            )
        residency = config.feature_residency
        if residency not in ("closure", "exchange"):
            raise ValueError(f"unknown feature_residency {residency!r}")
        if feature_kw and residency != "exchange":
            # tiered owner features (disk/adaptive knobs) gather host-side
            # through Feature; the closure residency is a dense in-jit
            # table by construction, so the knobs would be silently dead
            raise ValueError(
                "feature_kw (tiered owner features) requires "
                "feature_residency='exchange'"
            )
        if config.streaming and residency != "closure":
            raise ValueError(
                "streaming graphs require feature_residency='closure' — "
                "closure-entering nodes install into the ClosureFeature "
                "reserve; the exchange residency's owners gather "
                "host-side (rebuild to stream them)"
            )
        # feature-exchange budget ("exchange" residency only): a shard
        # forward gathers up to the final padded n_id width of the largest
        # bucket, all of which could be remote in the worst case
        from ..ops.sample import pad_widths

        shard_cfg = config.resolved_shard_config()
        kw = dict(sampler_kw or {})
        widths = pad_widths(
            max(shard_cfg.resolved_buckets()), sizes, kw.get("caps")
        )
        feat_budget = round_up_pow2(widths[-1])
        engines: Dict[int, ServeEngine] = {}
        topo_stats: Dict[int, Dict[str, float]] = {}
        owner_masks: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        owner_streams: Dict[int, object] = {}
        owner_feats: Dict[int, ClosureFeature] = {}
        indptr_full = np.asarray(csr_topo.indptr, np.int64)
        indices_full = np.asarray(csr_topo.indices, np.int64)
        src_per_edge = np.repeat(
            np.arange(indptr_full.shape[0] - 1, dtype=np.int64),
            (indptr_full[1:] - indptr_full[:-1]),
        )
        for h in range(hosts):
            # adjacency closure: len(sizes)-1 expansion hops; FEATURE
            # closure one deeper — the last hop's leaves are gathered but
            # never expanded (shard_topology_by_owner docstring). The
            # masks are KEPT per owner: a later range handoff extends
            # them incrementally instead of re-BFS-ing the owned set.
            seed_mask = np.asarray(global2host) == h
            topo_mask, feat_mask = closure_masks(
                indptr_full, indices_full, seed_mask,
                hops=len(sizes) - 1, feat_hops=len(sizes),
                src_per_edge=src_per_edge,
            )
            topo_h, edge_stats = shard_from_mask(
                csr_topo, topo_mask, src_per_edge=src_per_edge
            )
            closure_ids = np.nonzero(feat_mask)[0]
            owner_masks[h] = (topo_mask, feat_mask)
            st = {
                "owned_nodes": int(seed_mask.sum()),
                "closure_nodes": int(topo_mask.sum()),
                "feature_closure_nodes": int(feat_mask.sum()),
                **edge_stats,
            }
            topo_stats[h] = st
            sampler = GraphSageSampler(
                topo_h, sizes=sizes, mode=sampler_mode, seed=sampler_seed, **kw
            )
            if config.streaming:
                # round 17: the owner shard becomes a streaming tile
                # layout — update_graph commits land as in-place pad-lane
                # writes + batched device tile swaps, never a reshard
                from ..stream import StreamingTiledGraph

                owner_streams[h] = StreamingTiledGraph(
                    topo_h, reserve_frac=config.stream_reserve_frac
                )
                sampler.bind_stream(owner_streams[h])
            if residency == "closure":
                # materialize the closure's rows ONCE (the rows the
                # per-flush exchange would fetch) — the owner gather is
                # then in-jit, so the shard engine serves on the FUSED
                # one-dispatch program; residency is honest: closure ==
                # owned (exactly 1/H) on k-hop-closed partitions, the halo
                # elsewhere is already reported in topo_stats
                local_map = np.full(n, -1, np.int32)
                local_map[closure_ids] = np.arange(
                    closure_ids.shape[0], dtype=np.int32
                )
                shard_feat = ClosureFeature(
                    feat[closure_ids], local_map,
                    reserve_rows=_feat_reserve(config,
                                               closure_ids.shape[0]),
                )
                owner_feats[h] = shard_feat
            else:
                owned = np.nonzero(global2host == h)[0]
                fkw = dict(feature_kw or {})
                if fkw.get("disk_path"):
                    # per-owner flat files: "{host}" in the template keeps
                    # H shards from clobbering one backing file
                    fkw["disk_path"] = fkw["disk_path"].format(host=h)
                f = Feature(rank=0, device_list=[0],
                            **{"device_cache_size": 0, **fkw})
                f.from_cpu_tensor(feat[owned])
                f.set_local_order(owned)
                if mode == "collective":
                    fcomm = TpuComm(
                        rank=h, world_size=hosts, hosts=hosts, mesh=mesh,
                        axis="serve_host",
                    )
                    fcomm.static_budget = feat_budget
                else:
                    fcomm = LoopbackComm(hosts)
                feat_comms.append(fcomm)
                info = PartitionInfo(
                    device=0, host=h, hosts=hosts, global2host=global2host
                )
                shard_feat = _ShardFeature(DistFeature(f, info, fcomm), n)
            engines[h] = ServeEngine(model, params, sampler, shard_feat, shard_cfg)
        # single-controller mode: every feature comm holds every block (a
        # real pod registers only its own — the 1/H HBM claim is about the
        # per-process resident set, which IS one block per host there)
        for h in range(hosts):
            block = np.asarray(feat[np.nonzero(global2host == h)[0]], np.float32)
            for fcomm in feat_comms:
                fcomm.register_local_table(h, block)
        dist = cls(
            engines, global2host, out_dim, config=config, comm=comm,
            shard_topo_stats=topo_stats,
        )
        # round-15 fleet policies need build-time materials: the replica
        # is rebuilt from the full graph/table on every refresh, and the
        # fallback engine IS a full-graph single-host engine (the degraded
        # path any seed can fail over to). Multi-process constructions
        # (bare __init__) have neither — they hold only their own shard.
        dist._params = params
        dist._replica_materials = {
            "model": model, "csr_topo": csr_topo, "feat": feat,
            "sizes": tuple(sizes), "sampler_mode": sampler_mode,
            "sampler_seed": sampler_seed, "sampler_kw": dict(kw),
            "shard_config": shard_cfg,
        }
        dist._owner_masks = owner_masks
        dist._src_per_edge = src_per_edge
        if config.streaming:
            from ..stream import StreamingAdjacency

            dist._stream_adj = StreamingAdjacency(csr_topo)
            dist._owner_streams = owner_streams
            dist._owner_feats = owner_feats
        if config.full_graph_fallback:
            fb_sampler = GraphSageSampler(
                csr_topo, sizes=sizes, mode=sampler_mode, seed=sampler_seed,
                **kw,
            )
            if config.streaming:
                # the degraded-mode hedge target must see deltas too — a
                # frozen fallback would serve pre-delta draws for any
                # failed-over seed
                from ..stream import StreamingTiledGraph

                fb_sampler.bind_stream(StreamingTiledGraph(
                    csr_topo, reserve_frac=config.stream_reserve_frac
                ))
            dist.fallback = ServeEngine(model, params, fb_sampler, feat,
                                        shard_cfg)
        return dist

    def _make_answerer(self, host: int):
        """The owner-side hook of the serve exchange: ids arrive
        requester-major [H, L] (-1-padded), each requester's valid lanes go
        through the owner engine's FULL local path (cache, coalescing,
        micro-batching, window), invalid lanes return zeros.
        ``recv_tenants`` (same shape, int32 indices into the sorted QoS
        registry, -1 = default) arrives when the router ships tenants —
        the owner engine then applies the submitting tenants' flush
        quotas (round 16)."""

        def answer(recv_ids: np.ndarray,
                   recv_tenants: Optional[np.ndarray] = None) -> np.ndarray:
            recv_ids = np.asarray(recv_ids)
            out = np.zeros(
                (recv_ids.shape[0], recv_ids.shape[1], self.out_dim), np.float32
            )
            for req in range(recv_ids.shape[0]):
                valid = recv_ids[req] >= 0
                if valid.any():
                    ids = recv_ids[req][valid].astype(np.int64)
                    tenants = None
                    if recv_tenants is not None:
                        tenants = [
                            self._tenant_names[t] if 0 <= t < len(
                                self._tenant_names
                            ) else DEFAULT_TENANT
                            for t in np.asarray(recv_tenants[req])[valid]
                        ]
                    out[req, valid] = np.asarray(
                        self._predict_leg(self.engines[host], ids, tenants)
                    )
            return out

        return answer

    # -- request path ------------------------------------------------------

    def submit(self, node_id: int,
               tenant: Optional[str] = None) -> ServeResult:
        """Enqueue one request: the front-end result cache answers repeats
        of already-served nodes outright (no routing, no exchange bytes),
        then the same dedup/coalesce semantics as `ServeEngine.submit`
        apply to the rest. ``tenant`` drives the round-15 per-tenant
        admission exactly as on the single-host engine (weighted flush
        quotas, deterministic queue-depth shedding, per-tenant latency).
        Round 20: `submit_many` of ONE, like `ServeEngine.submit`.
        KEEP IN LOCKSTEP with `ServeEngine.submit` — the hosts=1
        bit-parity contract depends on the two front ends making
        identical cache/coalesce decisions per request, and
        `test_shards1_bit_equal_single_host_engine` pins it."""
        return self.submit_many((node_id,), tenant=tenant)[0]

    def submit_many(self, node_ids, t=None,
                    tenant=None) -> List[ServeResult]:
        """Vectorized batch submit at the router (round 20, the
        `ServeEngine.submit_many` twin): id-range validation is VECTORIZED
        up front (the whole batch is rejected before any admission — the
        one documented batch/scalar difference), then admission runs per
        request in request order under one striped-lock hold per chunk,
        with one batched journal append and inline flush at every fill —
        so the router's dispatch log is bit-identical to N scalar
        ``submit`` calls."""
        if t is not None:
            raise TypeError(
                "t= is a temporal-serving argument (TemporalDistServeEngine);"
                " this router serves untimed nodes"
            )
        ids = np.asarray(node_ids, dtype=np.int64).reshape(-1)
        n_ids = self.global2host.shape[0]
        bad = (ids < 0) | (ids >= n_ids)
        if bad.any():
            raise ValueError(
                f"node id {int(ids[bad][0])} outside [0, {n_ids})"
            )
        keys = ids.tolist()
        return self._submit_keyed_many(keys, keys, tenant, uniq_arr=ids)

    def _submit_keyed_many(self, keys: List, nodes: List[int],
                           tenant, uniq_arr=None) -> ResultBatch:
        """KEEP IN LOCKSTEP with `ServeEngine._submit_keyed_many` (the
        router has no submit-time prefetch leg; its per-owner prefetch
        runs at seal off the routed split) — including the round-22
        whole-batch vectorized admission gate: `_admit_batch_vector`
        stripes per owner through ``pend.stripe_of`` exactly as the
        scalar inserts would."""
        n = len(keys)
        if n and uniq_arr is not None and self._vector_admissible(tenant):
            pre = _batch_uniq(uniq_arr)
            if pre is not None:
                ten = DEFAULT_TENANT if tenant is None else str(tenant)
                now = self._clock()
                with self._pending.all_locks():
                    rb = _admit_batch_vector(self, keys, ten, now, *pre)
                if rb is not None:
                    return rb
        tenants = resolve_tenants(tenant, n)
        results: List[Optional[ServeResult]] = [None] * n
        max_batch = self.config.max_batch
        jr = self.journal
        i = 0
        while i < n:
            events: List[Tuple] = []
            need_flush = False
            now = self._clock()
            with self._pending.all_locks():
                if (self.workload is None
                        and self.config.max_queue_depth == 0):
                    # round-20 vectorized chunk admission, shared with
                    # the single-host engine (`_admit_chunk_fast`):
                    # the router's per-owner stripes and late-admission
                    # window behave identically under it
                    i, need_flush = _admit_chunk_fast(
                        self, keys, nodes, tenants, i, now, events,
                        results,
                    )
                while i < n and not need_flush:
                    res = self._admit_one_locked(
                        keys[i], nodes[i], tenants[i], now, events
                    )
                    results[i] = res
                    i += 1
                    if (res._slot is not None
                            and len(self._pending) >= max_batch):
                        need_flush = True
            jr.record_many(events)
            if need_flush:
                self.flush()
        return ResultBatch(items=results)

    # the engine-shape gates are identical on both front ends (the
    # router's extra state — owner split, exchange — only matters after
    # assembly, never at admission)
    _vector_admissible = ServeEngine._vector_admissible

    def _submit_keyed(self, key, node: int,
                      tenant: Optional[str]) -> ServeResult:
        """The router's single-key submit body (`ServeEngine._submit_keyed`'s
        dist twin, one stripe lock = one owner's queue): ``key`` is the
        coalescing/cache identity — the plain node id here, ``(node,
        t_bucket)`` on the round-19 temporal router — and ``node`` what
        telemetry/journal/shed entries carry."""
        tenant = DEFAULT_TENANT if tenant is None else str(tenant)
        now = self._clock()
        events: List[Tuple] = []
        with self._pending.lock_for(key):
            res = self._admit_one_locked(key, node, tenant, now, events)
            need_flush = (res._slot is not None
                          and len(self._pending) >= self.config.max_batch)
        self.journal.record_many(events)
        if need_flush:
            self.flush()
        return res

    def _admit_one_locked(self, key, node: int, tenant: str, now: float,
                          events: List[Tuple]) -> ServeResult:
        """KEEP IN LOCKSTEP with `ServeEngine._admit_one_locked` — same
        cache/coalesce/shed/late-admit decision sequence, router-flavored
        shed message. Caller holds ``key``'s stripe lock (or all of
        them); ``_lock`` is taken only for the rid/late-admission
        window."""
        self.stats.requests += 1
        wl = self.workload
        if wl is not None:
            wl.observe_seed(node)  # observe-only frequency tap
        cached = self.cache.get(key, self.params_version)
        if cached is not None:
            ms = (self._clock() - now) * 1e3
            self.stats.latency.record_ms(ms)
            self.stats.tenant_hist(tenant).record_ms(ms)
            events.append(("cache_hit", -1, -1, node, 0))
            return ServeResult(value=cached)
        slot = self._pending.get(key) or self._inflight.get(key)
        if slot is not None and slot.version == self.params_version:
            self.stats.coalesced += 1
            events.append(("coalesce", slot.rid, -1, node, 0))
        else:
            if shed_decision(
                len(self._pending), self._pending.tenant_count(tenant),
                tenant, self.config.max_queue_depth,
                self.config.tenant_weights,
            ):
                self.stats.shed += 1
                self.shed_log.append((self.stats.requests, tenant, node))
                events.append(("shed", -1, -1, node, 0))
                return ServeResult(error=ShedError(
                    f"router queue depth {len(self._pending)} >= "
                    f"{self.config.max_queue_depth} and tenant "
                    f"{tenant!r} is at its weighted quota"
                ))
            admitted_late = False
            with self._lock:
                rid = -1
                if self.journal.enabled:
                    rid = self._next_rid
                    self._next_rid += 1
                slot = _Slot(key, self.params_version, now, rid=rid,
                             tenant=tenant)
                fl = self._open
                if fl is not None and len(fl.keys) < fl.bucket:
                    # late admission into the routed flush still waiting
                    # for its window slot (owner split happens at seal)
                    fl.keys.append(key)
                    fl.slots.append(slot)
                    self._inflight[key] = slot
                    self.stats.late_admitted += 1
                    events.append(("late_admit", rid, fl.fid, node, 0))
                    admitted_late = True
            if not admitted_late:
                self._pending.insert_unlocked(key, slot, tenant)
                events.append(("submit", rid, -1, node, 0))
        slot.waiters.append((now, tenant))
        return ServeResult(slot=slot)

    def predict(self, node_ids, timeout: Optional[float] = None,
                tenants: Optional[Sequence[str]] = None) -> np.ndarray:
        ids = np.asarray(node_ids).reshape(-1)
        if tenants is not None and len(tenants) != ids.shape[0]:
            raise ValueError(
                f"tenants has {len(tenants)} entries for {ids.shape[0]} ids"
            )
        handles = self.submit_many(ids, tenant=tenants)
        if not handles:
            return np.zeros((0, self.out_dim), np.float32)
        if not self._running:
            while not handles.done() and self._drainable():
                self.flush()
        return self.results_many(handles, timeout)

    # batch consumption surface (round 22), identical on both front ends:
    # a ResultBatch gathers per unique slot + one inverse-map expansion,
    # anything else degrades to the per-handle result() stack
    results_many = ServeEngine.results_many

    # -- flush policy ------------------------------------------------------

    def should_flush(self) -> bool:
        # lock-free probe, mirroring ServeEngine.should_flush (round 20)
        if not self._pending:
            return False
        if len(self._pending) >= self.config.max_batch:
            return True
        oldest = self._pending.oldest_enqueue_t()
        if oldest is None:
            return False
        return (self._clock() - oldest) * 1e3 >= self.config.max_delay_ms

    def pump(self) -> int:
        return self.flush() if self.should_flush() else 0

    # -- the three router stages ------------------------------------------

    def _assemble(self) -> Optional[_RoutedFlush]:
        """Drain + publish (mirrors `ServeEngine._assemble`): the owner
        split waits for `_seal_assembled` so late-admitted seeds route with
        their flush. Lock order (round 20): every stripe lock, THEN
        ``_lock`` — same hierarchy as `ServeEngine._assemble`."""
        with self._pending.all_locks(), self._lock:
            if not self._pending:
                return None
            keys = weighted_drain_keys(
                self._pending.ordered_dict_unlocked(),
                self.config.max_batch, self.config.tenant_weights,
            )
            slots = [self._pending.pop_unlocked(k) for k in keys]
            self._inflight.update(zip(keys, slots))
            fl = _RoutedFlush(keys, slots, [])
            fl.bucket = self.config.max_batch
            self._inflight_flushes += 1
            self.stats.inflight_peak = max(
                self.stats.inflight_peak, self._inflight_flushes
            )
            # caller holds _seq: the index _seal_assembled will draw. The
            # fid is stamped UNCONDITIONALLY since round 15 — the fault
            # injector and the ejection state machine key off it, not
            # just the journal
            fl.fid = self._flush_index + 1
            jr = self.journal
            if jr.enabled:
                # a = the NODE id per the EVENT_KINDS contract (a
                # temporal key is a (node, t_bucket) tuple); one batched
                # ring append for the whole drain (round 20)
                jr.record_many([
                    ("assemble", slot.rid, fl.fid,
                     k[0] if isinstance(k, tuple) else k, 0)
                    for k, slot in zip(keys, slots)
                ])
                jr.emit("flush", -1, fl.fid, len(keys), fl.bucket)
            if self.config.late_admission and len(keys) < fl.bucket:
                self._open = fl
        return fl

    def _seal_assembled(self, fl: _RoutedFlush) -> None:
        with self._lock:
            self._open = None
        self._flush_index += 1
        if self.workload is not None:
            # decay tick on the router's dispatch index (caller holds
            # _seq) — replay-deterministic, never wall time
            self.workload.tick()
        self.journal.emit("seal", -1, fl.fid, len(fl.keys), fl.bucket)
        # epoch pin (round 24): the router version this flush seals
        # against. Zero-stall commits flip graph_version under _seq (the
        # lock the caller holds here), so the stamp and the routing it
        # governs belong to ONE epoch, never a mix.
        fl.graph_version = self.graph_version
        try:
            arr = np.asarray(fl.keys, np.int64)
            fl.tenants = [s.tenant for s in fl.slots]
            fl.ids = arr
            fl.rids = np.fromiter(
                (s.rid for s in fl.slots), np.int64, len(fl.slots)
            )
            tix = self._tenant_index
            fl.tenant_ix = np.fromiter(
                (tix.get(t, -1) for t in fl.tenants), np.int32, len(fl.tenants)
            )
            owners = self.global2host[arr].astype(np.int64)
            rep = self.replica  # swapped only under the fence: stable here
            if rep is not None and rep.ids.size:
                # hot-set replication: replicated seeds re-route to the
                # LOCAL replica pseudo-owner — they never enter the serve
                # exchange (the whole point of the replica)
                owners = np.where(np.isin(arr, rep.ids), REPLICA_HOST,
                                  owners)
            # ONE owner partition via stable argsort (round 20), replacing
            # the per-host nonzero scan: ascending owner groups put the
            # REPLICA_HOST (-2) leg first and hosts in ascending order,
            # positions ascending within each group — exactly the split
            # the old loop built, at O(n log n) instead of O(n·hosts)
            if arr.size:
                order = np.argsort(owners, kind="stable")
                so = owners[order]
                cuts = np.nonzero(np.diff(so))[0] + 1
                for pos in np.split(order, cuts):
                    h = int(owners[pos[0]])
                    if h == REPLICA_HOST or 0 <= h < self.hosts:
                        fl.split.append((h, arr[pos], pos))
            if self.config.record_dispatches:
                self.dispatch_log.append(
                    (arr.copy(), [(h, ids.copy()) for h, ids, _ in fl.split])
                )
                self.dispatch_graph_versions.append(fl.graph_version)
            if self.config.tier_prefetch:
                # round-18: flush-ahead prefetch PER OWNER off the routed
                # sub-batches — one window earlier than each owner's own
                # assemble-time prefetch (their buffers dedup the
                # overlap). Observe-only: a failing issue never fails the
                # routed flush, and no owner key is consumed.
                for h, ids, _ in fl.split:
                    eng = self.engines.get(h)
                    if eng is None:  # replica / retired host
                        continue
                    try:
                        eng.prefetch_seeds(ids, fid=fl.fid)
                    except Exception:
                        pass
        except BaseException as exc:
            fl.error = exc

    def _dispatch(self, fl: _RoutedFlush) -> Optional[np.ndarray]:
        """Forward the per-owner sub-batches and re-interleave the answers
        into flush-key order. Collective mode ships ids/logits over the
        mesh; host mode calls the owner engines directly — per-owner legs
        there carry the round-15 fault-injection hook, the
        ``hedge_deadline_ms`` deadline, and the failover re-route, and an
        owner failure lands in ``fl.slot_errors`` (that sub-batch's slots
        only), never in ``fl.error``. Replica legs (host `REPLICA_HOST`)
        are answered locally in BOTH modes and never touch the
        exchange.

        Round 23: host-mode legs (replica included) FAN OUT onto
        per-flush worker threads and join in split order, so a routed
        flush's wall is max(leg latencies) + merge instead of their sum
        — `sequential_legs=True` keeps the sequential pass as the
        bit-parity twin, and a single-leg flush short-circuits to it
        (one leg has nothing to overlap, so no thread is spawned).
        Collective mode stays one launch either way."""
        # a = bucket per the EVENT_KINDS vocabulary; the router's "bucket"
        # is its admission cap (it pads nothing)
        self.journal.emit("dispatch", -1, fl.fid, fl.bucket)
        wl = self.workload
        out = np.zeros((len(fl.keys), self.out_dim), np.float32)
        owner_split = []
        replica_split = []
        for h, ids, pos in fl.split:
            if h == REPLICA_HOST:
                replica_split.append((h, ids, pos))
            else:
                owner_split.append((h, ids, pos))
        if self.exchange_mode == "collective":
            for _h, ids, pos in replica_split:
                self._replica_leg(fl, ids, pos, out)
            by_host = {h: (ids, pos) for h, ids, pos in owner_split}
            if by_host:  # an all-replica flush skips the collective whole
                host2ids = [
                    by_host[h][0] if h in by_host else np.array([], np.int64)
                    for h in range(self.hosts)
                ]
                host2tenants = None
                if self._tenant_names and fl.tenants:
                    # owner-side QoS: ship each sub-batch's submitting
                    # tenants as int32 registry indices beside the ids
                    # (no QoS config = no second collective — the round-15
                    # wire byte for byte)
                    host2tenants = [
                        (
                            [self._tenant_index.get(fl.tenants[int(p)], -1)
                             for p in by_host[h][1]]
                            if h in by_host else []
                        )
                        for h in range(self.hosts)
                    ]
                t_x0 = self._clock() if wl is not None else 0.0
                try:
                    res = self.comm.exchange_serve(
                        host2ids, out_dim=self.out_dim, budget=self._budget,
                        host2tenants=host2tenants,
                    )
                except comm_mod.OwnerAnswerError as exc:
                    # the collective is one launch: it cannot fail
                    # per-owner, but the failure IS attributable — feed
                    # the health/ejection state before the whole-flush
                    # error propagates
                    self._owner_failed(exc.host, fl.fid)
                    raise
                if wl is not None:
                    # one exchange round-trip covers every owner: its
                    # duration is each participating owner's flush latency
                    # at the router grain (per-owner separation needs host
                    # mode or the owners' own monitors)
                    dt = self._clock() - t_x0
                    for h, ids, _ in owner_split:
                        wl.observe_flush(h, len(ids), dt)
                L = self._budget
                with self._lock:
                    self.stats.exchange_id_bytes += (
                        self.hosts * self.hosts * L * 4
                    )
                    self.stats.exchange_logit_bytes += (
                        self.hosts * self.hosts * L * self.out_dim * 4
                    )
                for h, (ids, pos) in by_host.items():
                    out[pos] = res[h]
                # a successful exchange is a successful leg for every
                # participating owner: reset their failure counts, so
                # `fails` stays CONSECUTIVE (not cumulative over days)
                # and a past ejection never latches in collective mode
                for h, _, _ in owner_split:
                    self._owner_ok(h)
        elif self.config.sequential_legs or len(fl.split) <= 1:
            for _h, ids, pos in replica_split:
                self._replica_leg(fl, ids, pos, out)
            for h, ids, pos in owner_split:
                self._owner_leg(fl, h, ids, pos, out)
        else:
            self._fanout_legs(fl, replica_split + owner_split, out)
        out.setflags(write=False)
        # one routed round-trip = one "execute" at the router grain
        self.journal.emit("execute_done", -1, fl.fid, len(fl.split))
        return out

    # -- round-15 dispatch legs: replica, hedged owner, failover -----------

    def _leg_tenants(self, fl: _RoutedFlush, pos) -> Optional[List[str]]:
        """The submitting tenants of a sub-batch's positions — forwarded
        to the serving engine so owner-side quotas see the real tenants
        (round 16). None when no QoS is configured (tenants then change
        nothing downstream — and the legs keep calling bare
        ``predict(ids)``, byte-compatible with round-15 callables and
        test doubles)."""
        if not self.config.tenant_weights or not fl.tenants:
            return None
        return [fl.tenants[int(p)] for p in pos]

    @staticmethod
    def _predict_leg(engine, ids, tenants: Optional[List[str]]):
        if tenants is None:
            return engine.predict(ids)
        return engine.predict(ids, tenants=tenants)

    def _replica_leg(self, fl: _RoutedFlush, ids, pos, out) -> None:
        """Serve a replicated sub-batch from the LOCAL hot-set replica —
        no routing, no exchange bytes. A (should-be-impossible) local
        failure takes the same failover path as an owner failure."""
        wl = self.workload
        t0 = self._clock()
        try:
            rows = np.asarray(
                self._predict_leg(self.replica.engine, ids,
                                  self._leg_tenants(fl, pos))
            )
        except BaseException as exc:
            self._failover(fl, REPLICA_HOST, ids, pos, out, "error", exc)
            self.journal.emit("leg_done", -1, fl.fid, REPLICA_HOST,
                              len(ids))
            return
        if wl is not None:
            wl.observe_flush(REPLICA_HOST, len(ids), self._clock() - t0)
        out[pos] = rows
        with self._lock:
            self.stats.replica_hits += len(ids)
        self.journal.emit("leg_done", -1, fl.fid, REPLICA_HOST, len(ids))

    def _owner_leg(self, fl: _RoutedFlush, h: int, ids, pos, out) -> None:
        """One host-mode owner sub-batch: fault-injection hook, optional
        per-owner deadline, failover on timeout/error/ejection. Success
        resets the owner's health; failure feeds the ejection state
        machine (flush-indexed backoff — deterministic under replay)."""
        wl = self.workload
        deadline_s = self.config.hedge_deadline_ms / 1e3
        # honoring an ejection only makes sense when someone else can
        # serve the sub-batch: with no failover target, skipping the
        # owner would CONVERT its traffic into guaranteed errors for the
        # whole backoff window — attempt it instead
        ejected = (self._has_failover(h, ids)
                   and self._owner_ejected(h, fl.fid))
        rows, err, timed_out = None, None, False
        if not ejected:
            t0 = self._clock()
            try:
                if deadline_s > 0:
                    # the fault hook runs INSIDE the supervised leg so a
                    # stalled owner is indistinguishable from a slow one
                    # — exactly what the deadline exists to catch
                    rows, timed_out = self._call_with_deadline(
                        h, ids, deadline_s, fl.fid,
                        tenants=self._leg_tenants(fl, pos),
                    )
                    if timed_out:
                        err = OwnerTimeout(
                            f"owner {h} missed the "
                            f"{self.config.hedge_deadline_ms} ms hedge "
                            f"deadline at dispatch index {fl.fid}"
                        )
                else:
                    if self.faults is not None:
                        self.faults.check(h, fl.fid)
                    rows = np.asarray(
                        self._predict_leg(self.engines[h], ids,
                                          self._leg_tenants(fl, pos))
                    )
            except BaseException as exc:
                err = exc
            if wl is not None:
                # each leg individually timed — TRUE per-owner straggler
                # evidence (the fan-out path times INSIDE the leg body
                # for the same reason, so the evidence survives
                # concurrency — round 23). A timed-out leg is CENSORED
                # at the deadline (the owner did NOT answer in the
                # measured wall; the wedged-owner fast path would
                # otherwise record ~0 ms and rank the slowest owner
                # fastest)
                dt = self._clock() - t0
                if timed_out:
                    dt = max(dt, deadline_s)
                wl.observe_flush(h, len(ids), dt)
        if rows is not None and err is None:
            self._owner_ok(h)
            out[pos] = rows
            self.journal.emit("leg_done", -1, fl.fid, h, len(ids))
            return
        if not ejected:
            self._owner_failed(h, fl.fid)
        reason = ("ejected" if ejected
                  else "timeout" if timed_out else "error")
        self._failover(fl, h, ids, pos, out, reason, err)
        self.journal.emit("leg_done", -1, fl.fid, h, len(ids))

    def _call_with_deadline(self, h: int, ids, deadline_s: float,
                            fid: int, tenants: Optional[List[str]] = None):
        """Run an owner leg (fault hook included) on a worker thread
        with a deadline. On timeout the worker is ABANDONED (its eventual
        answer lands in a local box nobody reads — never the flush's
        output) and the caller hedges; an in-leg exception re-raises
        here. While ANY abandoned leg to an owner is still alive, further
        legs to it time out immediately instead of stacking more blocked
        threads — at most ``max_in_flight`` concurrent checks can slip
        through per wedge episode, so thread growth is bounded."""
        with self._lock:
            legs = self._abandoned_legs.get(h, [])
            legs[:] = [t for t in legs if t.is_alive()]
            if legs:
                return None, True  # owner still wedged from earlier legs
        box: Dict[str, object] = {}
        engine = self.engines[h]

        def run():
            try:
                if self.faults is not None:
                    self.faults.check(h, fid)
                box["rows"] = np.asarray(
                    self._predict_leg(engine, ids, tenants)
                )
            except BaseException as exc:  # delivered to the caller below
                box["err"] = exc

        th = threading.Thread(target=run, daemon=True,
                              name="quiver-hedged-owner-leg")
        th.start()
        th.join(deadline_s)
        if th.is_alive():
            with self._lock:
                self._abandoned_legs.setdefault(h, []).append(th)
            return None, True
        if "err" in box:
            raise box["err"]
        return box["rows"], False

    # -- round-23 concurrent fan-out: max(legs) + merge --------------------

    def _fanout_legs(self, fl: _RoutedFlush, split, out) -> None:
        """Run host-mode dispatch legs CONCURRENTLY and join them in
        split order, so a routed flush's wall is max(leg latencies) +
        merge instead of the sequential pass's sum — owner ``predict``
        blocks in XLA with the GIL released (and the fault hook's stall
        sleeps release it too), so the overlap is real even on one
        core.

        Determinism contract (the bit-parity twin is
        ``sequential_legs=True``; docs/api.md "Concurrent owner
        fan-out" tabulates it): leg workers fill ONLY their private
        `_LegRun.box`, and the joiner applies every side effect in
        fl.split order — replica leg first, owners ascending, exactly
        the sequential order: workload `observe_flush` sample (the
        leg's own internal duration, censored at the deadline),
        health/ejection transition, ``out[pos]`` rows, failover
        re-route (failover predicts are thereby serialized in
        deterministic order on the joining thread — one key stream on
        the fallback/replica engines), hedge log + stats, journal tail.
        So logits, dispatch logs, `hedge_events()`, owner health, and
        the journal are bit-identical to the sequential pass.

        A ``hedge_deadline_ms`` deadline becomes a BOUNDED JOIN on the
        leg's thread (`_call_with_deadline` folded into the fan-out):
        timeout abandons the worker into ``_abandoned_legs`` and
        hedges; while any abandoned leg to an owner is alive, further
        legs to it are born timed out instead of spawning — the
        wedged-owner fast path, decided HERE in split order before any
        leg starts. The ejection honor decision is prechecked the same
        way; both are bit-equivalent to the sequential pass deciding at
        leg start because each owner appears at most once per split, so
        no leg's health transition can change another leg's decision
        within one flush."""
        deadline_s = self.config.hedge_deadline_ms / 1e3
        runs = []
        for h, ids, pos in split:
            r = _LegRun(h, ids, pos, self._leg_tenants(fl, pos))
            if h != REPLICA_HOST:
                r.ejected = (self._has_failover(h, ids)
                             and self._owner_ejected(h, fl.fid))
                if not r.ejected and deadline_s > 0:
                    with self._lock:
                        legs = self._abandoned_legs.get(h, [])
                        legs[:] = [t for t in legs if t.is_alive()]
                        if legs:
                            r.box["wedged"] = True
            runs.append(r)
        cap = (self.config.leg_fanout if self.config.leg_fanout > 0
               else len(runs))

        def start_leg(r: _LegRun) -> bool:
            if r.ejected or r.box:  # ejected / wedged: never spawns
                return False
            r.t_start = self._clock()
            r.thread = threading.Thread(
                target=self._leg_body, args=(fl, r), daemon=True,
                name=f"quiver-owner-leg-{r.h}",
            )
            r.thread.start()
            return True

        for r in _bounded_leg_schedule(runs, cap, start_leg):
            self._join_leg(fl, r, deadline_s, out)

    def _leg_body(self, fl: _RoutedFlush, r: _LegRun) -> None:
        """A fan-out leg's WORKER half: fault hook + predict into the
        leg's private box. Deliberately effect-free — no stats, no
        journal, no ``out`` writes — so an abandoned (timed-out) worker
        finishing late touches nothing the joiner already settled (the
        `_call_with_deadline` abandonment contract, kept)."""
        box = r.box
        t0 = self._clock()
        try:
            engine = (self.replica.engine if r.h == REPLICA_HOST
                      else self.engines[r.h])
            if r.h != REPLICA_HOST and self.faults is not None:
                # the fault hook fires INSIDE the leg at the same
                # (owner, dispatch-index) point as the sequential pass
                self.faults.check(r.h, fl.fid)
            box["rows"] = np.asarray(
                self._predict_leg(engine, r.ids, r.tenants)
            )
        except BaseException as exc:
            box["err"] = exc
        finally:
            # leg-INTERNAL duration: true per-owner straggler evidence
            # even though legs overlap (the round-23 fix for the
            # sequential-timing caveat `_owner_leg` documents)
            box["dt"] = self._clock() - t0

    def _join_leg(self, fl: _RoutedFlush, r: _LegRun, deadline_s: float,
                  out) -> None:
        """A fan-out leg's JOINER half, run in split order on the
        flushing thread: bounded join (the hedge deadline), then apply
        the leg's side effects exactly as the sequential pass would."""
        wl = self.workload
        h, ids, pos, box = r.h, r.ids, r.pos, r.box
        if h == REPLICA_HOST:
            r.thread.join()
            err = box.get("err")
            if err is not None:
                self._failover(fl, REPLICA_HOST, ids, pos, out, "error",
                               err)
            else:
                if wl is not None:
                    wl.observe_flush(REPLICA_HOST, len(ids), box["dt"])
                out[pos] = box["rows"]
                with self._lock:
                    self.stats.replica_hits += len(ids)
            self.journal.emit("leg_done", -1, fl.fid, h, len(ids))
            return
        rows, err, timed_out = None, None, False
        if not r.ejected:
            if r.thread is not None:
                if deadline_s > 0:
                    r.thread.join(
                        max(r.t_start + deadline_s - self._clock(), 0.0)
                    )
                    if r.thread.is_alive():
                        with self._lock:
                            self._abandoned_legs.setdefault(
                                h, []).append(r.thread)
                        timed_out = True
                else:
                    r.thread.join()
            if box.get("wedged"):
                timed_out = True
            if not timed_out:
                if "err" in box:
                    err = box["err"]
                else:
                    rows = box.get("rows")
            if timed_out:
                err = OwnerTimeout(
                    f"owner {h} missed the "
                    f"{self.config.hedge_deadline_ms} ms hedge "
                    f"deadline at dispatch index {fl.fid}"
                )
            if wl is not None:
                # the leg's OWN duration (never the join wait), censored
                # at the deadline when it missed it — a wedged leg never
                # ran, so it records the deadline, like the sequential
                # fast path
                if "dt" in box:
                    dt = box["dt"]
                elif r.thread is not None:
                    dt = self._clock() - r.t_start
                else:
                    dt = 0.0
                if timed_out:
                    dt = max(dt, deadline_s)
                wl.observe_flush(h, len(ids), dt)
        if rows is not None and err is None:
            self._owner_ok(h)
            out[pos] = rows
            self.journal.emit("leg_done", -1, fl.fid, h, len(ids))
            return
        if not r.ejected:
            self._owner_failed(h, fl.fid)
        reason = ("ejected" if r.ejected
                  else "timeout" if timed_out else "error")
        self._failover(fl, h, ids, pos, out, reason, err)
        self.journal.emit("leg_done", -1, fl.fid, h, len(ids))

    def _pick_failover(self, h: int, ids
                       ) -> Tuple[Optional[ServeEngine], str]:
        """THE failover target-selection rule, used by both the ejection
        honor decision and the re-route itself (one copy — if they
        disagreed, an ejected owner could be skipped with no target and
        its sub-batch error needlessly): the full-graph fallback serves
        anything; the replica only sub-batches fully inside the hot
        set."""
        if self.fallback is not None:
            return self.fallback, "fallback"
        rep = self.replica
        if (rep is not None and h != REPLICA_HOST
                and all(int(x) in rep.id_set for x in ids)):
            return rep.engine, "replica"
        return None, ""

    def _has_failover(self, h: int, ids) -> bool:
        return self._pick_failover(h, ids)[0] is not None

    def _failover(self, fl: _RoutedFlush, h: int, ids, pos, out,
                  reason: str, err: Optional[BaseException]) -> None:
        """Re-route a failed sub-batch: the full-graph fallback serves
        anything; the replica serves sub-batches fully inside the hot
        set. No (working) target -> the sub-batch's OWN slots resolve
        with the error (per-request isolation — the flush, the engine,
        and every other sub-batch keep serving). Every decision lands in
        the hedge log keyed by the dispatch index."""
        target, tname = self._pick_failover(h, ids)
        if target is not None:
            try:
                rows = np.asarray(
                    self._predict_leg(target, ids,
                                      self._leg_tenants(fl, pos))
                )
                out[pos] = rows
                with self._lock:
                    self.stats.hedges += 1
                    self.stats.hedged_seeds += len(ids)
                    if reason == "timeout":
                        self.stats.hedge_timeouts += 1
                    elif reason == "ejected":
                        self.stats.hedge_ejected += 1
                    else:
                        self.stats.hedge_errors += 1
                self.hedge_log.append((fl.fid, int(h), reason, tname))
                self.journal.emit("hedge", -1, fl.fid, h)
                return
            except BaseException as exc:
                err = exc
        with self._lock:
            self.stats.hedge_failed += 1
        self.hedge_log.append((fl.fid, int(h), reason, "none"))
        final = err if err is not None else RuntimeError(
            f"owner {h} unavailable ({reason}) and no failover target"
        )
        for p in pos:
            fl.slot_errors[int(p)] = final

    # -- owner health / ejection state (flush-indexed, replay-stable) ------

    def _owner_ejected(self, h: int, fid: int) -> bool:
        with self._lock:
            st = self._owner_health.get(h)
            if st is None or st["ejected_at"] < 0:
                return False
            if fid >= st["ejected_at"] + self.config.eject_backoff_flushes:
                st["ejected_at"] = -1  # backoff expired: half-open probe
                return False
            return True

    def _owner_failed(self, h: int, fid: int) -> None:
        with self._lock:
            st = self._owner_health.setdefault(
                h, {"fails": 0, "ejected_at": -1}
            )
            st["fails"] += 1
            if st["fails"] >= self.config.eject_after and st["ejected_at"] < 0:
                st["ejected_at"] = fid
                self.stats.owner_ejections += 1
                self.journal.emit("eject", -1, fid, h)

    def _owner_ok(self, h: int) -> None:
        with self._lock:
            st = self._owner_health.get(h)
            if st is not None:
                st["fails"] = 0
                st["ejected_at"] = -1

    def owner_health(self) -> Dict[int, Dict[str, int]]:
        """Per-owner hedging health snapshot: consecutive ``fails`` and
        ``ejected_at`` (the dispatch index an ejection started at; -1 =
        serving)."""
        with self._lock:
            return {h: dict(st)
                    for h, st in sorted(self._owner_health.items())}

    def hedge_events(self) -> List[Tuple[int, int, str, str]]:
        """The hedge log sorted by (dispatch index, owner, reason,
        target) — the deterministic replay view (append order may
        interleave across concurrent in-flight flushes)."""
        return sorted(self.hedge_log)

    def _resolve(self, fl: _RoutedFlush, rows: Optional[np.ndarray]) -> None:
        """Per-request error isolation (round 15): a slot resolves with
        ITS error — ``fl.error`` (whole-flush: assemble/collective
        failure) or its position's ``fl.slot_errors`` entry (its owner
        sub-batch failed with no failover) — and every other slot
        resolves normally. An errored slot is never cached."""
        with self._lock:
            now = t_res0 = self._clock()
            slots = fl.slots
            if (fl.error is None and not fl.slot_errors and slots
                    and not slots[0].resolved
                    and slots[0].version == self.params_version
                    and not self._scalar_resolve):
                # round-22 block resolution, shared with ServeEngine —
                # the extra dist gate is ``slot_errors``: any per-owner
                # sub-batch failure sends the flush down the per-slot
                # loop that knows how to split error from value rows
                _resolve_block(self, fl, rows, now)
            else:
                for i, (k, slot) in enumerate(zip(fl.keys, fl.slots)):
                    self._inflight.pop(k, None)
                    if slot.resolved:
                        # abandoned by a bounded stop() drain (resolve-
                        # once rule — see ServeEngine._resolve)
                        continue
                    err = fl.error or fl.slot_errors.get(i)
                    if err is None:
                        if slot.version == self.params_version:
                            self.cache.put(k, slot.version, rows[i],
                                           gv=fl.graph_version)
                        slot.resolve(rows[i])
                    else:
                        slot.resolve(None, error=err)
                        self.stats.request_errors += 1
                    for t0, tenant in slot.waiters:
                        ms = (now - t0) * 1e3
                        self.stats.latency.record_ms(ms)
                        self.stats.tenant_hist(tenant).record_ms(ms)
            if fl.error is None:
                self.stats.router_dispatches += 1
                self.stats.routed_seeds += len(fl.keys)
                for h, ids, _ in fl.split:
                    self.stats.sub_batches[h] = self.stats.sub_batches.get(h, 0) + 1
                    self.stats.sub_batch_seeds[h] = (
                        self.stats.sub_batch_seeds.get(h, 0) + len(ids)
                    )
            self._inflight_flushes -= 1
            self._fence.notify_all()
            self.stats.spans.record("resolve", t_res0, self._clock())
            self.journal.record_many((("resolve", -1, fl.fid,
                                       len(fl.keys), 0),))

    def flush(self) -> int:
        """Route up to ``max_batch`` pending unique seeds NOW. Synchronous
        on the calling thread; up to ``max_in_flight`` concurrent callers
        overlap (the router's assemble/split is serialized in dispatch
        order under ``_seq``, so the router log — and through it every
        shard's key stream — stays deterministic). As in
        `ServeEngine.flush`, the window permit is taken under ``_seq``
        AFTER the drain, so seeds arriving while this flush waits for a
        slot join it (late admission) before the owner split is sealed.

        ERROR CONTRACT (round 15): an owner sub-batch failure in host
        mode is PER-REQUEST — it resolves only that sub-batch's slots
        with the exception (after failover was tried) and `flush` returns
        normally; only whole-flush infrastructure failures (assemble/seal
        errors, a collective-exchange abort) re-raise here."""
        fl = None
        have_permit = False
        try:
            with self._seq:
                t0 = self._clock()
                fl = self._assemble()
                if fl is not None:
                    self.stats.spans.record("assemble", t0, self._clock())
                if fl is None:
                    return 0
                try:
                    jr = self.journal
                    t_w0 = self._clock() if jr.enabled else 0.0
                    self._window.acquire()
                    have_permit = True
                    if jr.enabled:
                        jr.emit("window_wait", -1, fl.fid,
                                self._clock() - t_w0)
                    t0 = self._clock()
                    self._seal_assembled(fl)
                    self.stats.spans.record("assemble", t0, self._clock())
                finally:
                    # _seal_assembled's first act already closed admission
                    # (it MUST happen under _lock before the key draw);
                    # this repeat only covers an interrupt landing between
                    # the window acquire and the seal
                    with self._lock:
                        self._open = None
            rows = None
            if fl.error is None:
                t0 = self._clock()
                try:
                    rows = self._dispatch(fl)
                except BaseException as exc:
                    fl.error = exc
                self.stats.spans.record("dispatch", t0, self._clock())
            self._resolve(fl, rows)
            if fl.error is not None:
                raise fl.error
            return len(fl.keys)
        finally:
            if have_permit:
                self._window.release()

    def _drainable(self) -> bool:
        return bool(self._pending)

    # -- weight updates / warmup / lifecycle -------------------------------

    def update_params(self, params) -> None:
        """Fence the ROUTER (no routed flush in the air), then fence every
        shard engine through its own `update_params` — so no served logit
        anywhere crosses the weight update, and every shard's embedding
        cache is invalidated together. Lock order (round 20): stripes
        before ``_lock``, same hierarchy as `ServeEngine.update_params` —
        the fence wait releases only ``_lock`` while the stripe locks
        stay held, so submits park at stripe acquire and resolves (which
        need only ``_lock``) drain freely."""
        with self._seq:
            with self._pending.all_locks():
                with self._fence:
                    while self._inflight_flushes:
                        self._fence.wait()
                    for eng in self.engines.values():
                        eng.update_params(params)
                    # the hot-set replica and the full-graph fallback
                    # serve under the same weights as the owners — same
                    # fence
                    if self.replica is not None:
                        self.replica.engine.update_params(params)
                    if self.fallback is not None:
                        self.fallback.update_params(params)
                    self._params = params
                    self.params_version += 1
                    self.cache.invalidate()
                    for slot in self._pending.values_unlocked():
                        slot.version = self.params_version

    # -- round-17 streaming graphs (ROADMAP item 1) -------------------------

    def stage_edges(self, src, dst) -> int:
        """Accumulate edge arrivals host-side into ``pending_delta`` —
        observe-only until `update_graph` commits (mirrors
        `ServeEngine.stage_edges`, including the stage-time id
        validation: a bad arrival raises here and never poisons the
        pending buffer)."""
        from ..stream import GraphDelta, validate_edge_ids

        src, dst = validate_edge_ids(
            src, dst,
            (self._stream_adj.n if self._stream_adj is not None
             else self.global2host.shape[0]),
            "staged",
        )
        with self._lock:
            if self.pending_delta is None:
                self.pending_delta = GraphDelta()
            self.pending_delta.add_edges(src, dst)
            n = len(self.pending_delta)
        self.journal.emit("graph_delta", -1, -1, n)
        return n

    def stage_removals(self, src, dst) -> int:
        """Accumulate edge DELETIONS into ``pending_delta`` (round 21)
        — mirrors `ServeEngine.stage_removals`: ids validated here,
        existence validated fleet-wide at commit preflight (the edge may
        net out against a same-batch append). Timestamp updates are NOT
        staged here: dist streaming is structural-only, updates ride the
        single-host temporal engine."""
        from ..stream import GraphDelta, validate_edge_ids

        src, dst = validate_edge_ids(
            src, dst,
            (self._stream_adj.n if self._stream_adj is not None
             else self.global2host.shape[0]),
            "removed",
        )
        with self._lock:
            if self.pending_delta is None:
                self.pending_delta = GraphDelta()
            self.pending_delta.remove_edges(src, dst)
            n = len(self.pending_delta)
        self.journal.emit("graph_delta", -1, -1, n)
        return n

    def _current_full_topo(self):
        """The build()-time full topology, RE-MATERIALIZED from the
        stream when graph deltas landed since (lazy: only the auxiliary
        rebuild paths — replica refresh, migration shard builds — pay
        the O(E) materialize; the serving path mutates tiles in place
        and never touches this)."""
        m = self._replica_materials
        with self._mat_lock:
            if self._stream_adj is not None and self._materials_stale:
                m["csr_topo"] = self._stream_adj.to_csr_topo()
                self._src_per_edge = None
                self._materials_stale = False
            return m["csr_topo"]

    def update_graph(self, delta=None) -> Dict[str, object]:
        """Commit a graph delta FLEET-WIDE behind the router's
        `update_params` fence, with the three consumers the round-10
        fence never had (ROADMAP item 1):

        1. **Owner shards extend incrementally** — for each owner, the
           delta's closure growth is BFS'd over the updated graph from
           the arriving endpoints only (k-hop closures are
           union-homomorphic: new mask = old mask OR the arrivals'
           closure — the `closure_masks` argument the r16 migration path
           rides, never a reshard). Rows already in the closure take
           in-place pad-lane appends; rows ENTERING it install their
           full adjacency into the owner stream's reserve, and their
           feature rows land in the `ClosureFeature` reserve — the
           owner's sealed fused executables just rebind arguments.
        2. **Versioned node stamps invalidate caches** — every cached
           seed whose expansion closure touched a changed row is dropped
           at the ROUTER and at every owner (reverse k-hop closure over
           the updated graph; everything else stays warm).
        3. **Stale replicas drop** — a live hot-set replica whose
           replicated seeds lie in the invalidation closure would keep
           serving PRE-delta draws; it is retired under the fence
           (oracle rules: dispatch logs kept) and, with
           ``stream_replica_rebuild``, rebuilt over the updated graph
           right after. (Tier re-placement, consumer (c), rides the
           single-host `ServeEngine.update_graph` — tiered owner
           features gather host-side and require the exchange
           residency, which streaming rebuilds instead.)

        The full-graph fallback commits the same delta so failed-over
        seeds see it too. ``delta=None`` commits ``pending_delta``; an
        empty commit is a strict no-op (frozen == empty-delta replay,
        pinned). An appended edge is visible to the next routed sample
        after this returns.

        Round 21 — staged REMOVALS commit fleet-wide under the same
        fence: existence is validated all-or-none before any mutation,
        each owner holding the row (per its post-install mask) rewrites
        the lanes locally, the fallback and the shared adjacency follow,
        and the removal sources join the invalidation closure — a
        delete-then-replay matches a fleet built without the edge, bit
        for bit (tests/test_lifecycle.py, hosts=2). Timestamp updates
        are rejected here: dist streaming is structural-only."""
        from ..stream import GraphDelta

        if self._stream_adj is None:
            raise ValueError(
                "streaming is off — build with "
                "DistServeConfig(streaming=True)"
            )
        from_pending = delta is None
        with self._lock:
            if delta is None:
                delta, self.pending_delta = self.pending_delta, None
        if delta is None or len(delta) == 0:
            return {"edges": 0, "graph_version": self.graph_version,
                    "cache_invalidated": 0, "closure_installs": 0,
                    "replica_invalidated": False}
        src, dst = delta.edges()
        rsrc, rdst = delta.removals()
        usrc, _, _ = delta.updates()
        if usrc.size:
            raise ValueError(
                "timestamp updates ride the single-host temporal engine "
                "— dist streaming is structural-only (owner streams "
                "carry no ts payload to rewrite)"
            )
        if rsrc.size:
            # all-or-none existence check BEFORE any mutation: count each
            # removal against the shared adjacency plus this batch's own
            # appends, so a bad removal raises with the whole fleet (and
            # the staged buffer, re-staged in the except below) untouched
            avail: Dict[Tuple[int, int], int] = {}
            for u, v in zip(src.tolist(), dst.tolist()):
                avail[(u, v)] = avail.get((u, v), 0) + 1
            adj0 = self._stream_adj
            for u, v in zip(rsrc.tolist(), rdst.tolist()):
                k = (u, v)
                if k not in avail:
                    avail[k] = int(np.sum(
                        np.asarray(adj0.neighbors(u)) == v
                    ))
                if avail[k] <= 0:
                    if from_pending:
                        with self._lock:
                            if self.pending_delta is not None:
                                delta.extend(self.pending_delta)
                            self.pending_delta = delta
                    raise ValueError(
                        f"removal of absent edge ({u}, {v}) — the whole "
                        "batch is rejected (all-or-none), nothing was "
                        "applied"
                    )
                avail[k] -= 1
        m = self._replica_materials
        sizes = list(m["sizes"])
        hops = max(len(sizes) - 1, 0)
        feat_hops = len(sizes)
        inv_hops = self.config.stream_invalidate_hops
        if inv_hops is None:
            inv_hops = hops
        m_feat = np.asarray(m["feat"], np.float32)
        if self.config.fenced_commits:
            return self._update_graph_fenced(
                delta, src, dst, rsrc, rdst, from_pending,
                hops, feat_hops, inv_hops, m_feat)
        return self._update_graph_zerostall(
            delta, src, dst, rsrc, rdst, from_pending,
            hops, feat_hops, inv_hops, m_feat)

    def _plan_commit_window(self, delta, src, dst, rsrc, rdst,
                            from_pending, hops, feat_hops, inv_hops):
        """The tentative-adjacency window (add -> plan/preflight ->
        commit-or-rollback), shared by the fenced and zero-stall commit
        paths. Caller holds ``_mat_lock`` (and either the router fence or
        ``_commit_lock``). On success the shared adjacency carries the
        post-append, post-removal graph, ``_materials_stale`` is set, and
        ``(affected, plans, fb_delta)`` comes back; on ANY failure the
        adjacency is rolled back, a pending-origin delta is re-staged,
        and the error re-raises — the whole fleet untouched."""
        from ..stream import GraphDelta

        adj = self._stream_adj
        adj.add_edges(src, dst)  # validates ids first
        # plan + preflight EVERY consumer over the updated adjacency
        # before mutating ANY owner — a capacity error must leave the
        # whole fleet (and the adjacency, rolled back below) untouched,
        # never one owner committed and the next one not
        try:
            # invalidation seeds: append sources UNION removal
            # sources — a removal changes its src row's draws
            # too. The reverse closure runs over the POST-
            # append, PRE-removal adjacency: reverse reach is
            # a superset there (removals only shrink forward
            # lists), so we over-invalidate, never under
            inv_seeds = (np.unique(np.concatenate([src, rsrc]))
                         if rsrc.size else np.unique(src))
            affected = adj.reverse_closure(inv_seeds, inv_hops)
            plans = []
            for h in sorted(self.engines):
                stream_h = self._owner_streams.get(h)
                if stream_h is None:
                    continue
                topo_mask, feat_mask = self._owner_masks[h]
                # fixpoint over delta chains: an edge whose
                # src entered the mask via an EARLIER delta
                # edge of this batch extends it further.
                # EVERY dst of an in-mask src seeds a BFS —
                # including dsts already in the mask: a node
                # previously at the closure BOUNDARY (row
                # kept, own closure not) can now be reached
                # at a shallower depth and gets EXPANDED, so
                # its k-hop closure must enter the mask too
                # (the >=3-layer under-extension case; a
                # superset costs reserve rows, never
                # correctness)
                new_topo = topo_mask.copy()
                while True:
                    seeds = np.unique(dst[new_topo[src]])
                    if seeds.size == 0:
                        break
                    add = adj.forward_closure(seeds, hops)
                    if not (add & ~new_topo).any():
                        break
                    new_topo |= add
                feat_seeds = np.unique(dst[new_topo[src]])
                new_feat = feat_mask | new_topo
                if feat_seeds.size:
                    # one hop deeper than the adjacency
                    # closure (leaves gathered, never
                    # expanded)
                    new_feat |= adj.forward_closure(
                        feat_seeds, feat_hops
                    )
                topo_new = np.nonzero(new_topo & ~topo_mask)[0]
                installs = [(int(nd), adj.neighbors(int(nd)))
                            for nd in topo_new]
                rel = topo_mask[src]
                owner_delta = GraphDelta(src[rel], dst[rel])
                if rsrc.size:
                    # filter removals by the NEW mask: install
                    # rows are snapshotted from the shared
                    # adjacency BEFORE removals apply (below),
                    # so a freshly-installed row still carries
                    # the doomed edge — every owner holding
                    # the row (old or just-installed) must
                    # delete it locally
                    rel_r = new_topo[rsrc]
                    owner_delta.remove_edges(rsrc[rel_r],
                                             rdst[rel_r])
                feat_new = np.nonzero(new_feat & ~feat_mask)[0]
                stream_h.preflight(owner_delta,
                                   installs=installs)
                if feat_new.size:
                    self._owner_feats[h].preflight_install(
                        feat_new
                    )
                plans.append((h, new_topo, new_feat, installs,
                              owner_delta, feat_new))
            fb_delta = GraphDelta(src, dst)
            if rsrc.size:
                fb_delta.remove_edges(rsrc, rdst)
            fb_stream = (getattr(self.fallback._sampler,
                                 "stream", None)
                         if self.fallback is not None
                         else None)
            if fb_stream is not None:
                fb_stream.preflight(fb_delta)
        except BaseException:
            adj.pop_edges(src, dst)
            if from_pending:
                # a failed commit must not DROP staged
                # arrivals (ServeEngine.update_graph's
                # contract): re-staged ahead of anything
                # staged meanwhile — arrival order is the
                # replay order. _lock guards pending_delta
                # against a concurrent stage_edges (which
                # never takes the fence)
                with self._lock:
                    if self.pending_delta is not None:
                        delta.extend(self.pending_delta)
                    self.pending_delta = delta
            raise
        # every preflight passed: apply removals to the shared
        # adjacency (cannot fail — existence was validated
        # upfront and the batch's appends just landed). Owner
        # install rows above were snapshotted pre-removal; the
        # filtered owner_delta removals bring them in line
        for u, v in zip(rsrc.tolist(), rdst.tolist()):
            adj.remove_one(int(u), int(v))
        self._materials_stale = True
        return affected, plans, fb_delta

    def _sync_fleet_epoch(self) -> None:
        """Align every LIVE engine's ``graph_version`` with the router's
        (round 24). An owner whose slice of a commit was empty (no delta
        edges in its closure, no installs) never sees an `update_graph`
        call and would lag the fleet epoch — but its arrays are
        unchanged across the commit, so its draws are identical at
        either version and the stamp realignment is bit-harmless. Owners
        that DID commit just bumped to exactly this value. Retired
        engines keep their historical stamps (their logs end at the
        epoch they served)."""
        v = self.graph_version
        for h in sorted(self.engines):
            self.engines[h].graph_version = v
        if self.fallback is not None:
            self.fallback.graph_version = v
        rep = self.replica
        if rep is not None:
            rep.engine.graph_version = v

    def _update_graph_fenced(self, delta, src, dst, rsrc, rdst,
                             from_pending, hops, feat_hops, inv_hops,
                             m_feat):
        """The round-23 parity twin (``fenced_commits=True``): drain the
        routed window under the fence, then plan + mutate + invalidate
        synchronously inside the quiet period. Served bits are identical
        to the zero-stall path; what this buys is the simpler ordering
        argument (nothing in flight ever observes a commit) at the cost
        of stalling admission for the whole drain + plan + apply."""
        stale_replica_ids = None
        installs_total = 0
        with self._seq:
            t_stall0 = self._clock()
            with self._fence:
                while self._inflight_flushes:
                    self._fence.wait()
                # _mat_lock covers the whole tentative-adjacency window
                # (add -> plan/preflight -> commit-or-rollback): a
                # background replica refresh / migration build
                # re-materializing via `_current_full_topo` must never
                # iterate the adjacency dicts mid-mutation or capture a
                # graph that is about to roll back (ordering: router
                # fence -> _mat_lock, per the lock's contract)
                with self._mat_lock:
                    affected, plans, fb_delta = self._plan_commit_window(
                        delta, src, dst, rsrc, rdst, from_pending,
                        hops, feat_hops, inv_hops)
                self.graph_version += 1
                for (h, new_topo, new_feat, installs, owner_delta,
                     feat_new) in plans:
                    if feat_new.size:
                        self._owner_feats[h].install_rows(
                            feat_new, m_feat[feat_new]
                        )
                    if len(owner_delta) or installs:
                        self.engines[h].update_graph(
                            owner_delta, installs=installs,
                            invalidate=affected,
                        )
                        installs_total += len(installs)
                    self._owner_masks[h] = (new_topo, new_feat)
                if self.fallback is not None:
                    self.fallback.update_graph(
                        fb_delta, invalidate=affected
                    )
                rep = self.replica
                if (rep is not None and rep.ids.size
                        and np.intersect1d(rep.ids, affected).size):
                    # consumer (b): the replica's closure topology went
                    # stale — retire it under the fence (oracle rules)
                    # so no routed flush ever serves a pre-delta draw
                    stale_replica_ids = rep.ids
                    if rep.engine.config.record_dispatches:
                        self._retired_replicas.append(rep.engine)
                    else:
                        self._retired_stats.merge(rep.engine.stats)
                    self.replica = None
                    self.replica_version += 1
                    self.cache.invalidate_keys(
                        int(x) for x in stale_replica_ids
                    )
                    self.stats.replica_delta_invalidations += 1
                self._sync_fleet_epoch()
                # node-keyed drop (not exact keys): temporal router-cache
                # entries are (node, t)-keyed; identical behavior for the
                # plain int keys of this engine (see
                # EmbeddingCache.invalidate_nodes)
                invalidated = self.cache.invalidate_nodes(
                    int(x) for x in affected
                )
                self.stats.graph_deltas += 1
                self.stats.delta_edges += int(src.size)
                self.stats.edges_deleted += int(rsrc.size)
                self.stats.delta_cache_invalidated += invalidated
                self.stats.delta_closure_installs += installs_total
                # per-commit serving stall = the whole _seq hold: drain
                # wait + plan + owner commits + invalidation (round 24)
                t_now = self._clock()
                stall_us = (t_now - t_stall0) * 1e6
                self.stats.commit_stall.record_ms(stall_us)
                self._commit_samples.append(
                    ("graph_version", t_now, self.graph_version))
                self._commit_samples.append(
                    ("commit_stall_us", t_now, stall_us))
        self.journal.emit("delta_commit", -1, self.graph_version,
                          int(src.size), invalidated)
        if rsrc.size:
            self.journal.emit("edge_delete", -1, self.graph_version,
                              int(rsrc.size))
        out = {"edges": int(src.size),
               "edges_deleted": int(rsrc.size),
               "graph_version": self.graph_version,
               "cache_invalidated": invalidated,
               "affected_seeds": int(affected.size),
               "closure_installs": installs_total,
               "replica_invalidated": stale_replica_ids is not None,
               "commit_stall_us": stall_us}
        if stale_replica_ids is not None and self.config.stream_replica_rebuild:
            # rebuild OUTSIDE the fence (AOT warmup costs seconds;
            # refresh_replicas takes the fence itself for the swap)
            out["replica_refresh"] = self.refresh_replicas(
                ids=stale_replica_ids
            )
        return out

    def _update_graph_zerostall(self, delta, src, dst, rsrc, rdst,
                                from_pending, hops, feat_hops, inv_hops,
                                m_feat):
        """Round-24 tentpole: the fleet commit with NO window drain. The
        plan/preflight window and every owner's array build run entirely
        off-fence under ``_commit_lock`` (owner engines flip under their
        OWN ``_seq`` via their zero-stall `update_graph`); the router's
        flip — version bump + replica retire — holds ``_seq`` only long
        enough for a few reference assignments. Routed flushes sealed
        before the flip complete against the arrays (and owner routing)
        they pinned at seal; flushes sealed after serve the new epoch.
        Invalidation is the post-flip `EmbeddingCache.raise_floor` pass:
        resident pre-commit rows for affected seeds drop eagerly, and
        the per-node floor gates the late writeback of any old-epoch
        flush still in the air — the lazy equivalent of the fenced
        path's synchronous `invalidate_nodes`. The visibility contract
        is unchanged: an appended edge is visible to the next routed
        sample after this returns; a flush RACING the commit may serve
        either epoch (its stamp says which)."""
        stale_replica_ids = None
        installs_total = 0
        with self._commit_lock:
            # same tentative window as the fenced path, minus the fence:
            # _mat_lock alone serializes the shared-adjacency mutation
            # against background replica/migration materializes
            with self._mat_lock:
                affected, plans, fb_delta = self._plan_commit_window(
                    delta, src, dst, rsrc, rdst, from_pending,
                    hops, feat_hops, inv_hops)
            new_version = self.graph_version + 1
            # owner commits BEFORE the router flip: each is itself
            # zero-stall (propagated `fenced_commits`), flipping under
            # its own _seq after building off-fence. Until the router
            # flip lands, routed flushes seal at the OLD router version
            # while an already-flipped owner serves new-epoch draws —
            # exactly the commit race window the epoch stamps resolve
            # (each owner flush replays against its own stamp)
            for (h, new_topo, new_feat, installs, owner_delta,
                 feat_new) in plans:
                if feat_new.size:
                    # reserve rows are fresh (never yet gathered), so
                    # concurrent owner traffic cannot observe the write
                    self._owner_feats[h].install_rows(
                        feat_new, m_feat[feat_new]
                    )
                if len(owner_delta) or installs:
                    self.engines[h].update_graph(
                        owner_delta, installs=installs,
                        invalidate=affected,
                    )
                    installs_total += len(installs)
                self._owner_masks[h] = (new_topo, new_feat)
            if self.fallback is not None:
                self.fallback.update_graph(
                    fb_delta, invalidate=affected
                )
            # THE router flip: O(1) assignments under _seq — no drain,
            # no in-flight wait. _seal_assembled stamps and routes under
            # this same lock, so version, replica routing and the stamp
            # stay one epoch per flush.
            with self._seq:
                t_stall0 = self._clock()
                self.graph_version = new_version
                rep = self.replica
                if (rep is not None and rep.ids.size
                        and np.intersect1d(rep.ids, affected).size):
                    # consumer (b), deferred flavor: the stale replica
                    # unroutes AT the flip; in-flight replica legs
                    # complete against the retired engine's pinned
                    # arrays and replay under their old-epoch stamp
                    stale_replica_ids = rep.ids
                    if rep.engine.config.record_dispatches:
                        self._retired_replicas.append(rep.engine)
                    else:
                        self._retired_stats.merge(rep.engine.stats)
                    self.replica = None
                    self.replica_version += 1
                t_now = self._clock()
                stall_us = (t_now - t_stall0) * 1e6
            self._sync_fleet_epoch()
            # post-flip deferred invalidation (consumer (a)): floors gate
            # stale writebacks from old-epoch in-flight flushes; the
            # replica's exact keys drop conservatively as before
            if stale_replica_ids is not None:
                self.cache.invalidate_keys(
                    int(x) for x in stale_replica_ids
                )
            invalidated = self.cache.raise_floor(
                (int(x) for x in affected), new_version
            )
            with self._lock:
                if stale_replica_ids is not None:
                    self.stats.replica_delta_invalidations += 1
                self.stats.graph_deltas += 1
                self.stats.delta_edges += int(src.size)
                self.stats.edges_deleted += int(rsrc.size)
                self.stats.delta_cache_invalidated += invalidated
                self.stats.delta_closure_installs += installs_total
                self.stats.commit_stall.record_ms(stall_us)
                self._commit_samples.append(
                    ("graph_version", t_now, new_version))
                self._commit_samples.append(
                    ("commit_stall_us", t_now, stall_us))
        self.journal.emit("delta_commit", -1, self.graph_version,
                          int(src.size), invalidated)
        if rsrc.size:
            self.journal.emit("edge_delete", -1, self.graph_version,
                              int(rsrc.size))
        out = {"edges": int(src.size),
               "edges_deleted": int(rsrc.size),
               "graph_version": self.graph_version,
               "cache_invalidated": invalidated,
               "affected_seeds": int(affected.size),
               "closure_installs": installs_total,
               "replica_invalidated": stale_replica_ids is not None,
               "commit_stall_us": stall_us}
        if stale_replica_ids is not None and self.config.stream_replica_rebuild:
            # rebuild outside the commit lock's critical tail (AOT
            # warmup costs seconds; refresh_replicas fences itself for
            # the swap)
            out["replica_refresh"] = self.refresh_replicas(
                ids=stale_replica_ids
            )
        return out

    def compact_graph(self, max_moves: Optional[int] = None
                      ) -> Dict[str, Dict[str, object]]:
        """One fleet-wide compaction pass (round 21): each owner
        engine's `ServeEngine.compact_graph` plus the fallback's, in
        deterministic host order. Each engine plans off-fence and flips
        under its OWN fence (compaction is per-stream row bookkeeping —
        no cross-owner coordination needed, because it is strictly
        observe-only on served bits: no version bump, no invalidation,
        no routing change). Owners without a bound stream are skipped.
        Returns per-owner summaries keyed ``"host<h>"`` plus
        ``"fallback"``, and an aggregate ``"tiles_reclaimed"``."""
        out: Dict[str, Dict[str, object]] = {}
        total = 0
        for h in sorted(self.engines):
            eng = self.engines[h]
            if getattr(eng._sampler, "stream", None) is None:
                continue
            s = eng.compact_graph(max_moves=max_moves)
            out[f"host{h}"] = s
            total += int(s["tiles_reclaimed"])
        if (self.fallback is not None
                and getattr(self.fallback._sampler, "stream", None)
                is not None):
            s = self.fallback.compact_graph(max_moves=max_moves)
            out["fallback"] = s
            total += int(s["tiles_reclaimed"])
        out["tiles_reclaimed"] = total  # type: ignore[assignment]
        return out

    def adapt_tiers(self) -> Dict[int, Dict[str, object]]:
        """One fleet-wide promote/demote pass (round 14): fence the
        ROUTER (no routed flush in the air — the same drain as
        `update_params`), then run each owner engine's `adapt_tiers`
        under it; every owner fences its own in-flight flushes too, so
        no flush anywhere straddles a placement batch. Owners whose
        feature has no adaptive store (or no workload sketch) are
        skipped. Per-owner summaries keyed by host, deterministic order.
        NOTE the owner engines' own background consumers stay OFF in
        dist mode (``tier_adapt_every_s`` is not inherited by the shard
        config) — the router is the single adaptation driver, which is
        what keeps fleet passes fenced against routed flushes."""
        out: Dict[int, Dict[str, object]] = {}
        with self._seq:
            with self._fence:
                while self._inflight_flushes:
                    self._fence.wait()
                for h in sorted(self.engines):
                    eng = self.engines[h]
                    if eng._tier_feature is None or eng.workload is None:
                        continue
                    out[h] = eng.adapt_tiers()
        return out

    @property
    def placement_version(self) -> int:
        """Sum of the owner engines' fenced placement batches (a fleet
        placement-progress gauge, not a coherence version — shards move
        rows independently)."""
        return sum(e.placement_version for e in self.engines.values())

    def refresh_replicas(self, ids=None, k: Optional[int] = None,
                         ) -> Dict[str, object]:
        """(Re)build the hot-set replica (round 15, ROADMAP item 3a):
        pick the head — ``ids`` explicitly, or the ``k`` hottest seeds
        from the ROUTER's workload sketch (``k`` defaults to
        ``config.replicate_top_k``; price it with `scaling.skew_table`
        from the measured head-concentration curve) — and mirror it
        locally as a full `ServeEngine` over the head's halo-closure
        topology (`shard_topology_for_seeds`) + feature rows
        (`ClosureFeature`).

        The swap runs under the SAME fence as `update_params` /
        `apply_placement` (sequencing lock + in-flight drain), so no
        routed flush ever straddles a replica version; the router cache
        entries of every REFRESHED key (old set union new set — the keys
        whose serving path changed) are invalidated, and exactly those
        (pinned in tests/test_serve_dist.py). ``replica_version`` bumps
        per refresh. ``ids=[]`` disables replication.

        Replica-served rows keep the standing parity contract: the
        closure topology makes the replica sampler's draws for
        replicated seeds bit-equal to a full-graph sampler's on the same
        key stream, so `replay_fleet_oracle` replays its dispatch log
        exactly like an owner shard's."""
        if self._replica_materials is None:
            raise ValueError(
                "hot-set replication needs the build()-time materials "
                "(full topology + feature table); a bare-constructed "
                "multi-process engine holds only its own shard"
            )
        m = self._replica_materials
        if ids is None:
            k = int(self.config.replicate_top_k if k is None else k)
            if k <= 0:
                raise ValueError(
                    "pass ids= or set DistServeConfig.replicate_top_k > 0"
                )
            if self.workload is None:
                raise ValueError(
                    "picking the hot set reads the router workload sketch "
                    "— pass DistServeConfig(workload=WorkloadConfig(...)) "
                    "or give ids= explicitly"
                )
            ids = self.workload.hot_set(k)
        ids = np.unique(np.asarray(ids, np.int64))
        new_replica = None
        st: Dict[str, float] = {}
        if ids.size:
            from ..pyg.sage_sampler import GraphSageSampler

            sizes = list(m["sizes"])
            # adjacency closure: len(sizes)-1 expansion hops; feature
            # closure one deeper (leaves gathered, never expanded) — the
            # same construction as the owner shards in `build`. The
            # source topology is the CURRENT one: a streaming fleet
            # re-materializes the full graph from the stream first, so a
            # rebuilt replica serves post-delta draws (round 17).
            full_topo = self._current_full_topo()
            topo_r, st, closure_ids = shard_topology_for_seeds(
                full_topo, ids, hops=len(sizes) - 1,
                closure_hops=len(sizes),
            )
            sampler = GraphSageSampler(
                topo_r, sizes=sizes, mode=m["sampler_mode"],
                seed=m["sampler_seed"], **m["sampler_kw"],
            )
            n = full_topo.indptr.shape[0] - 1
            local_map = np.full(n, -1, np.int32)
            local_map[closure_ids] = np.arange(
                closure_ids.shape[0], dtype=np.int32
            )
            feat_r = ClosureFeature(
                np.asarray(m["feat"], np.float32)[closure_ids], local_map
            )
        # construct + AOT-warmup the replica engine OUTSIDE the fence:
        # the bucket compiles take seconds, and a routine refresh must
        # not stall every submit() (the fence Condition wraps the
        # router's request lock) for that long. Only the pointer swap +
        # cache invalidation need the fence.
        eng = None
        if ids.size:
            with self._lock:
                params_snapshot = self._params
            eng = ServeEngine(
                m["model"], params_snapshot, sampler, feat_r,
                m["shard_config"],
            )
            # a mid-run engine is born AT the current fleet epoch: its
            # dispatch-log stamps must line up with the router's (round
            # 24 epoch-filtered replay)
            eng.graph_version = self.graph_version
            eng.warmup()
        with self._seq:
            with self._fence:
                while self._inflight_flushes:
                    self._fence.wait()
                if eng is not None and self._params is not params_snapshot:
                    # a weight update landed while we compiled: re-stamp
                    # under the fence (cheap — swap + invalidate) so the
                    # replica never serves stale params
                    eng.update_params(self._params)
                old = self.replica
                if old is not None and old.engine.config.record_dispatches:
                    # kept ONLY for the replay oracle (its dispatch log
                    # vouches for pre-refresh rows) — a production engine
                    # without dispatch recording retains nothing, so
                    # periodic refreshes never accumulate dead engines
                    self._retired_replicas.append(old.engine)
                elif old is not None:
                    # dropped engine: counters fold so the merged fleet
                    # view never goes backwards across a refresh
                    self._retired_stats.merge(old.engine.stats)
                self.replica_version += 1
                if eng is not None:
                    new_replica = _HotReplica(
                        eng, ids, self.replica_version, dict(st)
                    )
                self.replica = new_replica
                old_ids = old.ids if old is not None else np.array(
                    [], np.int64
                )
                refreshed = np.union1d(old_ids, ids)
                invalidated = self.cache.invalidate_keys(
                    int(x) for x in refreshed
                )
        return {
            "replicated": int(ids.size),
            "version": self.replica_version,
            "invalidated": invalidated,
            "closure_nodes": int(st.get("closure_nodes", 0)),
            "edge_frac": float(st.get("edge_frac", 0.0)),
        }

    # -- round-16 elastic fleet: live resharding ---------------------------

    def _elastic_gate(self) -> None:
        """Preconditions for `scale`/`rebalance`: build()-time materials
        (the full topology + feature table the extended shards are cut
        from), host-mode per-owner legs (the collective mesh is sized at
        build — growing it means a new mesh, comm, and answerer set, not
        a range flip), and closure feature residency (the exchange
        residency's `DistFeature` partition is registered against a fixed
        ownership map)."""
        if self._replica_materials is None:
            raise ValueError(
                "live resharding needs the build()-time materials (full "
                "topology + feature table); a bare-constructed "
                "multi-process engine holds only its own shard"
            )
        if self.exchange_mode != "host":
            raise ValueError(
                "scale/rebalance ride the host-mode per-owner legs; the "
                "collective mesh is sized at build and cannot gain or "
                "lose hosts mid-run — build with exchange='host'"
            )
        if self.config.feature_residency != "closure":
            raise ValueError(
                "live resharding requires feature_residency='closure' "
                "(the exchange residency's DistFeature partition is "
                "registered against a fixed ownership map)"
            )

    def _build_extended_owner(self, dst: int, ids: np.ndarray):
        """Land ``ids``'s closure on owner ``dst`` OUTSIDE any fence (the
        old owner keeps serving the range): BFS only the migrated range
        (`closure_masks` — k-hop closures are union-homomorphic, so the
        destination's new masks are old-OR-range, no re-BFS of rows it
        already held), materialize the extended shard topology + closure
        feature rows, and AOT-warm a fresh `ServeEngine` over them.

        The new engine's sampler is BORN FRESH (same seed as every shard
        sampler), so its draws for any owned seed are bit-equal to a
        freshly born full-graph sampler's at the same key index — the
        standing parity argument; the replaced engine retires WITH its
        dispatch log so `replay_fleet_oracle` can still vouch for every
        row it served (ownership epochs change WHO computes, never any
        completed bit)."""
        from ..pyg.sage_sampler import GraphSageSampler

        m = self._replica_materials
        # streaming fleets migrate over the UPDATED graph (lazy
        # re-materialize; the masks stay valid — update_graph extends
        # them at every commit)
        topo = self._current_full_topo()
        indptr = np.asarray(topo.indptr, np.int64)
        indices = np.asarray(topo.indices, np.int64)
        n = indptr.shape[0] - 1
        if self._src_per_edge is None:
            self._src_per_edge = np.repeat(
                np.arange(n, dtype=np.int64), (indptr[1:] - indptr[:-1])
            )
        seed_mask = np.zeros(n, bool)
        seed_mask[ids] = True
        sizes = list(m["sizes"])
        add_topo, add_feat = closure_masks(
            indptr, indices, seed_mask,
            hops=len(sizes) - 1, feat_hops=len(sizes),
            src_per_edge=self._src_per_edge,
        )
        base = self._owner_masks.get(dst)
        if base is not None:
            new_topo, new_feat = base[0] | add_topo, base[1] | add_feat
        else:
            new_topo, new_feat = add_topo, add_feat
        shard, _ = shard_from_mask(topo, new_topo,
                                   src_per_edge=self._src_per_edge)
        closure_ids = np.nonzero(new_feat)[0]
        local_map = np.full(n, -1, np.int32)
        local_map[closure_ids] = np.arange(closure_ids.shape[0],
                                           dtype=np.int32)
        feat_r = ClosureFeature(
            np.asarray(m["feat"], np.float32)[closure_ids], local_map,
            reserve_rows=_feat_reserve(self.config, closure_ids.shape[0]),
        )
        sampler = GraphSageSampler(
            shard, sizes=sizes, mode=m["sampler_mode"],
            seed=m["sampler_seed"], **m["sampler_kw"],
        )
        new_stream = None
        if self.config.streaming:
            # a migrated-in owner must keep streaming: bind the extended
            # shard to its own tile stream so later deltas apply in place
            from ..stream import StreamingTiledGraph

            new_stream = StreamingTiledGraph(
                shard, reserve_frac=self.config.stream_reserve_frac
            )
            sampler.bind_stream(new_stream)
        with self._lock:
            params_snapshot = self._params
        eng = ServeEngine(
            m["model"], params_snapshot, sampler, feat_r, m["shard_config"]
        )
        # born at the current fleet epoch (round-24 stamp alignment)
        eng.graph_version = self.graph_version
        eng.warmup()
        return eng, (new_topo, new_feat), params_snapshot, new_stream, feat_r

    def _migrate_batch(self, lo: int, hi: int, src: int, dst: int) -> str:
        """Hand ONE bounded ownership range ``[lo, hi)`` from ``src`` to
        ``dst`` — the migration unit. Build/land outside the fence (old
        owner serves throughout), then a PER-RANGE fence (the
        `update_params`/`apply_placement` drain, held only for the
        pointer flip) swaps the destination engine, flips
        ``global2host[lo:hi]``, bumps the ownership epoch, and
        invalidates exactly the migrated seeds' router-cache and
        old-owner-cache entries. Returns the outcome, one of:

        - ``"commit"``       — the range now routes to ``dst``;
        - ``"rollback"``     — ``dst`` died mid-landing (fault hook at
          this batch's migration index): the built shard is discarded
          and the range STAYS with ``src``, which never stopped serving
          it — no fence was taken, no state moved;
        - ``"rollforward"``  — ``src`` died after the shard landed: the
          flip completes (``dst`` holds everything the range needs) and
          the dead owner's remaining traffic is the hedging machinery's
          problem, exactly like any serve-time kill.

        Deterministic by construction: the outcome reads only (owner,
        migration batch index) — same plan, same batch log."""
        with self._migration_lock:
            mig = self._mig_index
            self._mig_index += 1
            ids = np.arange(lo, hi, dtype=np.int64)
            jr = self.journal
            jr.emit("migrate", -1, mig, lo, hi)
            rollforward = False
            try:
                if self.faults is not None:
                    # destination-side hook: a dst kill/error here is a
                    # death while the shard lands → roll back
                    self.faults.check_migration(dst, mig)
                built = self._build_extended_owner(dst, ids)
                if self.faults is not None:
                    # source-side hook: src died AFTER the shard landed
                    # → roll forward (dst has everything it needs)
                    try:
                        self.faults.check_migration(src, mig)
                    except OwnerFault:
                        rollforward = True
            except OwnerFault:
                self.migration_log.append(
                    (mig, self.ownership_epoch, lo, hi, src, dst, 0,
                     "rollback")
                )
                with self._lock:
                    self.stats.migration_rollbacks += 1
                jr.emit("migrate_rollback", -1, mig, src, dst)
                return "rollback"
            eng, new_masks, params_snapshot, new_stream, new_feat = built
            with self._seq:
                with self._fence:
                    while self._inflight_flushes:
                        self._fence.wait()
                    if self._params is not params_snapshot:
                        # a weight update landed while the shard built:
                        # re-stamp under the fence (cheap), same rule as
                        # a replica refresh
                        eng.update_params(self._params)
                    old = self.engines.get(dst)
                    if old is not None:
                        if old.config.record_dispatches:
                            self._retired_engines.append(old)
                        else:
                            self._retired_stats.merge(old.stats)
                    self.engines[dst] = eng
                    self._owner_masks[dst] = new_masks
                    if new_stream is not None:
                        self._owner_streams[dst] = new_stream
                        self._owner_feats[dst] = new_feat
                    self.global2host[lo:hi] = dst
                    self.ownership_epoch += 1
                    # range-scoped invalidation: exactly the migrated
                    # seeds' entries — their serving path changed (the
                    # replica-refresh rule); everything else stays warm
                    self.cache.invalidate_keys(range(lo, hi))
                    src_eng = self.engines.get(src)
                    if src_eng is not None:
                        src_eng.cache.invalidate_keys(int(i) for i in ids)
                    outcome = "rollforward" if rollforward else "commit"
                    self.migration_log.append(
                        (mig, self.ownership_epoch, lo, hi, src, dst,
                         int(ids.size), outcome)
                    )
                    # the fence Condition wraps _lock — already held here
                    self.stats.migration_batches += 1
                    self.stats.migrated_seeds += int(ids.size)
                    if rollforward:
                        self.stats.migration_rollforwards += 1
            jr.emit("migrate_commit", -1, mig, src, dst)
            return outcome

    def rebalance(self, target_global2host=None,
                  max_seeds: Optional[int] = None) -> Dict[str, object]:
        """Migrate seed ownership toward ``target_global2host`` one
        bounded range at a time (``config.migrate_batch_seeds`` per
        fenced flip; `plan_migration_ranges` cuts the delta into
        per-(src, dst) contiguous runs). With no explicit target, plans
        one load-shedding move off the hottest owner from the router's
        `OwnerLoadStats` + Count-Min estimates (`_plan_load_target`) —
        the telemetry-driven path `maybe_rebalance` and the background
        timer ride. Ranges whose destination dies mid-landing roll back
        (and keep counting); a `stop()` in progress halts BETWEEN
        batches (never mid-range). Returns the pass summary."""
        self._elastic_gate()
        if target_global2host is None:
            target_global2host = self._plan_load_target(max_seeds)
            if target_global2host is None:
                return {"batches": 0, "migrated_seeds": 0, "rollbacks": 0,
                        "rollforwards": 0, "epoch": self.ownership_epoch,
                        "planned": 0, "skipped": "balanced"}
        target = np.asarray(target_global2host, np.int32)
        if target.shape != self.global2host.shape:
            raise ValueError(
                f"target has {target.shape[0]} rows, graph has "
                f"{self.global2host.shape[0]}"
            )
        if target.size and (target.min() < 0 or target.max() >= self.hosts):
            raise ValueError(
                f"target owners outside [0, {self.hosts})"
            )
        ranges = plan_migration_ranges(
            self.global2host, target, self.config.migrate_batch_seeds
        )
        batches = rollbacks = rollforwards = moved = 0
        for lo, hi, src, dst in ranges:
            if self._draining:
                break  # stop() halts between batches, never mid-range
            outcome = self._migrate_batch(lo, hi, src, dst)
            if outcome == "rollback":
                rollbacks += 1
            else:
                batches += 1
                moved += hi - lo
                if outcome == "rollforward":
                    rollforwards += 1
        return {"batches": batches, "migrated_seeds": moved,
                "rollbacks": rollbacks, "rollforwards": rollforwards,
                "epoch": self.ownership_epoch, "planned": len(ranges)}

    def scale(self, hosts: int) -> Dict[str, object]:
        """Grow or shrink the serving fleet to ``hosts`` under live
        traffic (ROADMAP item 2): the target ownership is the canonical
        balanced `contiguous_partition`, and every changed range migrates
        through `rebalance`'s bounded fenced batches — the old owner
        serves each range until the new owner's halo-closure shard and
        feature rows land. Shrinks retire the emptied hosts' engines
        (dispatch logs kept for the replay oracle); if a rollback left
        seeds on a to-be-removed host, that host SURVIVES (reported in
        ``incomplete_hosts``) — a seed is never stranded ownerless."""
        self._elastic_gate()
        new_h = int(hosts)
        if new_h < 1:
            raise ValueError("hosts must be >= 1")
        old_h = self.hosts
        n = self.global2host.shape[0]
        target = contiguous_partition(n, new_h)
        if new_h > old_h:
            # routing to the new owners only begins at their first range
            # flip; until then they own nothing and get no sub-batches
            self.hosts = new_h
        summary = self.rebalance(target)
        summary["hosts_before"], summary["hosts_target"] = old_h, new_h
        if new_h < old_h:
            with self._seq:
                with self._fence:
                    while self._inflight_flushes:
                        self._fence.wait()
                    leftover = np.unique(
                        self.global2host[self.global2host >= new_h]
                    )
                    if leftover.size:
                        summary["incomplete_hosts"] = [
                            int(x) for x in leftover
                        ]
                    else:
                        for h in range(new_h, self.hosts):
                            eng = self.engines.pop(h, None)
                            self._owner_masks.pop(h, None)
                            self._owner_health.pop(h, None)
                            self._owner_streams.pop(h, None)
                            self._owner_feats.pop(h, None)
                            if eng is None:
                                continue
                            if eng.config.record_dispatches:
                                self._retired_engines.append(eng)
                            else:
                                self._retired_stats.merge(eng.stats)
                        self.hosts = new_h
        summary["hosts"] = self.hosts
        return summary

    def maybe_rebalance(self) -> Optional[Dict[str, object]]:
        """The telemetry trigger: migrate ranges off the hottest owner
        iff `OwnerLoadStats` imbalance crossed
        ``config.rebalance_imbalance``. Returns the rebalance summary or
        None when balanced (or no telemetry). `start()` runs this on a
        timer when ``rebalance_every_s`` > 0."""
        self._elastic_gate()
        target = self._plan_load_target()
        if target is None:
            return None
        return self.rebalance(target)

    def _plan_load_target(self, max_seeds: Optional[int] = None
                          ) -> Optional[np.ndarray]:
        """One load-shedding ownership target from the router telemetry:
        when the hottest owner's routed-seed load exceeds
        ``rebalance_imbalance`` x the mean, move its hottest contiguous
        owned runs (scored by the Count-Min per-seed estimate — the
        sketch names WHICH ranges carry the excess) to the least-loaded
        owner, until ~half the excess moved or ``rebalance_max_seeds``
        seeds are in flight. Deterministic: reads only sketch/owner
        state, ties break on ids. None = balanced or not enough
        telemetry."""
        if self.workload is None or self.hosts < 2:
            return None
        loads = {h: 0 for h in range(self.hosts)}
        for h, v in self.workload.owners.seeds_by_owner().items():
            if 0 <= h < self.hosts:
                loads[h] = int(v)
        total = sum(loads.values())
        if total <= 0:
            return None
        mean = total / self.hosts
        hot = max(loads, key=lambda h: (loads[h], -h))
        cold = min(loads, key=lambda h: (loads[h], h))
        if hot == cold or loads[hot] < self.config.rebalance_imbalance * mean:
            return None
        excess = loads[hot] - mean
        owned = np.nonzero(self.global2host == hot)[0]
        if owned.size == 0:
            return None
        cms = self.workload.cms
        est = np.asarray(cms.estimate_many(owned), np.float64)
        # contiguous runs of the hot owner's ids, hottest-first
        cuts = np.nonzero(np.diff(owned) != 1)[0] + 1
        run_bounds = zip(np.concatenate(([0], cuts)),
                         np.concatenate((cuts, [owned.size])))
        runs = sorted(
            ((float(est[a:b].sum()), int(owned[a]), int(owned[b - 1]) + 1)
             for a, b in run_bounds),
            key=lambda r: (-r[0], r[1]),
        )
        budget = int(max_seeds or self.config.rebalance_max_seeds)
        target = self.global2host.copy()
        moved_est, moved_seeds = 0.0, 0
        goal = excess / 2.0
        for score, lo, hi in runs:
            if moved_est >= goal or moved_seeds >= budget:
                break
            take = min(hi - lo, budget - moved_seeds)
            target[lo:lo + take] = cold
            sl = (owned >= lo) & (owned < lo + take)
            moved_est += float(est[sl].sum())
            moved_seeds += take
        if moved_seeds == 0:
            return None
        return target

    def routing_epochs(self) -> List[Tuple[int, int, int, int, int]]:
        """Committed ownership flips as (epoch, lo, hi, src, dst) — the
        deterministic routing-epoch history replay comparisons read
        (rollbacks never bump the epoch and are excluded; read
        ``migration_log`` for the full batch log including them)."""
        return [(e, lo, hi, src, dst)
                for (_mig, e, lo, hi, src, dst, _n, oc) in self.migration_log
                if oc != "rollback"]

    def _replica_refresh_pass(self) -> Optional[Dict[str, object]]:
        """One background-refresh check (the r15 remaining-leverage
        note): re-run `refresh_replicas` iff the router sketch's hot set
        drifted at least ``replica_drift_frac`` away from what the live
        replica holds (`WorkloadMonitor.hot_set_drift`); a first pass
        with no replica builds one. Returns the refresh summary or None
        when skipped — fenced and observe-parity pinned exactly like the
        manual path, because it IS the manual path behind a drift
        check."""
        if self.workload is None or self.config.replicate_top_k <= 0:
            return None
        k = self.config.replicate_top_k
        hot = self.workload.hot_set(k)
        if hot.size == 0:
            return None
        rep = self.replica
        if rep is not None:
            drift = self.workload.hot_set_drift(rep.ids, k)
            if drift < self.config.replica_drift_frac:
                return None
        out = self.refresh_replicas(k=k)
        with self._lock:
            self.stats.replica_refreshes += 1
        return out

    def _policy_loop(self, period: float, fn, err_attr: str) -> None:
        """Shared background-policy driver (replica refresh, rebalance):
        sleep in small slices so stop() never waits a full period; a
        failing pass bumps its error counter instead of killing the
        thread (the tier-daemon contract)."""
        while self._running:
            deadline = time.monotonic() + period
            while self._running and time.monotonic() < deadline:
                time.sleep(min(0.05, period))
            if not self._running:
                return
            try:
                fn()
            except Exception:
                setattr(self, err_attr, getattr(self, err_attr) + 1)

    def warmup(self) -> Dict[object, Dict[int, float]]:
        """Pre-trace every shard engine's bucket programs (twin samplers
        where supported, so no shard's key stream moves) — plus the
        full-graph fallback's and the live replica's, under the
        ``"fallback"`` / ``"replica"`` keys. Returns
        {host: {bucket: seconds}}."""
        out: Dict[object, Dict[int, float]] = {
            h: eng.warmup() for h, eng in self.engines.items()
        }
        if self.fallback is not None:
            out["fallback"] = self.fallback.warmup()
        if self.replica is not None:
            out["replica"] = self.replica.engine.warmup()
        return out

    def aggregate_stats(self) -> Dict[str, object]:
        """Router snapshot + the per-shard `ServeStats` merged into one
        view (`ServeStats.merge` -> the `trace` merge family) + per-shard
        topology shard stats. The merged latency histogram is OWNER-side
        latency; end-to-end latency (queue + route + owner + return) is the
        router's own ``stats.latency``. The replica/fallback engines (when
        built) merge into ``shards_merged`` and appear under their own
        keys — they are serving engines like any owner."""
        merged = ServeStats()
        for h in sorted(self.engines):
            merged.merge(self.engines[h].stats)
        # engines retired by a range handoff or a shrink served real
        # traffic — their counters stay in the merged fleet view
        # (retained engines merge live; dropped ones were folded into
        # _retired_stats at retirement)
        for eng in self._retired_engines:
            merged.merge(eng.stats)
        merged.merge(self._retired_stats)
        out: Dict[str, object] = {
            "router": self.stats.snapshot(),
            "per_shard": {
                h: self.engines[h].stats.snapshot() for h in sorted(self.engines)
            },
            "topology": self.shard_topo_stats,
            "retired_engines": len(self._retired_engines),
        }
        if self.replica is not None:
            merged.merge(self.replica.engine.stats)
            out["replica"] = self.replica.engine.stats.snapshot()
            out["replica"]["replicated_ids"] = int(self.replica.ids.size)
        if self.fallback is not None:
            merged.merge(self.fallback.stats)
            out["fallback"] = self.fallback.stats.snapshot()
        out["shards_merged"] = merged.snapshot()
        return out

    def reset_stats(self) -> None:
        """Zero router counters (re-pointing the router cache's counter at
        the fresh stats, same contract as `ServeEngine.reset_stats`) and
        every shard engine's stats (journals included). Cache CONTENTS are
        untouched."""
        with self._lock:
            self.stats = DistServeStats()
            self.cache.counters = self.stats.router_cache
            if self.journal.enabled:
                self.journal.clear()
            if self.workload is not None:
                self.workload.clear()
        for eng in self.engines.values():
            eng.reset_stats()
        if self.replica is not None:
            self.replica.engine.reset_stats()
        if self.fallback is not None:
            self.fallback.reset_stats()

    # -- fleet observability ----------------------------------------------

    def register_metrics(self, registry: Optional[MetricsRegistry] = None,
                         prefix: str = "quiver_router",
                         labels: Optional[Dict[str, str]] = None,
                         ) -> MetricsRegistry:
        """Adapt the ROUTER's live state into a registry (created when not
        given): `DistServeStats` counters, queue/window gauges, exchange
        wire bytes, per-owner sub-batch counters (``host`` label), the
        router result cache, and the end-to-end latency histogram. All
        callback-backed (read at exposition time, `reset_stats`-safe).
        Owner-engine metrics ride :meth:`fleet_registry`."""
        reg = registry if registry is not None else MetricsRegistry()
        for f in ("requests", "coalesced", "router_dispatches",
                  "routed_seeds", "late_admitted", "replica_hits",
                  "hedges", "hedged_seeds", "hedge_timeouts",
                  "hedge_errors", "hedge_ejected", "hedge_failed",
                  "owner_ejections", "shed", "request_errors",
                  "undrained", "migration_batches", "migration_rollbacks",
                  "migration_rollforwards", "migrated_seeds",
                  "replica_refreshes", "graph_deltas", "delta_edges",
                  "delta_cache_invalidated", "delta_closure_installs",
                  "replica_delta_invalidations", "edges_deleted"):
            reg.counter_fn(f"{prefix}_{f}_total",
                           (lambda f=f: getattr(self.stats, f)),
                           f"DistServeStats.{f}", labels)
        reg.gauge_fn(f"{prefix}_ownership_epoch",
                     lambda: self.ownership_epoch,
                     "committed ownership range flips", labels)
        reg.gauge_fn(f"{prefix}_graph_version",
                     lambda: self.graph_version,
                     "streaming-graph delta commits applied (the fleet "
                     "epoch routed flushes pin against)",
                     labels)
        reg.histogram(f"{prefix}_commit_stall_us",
                      "per-commit routed-serving stall, µs (fenced: the "
                      "whole drain+apply hold; zero-stall: the _seq "
                      "flip)", labels,
                      fn=lambda: self.stats.commit_stall)
        reg.gauge_fn(f"{prefix}_delta_pending_edges",
                     lambda: (len(self.pending_delta)
                              if self.pending_delta is not None else 0),
                     "edge arrivals staged and not yet committed", labels)
        # round-19 satellite: every owner stream's reserve runway as
        # gauges (host label), same family names as the single-host
        # engine's so one alert rule covers both
        from .engine import register_stream_reserve

        for h in sorted(self._owner_streams):
            register_stream_reserve(
                reg, prefix,
                (lambda h=h: self._owner_streams.get(h)),
                dict(labels or {}, host=str(h)),
            )
        reg.gauge_fn(f"{prefix}_hosts",
                     lambda: self.hosts,
                     "current serving fleet host count", labels)
        reg.gauge_fn(f"{prefix}_replica_refresh_errors",
                     lambda: self.replica_refresh_errors,
                     "failed background replica-refresh passes", labels)
        reg.gauge_fn(f"{prefix}_rebalance_errors",
                     lambda: self.rebalance_errors,
                     "failed background rebalance passes", labels)
        reg.gauge_fn(f"{prefix}_replica_version",
                     lambda: self.replica_version,
                     "hot-set replica refreshes applied", labels)
        reg.gauge_fn(f"{prefix}_replica_rows",
                     lambda: (self.replica.ids.size
                              if self.replica is not None else 0),
                     "seeds currently replicated on every host", labels)
        reg.gauge_fn(f"{prefix}_owners_ejected",
                     lambda: sum(
                         1 for st in self.owner_health().values()
                         if st["ejected_at"] >= 0
                     ),
                     "owners currently in ejection backoff", labels)
        register_tenant_latency(
            reg, prefix, "end-to-end routed latency by submitting tenant",
            lambda: self.stats, self.config.tenant_weights, labels,
        )
        reg.counter_fn(f"{prefix}_exchange_id_bytes_total",
                       lambda: self.stats.exchange_id_bytes,
                       "global collective id payload bytes", labels)
        reg.counter_fn(f"{prefix}_exchange_logit_bytes_total",
                       lambda: self.stats.exchange_logit_bytes,
                       "global collective logits payload bytes", labels)
        reg.gauge_fn(f"{prefix}_pending_depth", lambda: len(self._pending),
                     "unique seeds queued at the router", labels)
        reg.gauge_fn(f"{prefix}_inflight_flushes",
                     lambda: self._inflight_flushes,
                     "routed flushes between assemble and resolve", labels)
        reg.gauge_fn(f"{prefix}_inflight_window",
                     lambda: self.config.max_in_flight,
                     "configured router max_in_flight bound", labels)
        reg.gauge_fn(f"{prefix}_inflight_peak",
                     lambda: self.stats.inflight_peak,
                     "largest routed in-flight occupancy observed", labels)
        reg.gauge_fn(f"{prefix}_cache_rows", lambda: len(self.cache),
                     "router result-cache resident rows", labels)
        reg.gauge_fn(f"{prefix}_params_version", lambda: self.params_version,
                     "current weights version", labels)
        reg.gauge_fn(f"{prefix}_placement_version",
                     lambda: self.placement_version,
                     "fenced tier-placement batches across the fleet",
                     labels)
        reg.gauge_fn(f"{prefix}_tier_adapt_errors",
                     lambda: self.tier_adapt_errors,
                     "failed fleet tier-adaptation passes", labels)
        for h in sorted(self.engines):
            reg.counter_fn(
                f"{prefix}_sub_batches_total",
                (lambda h=h: self.stats.sub_batches.get(h, 0)),
                "owner sub-batches routed",
                dict(labels or {}, host=str(h)),
            )
            reg.counter_fn(
                f"{prefix}_sub_batch_seeds_total",
                (lambda h=h: self.stats.sub_batch_seeds.get(h, 0)),
                "seeds routed to owner",
                dict(labels or {}, host=str(h)),
            )
        register_hit_rate(reg, f"{prefix}_cache",
                          lambda: self.stats.router_cache, labels)
        reg.histogram(f"{prefix}_latency_ms",
                      "end-to-end routed request latency", labels,
                      fn=lambda: self.stats.latency)
        if self.workload is not None:
            self.workload.register_metrics(
                reg, prefix=f"{prefix}_workload", labels=labels,
                owners=range(self.hosts),
            )
        return reg

    def fleet_registry(self, registry: Optional[MetricsRegistry] = None,
                       ) -> MetricsRegistry:
        """ONE registry over the whole fleet: the router's metrics plus
        every owner engine's (`ServeEngine.register_metrics`) under a
        ``host`` label, registered in sorted-host order — the same
        deterministic merge discipline as `aggregate_stats`, so two
        expositions of the same state are textually identical. With no
        ``registry`` argument the engine's CACHED fleet registry is
        returned (adapters are callback-backed readers, so one registry
        serves every scrape; re-registration re-points, never
        duplicates)."""
        if registry is None:
            if getattr(self, "_fleet_reg", None) is None:
                self._fleet_reg = MetricsRegistry()
            registry = self._fleet_reg
        reg = self.register_metrics(registry)
        for h in sorted(self.engines):
            self.engines[h].register_metrics(
                reg, prefix="quiver_serve", labels={"host": str(h)}
            )
        # the replica/fallback engines are serving engines like any owner
        # — same families under reserved host labels. A replica refresh
        # swaps the engine; re-calling fleet_registry re-points the
        # adapters (last-writer-wins, the registry's documented rule).
        if self.replica is not None:
            self.replica.engine.register_metrics(
                reg, prefix="quiver_serve", labels={"host": "replica"}
            )
        if self.fallback is not None:
            self.fallback.register_metrics(
                reg, prefix="quiver_serve", labels={"host": "fallback"}
            )
        return reg

    def aggregate_journal(self) -> List[Tuple]:
        """The fleet's lifecycle events as (host, t, kind, rid, fid, a, b)
        tuples — router events first under host=-1, then each owner's in
        sorted-host order. Within one journal the ring is already in
        emit order, and flush events emit in dispatch-index order (seals
        are serialized under each engine's sequencing lock), so the merge
        is deterministic for a deterministic run — the same contract as
        the dispatch-log/stats merges."""
        merged: List[Tuple] = [(-1, *ev) for ev in self.journal.snapshot()]
        for h in sorted(self.engines):
            merged.extend(
                (h, *ev) for ev in self.engines[h].journal.snapshot()
            )
        return merged

    def fleet_snapshot(self) -> Dict[str, object]:
        """Fleet observability in one JSON-able document: the router's
        request breakdown (end-to-end stages), per-owner breakdowns
        (sorted hosts), and the fleet registry snapshot. This is the
        serve-stack answer to "where did this request's time go" at fleet
        grain — queue/route at the router, device/resolve at the owners."""
        return {
            "router": self.journal.request_breakdown(),
            "per_shard": {
                h: self.engines[h].journal.request_breakdown()
                for h in sorted(self.engines)
            },
            "metrics": self.fleet_registry().snapshot(),
        }

    def workload_report(self, capacities: Sequence[int] = (),
                        ) -> Dict[str, object]:
        """The fleet's skew/imbalance planning document (round 13;
        requires ``DistServeConfig.workload``):

        - ``router`` — the ROUTER monitor's `skew_report`: since the
          router observes every submitted seed, this is the fleet's
          access-frequency truth (head-concentration curve, predicted
          hit rate vs capacity) plus per-owner routed load, imbalance
          and straggler stats;
        - ``per_shard`` — each owner engine's own report (owner-side
          cache outcomes, tier attribution);
        - ``shards_merged`` — `WorkloadMonitor.merge_all` over the owner
          monitors in sorted-host order: the multi-process deployment
          shape, where no single router sees every seed and the fleet
          view IS the merge (order-independent by construction — pinned
          in tests/test_skew.py). NOT router + owners: the router
          already counted every seed the owners saw, and summing the two
          would double-count.
        """
        if self.workload is None:
            raise ValueError(
                "workload telemetry is off — pass "
                "DistServeConfig(workload=WorkloadConfig(...))"
            )
        owner_monitors = [
            self.engines[h].workload
            for h in sorted(self.engines)
            if self.engines[h].workload is not None
        ]
        out: Dict[str, object] = {
            "router": self.workload.skew_report(capacities=capacities),
            "per_shard": {
                str(h): self.engines[h].workload.skew_report(
                    capacities=capacities
                )
                for h in sorted(self.engines)
                if self.engines[h].workload is not None
            },
        }
        if owner_monitors:
            out["shards_merged"] = WorkloadMonitor.merge_all(
                owner_monitors
            ).skew_report(capacities=capacities)
        return out

    def export_chrome_trace(self, path: str, extra_sources: Sequence = (),
                            metadata: Optional[Dict[str, object]] = None,
                            ) -> Dict[str, object]:
        """One Perfetto-loadable timeline for the fleet: router spans +
        journal, every owner engine's spans + journal (sorted hosts), and —
        when `comm.record_exchange_spans` installed a recorder — the wire
        legs, all on the shared monotonic clock."""
        sources: List = [("router.spans", self.stats.spans)]
        if self.journal.enabled:
            sources.append(("router.journal", self.journal))
        if self.workload is not None and self.workload.counters is not None:
            sources.append(("router.workload", self.workload.counters))
        for h in sorted(self.engines):
            eng = self.engines[h]
            sources.append((f"owner{h}.spans", eng.stats.spans))
            if eng.journal.enabled:
                sources.append((f"owner{h}.journal", eng.journal))
            if eng.workload is not None and eng.workload.counters is not None:
                sources.append((f"owner{h}.workload", eng.workload.counters))
        rec = comm_mod.EXCHANGE_SPANS
        if rec is not None and len(rec):
            sources.append(("comm.exchange", rec))
        if self._commit_samples:
            # round-24 counter lane: the fleet graph-version staircase +
            # per-commit stall, rendered as ph:"C" tracks
            from .engine import _CommitCounterSource

            sources.append(
                ("router.commits", _CommitCounterSource(self._commit_samples))
            )
        sources.extend(extra_sources)
        return _export_chrome_trace(path, sources, metadata)

    def start(self) -> "DistServeEngine":
        if self._running:
            return self
        self._running = True
        self._draining = False  # re-arm migrations after a stop()
        self._threads = [
            threading.Thread(
                target=self._poll_loop,
                name=f"quiver-dist-serve-flusher-{i}",
                daemon=True,
            )
            for i in range(self.config.max_in_flight)
        ]
        if self.config.tier_adapt_every_s > 0 and any(
            e._tier_feature is not None and e.workload is not None
            for e in self.engines.values()
        ):
            self._threads.append(
                threading.Thread(
                    target=self._tier_loop,
                    name="quiver-dist-serve-tiers",
                    daemon=True,
                )
            )
        # round-16 background policies: the drift-gated replica refresh
        # (the r15 remaining-leverage note) and the imbalance-gated
        # rebalance — both fenced inside their passes, both surviving
        # failures as error counters (the tier-daemon contract)
        if (self.config.replica_refresh_every_s > 0
                and self.config.replicate_top_k > 0
                and self.workload is not None
                and self._replica_materials is not None):
            self._threads.append(
                threading.Thread(
                    target=lambda: self._policy_loop(
                        self.config.replica_refresh_every_s,
                        self._replica_refresh_pass,
                        "replica_refresh_errors",
                    ),
                    name="quiver-dist-serve-replica-refresh",
                    daemon=True,
                )
            )
        if (self.config.rebalance_every_s > 0
                and self.workload is not None
                and self._replica_materials is not None
                and self.exchange_mode == "host"
                and self.config.feature_residency == "closure"):
            self._threads.append(
                threading.Thread(
                    target=lambda: self._policy_loop(
                        self.config.rebalance_every_s,
                        self.maybe_rebalance,
                        "rebalance_errors",
                    ),
                    name="quiver-dist-serve-rebalance",
                    daemon=True,
                )
            )
        for t in self._threads:
            t.start()
        return self

    def _tier_loop(self) -> None:
        from ..tiers import tier_daemon_loop

        tier_daemon_loop(self)

    def stop(self, drain: bool = True) -> None:
        """Stop the pollers and retire queued work, BOUNDED by
        ``config.drain_deadline_s`` (round 15): a poller or owner that
        died mid-flush must not hang the caller. Work not retired by the
        deadline resolves with `serve.engine.DrainTimeout` and is counted
        in ``stats.undrained`` — in the snapshot, never silently
        dropped.

        An OPEN migration range (round 16) is settled FIRST, outside the
        drain budget: ``_draining`` halts rebalance loops between
        batches, and taking the migration lock waits for the in-flight
        batch to commit or roll back — a range handoff is atomic, so
        after the wait every seed has exactly one owner. Only then does
        the drain deadline start counting. A half-landed range abandoned
        to a deadline would strand its seeds ownerless; completing it
        can exceed the deadline, and that is the correct trade."""
        self._running = False
        self._draining = True
        try:
            # settle the open range before any deadline starts: batches
            # are atomic under this lock, and rebalance loops check
            # _draining between batches
            with self._migration_lock:
                pass
            # one deadline covers poller joins too (a poller wedged
            # mid-flush must not defeat the bound — see ServeEngine.stop)
            deadline = self._clock() + self.config.drain_deadline_s
            for t in self._threads:
                t.join(timeout=max(deadline - self._clock(), 0.05))
            self._threads = []
            if drain:
                while self._drainable() and self._clock() < deadline:
                    try:
                        self.flush()
                    except Exception:
                        pass  # the failing flush resolved its own waiters
            with self._fence:
                while self._inflight_flushes and self._clock() < deadline:
                    self._fence.wait(timeout=0.05)
            abandon_undrained(self, drained=drain)
            # owner engines run un-started in dist mode (the router
            # drives them synchronously), so their staged prefetch rows
            # must be cancelled here — futures observed, no worker leaks
            for eng in self.engines.values():
                eng._cancel_prefetch()
        finally:
            # _draining stays TRUE after stop: a rebalance loop still
            # holding batches must keep halting even though stop already
            # returned (it only checks the flag between batches, so
            # resetting here would let it resume flipping ownership on
            # an engine the caller believes is quiesced). start() is the
            # explicit path back to a migrating engine.
            pass

    def _poll_loop(self) -> None:
        while self._running:
            try:
                self.pump()
            except Exception:
                # whole-flush infrastructure errors only (round-15
                # contract: owner failures are per-request and never
                # raise out of flush); the failing flush already resolved
                # its waiters with the error — keep serving
                pass
            time.sleep(self.config.flush_poll_ms / 1e3)

    def __enter__(self) -> "DistServeEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def replay_shard_oracle(
    dist: DistServeEngine,
    model,
    params,
    full_sampler_factory: Callable[[], object],
    full_feature,
) -> Dict[int, np.ndarray]:
    """THE parity oracle: replay every shard engine's dispatch log through
    a FRESH sampler over the FULL graph (`full_sampler_factory` must birth
    it exactly like the shard samplers — same seed — so its key stream
    matches) and the offline `inference.batch_logits` path over the full
    feature table. Returns {node_id: logits row} for the first computation
    of each node per shard.

    That this oracle uses the FULL topology + FULL features is the point:
    it proves a shard served from 1/H of each table produced logits
    bit-identical to single-host offline eval. Shard engines must have
    been built with ``record_dispatches=True`` (`DistServeConfig` default
    shard config inherits the router's flag)."""
    from ..inference import _cached_apply, batch_logits

    apply = _cached_apply(model)
    served: Dict[int, np.ndarray] = {}
    for h in sorted(dist.engines):
        sampler = full_sampler_factory()
        for padded, nvalid in dist.engines[h].dispatch_log:
            logits = np.asarray(
                batch_logits(apply, params, sampler, full_feature, padded)
            )
            for i in range(nvalid):
                served.setdefault(int(padded[i]), logits[i])
    return served


def replay_fleet_oracle(
    dist: DistServeEngine,
    model,
    params,
    full_sampler_factory: Callable[[], object],
    full_feature,
    graph_version: Optional[int] = None,
) -> Dict[int, List[np.ndarray]]:
    """`replay_shard_oracle` extended over the WHOLE fleet: owners + the
    hot-set replica + the full-graph fallback + every engine RETIRED by a
    replica refresh, a range handoff, or a shrink (round 16: the oracle
    understands ownership epochs — an epoch changes which engine computes
    a seed, and each epoch's engine vouches for its own dispatch log).
    Each engine's log replays through a fresh FULL-graph sampler and the
    offline `batch_logits` path, collecting EVERY computation of every
    node (not just the first — a cache invalidation, e.g. a replica
    refresh or a migrated range, can legitimately recompute a node under
    a later key draw).

    Returns {node_id: [candidate rows]}. Under hedged/failover dispatch a
    node may be computed by more than one engine over a run (its owner
    before a fault, the fallback after) — a served row is CORRECT iff it
    bit-matches one candidate, which is exactly the fault-parity
    acceptance the probe and tests/test_faults.py assert: faults and
    failovers change WHO computes, never change any completed bit away
    from an offline full-graph replay.

    Round 24 — epoch-aware replay: with ``graph_version=v`` set,
    ``full_sampler_factory`` must birth a sampler over the graph AS OF
    fleet epoch ``v``; every engine's WHOLE log still replays through it
    (the key stream must advance exactly as the live run's did), but
    only rows whose aligned ``dispatch_graph_versions`` stamp equals
    ``v`` are collected. Under zero-stall commits a run's log spans
    epochs — each completed row is bit-equal to the oracle of the epoch
    it SEALED against, which is exactly what the per-epoch sweep
    (one call per version, candidates unioned) asserts."""
    from ..inference import _cached_apply, batch_logits

    apply = _cached_apply(model)
    engines: Dict[object, ServeEngine] = dict(dist.engines)
    if dist.replica is not None:
        engines["replica"] = dist.replica.engine
    for i, retired in enumerate(dist._retired_replicas):
        engines[f"replica_retired_{i}"] = retired
    # round-16 ownership epochs: owner engines replaced by a range
    # handoff (or removed by a shrink) served real traffic under earlier
    # epochs — their dispatch logs are candidates exactly like a live
    # owner's. Every shard sampler (any epoch) is born with the same
    # seed, so one fresh full-graph sampler per engine replays it.
    for i, retired in enumerate(dist._retired_engines):
        engines[f"owner_retired_{i}"] = retired
    if dist.fallback is not None:
        engines["fallback"] = dist.fallback
    served: Dict[int, List[np.ndarray]] = {}
    for h in sorted(engines, key=str):
        sampler = full_sampler_factory()
        eng = engines[h]
        gvs = getattr(eng, "dispatch_graph_versions", None)
        for ix, (padded, nvalid) in enumerate(eng.dispatch_log):
            # the replay ALWAYS computes (each batch advances the
            # sampler's key stream exactly like the live dispatch did);
            # the epoch filter only gates collection
            logits = np.asarray(
                batch_logits(apply, params, sampler, full_feature, padded)
            )
            if graph_version is not None and (
                    gvs is None or ix >= len(gvs)
                    or gvs[ix] != graph_version):
                continue
            for i in range(nvalid):
                served.setdefault(int(padded[i]), []).append(logits[i])
    return served

"""Params-versioned embedding cache for the online serving engine.

Serving traffic is skewed — a Zipf-0.99 trace sends >50% of requests to a
few percent of nodes (scripts/serve_probe.py measures it) — so the cheapest
"device work" is the dispatch that never happens: repeat requests for a hot
node are answered straight from host memory. Correctness hinges on the
cache never outliving the weights that produced its entries, hence every
entry is keyed by ``(node_id, params_version)`` and the engine bumps the
version (and calls :meth:`EmbeddingCache.invalidate`) on every weight
update. A stale-versioned entry is treated as a miss and dropped on touch,
so even a racing insert from an in-flight flush of the previous version can
never be served.

Note the semantics the engine documents: a served result may be CACHE-AGED
— computed any time since the current ``params_version`` was installed —
but never crosses a version boundary.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Hashable, Optional, Set

import numpy as np

from ..trace import HitRateCounter


def _key_node(key: Hashable) -> Hashable:
    """The node element of a cache key — composite temporal keys
    (``(node, t_bucket)`` tuples) index on their first element, plain
    int keys on themselves."""
    return key[0] if isinstance(key, tuple) else key


class EmbeddingCache:
    """LRU of computed embeddings/logits keyed by ``(node_id,
    params_version)``.

    One entry per node id: a put under a newer version overwrites the
    node's older entry (the old value could never be served again anyway).
    ``capacity`` counts entries (rows), not bytes — the engine sizes it as
    ``cache_entries``. Thread-safe; hit/miss/eviction counters live in
    ``self.counters`` (:class:`quiver_tpu.trace.HitRateCounter`).
    """

    def __init__(self, capacity: int, counters: Optional[HitRateCounter] = None):
        if capacity < 0:
            raise ValueError("capacity must be >= 0")
        self.capacity = int(capacity)
        self.counters = counters if counters is not None else HitRateCounter()
        self.invalidations = 0
        # observe-only workload tap (round 13): when the owning engine
        # attaches its WorkloadMonitor here, every get() outcome feeds
        # monitor.observe_cache(node, hit) — the cache half of the access
        # sketch's evidence. Never read by the cache itself.
        self.workload = None
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Hashable, tuple]" = OrderedDict()
        # True once any composite (tuple) key was inserted (guarded by
        # _lock; never reset — a temporal engine stays temporal)
        self._tuple_keys = False
        # per-node resident-key index (round 24): node -> the set of
        # full keys currently resident for it. Makes `invalidate_nodes`
        # O(touched keys) instead of O(resident) on composite-keyed
        # caches; maintained at every insert/delete/evict under _lock
        self._node_index: Dict[Hashable, Set[Hashable]] = {}
        # zero-stall commit support: per-node graph-version FLOORS. A
        # put stamped with a graph version below its node's floor is
        # silently dropped — that is the writeback gate that replaces
        # the round-17 drain: an old-epoch in-flight flush resolving
        # AFTER a commit can no longer re-insert a stale row. Entries
        # carry their gv stamp; `raise_floor` both sets the floor and
        # drops already-resident below-floor entries eagerly.
        self._floor: Dict[Hashable, int] = {}

    def __len__(self) -> int:
        return len(self._entries)

    # -- node-index maintenance (caller holds _lock) -------------------
    def _index_add(self, key: Hashable) -> None:
        self._node_index.setdefault(_key_node(key), set()).add(key)

    def _index_drop(self, key: Hashable) -> None:
        node = _key_node(key)
        s = self._node_index.get(node)
        if s is not None:
            s.discard(key)
            if not s:
                del self._node_index[node]

    def get(self, node_id: Hashable, version: int) -> Optional[np.ndarray]:
        """Value for ``node_id`` at exactly ``version``, else None. A hit
        refreshes LRU recency; a stale-versioned entry counts as a miss AND
        an eviction (it is dropped on touch)."""
        wl = self.workload
        with self._lock:
            ent = self._entries.get(node_id)
            if ent is None:
                self.counters.miss()
                if wl is not None:
                    wl.observe_cache(node_id, False)
                return None
            ver, value, gv = ent
            if (ver != version
                    or gv < self._floor.get(_key_node(node_id), 0)):
                del self._entries[node_id]
                self._index_drop(node_id)
                self.counters.evict()
                self.counters.miss()
                if wl is not None:
                    wl.observe_cache(node_id, False)
                return None
            self._entries.move_to_end(node_id)
            self.counters.hit()
            if wl is not None:
                wl.observe_cache(node_id, True)
            return value

    def get_many(self, node_ids, version: int) -> list:
        """Batch :meth:`get` (round 20): one lock hold for the whole
        block, per-key outcomes/LRU touches identical to N scalar gets
        in the same order, counters moved in bulk. When the cache is
        EMPTY and untapped (the ``cache_entries=0`` serving config, or
        any cache before its first resolve) the block short-circuits to
        a single miss count — the vectorized probe the batch submit
        fast path rides."""
        out = [None] * len(node_ids)
        wl = self.workload
        hits = misses = evictions = 0
        with self._lock:
            d = self._entries
            if not d and wl is None:
                self.counters.miss(len(node_ids))
                return out
            floors = self._floor
            for ix, node_id in enumerate(node_ids):
                ent = d.get(node_id)
                if ent is None:
                    misses += 1
                    if wl is not None:
                        wl.observe_cache(node_id, False)
                    continue
                ver, value, gv = ent
                if (ver != version
                        or (floors and gv < floors.get(
                            _key_node(node_id), 0))):
                    del d[node_id]
                    self._index_drop(node_id)
                    evictions += 1
                    misses += 1
                    if wl is not None:
                        wl.observe_cache(node_id, False)
                    continue
                d.move_to_end(node_id)
                hits += 1
                if wl is not None:
                    wl.observe_cache(node_id, True)
                out[ix] = value
        if hits:
            self.counters.hit(hits)
        if misses:
            self.counters.miss(misses)
        if evictions:
            self.counters.evict(evictions)
        return out

    def put(self, node_id: Hashable, version: int, value: np.ndarray,
            gv: int = 0) -> None:
        """Insert at ``(params) version`` stamped with graph version
        ``gv``. A put below its node's graph-version FLOOR is silently
        dropped — the zero-stall writeback gate (an old-epoch flush
        resolving after a commit must not re-insert the stale row);
        fenced engines never raise floors, so the default ``gv=0``
        always lands."""
        if self.capacity == 0:
            return
        with self._lock:
            if isinstance(node_id, tuple):
                self._tuple_keys = True
            if (self._floor
                    and gv < self._floor.get(_key_node(node_id), 0)):
                return
            if node_id in self._entries:
                del self._entries[node_id]
            else:
                self._index_add(node_id)
            self._entries[node_id] = (version, value, gv)
            while len(self._entries) > self.capacity:
                k, _ = self._entries.popitem(last=False)
                self._index_drop(k)
                self.counters.evict()

    def put_many(self, node_ids, version: int, values,
                 gv: int = 0) -> None:
        """Batch :meth:`put` (round 22) — `get_many`'s writeback twin:
        ONE lock hold and ONE version for the whole batch (the resolve
        path's update_params fence guarantees every row in a flush was
        computed under the live version, so the version check happens
        once per batch, not per key), with eviction counters moved in
        bulk after the lock drops. The per-key mechanics — delete-then-
        insert LRU placement and the eviction loop INSIDE the per-key
        pass — are exactly N scalar puts in order, so resident entries,
        LRU order AND eviction counts are bit-identical (an early key
        evicted by a later one and then re-inserted must count both
        evictions, which a deferred one-shot trim would miss)."""
        if self.capacity == 0 or not len(node_ids):
            return
        version = int(version)
        evictions = 0
        with self._lock:
            d = self._entries
            cap = self.capacity
            floors = self._floor
            for k, v in zip(node_ids, values):
                if isinstance(k, tuple):
                    self._tuple_keys = True
                if floors and gv < floors.get(_key_node(k), 0):
                    continue  # below-floor writeback: see put()
                if k in d:
                    del d[k]
                else:
                    self._index_add(k)
                d[k] = (version, v, gv)
                while len(d) > cap:
                    ek, _ = d.popitem(last=False)
                    self._index_drop(ek)
                    evictions += 1
        if evictions:
            self.counters.evict(evictions)

    def entry_version(self, node_id: Hashable) -> Optional[int]:
        """The params version a node's entry was computed under, or None
        when the node has no entry — an INSPECTION helper (no LRU touch,
        no counter movement): the round-15 replication tests pin
        "one entry per node, whichever engine computed it" and "refresh
        invalidates exactly the refreshed keys" through this."""
        with self._lock:
            ent = self._entries.get(node_id)
            return None if ent is None else ent[0]

    def keys(self):
        """Resident node ids, LRU order (coldest first) — inspection
        only, same no-side-effect rule as `entry_version`."""
        with self._lock:
            return list(self._entries)

    def invalidate(self) -> int:
        """Drop every entry (the engine calls this on weight update).
        Returns how many entries were dropped."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self._node_index.clear()
            self.invalidations += 1
            return n

    def invalidate_nodes(self, node_ids) -> int:
        """Drop every entry belonging to the given NODES, whatever its
        full key shape (round 19): plain int keys match directly;
        composite keys — the temporal workload's ``(node, t_bucket)``
        tuples — match on their node element. This is the graph-delta
        invalidation surface: a changed row staleness-taints a seed's
        cached result at EVERY query time (any cached t could have
        sampled the changed row's past), so all its t-entries drop
        together. Cost: O(touched keys) via the per-node resident-key
        index (round 24 — previously composite-keyed caches paid an
        O(resident) scan per commit). Exact-key paths (placement moves,
        replica refreshes) keep `invalidate_keys`. Returns entries
        dropped."""
        nodes = {int(x) for x in node_ids}
        if not nodes:
            return 0
        n = 0
        with self._lock:
            for node in nodes:
                keys = self._node_index.pop(node, None)
                if not keys:
                    continue
                for k in keys:
                    del self._entries[k]
                    n += 1
            if n:
                self.invalidations += 1
        return n

    def raise_floor(self, node_ids, floor: int) -> int:
        """Zero-stall invalidation (round 24): for each given node, set
        its graph-version floor to ``floor`` and eagerly drop resident
        entries stamped BELOW it (entries written by flushes already
        sealed at the new version survive). From then on the floor gates
        late writebacks from old-epoch in-flight flushes — the lazy
        miss-at-new-version semantics the drain used to provide
        synchronously. Returns entries dropped."""
        floor = int(floor)
        n = 0
        with self._lock:
            for node in node_ids:
                node = int(node)
                if self._floor.get(node, 0) < floor:
                    self._floor[node] = floor
                keys = self._node_index.get(node)
                if not keys:
                    continue
                for k in list(keys):
                    if self._entries[k][2] < floor:
                        del self._entries[k]
                        keys.discard(k)
                        n += 1
                if not keys:
                    del self._node_index[node]
            if n:
                self.invalidations += 1
        return n

    def graph_floor(self, node_id: Hashable) -> int:
        """A node's current graph-version floor (0 when never raised) —
        inspection only."""
        with self._lock:
            return self._floor.get(int(node_id), 0)

    def entry_graph_version(self, node_id: Hashable) -> Optional[int]:
        """The graph version an entry's row was computed under, or None
        — inspection only, `entry_version`'s graph-axis twin."""
        with self._lock:
            ent = self._entries.get(node_id)
            return None if ent is None else ent[2]

    def invalidate_keys(self, node_ids) -> int:
        """Drop the entries for specific nodes (round 14: a placement
        batch invalidates the MOVED rows only — placement is bit-neutral
        for the logits, but the conservative drop keeps the cache's
        contents arguable from the current placement alone). Returns how
        many entries were actually dropped."""
        n = 0
        with self._lock:
            for k in node_ids:
                if self._entries.pop(k, None) is not None:
                    self._index_drop(k)
                    n += 1
            if n:
                self.invalidations += 1
        return n

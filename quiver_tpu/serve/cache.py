"""Params-versioned embedding cache for the online serving engine.

Serving traffic is skewed — a Zipf-0.99 trace sends >50% of requests to a
few percent of nodes (scripts/serve_probe.py measures it) — so the cheapest
"device work" is the dispatch that never happens: repeat requests for a hot
node are answered straight from host memory. Correctness hinges on the
cache never outliving the weights that produced its entries, hence every
entry is keyed by ``(node_id, params_version)`` and the engine bumps the
version (and calls :meth:`EmbeddingCache.invalidate`) on every weight
update. A stale-versioned entry is treated as a miss and dropped on touch,
so even a racing insert from an in-flight flush of the previous version can
never be served.

Note the semantics the engine documents: a served result may be CACHE-AGED
— computed any time since the current ``params_version`` was installed —
but never crosses a version boundary.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Hashable, Optional

import numpy as np

from ..trace import HitRateCounter


class EmbeddingCache:
    """LRU of computed embeddings/logits keyed by ``(node_id,
    params_version)``.

    One entry per node id: a put under a newer version overwrites the
    node's older entry (the old value could never be served again anyway).
    ``capacity`` counts entries (rows), not bytes — the engine sizes it as
    ``cache_entries``. Thread-safe; hit/miss/eviction counters live in
    ``self.counters`` (:class:`quiver_tpu.trace.HitRateCounter`).
    """

    def __init__(self, capacity: int, counters: Optional[HitRateCounter] = None):
        if capacity < 0:
            raise ValueError("capacity must be >= 0")
        self.capacity = int(capacity)
        self.counters = counters if counters is not None else HitRateCounter()
        self.invalidations = 0
        # observe-only workload tap (round 13): when the owning engine
        # attaches its WorkloadMonitor here, every get() outcome feeds
        # monitor.observe_cache(node, hit) — the cache half of the access
        # sketch's evidence. Never read by the cache itself.
        self.workload = None
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Hashable, tuple]" = OrderedDict()
        # True once any composite (tuple) key was inserted — the flag
        # that lets `invalidate_nodes` skip its full-cache scan on
        # plain int-keyed engines (guarded by _lock; never reset — a
        # temporal engine stays temporal)
        self._tuple_keys = False

    def __len__(self) -> int:
        return len(self._entries)

    def get(self, node_id: Hashable, version: int) -> Optional[np.ndarray]:
        """Value for ``node_id`` at exactly ``version``, else None. A hit
        refreshes LRU recency; a stale-versioned entry counts as a miss AND
        an eviction (it is dropped on touch)."""
        wl = self.workload
        with self._lock:
            ent = self._entries.get(node_id)
            if ent is None:
                self.counters.miss()
                if wl is not None:
                    wl.observe_cache(node_id, False)
                return None
            ver, value = ent
            if ver != version:
                del self._entries[node_id]
                self.counters.evict()
                self.counters.miss()
                if wl is not None:
                    wl.observe_cache(node_id, False)
                return None
            self._entries.move_to_end(node_id)
            self.counters.hit()
            if wl is not None:
                wl.observe_cache(node_id, True)
            return value

    def get_many(self, node_ids, version: int) -> list:
        """Batch :meth:`get` (round 20): one lock hold for the whole
        block, per-key outcomes/LRU touches identical to N scalar gets
        in the same order, counters moved in bulk. When the cache is
        EMPTY and untapped (the ``cache_entries=0`` serving config, or
        any cache before its first resolve) the block short-circuits to
        a single miss count — the vectorized probe the batch submit
        fast path rides."""
        out = [None] * len(node_ids)
        wl = self.workload
        hits = misses = evictions = 0
        with self._lock:
            d = self._entries
            if not d and wl is None:
                self.counters.miss(len(node_ids))
                return out
            for ix, node_id in enumerate(node_ids):
                ent = d.get(node_id)
                if ent is None:
                    misses += 1
                    if wl is not None:
                        wl.observe_cache(node_id, False)
                    continue
                ver, value = ent
                if ver != version:
                    del d[node_id]
                    evictions += 1
                    misses += 1
                    if wl is not None:
                        wl.observe_cache(node_id, False)
                    continue
                d.move_to_end(node_id)
                hits += 1
                if wl is not None:
                    wl.observe_cache(node_id, True)
                out[ix] = value
        if hits:
            self.counters.hit(hits)
        if misses:
            self.counters.miss(misses)
        if evictions:
            self.counters.evict(evictions)
        return out

    def put(self, node_id: Hashable, version: int, value: np.ndarray) -> None:
        if self.capacity == 0:
            return
        with self._lock:
            if isinstance(node_id, tuple):
                self._tuple_keys = True
            if node_id in self._entries:
                del self._entries[node_id]
            self._entries[node_id] = (version, value)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self.counters.evict()

    def put_many(self, node_ids, version: int, values) -> None:
        """Batch :meth:`put` (round 22) — `get_many`'s writeback twin:
        ONE lock hold and ONE version for the whole batch (the resolve
        path's update_params fence guarantees every row in a flush was
        computed under the live version, so the version check happens
        once per batch, not per key), with eviction counters moved in
        bulk after the lock drops. The per-key mechanics — delete-then-
        insert LRU placement and the eviction loop INSIDE the per-key
        pass — are exactly N scalar puts in order, so resident entries,
        LRU order AND eviction counts are bit-identical (an early key
        evicted by a later one and then re-inserted must count both
        evictions, which a deferred one-shot trim would miss)."""
        if self.capacity == 0 or not len(node_ids):
            return
        version = int(version)
        evictions = 0
        with self._lock:
            d = self._entries
            cap = self.capacity
            for k, v in zip(node_ids, values):
                if isinstance(k, tuple):
                    self._tuple_keys = True
                if k in d:
                    del d[k]
                d[k] = (version, v)
                while len(d) > cap:
                    d.popitem(last=False)
                    evictions += 1
        if evictions:
            self.counters.evict(evictions)

    def entry_version(self, node_id: Hashable) -> Optional[int]:
        """The params version a node's entry was computed under, or None
        when the node has no entry — an INSPECTION helper (no LRU touch,
        no counter movement): the round-15 replication tests pin
        "one entry per node, whichever engine computed it" and "refresh
        invalidates exactly the refreshed keys" through this."""
        with self._lock:
            ent = self._entries.get(node_id)
            return None if ent is None else ent[0]

    def keys(self):
        """Resident node ids, LRU order (coldest first) — inspection
        only, same no-side-effect rule as `entry_version`."""
        with self._lock:
            return list(self._entries)

    def invalidate(self) -> int:
        """Drop every entry (the engine calls this on weight update).
        Returns how many entries were dropped."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self.invalidations += 1
            return n

    def invalidate_nodes(self, node_ids) -> int:
        """Drop every entry belonging to the given NODES, whatever its
        full key shape (round 19): plain int keys match directly;
        composite keys — the temporal workload's ``(node, t_bucket)``
        tuples — match on their node element. This is the graph-delta
        invalidation surface: a changed row staleness-taints a seed's
        cached result at EVERY query time (any cached t could have
        sampled the changed row's past), so all its t-entries drop
        together. Cost: O(keys) exact deletes on a plain int-keyed cache
        (identical to `invalidate_keys` — a round-17 streaming
        deployment pays nothing new); the O(resident) scan runs only
        when a composite key was ever inserted (temporal engines), which
        is commit-grain work there. Exact-key paths (placement moves,
        replica refreshes) keep `invalidate_keys`. Returns entries
        dropped."""
        nodes = {int(x) for x in node_ids}
        if not nodes:
            return 0
        n = 0
        with self._lock:
            for node in nodes:
                if self._entries.pop(node, None) is not None:
                    n += 1
            if self._tuple_keys:
                for k in list(self._entries):
                    if isinstance(k, tuple) and k[0] in nodes:
                        del self._entries[k]
                        n += 1
            if n:
                self.invalidations += 1
        return n

    def invalidate_keys(self, node_ids) -> int:
        """Drop the entries for specific nodes (round 14: a placement
        batch invalidates the MOVED rows only — placement is bit-neutral
        for the logits, but the conservative drop keeps the cache's
        contents arguable from the current placement alone). Returns how
        many entries were actually dropped."""
        n = 0
        with self._lock:
            for k in node_ids:
                if self._entries.pop(k, None) is not None:
                    n += 1
            if n:
                self.invalidations += 1
        return n

"""quiver_tpu.serve — online inference engine.

Turns individual node-prediction requests into efficient fixed-shape device
work: dynamic micro-batching (bucketed pad-to-fixed shapes, one compiled
program per bucket, pre-traceable via `ServeEngine.warmup`), cross-request
coalescing (identical seeds within a flush window share one
sample/gather/forward), a params-versioned embedding cache (hot nodes
served from host memory; `update_params` fences in-flight work, then
invalidates), and pipelined dispatch (flushes run as assemble -> dispatch
-> resolve stages under a bounded `max_in_flight` window; the sampler key
stream and replay log stay deterministic in dispatch-index order). See
`engine.py` for the design and docs/api.md "Online serving" for the
contract.

`dist.py` scales the engine past one host: `DistServeEngine` routes
requests by seed ownership over the `HostRankTable` exchange (seed ids
out, logits back) to per-owner `ServeEngine`s serving from ~1/H topology
+ feature shards — docs/api.md "Distributed serving".

Round 15 makes the fleet production-shaped (docs/api.md "Fleet serving"):
hot-set replication (`DistServeEngine.refresh_replicas` mirrors the Zipf
head locally so head traffic never crosses the exchange), hedged/failover
dispatch (per-owner deadlines, re-route to replica/full-graph fallback,
flush-indexed ejection backoff, per-request error isolation), per-tenant
admission (`submit(node, tenant=)`: weighted flush quotas, deterministic
queue-depth shedding, per-tenant latency tails), and the deterministic
`faults.FaultInjector` that proves all of it replayable.

Round 17 makes the GRAPH live (docs/api.md "Streaming graphs"):
`ServeEngine.update_graph(delta)` / `DistServeEngine.update_graph(delta)`
commit edge arrivals behind the `update_params` fence — in-place pad-lane
tile writes + batched device tile swaps over a bound
`quiver_tpu.stream.StreamingTiledGraph` (gather-only sampling untouched,
sealed AOT executables rebind arguments, never recompile), with the three
consumers the round-10 fence never had: closure-touched cache
invalidation at every grain, stale hot-set replicas dropped + rebuilt,
and an immediate tier re-placement pass for delta-hot subgraphs. Owner
shards extend their halo closures INCREMENTALLY (union-homomorphic BFS
from the arrivals only; rows entering a closure install into reserved
tile/feature capacity). Frozen-graph replay == delta-replay with an empty
delta, and an appended edge is visible to the next sample after the
commit returns. `trace_gen.delta_interleaved_trace` drives churn
deterministically.

Round 16 makes the fleet ELASTIC (docs/api.md "Elastic fleet"):
`DistServeEngine.scale(hosts=H±k)` / `rebalance()` migrate seed
ownership one bounded contiguous range at a time — the range's
halo-closure shard + feature rows build outside any fence while the old
owner keeps serving, then a per-range fence flips routing, bumps the
ownership epoch, and invalidates exactly the migrated seeds' cached
state. `replay_fleet_oracle` understands ownership epochs (retired
engines vouch for the rows they served), telemetry drives the triggers
(`maybe_rebalance` off `OwnerLoadStats` imbalance, the drift-gated
background replica refresh, `scaling.fleet_table` pricing
add-a-host vs replicate-the-head), owner engines apply tenant quotas
end-to-end, and `FaultSpec(at="migration")` proves mid-migration kills
roll the in-flight range back or forward deterministically.
"""

from .cache import EmbeddingCache
from .dist import (
    ClosureFeature,
    DistServeConfig,
    DistServeEngine,
    DistServeStats,
    OwnerTimeout,
    REPLICA_HOST,
    closure_masks,
    contiguous_partition,
    plan_migration_ranges,
    replay_fleet_oracle,
    replay_shard_oracle,
    shard_from_mask,
    shard_topology_by_owner,
    shard_topology_for_seeds,
)
from .engine import (
    DEFAULT_TENANT,
    DrainTimeout,
    ServeConfig,
    ServeEngine,
    ServeResult,
    ServeStats,
    ShedError,
    default_buckets,
)
from .faults import FaultInjector, FaultSpec, OwnerFault, OwnerKilled
from .trace_gen import (
    DeltaTrace,
    LPTrace,
    TemporalTrace,
    delta_interleaved_trace,
    lp_trace,
    poisson_arrivals,
    temporal_trace,
    trace_skew_stats,
    zipfian_trace,
)

__all__ = [
    "ClosureFeature",
    "DEFAULT_TENANT",
    "DeltaTrace",
    "LPTrace",
    "TemporalTrace",
    "delta_interleaved_trace",
    "lp_trace",
    "temporal_trace",
    "DistServeConfig",
    "DistServeEngine",
    "DistServeStats",
    "DrainTimeout",
    "EmbeddingCache",
    "FaultInjector",
    "FaultSpec",
    "OwnerFault",
    "OwnerKilled",
    "OwnerTimeout",
    "REPLICA_HOST",
    "ServeConfig",
    "ServeEngine",
    "ServeResult",
    "ServeStats",
    "ShedError",
    "closure_masks",
    "contiguous_partition",
    "default_buckets",
    "plan_migration_ranges",
    "poisson_arrivals",
    "replay_fleet_oracle",
    "replay_shard_oracle",
    "shard_from_mask",
    "shard_topology_by_owner",
    "shard_topology_for_seeds",
    "trace_skew_stats",
    "zipfian_trace",
]

"""quiver_tpu.serve — online inference engine.

Turns individual node-prediction requests into efficient fixed-shape device
work: dynamic micro-batching (bucketed pad-to-fixed shapes, one compiled
program per bucket, pre-traceable via `ServeEngine.warmup`), cross-request
coalescing (identical seeds within a flush window share one
sample/gather/forward), a params-versioned embedding cache (hot nodes
served from host memory; `update_params` fences in-flight work, then
invalidates), and pipelined dispatch (flushes run as assemble -> dispatch
-> resolve stages under a bounded `max_in_flight` window; the sampler key
stream and replay log stay deterministic in dispatch-index order). See
`engine.py` for the design and docs/api.md "Online serving" for the
contract.

`dist.py` scales the engine past one host: `DistServeEngine` routes
requests by seed ownership over the `HostRankTable` exchange (seed ids
out, logits back) to per-owner `ServeEngine`s serving from ~1/H topology
+ feature shards — docs/api.md "Distributed serving".
"""

from .cache import EmbeddingCache
from .dist import (
    ClosureFeature,
    DistServeConfig,
    DistServeEngine,
    DistServeStats,
    contiguous_partition,
    replay_shard_oracle,
    shard_topology_by_owner,
)
from .engine import (
    ServeConfig,
    ServeEngine,
    ServeResult,
    ServeStats,
    default_buckets,
)
from .trace_gen import poisson_arrivals, trace_skew_stats, zipfian_trace

__all__ = [
    "ClosureFeature",
    "DistServeConfig",
    "DistServeEngine",
    "DistServeStats",
    "EmbeddingCache",
    "ServeConfig",
    "ServeEngine",
    "ServeResult",
    "ServeStats",
    "contiguous_partition",
    "default_buckets",
    "poisson_arrivals",
    "replay_shard_oracle",
    "shard_topology_by_owner",
    "trace_skew_stats",
    "zipfian_trace",
]

"""quiver_tpu.serve — online inference engine.

Turns individual node-prediction requests into efficient fixed-shape device
work: dynamic micro-batching (bucketed pad-to-fixed shapes, one compiled
program per bucket), cross-request coalescing (identical seeds within a
flush window share one sample/gather/forward), and a params-versioned
embedding cache (hot nodes served from host memory; `update_params`
invalidates). See `engine.py` for the design and docs/api.md "Online
serving" for the contract.
"""

from .cache import EmbeddingCache
from .engine import (
    ServeConfig,
    ServeEngine,
    ServeResult,
    ServeStats,
    default_buckets,
)
from .trace_gen import poisson_arrivals, trace_skew_stats, zipfian_trace

__all__ = [
    "EmbeddingCache",
    "ServeConfig",
    "ServeEngine",
    "ServeResult",
    "ServeStats",
    "default_buckets",
    "poisson_arrivals",
    "trace_skew_stats",
    "zipfian_trace",
]

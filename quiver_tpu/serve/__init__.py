"""quiver_tpu.serve — online inference engine.

Turns individual node-prediction requests into efficient fixed-shape device
work: dynamic micro-batching (bucketed pad-to-fixed shapes, one compiled
program per bucket, pre-traceable via `ServeEngine.warmup`), cross-request
coalescing (identical seeds within a flush window share one
sample/gather/forward), a params-versioned embedding cache (hot nodes
served from host memory; `update_params` fences in-flight work, then
invalidates), and pipelined dispatch (flushes run as assemble -> dispatch
-> resolve stages under a bounded `max_in_flight` window; the sampler key
stream and replay log stay deterministic in dispatch-index order). See
`engine.py` for the design and docs/api.md "Online serving" for the
contract.

`dist.py` scales the engine past one host: `DistServeEngine` routes
requests by seed ownership over the `HostRankTable` exchange (seed ids
out, logits back) to per-owner `ServeEngine`s serving from ~1/H topology
+ feature shards — docs/api.md "Distributed serving".

Round 15 makes the fleet production-shaped (docs/api.md "Fleet serving"):
hot-set replication (`DistServeEngine.refresh_replicas` mirrors the Zipf
head locally so head traffic never crosses the exchange), hedged/failover
dispatch (per-owner deadlines, re-route to replica/full-graph fallback,
flush-indexed ejection backoff, per-request error isolation), per-tenant
admission (`submit(node, tenant=)`: weighted flush quotas, deterministic
queue-depth shedding, per-tenant latency tails), and the deterministic
`faults.FaultInjector` that proves all of it replayable.
"""

from .cache import EmbeddingCache
from .dist import (
    ClosureFeature,
    DistServeConfig,
    DistServeEngine,
    DistServeStats,
    OwnerTimeout,
    REPLICA_HOST,
    contiguous_partition,
    replay_fleet_oracle,
    replay_shard_oracle,
    shard_topology_by_owner,
    shard_topology_for_seeds,
)
from .engine import (
    DEFAULT_TENANT,
    DrainTimeout,
    ServeConfig,
    ServeEngine,
    ServeResult,
    ServeStats,
    ShedError,
    default_buckets,
)
from .faults import FaultInjector, FaultSpec, OwnerFault, OwnerKilled
from .trace_gen import poisson_arrivals, trace_skew_stats, zipfian_trace

__all__ = [
    "ClosureFeature",
    "DEFAULT_TENANT",
    "DistServeConfig",
    "DistServeEngine",
    "DistServeStats",
    "DrainTimeout",
    "EmbeddingCache",
    "FaultInjector",
    "FaultSpec",
    "OwnerFault",
    "OwnerKilled",
    "OwnerTimeout",
    "REPLICA_HOST",
    "ServeConfig",
    "ServeEngine",
    "ServeResult",
    "ServeStats",
    "ShedError",
    "contiguous_partition",
    "default_buckets",
    "poisson_arrivals",
    "replay_fleet_oracle",
    "replay_shard_oracle",
    "shard_topology_by_owner",
    "shard_topology_for_seeds",
    "trace_skew_stats",
    "zipfian_trace",
]

"""quiver_tpu — TPU-native graph-learning data engine.

Ground-up JAX/XLA/Pallas re-design of torch-quiver (reference public API:
srcs/python/quiver/__init__.py:2-17): GPU-class k-hop neighbor sampling over
CSR topology, a tiered feature cache (chip HBM -> ICI peers -> host DRAM ->
mmap disk), and multi-chip/multi-host scaling over ICI/DCN meshes.
"""

from .feature import DeviceConfig, DistFeature, Feature, PartitionInfo
from .shard_tensor import Offset, ShardTensor, ShardTensorConfig
from .utils import (
    CSRTopo,
    IciTopo,
    Topo,
    can_device_access_peer,
    init_p2p,
    p2pCliqueTopo,
    parse_size,
    reindex_by_config,
    reindex_feature,
    show_tensor_info,
)
from . import inference
from .partition import (
    load_quiver_feature_partition,
    partition_feature_without_replication,
    quiver_partition_feature,
)
from . import comm, obs, pyg, tiers, trace
from . import quant
from . import lifecycle
from . import serve
from . import stream
from . import workloads
from .lifecycle import CompactionPolicy, ProvisionPolicy, RetentionPolicy
from .stream import GraphDelta, StreamingAdjacency, StreamingTiledGraph
from .tiers import DiskShard, PlacementPlan, TierPlacement, TierStore
from .quant import QuantizedFeature
from .serve import DistServeConfig, DistServeEngine, ServeConfig, ServeEngine
from .comm import HostRankTable, NcclComm, TpuComm, getNcclId
from .pipeline import (
    AsyncReadPool,
    TieredBatch,
    TieredFeaturePipeline,
    TrainPipeline,
    make_tiered_train_step,
    tiered_lookup,
)

__version__ = "0.1.0"

__all__ = [
    "CSRTopo",
    "DeviceConfig",
    "DistFeature",
    "Feature",
    "HostRankTable",
    "IciTopo",
    "NcclComm",
    "TpuComm",
    "comm",
    "getNcclId",
    "obs",
    "trace",
    "Offset",
    "PartitionInfo",
    "ShardTensor",
    "ShardTensorConfig",
    "Topo",
    "can_device_access_peer",
    "init_p2p",
    "load_quiver_feature_partition",
    "p2pCliqueTopo",
    "parse_size",
    "partition_feature_without_replication",
    "pyg",
    "quant",
    "QuantizedFeature",
    "serve",
    "stream",
    "workloads",
    "lifecycle",
    "CompactionPolicy",
    "ProvisionPolicy",
    "RetentionPolicy",
    "GraphDelta",
    "StreamingAdjacency",
    "StreamingTiledGraph",
    "DistServeConfig",
    "DistServeEngine",
    "ServeConfig",
    "ServeEngine",
    "inference",
    "quiver_partition_feature",
    "reindex_by_config",
    "reindex_feature",
    "show_tensor_info",
    "AsyncReadPool",
    "DiskShard",
    "PlacementPlan",
    "TierPlacement",
    "TierStore",
    "tiers",
    "TieredBatch",
    "TieredFeaturePipeline",
    "TrainPipeline",
    "make_tiered_train_step",
    "tiered_lookup",
]

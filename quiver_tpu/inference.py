"""Evaluation / inference paths.

The reference evaluates two ways: layer-wise FULL-neighbor inference (the
`model.inference` loop of examples/multi_gpu/pyg/ogb-products/
dist_sampling_ogb_products_quiver.py:118-139, subgraph loader over all
nodes) and sampled eval with the training sampler. TPU equivalents:

- `sage_full_inference`: exact layered embeddings for ALL nodes. The
  full-neighbor mean aggregation is ONE edge-parallel pass over the CSR per
  layer (chunked `lax.fori_loop`, same trick as `ops.sample.neighbor_prob`)
  — no subgraph loader needed; XLA streams the gather/scatter chunks.
- `sampled_eval`: high-fanout sampled accuracy for any model (GraphSAGE or
  GAT — full-neighbor attention would need per-edge softmax passes; the
  reference evaluates GAT by sampling too, dist_sampling_reddit_gat.py).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax


@functools.partial(jax.jit, static_argnames=("edge_chunk",))
def full_mean_aggregate(
    indptr: jax.Array,
    indices: jax.Array,
    h: jax.Array,
    edge_chunk: int = 1 << 20,
) -> jax.Array:
    """Exact mean over ALL neighbors for every node: ``out[u] =
    mean_{v in N(u)} h[v]`` (zero where deg 0).

    Edge-parallel chunked segment-sum over the CSR — the dense-batch analog
    of `ops.sample.neighbor_prob`'s scalar pass; one traced chunk body
    regardless of graph size.
    """
    n = indptr.shape[0] - 1
    e = indices.shape[0]
    d = h.shape[1]
    out = jnp.zeros((n + 1, d), h.dtype)  # +1: out-of-range dump row
    if e == 0:
        return out[:n]
    chunk = min(edge_chunk, e)
    nchunks = -(-e // chunk)

    def body(c, out):
        start_u = c * chunk
        start = jnp.minimum(start_u, e - chunk)
        eidx = start + jnp.arange(chunk, dtype=indptr.dtype)
        fresh = eidx >= start_u
        src = jnp.searchsorted(indptr, eidx, side="right") - 1
        dst = lax.dynamic_slice(indices, (start,), (chunk,))
        rows = jnp.take(h, jnp.clip(dst, 0, h.shape[0] - 1), axis=0)
        rows = jnp.where(fresh[:, None], rows, 0)
        src = jnp.where(fresh, src, n)  # dump lane
        return out.at[src].add(rows, mode="drop")

    out = lax.fori_loop(0, nchunks, body, out)[:n]
    deg = (indptr[1:] - indptr[:-1]).astype(h.dtype)
    return out / jnp.maximum(deg, 1)[:, None]


def sage_full_inference(
    model,
    params,
    indptr: jax.Array,
    indices: jax.Array,
    x_all: jax.Array,
) -> jax.Array:
    """Layer-wise full-neighbor GraphSAGE inference over ALL nodes —
    the reference `SAGE.inference` semantics
    (dist_sampling_ogb_products_quiver.py:118-139) without a subgraph
    loader: per layer, one full-graph mean aggregation + the layer's dense
    projections, relu between layers (no dropout at eval).

    Works for the `models.GraphSAGE` flax module (reads its
    ``conv{i}/lin_l|lin_r`` params directly; GAT needs per-edge softmax —
    use `sampled_eval` there)."""
    p = params["params"] if "params" in params else params
    num_layers = model.num_layers
    h = jnp.asarray(x_all)
    for i in range(num_layers):
        layer = p[f"conv{i}"]
        agg = full_mean_aggregate(indptr, indices, h)
        out = agg @ layer["lin_l"]["kernel"]
        if "bias" in layer["lin_l"]:
            out = out + layer["lin_l"]["bias"]
        out = out + h @ layer["lin_r"]["kernel"]
        h = jax.nn.relu(out) if i != num_layers - 1 else out
    return h


@functools.lru_cache(maxsize=32)
def _cached_apply_hashable(model):
    return jax.jit(lambda p, x, adjs: model.apply(p, x, adjs))


def _cached_apply(model):
    """One jitted apply per model VALUE — a fresh jit per sampled_eval call
    would recompile an identical program every invocation.

    Value-keyed (flax modules are frozen dataclasses, hashable by field
    values: equal configs share one entry) and BOUNDED: the lru_cache holds
    at most 32 models + executables, so repeated model construction (e.g. a
    hyperparameter sweep) evicts old entries instead of growing without
    bound. Weak-keyed variants were rejected — a closure capturing the key
    pins it (no eviction), and a weakref proxy raises ReferenceError when a
    retrace outlives the first-seen equal model."""
    try:
        return _cached_apply_hashable(model)
    except TypeError:  # unhashable custom module: skip caching
        return jax.jit(lambda p, x, adjs: model.apply(p, x, adjs))


def pad_seed_batch(
    batch: np.ndarray, batch_size: int, out: Optional[np.ndarray] = None
) -> np.ndarray:
    """Pad a 1-D seed batch up to ``batch_size`` by repeating ``batch[-1]``
    (the convention every fixed-shape eval/serve path here uses — the
    duplicate rows are sliced off after the forward). Pass ``out`` to reuse
    one buffer across a loop instead of allocating per batch."""
    batch = np.asarray(batch)
    if batch.shape[0] == 0:
        raise ValueError("cannot pad an empty seed batch")
    if batch.shape[0] > batch_size:
        raise ValueError(f"batch of {batch.shape[0]} exceeds batch_size={batch_size}")
    if out is None or out.shape[0] != batch_size or out.dtype != batch.dtype:
        out = np.empty(batch_size, batch.dtype)
    out[: batch.shape[0]] = batch
    out[batch.shape[0] :] = batch[-1]
    return out


def lookup_features(feature, n_id, ids_out: Optional[np.ndarray] = None):
    """Feature rows for a sampled ``n_id`` — one helper for every consumer
    (``sampled_eval``, the serve engine): raw ``[N, D]`` numpy tables get the
    clip-and-take path (``ids_out`` reuses the clipped-id buffer across
    calls), quiver ``Feature``/``QuantizedFeature`` objects their tiered
    ``__getitem__``."""
    if isinstance(feature, np.ndarray):
        ids = np.asarray(n_id)
        if ids_out is not None and ids_out.shape == ids.shape:
            np.clip(ids, 0, feature.shape[0] - 1, out=ids_out)
            ids = ids_out
        else:
            ids = np.clip(ids, 0, feature.shape[0] - 1)
        return jnp.asarray(feature[ids])
    return feature[n_id]


def sample_batch(sampler, padded_batch):
    """Stage 1 of the fixed-shape eval step: draw the sampler's next key
    and dispatch the k-hop sample for ``padded_batch``. Split out of
    :func:`batch_logits` so the pipelined serve engine can consume the
    sampler's key stream in dispatch-index order (under its sequencing
    lock) while the forward of the PREVIOUS flush still runs."""
    return sampler.sample_dense(padded_batch)


def forward_logits(apply, params, feature, ds, ids_out=None) -> jax.Array:
    """Stage 2 of the fixed-shape eval step: gather features for an
    already-sampled ``ds`` and run the jitted ``apply``. Composes with
    :func:`sample_batch`; `batch_logits` is exactly the two in sequence."""
    x = lookup_features(feature, ds.n_id, ids_out=ids_out)
    return apply(params, x, ds.adjs)


def batch_logits(
    apply, params, sampler, feature, padded_batch, ids_out=None
) -> jax.Array:
    """One fixed-shape eval step: sample ``padded_batch`` with ``sampler``,
    gather its features, run the jitted ``apply``. This IS the unbatched
    `sampled_eval` inner loop — the serve engine dispatches through the same
    two stages (`sample_batch` + `forward_logits`), which is what makes
    served logits bit-identical to offline eval on the same (sampler state,
    batch) pair."""
    ds = sample_batch(sampler, padded_batch)
    return forward_logits(apply, params, feature, ds, ids_out=ids_out)


# -- fused one-dispatch serving (ROADMAP item 4a/4b) --------------------------

def draw_sample_key(sampler):
    """Consume the sampler's next key WITHOUT sampling — the fused serve
    path draws keys host-side in dispatch-index order (inside the engine's
    sequencing lock, exactly where `sample_batch` used to run) and defers
    the sample itself into the one pre-bound device program."""
    return sampler.next_key()


def feature_gather_spec(feature):
    """``(table, index_map)`` device arrays for an IN-JIT serve gather.

    ``table`` is a dense ``[R, D]`` row table; ``index_map`` is either None
    (ids index ``table`` directly, clipped) or an ``[N]`` int32 global→row
    map (clipped after mapping) — the indirection `serve.ClosureFeature`
    shards ride. Raises TypeError for features whose lookup is host-side by
    design (tiered `Feature`, `DistFeature`): materializing them onto the
    device would silently void the capacity contract the tiers exist for,
    so those engines stay on the split sample/forward path instead."""
    if isinstance(feature, np.ndarray):
        if feature.ndim != 2:
            raise TypeError(f"feature table must be [N, D]; got {feature.shape}")
        return jnp.asarray(feature), None
    if isinstance(feature, jax.Array):
        if feature.ndim != 2:
            raise TypeError(f"feature table must be [N, D]; got {feature.shape}")
        return feature, None
    spec = getattr(feature, "jit_gather_spec", None)
    if spec is not None:
        return spec()
    raise TypeError(
        f"{type(feature).__name__} has no in-jit gather (host-side lookup "
        "by design) — the serve engine falls back to the split path"
    )


def make_serve_step(model, sampler):
    """Build the fused serve step: ONE jittable function running
    sample + feature gather + forward for a padded seed batch.

    Returns ``(serve_step, graph, id_dtype)`` where ``serve_step(params,
    key, seeds, table, index_map, graph)`` reproduces
    `sample_batch` + `forward_logits` bit-for-bit in one program (the
    bit-parity tests in tests/test_serve.py pin it), ``graph`` is the
    sampler's device-array pytree (a jit ARGUMENT of every call — big
    closure constants are the remote-compile trap, NEXT.md), and
    ``id_dtype`` the seed dtype the program was built for. The sampler's
    key is an argument too: the ENGINE owns the key stream and draws it in
    dispatch order (`draw_sample_key`), so fused and split engines consume
    identical key indices."""
    from .pyg.sage_sampler import sample_dense_fused, sample_dense_pure

    graph, bind, id_dtype = sampler.fused_sample_spec()
    sizes, caps, dedup = sampler.sizes, sampler.caps, sampler.dedup

    def serve_step(params, key, seeds, table, index_map, graph):
        sample_fn = bind(graph)
        if dedup:
            ds = sample_dense_pure(
                None, None, key, seeds, sizes, caps, sample_fn=sample_fn
            )
        else:
            ds = sample_dense_fused(
                None, None, key, seeds, sizes, sample_fn=sample_fn
            )
        n = index_map.shape[0] if index_map is not None else table.shape[0]
        ids = jnp.clip(ds.n_id, 0, n - 1)
        if index_map is not None:
            ids = jnp.clip(jnp.take(index_map, ids), 0, table.shape[0] - 1)
        x = jnp.take(table, ids, axis=0)
        return model.apply(params, x, ds.adjs)

    return serve_step, graph, id_dtype


def make_temporal_serve_step(model, sampler):
    """The TEMPORAL analog of :func:`make_serve_step` (round 19,
    `quiver_tpu.workloads`): ``serve_step(params, key, seeds, table,
    index_map, graph, t)`` runs the masked temporal sample
    (`workloads.temporal.temporal_sample_dense`) + gather + forward as ONE
    program. ``t`` is the padded per-seed query-time vector — a jit
    ARGUMENT exactly like the graph arrays (the NEXT.md rule: a
    closure-constant t would recompile per query time; an argument serves
    every t through one sealed executable). The sampler must be
    temporal-bound (`GraphSageSampler.bind_temporal`); its recency/fanout
    config is baked statically, its graph arrays stay swappable via
    `BucketPrograms.rebind` (streaming commits)."""
    from .workloads.temporal import temporal_sample_dense

    if getattr(sampler, "temporal", None) is None:
        raise TypeError("make_temporal_serve_step needs a temporal-bound sampler")
    _, recency = sampler.temporal
    graph = sampler.temporal_graph_arrays()
    sizes, max_deg = sampler.sizes, sampler.max_deg
    id_dtype = graph[1].dtype

    def serve_step(params, key, seeds, table, index_map, graph, t):
        ds = temporal_sample_dense(
            graph, key, seeds, t, sizes, recency=recency, max_deg=max_deg
        )
        n = index_map.shape[0] if index_map is not None else table.shape[0]
        ids = jnp.clip(ds.n_id, 0, n - 1)
        if index_map is not None:
            ids = jnp.clip(jnp.take(index_map, ids), 0, table.shape[0] - 1)
        x = jnp.take(table, ids, axis=0)
        return model.apply(params, x, ds.adjs)

    return serve_step, graph, id_dtype


# Process-wide cache of compiled serve executables, keyed by everything the
# lowering depends on (model value, sampler config, graph/table/params
# AVALS, bucket). Two engines over same-shaped state share one executable —
# the sharing the jit cache used to provide, kept so per-engine AOT
# pre-binding doesn't multiply compile time across a test suite or a shard
# fleet — while each engine still holds its OWN pre-bound table with
# hard-miss semantics. LRU-bounded: live engines keep direct references to
# their executables, so eviction only reduces cross-engine sharing, never
# invalidates a sealed program table.
import collections as _collections
import threading as _threading

_SERVE_EXE_CACHE: "_collections.OrderedDict" = _collections.OrderedDict()
_SERVE_EXE_CACHE_MAX = 256
_SERVE_EXE_LOCK = _threading.Lock()


def _aval_spec(tree) -> tuple:
    return tuple(
        (tuple(leaf.shape), np.dtype(leaf.dtype).str)
        for leaf in jax.tree_util.tree_leaves(tree)
    )


class BucketPrograms:
    """AOT pre-bound per-bucket fused serve executables (ROADMAP item 4a —
    the CUDA-Graphs analog's capture step).

    `compile_bucket` turns the fused `make_serve_step` function into one
    LOADED executable per bucket via ``jax.jit(...).lower(...).compile()``
    — held here, not as a jit-cache entry, so a flush is a direct
    table-lookup + execute with zero trace-cache machinery on the hot path.
    The per-flush seed buffer is DONATED (``donate_argnums``) so XLA may
    reuse its device allocation for outputs/scratch; the feature table and
    graph arrays are NOT donated — they are persistent state every flush
    re-reads, and donating them would invalidate them after one call.

    `seal()` (called by `ServeEngine.warmup`) flips misses from
    compile-on-first-use to a HARD RuntimeError: after warmup a retrace or
    recompile is structurally impossible — a shape the fleet didn't warm is
    a bug surfaced in milliseconds, not a silent 12–60 s compile eaten by a
    live request."""

    def __init__(self, model, sampler, feature):
        # temporal samplers (round 19, quiver_tpu.workloads) compile the
        # temporal serve step, which takes ONE extra per-flush argument:
        # the padded per-seed query-time vector
        temporal = getattr(sampler, "temporal", None)
        if temporal is not None:
            self._fn, self._graph, self._id_dtype = make_temporal_serve_step(
                model, sampler
            )
            self._n_extra = 1
        else:
            self._fn, self._graph, self._id_dtype = make_serve_step(
                model, sampler
            )
            self._n_extra = 0
        self._sampler = sampler
        self._caps = sampler.caps  # snapshot the program was built for
        self._table, self._map = feature_gather_spec(feature)
        self._jit = jax.jit(self._fn, donate_argnums=(2,))
        self._exes: dict = {}
        self._sealed = False
        try:
            spec = (
                model, sampler.sizes, sampler.caps, sampler.dedup,
                getattr(sampler, "layout", None),
                getattr(sampler, "weighted", False),
                self._n_extra,
                None if temporal is None else (
                    float(temporal[1]), int(getattr(sampler, "max_deg", 0))
                ),
                np.dtype(self._id_dtype).str,
                _aval_spec(self._graph),
                _aval_spec(self._table),
                None if self._map is None else _aval_spec(self._map),
            )
            hash(spec)
            self._spec = spec
        except TypeError:  # unhashable custom model: per-engine compiles only
            self._spec = None

    def rebind(self, graph=None, table=None, index_map=None) -> None:
        """Swap the persistent graph / feature-table arguments for
        SAME-SHAPED updated arrays (round-17 streaming graph deltas: a
        fenced ``update_graph`` commit produces new device arrays; the
        executables take them as ARGUMENTS, so the swap is free — no
        recompile, the sealed table stays sealed). A shape/dtype mismatch
        raises instead of silently feeding the compiled avals garbage."""
        if graph is not None:
            if _aval_spec(graph) != _aval_spec(self._graph):
                raise ValueError(
                    "rebind graph avals differ from the compiled ones "
                    f"({_aval_spec(graph)} vs {_aval_spec(self._graph)}) — "
                    "streaming swaps contents, never shapes"
                )
            self._graph = graph
        if table is not None:
            if _aval_spec(table) != _aval_spec(self._table):
                raise ValueError("rebind table avals differ from compiled")
            self._table = table
        if index_map is not None:
            if self._map is None or _aval_spec(index_map) != _aval_spec(
                self._map
            ):
                raise ValueError("rebind index_map avals differ from compiled")
            self._map = index_map

    def reprovision(self, graph, params=None) -> int:
        """Rebind the graph arguments across a SHAPE change — the
        round-21 reserve re-provisioning event (`StreamingTiledGraph.
        provision_reserve` grew the tile tables by a whole bank). This
        is the one sanctioned exception to `rebind`'s shapes-never-
        change contract, and it is paid for honestly: the program spec
        is updated to the new graph avals, every previously-warmed
        bucket executable is dropped and recompiled against them (via
        the process-wide executable cache, so a second engine over the
        same shapes compiles nothing), and the sealed/unsealed state is
        preserved — after the rebuild the table is complete again, so
        sealed hard-miss semantics still hold. One rebuild per provision
        event; the per-commit path still never recompiles. Returns the
        number of buckets rebuilt."""
        new_avals = _aval_spec(graph)
        if new_avals == _aval_spec(self._graph):
            # same shapes (e.g. a retried provision already absorbed):
            # a plain content rebind
            self._graph = graph
            return 0
        self._graph = graph
        if self._spec is not None:
            # graph avals live at one spec slot — keep everything else
            # (model, sampler config, table/map avals) identical so the
            # executable cache shares across engines as before
            self._spec = self._spec[:9] + (new_avals,) + self._spec[10:]
        warmed = tuple(sorted(self._exes))
        self._exes = {}
        if params is not None:
            for b in warmed:
                self.compile_bucket(b, params)
        return len(warmed)

    @property
    def buckets(self) -> Tuple[int, ...]:
        return tuple(sorted(self._exes))

    @property
    def sealed(self) -> bool:
        return self._sealed

    def seal(self) -> None:
        self._sealed = True

    def compile_bucket(self, bucket: int, params) -> None:
        """Bind (compiling if no same-shaped executable exists anywhere in
        the process) the executable for ``bucket``."""
        bucket = int(bucket)
        if bucket in self._exes:
            return
        cache_key = None
        if self._spec is not None:
            cache_key = (self._spec, _aval_spec(params), bucket)
            with _SERVE_EXE_LOCK:
                exe = _SERVE_EXE_CACHE.get(cache_key)
                if exe is not None:
                    _SERVE_EXE_CACHE.move_to_end(cache_key)
            if exe is not None:
                self._exes[bucket] = exe
                return
        key = jax.random.fold_in(jax.random.key(0), 0)
        seeds = jnp.zeros((bucket,), self._id_dtype)
        extras = (
            (jnp.zeros((bucket,), jnp.float32),) if self._n_extra else ()
        )
        import warnings

        with warnings.catch_warnings():
            # the donated seed buffer has no same-shaped output to alias on
            # every backend; the donation is still declared so backends
            # that CAN reuse it (and future outputs) do
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable"
            )
            exe = self._jit.lower(
                params, key, seeds, self._table, self._map, self._graph,
                *extras,
            ).compile()
        if cache_key is not None:
            with _SERVE_EXE_LOCK:
                exe = _SERVE_EXE_CACHE.setdefault(cache_key, exe)
                _SERVE_EXE_CACHE.move_to_end(cache_key)
                while len(_SERVE_EXE_CACHE) > _SERVE_EXE_CACHE_MAX:
                    _SERVE_EXE_CACHE.popitem(last=False)
        self._exes[bucket] = exe

    def binding(self):
        """The persistent-argument triple ``(table, index_map, graph)``
        CURRENTLY bound — an epoch snapshot. Zero-stall engines capture
        this at seal time and pass it back as ``binding=`` so a flush
        dispatches against the graph arrays of ITS dispatch index even
        when a commit rebinds mid-flight (the arrays are immutable; a
        rebind swaps references, never bits)."""
        return (self._table, self._map, self._graph)

    def __call__(self, bucket: int, params, key, seeds, *extra,
                 binding=None) -> jax.Array:
        """ONE execute call: the whole sample+gather+forward for a padded
        seed batch at ``bucket``. Misses compile lazily before `seal()`,
        raise RuntimeError after. Temporal programs take one ``extra``
        argument — the padded per-seed query-time vector, float32
        ``[bucket]`` (the engine pads it exactly like the seeds).
        ``binding=`` (a `binding()` snapshot) overrides the live
        table/map/graph arguments — the epoch-pinning hook."""
        if len(extra) != self._n_extra:
            raise TypeError(
                f"this serve program takes {self._n_extra} extra "
                f"argument(s) (got {len(extra)}) — temporal engines pass "
                "the padded query-time vector, plain engines none"
            )
        if self._sampler.caps != self._caps:
            # the fused program bakes the caps' static shapes in; sampling
            # with mutated caps would silently diverge from the split path
            # and the replay oracle (calibrate_caps after engine build)
            raise RuntimeError(
                f"sampler caps changed from {self._caps} to "
                f"{self._sampler.caps} after the serve programs were built "
                "— calibrate caps BEFORE constructing the engine"
            )
        exe = self._exes.get(int(bucket))
        if exe is None:
            if self._sealed:
                raise RuntimeError(
                    f"serve bucket {bucket} has no pre-bound executable "
                    f"(warmed: {self.buckets}) — warmup() seals the program "
                    "table; a post-warmup miss means the bucket ladder and "
                    "the warmed shapes disagree"
                )
            self.compile_bucket(int(bucket), params)
            exe = self._exes[int(bucket)]
        seeds = jnp.asarray(np.asarray(seeds), self._id_dtype)
        extra = tuple(
            jnp.asarray(np.asarray(e, np.float32)) for e in extra
        )
        table, imap, graph = (
            binding if binding is not None
            else (self._table, self._map, self._graph)
        )
        return exe(params, key, seeds, table, imap, graph, *extra)


def time_eval_split(
    apply, params, sampler, feature, padded_batch, iters: int = 10
) -> Tuple[float, float]:
    """Measured per-call seconds of the two `batch_logits` stages —
    ``(t_sample_s, t_forward_s)`` at this batch shape — the EVAL-shaped
    dispatch costs `parallel.scaling.serve_table` wants instead of a
    train-step proxy. Warms one full untimed pass first; each timed leg
    syncs once at the end (raw averages — on a tunneled backend the RPC
    floor bounds both legs identically). One shared implementation so
    `bench.py` and `scripts/serve_probe.py` report the same methodology."""
    import time

    ds = sample_batch(sampler, padded_batch)
    jax.block_until_ready(ds.n_id)
    jax.block_until_ready(forward_logits(apply, params, feature, ds))
    t0 = time.perf_counter()
    for _ in range(iters):
        ds = sample_batch(sampler, padded_batch)
    jax.block_until_ready(ds.n_id)
    t_sample = (time.perf_counter() - t0) / iters
    t0 = time.perf_counter()
    for _ in range(iters):
        out = forward_logits(apply, params, feature, ds)
    jax.block_until_ready(out)
    t_forward = (time.perf_counter() - t0) / iters
    return t_sample, t_forward


def sampled_eval(
    model,
    params,
    sampler,
    feature,
    labels: np.ndarray,
    nodes: np.ndarray,
    batch_size: int = 1024,
) -> float:
    """Sampled accuracy over ``nodes`` (any model; use an eval sampler with
    higher fanouts than training for a tighter estimate — the reference's
    eval runs the same loop with test seeds). Returns fraction correct."""
    nodes = np.asarray(nodes)
    labels = np.asarray(labels)
    correct = 0
    apply = _cached_apply(model)
    # hoisted per-batch work: one padded seed buffer reused across the loop
    # (pad_seed_batch writes in place) and one clipped-id buffer for the
    # raw-table path, allocated lazily at the first batch's n_id shape
    seed_buf = np.empty(batch_size, nodes.dtype)
    ids_buf: Optional[np.ndarray] = None
    for lo in range(0, nodes.shape[0], batch_size):
        batch = pad_seed_batch(nodes[lo : lo + batch_size], batch_size, out=seed_buf)
        ds = sampler.sample_dense(batch)
        if isinstance(feature, np.ndarray) and ids_buf is None:
            ids_buf = np.empty(np.asarray(ds.n_id).shape, np.asarray(ds.n_id).dtype)
        x = lookup_features(feature, ds.n_id, ids_out=ids_buf)
        logits = apply(params, x, ds.adjs)
        pred = np.asarray(jnp.argmax(logits, axis=-1))[: min(batch_size, nodes.shape[0] - lo)]
        correct += int((pred == labels[nodes[lo : lo + batch_size]]).sum())
    return correct / nodes.shape[0]


def full_inference_accuracy(
    model, params, topo, x_all, labels, nodes
) -> float:
    """Accuracy of `sage_full_inference` on a node subset."""
    indptr, indices = topo.to_device()
    h = sage_full_inference(model, params, indptr, indices, jnp.asarray(x_all))
    pred = np.asarray(jnp.argmax(h, axis=-1))
    nodes = np.asarray(nodes)
    return float((pred[nodes] == np.asarray(labels)[nodes]).mean())

"""Evaluation / inference paths.

The reference evaluates two ways: layer-wise FULL-neighbor inference (the
`model.inference` loop of examples/multi_gpu/pyg/ogb-products/
dist_sampling_ogb_products_quiver.py:118-139, subgraph loader over all
nodes) and sampled eval with the training sampler. TPU equivalents:

- `sage_full_inference`: exact layered embeddings for ALL nodes. The
  full-neighbor mean aggregation is ONE edge-parallel pass over the CSR per
  layer (chunked `lax.fori_loop`, same trick as `ops.sample.neighbor_prob`)
  — no subgraph loader needed; XLA streams the gather/scatter chunks.
- `sampled_eval`: high-fanout sampled accuracy for any model (GraphSAGE or
  GAT — full-neighbor attention would need per-edge softmax passes; the
  reference evaluates GAT by sampling too, dist_sampling_reddit_gat.py).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax


@functools.partial(jax.jit, static_argnames=("edge_chunk",))
def full_mean_aggregate(
    indptr: jax.Array,
    indices: jax.Array,
    h: jax.Array,
    edge_chunk: int = 1 << 20,
) -> jax.Array:
    """Exact mean over ALL neighbors for every node: ``out[u] =
    mean_{v in N(u)} h[v]`` (zero where deg 0).

    Edge-parallel chunked segment-sum over the CSR — the dense-batch analog
    of `ops.sample.neighbor_prob`'s scalar pass; one traced chunk body
    regardless of graph size.
    """
    n = indptr.shape[0] - 1
    e = indices.shape[0]
    d = h.shape[1]
    out = jnp.zeros((n + 1, d), h.dtype)  # +1: out-of-range dump row
    if e == 0:
        return out[:n]
    chunk = min(edge_chunk, e)
    nchunks = -(-e // chunk)

    def body(c, out):
        start_u = c * chunk
        start = jnp.minimum(start_u, e - chunk)
        eidx = start + jnp.arange(chunk, dtype=indptr.dtype)
        fresh = eidx >= start_u
        src = jnp.searchsorted(indptr, eidx, side="right") - 1
        dst = lax.dynamic_slice(indices, (start,), (chunk,))
        rows = jnp.take(h, jnp.clip(dst, 0, h.shape[0] - 1), axis=0)
        rows = jnp.where(fresh[:, None], rows, 0)
        src = jnp.where(fresh, src, n)  # dump lane
        return out.at[src].add(rows, mode="drop")

    out = lax.fori_loop(0, nchunks, body, out)[:n]
    deg = (indptr[1:] - indptr[:-1]).astype(h.dtype)
    return out / jnp.maximum(deg, 1)[:, None]


def sage_full_inference(
    model,
    params,
    indptr: jax.Array,
    indices: jax.Array,
    x_all: jax.Array,
) -> jax.Array:
    """Layer-wise full-neighbor GraphSAGE inference over ALL nodes —
    the reference `SAGE.inference` semantics
    (dist_sampling_ogb_products_quiver.py:118-139) without a subgraph
    loader: per layer, one full-graph mean aggregation + the layer's dense
    projections, relu between layers (no dropout at eval).

    Works for the `models.GraphSAGE` flax module (reads its
    ``conv{i}/lin_l|lin_r`` params directly; GAT needs per-edge softmax —
    use `sampled_eval` there)."""
    p = params["params"] if "params" in params else params
    num_layers = model.num_layers
    h = jnp.asarray(x_all)
    for i in range(num_layers):
        layer = p[f"conv{i}"]
        agg = full_mean_aggregate(indptr, indices, h)
        out = agg @ layer["lin_l"]["kernel"]
        if "bias" in layer["lin_l"]:
            out = out + layer["lin_l"]["bias"]
        out = out + h @ layer["lin_r"]["kernel"]
        h = jax.nn.relu(out) if i != num_layers - 1 else out
    return h


@functools.lru_cache(maxsize=32)
def _cached_apply_hashable(model):
    return jax.jit(lambda p, x, adjs: model.apply(p, x, adjs))


def _cached_apply(model):
    """One jitted apply per model VALUE — a fresh jit per sampled_eval call
    would recompile an identical program every invocation.

    Value-keyed (flax modules are frozen dataclasses, hashable by field
    values: equal configs share one entry) and BOUNDED: the lru_cache holds
    at most 32 models + executables, so repeated model construction (e.g. a
    hyperparameter sweep) evicts old entries instead of growing without
    bound. Weak-keyed variants were rejected — a closure capturing the key
    pins it (no eviction), and a weakref proxy raises ReferenceError when a
    retrace outlives the first-seen equal model."""
    try:
        return _cached_apply_hashable(model)
    except TypeError:  # unhashable custom module: skip caching
        return jax.jit(lambda p, x, adjs: model.apply(p, x, adjs))


def pad_seed_batch(
    batch: np.ndarray, batch_size: int, out: Optional[np.ndarray] = None
) -> np.ndarray:
    """Pad a 1-D seed batch up to ``batch_size`` by repeating ``batch[-1]``
    (the convention every fixed-shape eval/serve path here uses — the
    duplicate rows are sliced off after the forward). Pass ``out`` to reuse
    one buffer across a loop instead of allocating per batch."""
    batch = np.asarray(batch)
    if batch.shape[0] == 0:
        raise ValueError("cannot pad an empty seed batch")
    if batch.shape[0] > batch_size:
        raise ValueError(f"batch of {batch.shape[0]} exceeds batch_size={batch_size}")
    if out is None or out.shape[0] != batch_size or out.dtype != batch.dtype:
        out = np.empty(batch_size, batch.dtype)
    out[: batch.shape[0]] = batch
    out[batch.shape[0] :] = batch[-1]
    return out


def lookup_features(feature, n_id, ids_out: Optional[np.ndarray] = None):
    """Feature rows for a sampled ``n_id`` — one helper for every consumer
    (``sampled_eval``, the serve engine): raw ``[N, D]`` numpy tables get the
    clip-and-take path (``ids_out`` reuses the clipped-id buffer across
    calls), quiver ``Feature``/``QuantizedFeature`` objects their tiered
    ``__getitem__``."""
    if isinstance(feature, np.ndarray):
        ids = np.asarray(n_id)
        if ids_out is not None and ids_out.shape == ids.shape:
            np.clip(ids, 0, feature.shape[0] - 1, out=ids_out)
            ids = ids_out
        else:
            ids = np.clip(ids, 0, feature.shape[0] - 1)
        return jnp.asarray(feature[ids])
    return feature[n_id]


def sample_batch(sampler, padded_batch):
    """Stage 1 of the fixed-shape eval step: draw the sampler's next key
    and dispatch the k-hop sample for ``padded_batch``. Split out of
    :func:`batch_logits` so the pipelined serve engine can consume the
    sampler's key stream in dispatch-index order (under its sequencing
    lock) while the forward of the PREVIOUS flush still runs."""
    return sampler.sample_dense(padded_batch)


def forward_logits(apply, params, feature, ds, ids_out=None) -> jax.Array:
    """Stage 2 of the fixed-shape eval step: gather features for an
    already-sampled ``ds`` and run the jitted ``apply``. Composes with
    :func:`sample_batch`; `batch_logits` is exactly the two in sequence."""
    x = lookup_features(feature, ds.n_id, ids_out=ids_out)
    return apply(params, x, ds.adjs)


def batch_logits(
    apply, params, sampler, feature, padded_batch, ids_out=None
) -> jax.Array:
    """One fixed-shape eval step: sample ``padded_batch`` with ``sampler``,
    gather its features, run the jitted ``apply``. This IS the unbatched
    `sampled_eval` inner loop — the serve engine dispatches through the same
    two stages (`sample_batch` + `forward_logits`), which is what makes
    served logits bit-identical to offline eval on the same (sampler state,
    batch) pair."""
    ds = sample_batch(sampler, padded_batch)
    return forward_logits(apply, params, feature, ds, ids_out=ids_out)


def time_eval_split(
    apply, params, sampler, feature, padded_batch, iters: int = 10
) -> Tuple[float, float]:
    """Measured per-call seconds of the two `batch_logits` stages —
    ``(t_sample_s, t_forward_s)`` at this batch shape — the EVAL-shaped
    dispatch costs `parallel.scaling.serve_table` wants instead of a
    train-step proxy. Warms one full untimed pass first; each timed leg
    syncs once at the end (raw averages — on a tunneled backend the RPC
    floor bounds both legs identically). One shared implementation so
    `bench.py` and `scripts/serve_probe.py` report the same methodology."""
    import time

    ds = sample_batch(sampler, padded_batch)
    jax.block_until_ready(ds.n_id)
    jax.block_until_ready(forward_logits(apply, params, feature, ds))
    t0 = time.perf_counter()
    for _ in range(iters):
        ds = sample_batch(sampler, padded_batch)
    jax.block_until_ready(ds.n_id)
    t_sample = (time.perf_counter() - t0) / iters
    t0 = time.perf_counter()
    for _ in range(iters):
        out = forward_logits(apply, params, feature, ds)
    jax.block_until_ready(out)
    t_forward = (time.perf_counter() - t0) / iters
    return t_sample, t_forward


def sampled_eval(
    model,
    params,
    sampler,
    feature,
    labels: np.ndarray,
    nodes: np.ndarray,
    batch_size: int = 1024,
) -> float:
    """Sampled accuracy over ``nodes`` (any model; use an eval sampler with
    higher fanouts than training for a tighter estimate — the reference's
    eval runs the same loop with test seeds). Returns fraction correct."""
    nodes = np.asarray(nodes)
    labels = np.asarray(labels)
    correct = 0
    apply = _cached_apply(model)
    # hoisted per-batch work: one padded seed buffer reused across the loop
    # (pad_seed_batch writes in place) and one clipped-id buffer for the
    # raw-table path, allocated lazily at the first batch's n_id shape
    seed_buf = np.empty(batch_size, nodes.dtype)
    ids_buf: Optional[np.ndarray] = None
    for lo in range(0, nodes.shape[0], batch_size):
        batch = pad_seed_batch(nodes[lo : lo + batch_size], batch_size, out=seed_buf)
        ds = sampler.sample_dense(batch)
        if isinstance(feature, np.ndarray) and ids_buf is None:
            ids_buf = np.empty(np.asarray(ds.n_id).shape, np.asarray(ds.n_id).dtype)
        x = lookup_features(feature, ds.n_id, ids_out=ids_buf)
        logits = apply(params, x, ds.adjs)
        pred = np.asarray(jnp.argmax(logits, axis=-1))[: min(batch_size, nodes.shape[0] - lo)]
        correct += int((pred == labels[nodes[lo : lo + batch_size]]).sum())
    return correct / nodes.shape[0]


def full_inference_accuracy(
    model, params, topo, x_all, labels, nodes
) -> float:
    """Accuracy of `sage_full_inference` on a node subset."""
    indptr, indices = topo.to_device()
    h = sage_full_inference(model, params, indptr, indices, jnp.asarray(x_all))
    pred = np.asarray(jnp.argmax(h, axis=-1))
    nodes = np.asarray(nodes)
    return float((pred[nodes] == np.asarray(labels)[nodes]).mean())

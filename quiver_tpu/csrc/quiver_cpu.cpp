// Native host sampling / gather engine.
//
// TPU-native counterpart of the reference's CPU engine
// (include/quiver/quiver.cpu.hpp: at::parallel_for degree pass + per-seed
// std::sample, quiver.cpu.hpp:57-102) and of the host-pointer branch of the
// feature gather kernel (include/quiver/shard_tensor.cu.hpp:44-55).
//
// Differences from the reference, by design:
//  - no torch/ATen dependency: raw std::thread parallelism over seed ranges,
//    per-thread SplitMix64-seeded mt19937 (reference uses thread_local mt19937,
//    quiver.cpu.hpp:14-27);
//  - fixed-k padded output (neighbors [B,k] + valid mask) instead of ragged
//    output + prefix sums — this matches the static shapes the XLA device
//    pipeline needs, so host batches stream straight into jit'd consumers;
//  - k-distinct draws use Floyd's algorithm (O(k) per seed, uniform k-subset)
//    instead of reservoir sampling; identical distribution over subsets.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this image).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <limits>
#include <random>
#include <thread>
#include <utility>
#include <vector>

namespace {

inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97f4A7C15ULL;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

// Uniform k-subset of [0, deg) via Floyd's algorithm; writes k positions.
inline void floyd_sample(std::mt19937_64 &rng, int64_t deg, int64_t k,
                         int64_t *out) {
  // tiny linear-probe set sized to the next pow2 >= 2k
  int64_t cap = 4;
  while (cap < 2 * k) cap <<= 1;
  std::vector<int64_t> set(cap, -1);
  const int64_t mask = cap - 1;
  auto insert = [&](int64_t v) -> bool {  // returns false if already present
    int64_t h = static_cast<int64_t>(splitmix64(static_cast<uint64_t>(v))) & mask;
    while (set[h] != -1) {
      if (set[h] == v) return false;
      h = (h + 1) & mask;
    }
    set[h] = v;
    return true;
  };
  int64_t n_out = 0;
  for (int64_t j = deg - k; j < deg; ++j) {
    std::uniform_int_distribution<int64_t> dist(0, j);
    int64_t t = dist(rng);
    int64_t pick;
    if (insert(t)) {
      pick = t;
    } else {
      pick = j;
      insert(j);
    }
    out[n_out++] = pick;
  }
}

}  // namespace

extern "C" {

// One-hop sample: for each seed, min(deg, k) neighbors without replacement;
// copy-all in CSR order when deg <= k (reference cuda_random.cu.hpp:33-38).
void qt_sample_layer(const int64_t *indptr, const int64_t *indices,
                     int64_t num_nodes, const int64_t *seeds, int64_t batch,
                     int64_t k, uint64_t seed, int64_t *out_nbrs,
                     uint8_t *out_valid) {
  if (batch <= 0 || k <= 0) return;
  int64_t n_threads =
      std::max<int64_t>(1, std::min<int64_t>(
                               std::thread::hardware_concurrency(), batch));
  int64_t chunk = (batch + n_threads - 1) / n_threads;
  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  for (int64_t t = 0; t < n_threads; ++t) {
    int64_t lo = t * chunk, hi = std::min(batch, lo + chunk);
    if (lo >= hi) break;
    threads.emplace_back([=]() {
      std::mt19937_64 rng(splitmix64(seed ^ splitmix64(0xC0FFEEULL + t)));
      std::vector<int64_t> pos(static_cast<size_t>(k));
      for (int64_t i = lo; i < hi; ++i) {
        int64_t s = seeds[i];
        int64_t *row = out_nbrs + i * k;
        uint8_t *vrow = out_valid + i * k;
        if (s < 0 || s >= num_nodes) {
          std::memset(vrow, 0, static_cast<size_t>(k));
          std::memset(row, 0, static_cast<size_t>(k) * sizeof(int64_t));
          continue;
        }
        int64_t start = indptr[s];
        int64_t deg = indptr[s + 1] - start;
        if (deg <= k) {
          for (int64_t j = 0; j < deg; ++j) {
            row[j] = indices[start + j];
            vrow[j] = 1;
          }
          for (int64_t j = deg; j < k; ++j) {
            row[j] = 0;
            vrow[j] = 0;
          }
        } else {
          floyd_sample(rng, deg, k, pos.data());
          for (int64_t j = 0; j < k; ++j) {
            row[j] = indices[start + pos[j]];
            vrow[j] = 1;
          }
        }
      }
    });
  }
  for (auto &th : threads) th.join();
}

// Weighted one-hop sample: k DISTINCT neighbors drawn with probability
// proportional to per-edge weights (CSR order), via Efraimidis-Spirakis
// exponential keys — the same weighted-k-subset distribution as the device
// engine's Gumbel-top-k (ops/sample.py gumbel_topk_positions); the
// reference's weight_sample is CUDA-only (cuda_random.cu.hpp:177-221), so
// its CPU engine has no weighted story at all. Non-positive weights are
// NEVER drawn: a row with fewer than k positive-weight edges returns that
// many valid lanes and the rest invalid, matching the -inf-logit Gumbel
// behavior on device.
void qt_sample_layer_weighted(const int64_t *indptr, const int64_t *indices,
                              const float *weights, int64_t num_nodes,
                              const int64_t *seeds, int64_t batch, int64_t k,
                              uint64_t seed, int64_t *out_nbrs,
                              uint8_t *out_valid) {
  if (batch <= 0 || k <= 0) return;
  int64_t n_threads =
      std::max<int64_t>(1, std::min<int64_t>(
                               std::thread::hardware_concurrency(), batch));
  int64_t chunk = (batch + n_threads - 1) / n_threads;
  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  for (int64_t t = 0; t < n_threads; ++t) {
    int64_t lo = t * chunk, hi = std::min(batch, lo + chunk);
    if (lo >= hi) break;
    threads.emplace_back([=]() {
      std::mt19937_64 rng(splitmix64(seed ^ splitmix64(0xBEEFULL + t)));
      std::uniform_real_distribution<double> uni(
          std::numeric_limits<double>::min(), 1.0);
      std::vector<std::pair<double, int64_t>> keys;
      for (int64_t i = lo; i < hi; ++i) {
        int64_t s = seeds[i];
        int64_t *row = out_nbrs + i * k;
        uint8_t *vrow = out_valid + i * k;
        std::memset(vrow, 0, static_cast<size_t>(k));
        std::memset(row, 0, static_cast<size_t>(k) * sizeof(int64_t));
        if (s < 0 || s >= num_nodes) continue;
        int64_t start = indptr[s];
        int64_t deg = indptr[s + 1] - start;
        // exponential key Exp(1)/w_j: the k smallest keys are a weighted
        // k-subset without replacement; w <= 0 -> +inf key (drawn last)
        keys.clear();
        keys.reserve(static_cast<size_t>(deg));
        int64_t positive = 0;
        for (int64_t j = 0; j < deg; ++j) {
          float w = weights[start + j];
          double key;
          if (w > 0.f) {
            key = -std::log(uni(rng)) / static_cast<double>(w);
            ++positive;
          } else {
            key = std::numeric_limits<double>::infinity();
          }
          keys.emplace_back(key, j);
        }
        int64_t take = std::min<int64_t>(k, positive);
        if (take <= 0) continue;
        if (take < deg)
          std::nth_element(keys.begin(), keys.begin() + take, keys.end());
        for (int64_t j = 0; j < take; ++j) {
          row[j] = indices[start + keys[static_cast<size_t>(j)].second];
          vrow[j] = 1;
        }
      }
    });
  }
  for (auto &th : threads) th.join();
}

// Hash-based local reindex — the host counterpart of the reference's GPU
// hash-table reindex (include/quiver/reindex.cu.hpp) and the bit-identical
// mirror of ops/reindex.local_reindex's contract:
//  - valid seeds keep slots 0..seed_count-1 VERBATIM (duplicates included;
//    lookups resolve to the FIRST slot holding a value);
//  - unique new neighbors follow in ascending-id order;
//  - masked-out lanes get local id 0.
// One open-addressing map + one sort of the (small) new-unique set replaces
// the numpy path's four full-width sort/searchsorted passes — this is where
// ~85% of the HostSampler's multi-hop time went.
// out_n_id must have room for seed_count + total entries (worst case).
void qt_reindex(const int64_t *head, int64_t seed_count, const int64_t *nbrs,
                const uint8_t *mask, int64_t total, int64_t *out_n_id,
                int64_t *out_count, int32_t *out_local) {
  const int64_t kEmpty = INT64_MIN;  // never a node id
  int64_t cap = 16;
  while (cap < 2 * (seed_count + total)) cap <<= 1;
  std::vector<int64_t> keys(static_cast<size_t>(cap), kEmpty);
  std::vector<int64_t> slots(static_cast<size_t>(cap), 0);
  const int64_t hmask = cap - 1;
  auto probe = [&](int64_t v) -> int64_t {  // index of v's cell (or empty)
    int64_t h = static_cast<int64_t>(splitmix64(static_cast<uint64_t>(v))) & hmask;
    while (keys[h] != kEmpty && keys[h] != v) h = (h + 1) & hmask;
    return h;
  };
  for (int64_t i = 0; i < seed_count; ++i) {
    int64_t h = probe(head[i]);
    if (keys[h] == kEmpty) {  // first slot wins (min-index contract)
      keys[h] = head[i];
      slots[h] = i;
    }
    out_n_id[i] = head[i];
  }
  std::vector<int64_t> new_vals;
  new_vals.reserve(static_cast<size_t>(total / 4 + 16));
  for (int64_t j = 0; j < total; ++j) {
    if (!mask[j]) continue;
    int64_t h = probe(nbrs[j]);
    if (keys[h] == kEmpty) {
      keys[h] = nbrs[j];
      new_vals.push_back(nbrs[j]);
    }
  }
  std::sort(new_vals.begin(), new_vals.end());
  for (size_t r = 0; r < new_vals.size(); ++r) {
    slots[probe(new_vals[r])] = seed_count + static_cast<int64_t>(r);
    out_n_id[seed_count + static_cast<int64_t>(r)] = new_vals[r];
  }
  *out_count = seed_count + static_cast<int64_t>(new_vals.size());
  for (int64_t j = 0; j < total; ++j)
    out_local[j] = mask[j] ? static_cast<int32_t>(slots[probe(nbrs[j])]) : 0;
}

// Parallel row gather by raw row size — dtype-agnostic (f32, bf16, f64,
// int rows all reduce to a strided memcpy; the reference's gather kernel is
// float32-only, quiver_feature.cu:65-69). Out-of-range ids zero their row.
void qt_gather_rows_bytes(const uint8_t *src, int64_t n, int64_t row_bytes,
                          const int64_t *ids, int64_t batch, uint8_t *out) {
  if (batch <= 0 || row_bytes <= 0) return;
  int64_t n_threads =
      std::max<int64_t>(1, std::min<int64_t>(
                               std::thread::hardware_concurrency(), batch));
  int64_t chunk = (batch + n_threads - 1) / n_threads;
  std::vector<std::thread> threads;
  for (int64_t t = 0; t < n_threads; ++t) {
    int64_t lo = t * chunk, hi = std::min(batch, lo + chunk);
    if (lo >= hi) break;
    threads.emplace_back([=]() {
      const size_t rb = static_cast<size_t>(row_bytes);
      for (int64_t i = lo; i < hi; ++i) {
        int64_t id = ids[i];
        if (id < 0 || id >= n) {
          std::memset(out + i * row_bytes, 0, rb);
        } else {
          std::memcpy(out + i * row_bytes, src + id * row_bytes, rb);
        }
      }
    });
  }
  for (auto &th : threads) th.join();
}

// Parallel row gather out[i, :] = src[ids[i], :] — the host cold-tier path
// (float32 spelling, kept for ABI compatibility with round-3 callers).
void qt_gather_rows(const float *src, int64_t n, int64_t d, const int64_t *ids,
                    int64_t batch, float *out) {
  qt_gather_rows_bytes(reinterpret_cast<const uint8_t *>(src), n,
                       d * static_cast<int64_t>(sizeof(float)), ids, batch,
                       reinterpret_cast<uint8_t *>(out));
}

}  // extern "C"

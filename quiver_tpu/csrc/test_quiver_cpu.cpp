// Direct C-ABI tests for the native host engine — the counterpart of the
// reference's gtest suite (/root/reference/tests/cpp/test_quiver_cpu.cpp:9-50)
// without a gtest dependency (plain asserts; the image has no gtest).
//
// Also a kernel microbench (`./test_quiver_cpu bench`) matching the
// reference's bench shape (benchmarks/cpp/bench_quiver_gpu.cu:57-97:
// 1M nodes / 4M edges, batch 1024, k=5) plus a products-fanout SEPS row.
//
// Build + run: make -C quiver_tpu/csrc test
// ASan build:  make -C quiver_tpu/csrc asan

#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <chrono>
#include <random>
#include <set>
#include <vector>

extern "C" {
void qt_sample_layer(const int64_t *indptr, const int64_t *indices,
                     int64_t num_nodes, const int64_t *seeds, int64_t batch,
                     int64_t k, uint64_t seed, int64_t *out_nbrs,
                     uint8_t *out_valid);
void qt_gather_rows(const float *src, int64_t n, int64_t d, const int64_t *ids,
                    int64_t batch, float *out);
void qt_gather_rows_bytes(const uint8_t *src, int64_t n, int64_t row_bytes,
                          const int64_t *ids, int64_t batch, uint8_t *out);
void qt_reindex(const int64_t *head, int64_t seed_count, const int64_t *nbrs,
                const uint8_t *mask, int64_t total, int64_t *out_n_id,
                int64_t *out_count, int32_t *out_local);
void qt_sample_layer_weighted(const int64_t *indptr, const int64_t *indices,
                              const float *weights, int64_t num_nodes,
                              const int64_t *seeds, int64_t batch, int64_t k,
                              uint64_t seed, int64_t *out_nbrs,
                              uint8_t *out_valid);
}

namespace {

// chain graph: node i -> i+1 (deg 1), last node deg 0 — the same oracle the
// Python suites use (tests/test_sampler.py chain fixtures).
void test_chain_copy_all() {
  const int64_t n = 6;
  std::vector<int64_t> indptr(n + 1), indices;
  for (int64_t i = 0; i < n; ++i) {
    indptr[i] = indices.size();
    if (i + 1 < n) indices.push_back(i + 1);
  }
  indptr[n] = indices.size();

  const int64_t k = 3;
  std::vector<int64_t> seeds = {0, 2, n - 1, -1, n + 5};
  const int64_t b = seeds.size();
  std::vector<int64_t> nbrs(b * k, -7);
  std::vector<uint8_t> valid(b * k, 9);
  qt_sample_layer(indptr.data(), indices.data(), n, seeds.data(), b, k, 42,
                  nbrs.data(), valid.data());
  // deg-1 seeds: copy-all -> neighbor in lane 0, lanes 1.. invalid
  assert(nbrs[0] == 1 && valid[0] == 1 && valid[1] == 0 && valid[2] == 0);
  assert(nbrs[k] == 3 && valid[k] == 1);
  // deg-0 (last node) and out-of-range seeds: all lanes invalid + zeroed
  for (int64_t i = 2; i < b; ++i)
    for (int64_t j = 0; j < k; ++j) {
      assert(valid[i * k + j] == 0);
      assert(nbrs[i * k + j] == 0);
    }
  std::printf("  chain copy-all ok\n");
}

// deg > k: k DISTINCT draws, all members of the CSR row.
void test_distinct_subset() {
  const int64_t n = 2, deg = 10, k = 4;
  std::vector<int64_t> indptr = {0, deg, deg};
  std::vector<int64_t> indices(deg);
  for (int64_t j = 0; j < deg; ++j) indices[j] = 100 + j;  // node 0's nbrs
  std::vector<int64_t> seeds(64, 0);
  std::vector<int64_t> nbrs(seeds.size() * k);
  std::vector<uint8_t> valid(seeds.size() * k);
  qt_sample_layer(indptr.data(), indices.data(), n, seeds.data(),
                  seeds.size(), k, 7, nbrs.data(), valid.data());
  for (size_t i = 0; i < seeds.size(); ++i) {
    std::set<int64_t> got;
    for (int64_t j = 0; j < k; ++j) {
      assert(valid[i * k + j] == 1);
      int64_t v = nbrs[i * k + j];
      assert(v >= 100 && v < 100 + deg);
      got.insert(v);
    }
    assert((int64_t)got.size() == k);  // without replacement
  }
  std::printf("  distinct k-subset ok\n");
}

// uniformity: over many draws each neighbor appears ~ k/deg of the time.
void test_uniformity() {
  const int64_t n = 2, deg = 20, k = 5, reps = 20000;
  std::vector<int64_t> indptr = {0, deg, deg};
  std::vector<int64_t> indices(deg);
  for (int64_t j = 0; j < deg; ++j) indices[j] = j;
  std::vector<int64_t> seeds(reps, 0);
  std::vector<int64_t> nbrs(reps * k);
  std::vector<uint8_t> valid(reps * k);
  qt_sample_layer(indptr.data(), indices.data(), n, seeds.data(), reps, k,
                  1234, nbrs.data(), valid.data());
  std::vector<int64_t> counts(deg, 0);
  for (int64_t i = 0; i < reps * k; ++i) counts[nbrs[i]]++;
  const double expect = double(reps) * k / deg;  // = 5000
  for (int64_t j = 0; j < deg; ++j) {
    double ratio = counts[j] / expect;
    assert(ratio > 0.9 && ratio < 1.1);  // ~14 sigma slack at these counts
  }
  std::printf("  uniformity ok\n");
}

// weighted draws: distinct, weight-biased, zero-weight edges excluded.
void test_weighted_sample() {
  const int64_t n = 2, deg = 4, k = 2, reps = 20000;
  std::vector<int64_t> indptr = {0, deg, deg};
  std::vector<int64_t> indices = {0, 1, 2, 3};
  std::vector<float> w = {1.f, 2.f, 4.f, 8.f};
  std::vector<int64_t> seeds(reps, 0);
  std::vector<int64_t> nbrs(reps * k);
  std::vector<uint8_t> valid(reps * k);
  qt_sample_layer_weighted(indptr.data(), indices.data(), w.data(), n,
                           seeds.data(), reps, k, 99, nbrs.data(),
                           valid.data());
  std::vector<int64_t> counts(deg, 0);
  for (int64_t i = 0; i < reps; ++i) {
    assert(valid[i * k] && valid[i * k + 1]);
    assert(nbrs[i * k] != nbrs[i * k + 1]);  // without replacement
    counts[nbrs[i * k]]++;
    counts[nbrs[i * k + 1]]++;
  }
  // Plackett-Luce inclusion prob of the heaviest item, w=(1,2,4,8), k=2:
  // P = 8/15 + sum_i (w_i/15)(8/(15-w_i)) = 0.847
  assert(counts[0] < counts[1] && counts[1] < counts[2] && counts[2] < counts[3]);
  double p3 = double(counts[3]) / reps;
  assert(p3 > 0.82 && p3 < 0.88);
  // zero-weight edge never drawn; only `positive` lanes valid
  std::vector<float> w0 = {1.f, 0.f, 1.f, 0.f};
  qt_sample_layer_weighted(indptr.data(), indices.data(), w0.data(), n,
                           seeds.data(), 64, 3, 5, nbrs.data(), valid.data());
  for (int64_t i = 0; i < 64; ++i)
    for (int64_t j = 0; j < 3; ++j)
      if (valid[i * 3 + j]) {
        int64_t v = nbrs[i * 3 + j];
        assert(v == 0 || v == 2);
      }
  std::printf("  weighted sample ok\n");
}

// the local_reindex contract: seed slots verbatim (first slot wins for
// duplicates), new uniques ascending, masked-out lanes -> 0.
void test_reindex_contract() {
  // head has a duplicate (7 at slots 1 and 3); nbrs mix head hits, new
  // values out of order, duplicates, and a masked lane
  std::vector<int64_t> head = {5, 7, 2, 7};
  std::vector<int64_t> nbrs = {9, 7, 3, /*masked*/ 123, 3, 2, 9, 11};
  std::vector<uint8_t> mask = {1, 1, 1, 0, 1, 1, 1, 1};
  std::vector<int64_t> n_id(head.size() + nbrs.size(), -1);
  std::vector<int32_t> local(nbrs.size(), -1);
  int64_t count = 0;
  qt_reindex(head.data(), head.size(), nbrs.data(), mask.data(), nbrs.size(),
             n_id.data(), &count, local.data());
  // new uniques: {3, 9, 11} ascending -> slots 4, 5, 6
  assert(count == 7);
  const int64_t want_nid[7] = {5, 7, 2, 7, 3, 9, 11};
  for (int64_t i = 0; i < count; ++i) assert(n_id[i] == want_nid[i]);
  // 9->5, 7->first head slot 1, 3->4, masked->0, 3->4, 2->2, 9->5, 11->6
  const int32_t want_local[8] = {5, 1, 4, 0, 4, 2, 5, 6};
  for (size_t j = 0; j < nbrs.size(); ++j) assert(local[j] == want_local[j]);
  std::printf("  reindex contract ok\n");
}

void test_gather_rows() {
  const int64_t n = 8, d = 3;
  std::vector<float> src(n * d);
  for (int64_t i = 0; i < n * d; ++i) src[i] = float(i);
  std::vector<int64_t> ids = {3, 0, 7, -1, n, 3};
  const int64_t b = ids.size();
  std::vector<float> out(b * d, -1.f);
  qt_gather_rows(src.data(), n, d, ids.data(), b, out.data());
  for (int64_t i = 0; i < b; ++i) {
    int64_t id = ids[i];
    for (int64_t j = 0; j < d; ++j) {
      float want = (id < 0 || id >= n) ? 0.f : src[id * d + j];
      assert(out[i * d + j] == want);
    }
  }
  std::printf("  gather rows (incl. OOB zeroing) ok\n");
}

// byte-row gather: odd row sizes (e.g. bf16 dim 3 = 6 bytes) round-trip.
void test_gather_rows_bytes() {
  const int64_t n = 5, rb = 6;
  std::vector<uint8_t> src(n * rb);
  for (size_t i = 0; i < src.size(); ++i) src[i] = static_cast<uint8_t>(i * 7);
  std::vector<int64_t> ids = {4, 0, -3, 5, 2};
  std::vector<uint8_t> out(ids.size() * rb, 0xAB);
  qt_gather_rows_bytes(src.data(), n, rb, ids.data(), ids.size(), out.data());
  for (size_t i = 0; i < ids.size(); ++i) {
    int64_t id = ids[i];
    for (int64_t j = 0; j < rb; ++j) {
      uint8_t want = (id < 0 || id >= n) ? 0 : src[id * rb + j];
      assert(out[i * rb + j] == want);
    }
  }
  std::printf("  gather rows bytes (odd row size) ok\n");
}

// power-law-ish CSR for the bench (fast to build; skew comparable to the
// Python bench's generator at small scale).
void build_graph(int64_t n, int64_t e, std::vector<int64_t> &indptr,
                 std::vector<int64_t> &indices) {
  std::mt19937_64 rng(0);
  std::vector<double> w(n);
  std::uniform_real_distribution<double> u(0.0, 1.0);
  for (int64_t i = 0; i < n; ++i) w[i] = std::pow(u(rng) + 1e-9, -0.6);
  double tot = 0;
  for (double x : w) tot += x;
  indptr.assign(n + 1, 0);
  for (int64_t i = 0; i < n; ++i)
    indptr[i + 1] = indptr[i] + std::max<int64_t>(1, int64_t(w[i] / tot * e));
  indices.resize(indptr[n]);
  std::uniform_int_distribution<int64_t> dst(0, n - 1);
  for (size_t j = 0; j < indices.size(); ++j) indices[j] = dst(rng);
}

void bench() {
  // reference kernel-bench shape: 1M nodes / ~4M edges, batch 1024, k=5
  {
    std::vector<int64_t> indptr, indices;
    build_graph(1'000'000, 4'000'000, indptr, indices);
    const int64_t b = 1024, k = 5, iters = 200;
    std::vector<int64_t> seeds(b), nbrs(b * k);
    std::vector<uint8_t> valid(b * k);
    std::mt19937_64 rng(1);
    std::uniform_int_distribution<int64_t> pick(0, 999'999);
    int64_t edges = 0;
    auto t0 = std::chrono::steady_clock::now();
    for (int64_t it = 0; it < iters; ++it) {
      for (auto &s : seeds) s = pick(rng);
      qt_sample_layer(indptr.data(), indices.data(), 1'000'000, seeds.data(),
                      b, k, it, nbrs.data(), valid.data());
      for (auto v : valid) edges += v;
    }
    double dt = std::chrono::duration<double>(
                    std::chrono::steady_clock::now() - t0).count();
    std::printf("1-hop k=5 (ref bench shape): %.2fM SEPS (%lld edges, %.2fs)\n",
                edges / dt / 1e6, (long long)edges, dt);
  }
  // products-fanout 3-hop row (the BASELINE.md CPU-sampler config)
  {
    std::vector<int64_t> indptr, indices;
    build_graph(2'449'029, 123'718'280, indptr, indices);
    const int64_t b = 1024, iters = 20;
    const int64_t ks[3] = {15, 10, 5};
    std::mt19937_64 rng(2);
    std::uniform_int_distribution<int64_t> pick(0, 2'449'028);
    int64_t edges = 0;
    auto t0 = std::chrono::steady_clock::now();
    std::vector<int64_t> frontier(b), nbrs;
    std::vector<uint8_t> valid;
    for (int64_t it = 0; it < iters; ++it) {
      for (auto &s : frontier) s = pick(rng);
      std::vector<int64_t> cur = frontier;
      for (int64_t l = 0; l < 3; ++l) {
        int64_t k = ks[l], w = cur.size();
        nbrs.assign(w * k, 0);
        valid.assign(w * k, 0);
        qt_sample_layer(indptr.data(), indices.data(), 2'449'029, cur.data(),
                        w, k, it * 10 + l, nbrs.data(), valid.data());
        std::vector<int64_t> next;
        next.reserve(w * k);
        for (int64_t i = 0; i < w * k; ++i)
          if (valid[i]) {
            next.push_back(nbrs[i]);
            ++edges;
          }
        cur.swap(next);
      }
    }
    double dt = std::chrono::duration<double>(
                    std::chrono::steady_clock::now() - t0).count();
    std::printf("3-hop [15,10,5] products-shape: %.2fM SEPS "
                "(%lld edges, %.2fs)\n",
                edges / dt / 1e6, (long long)edges, dt);
  }
}

}  // namespace

int main(int argc, char **argv) {
  if (argc > 1 && std::strcmp(argv[1], "bench") == 0) {
    bench();
    return 0;
  }
  test_chain_copy_all();
  test_distinct_subset();
  test_uniformity();
  test_weighted_sample();
  test_reindex_contract();
  test_gather_rows();
  test_gather_rows_bytes();
  std::printf("ALL NATIVE TESTS PASSED\n");
  return 0;
}

"""Offline probability-driven feature partitioner.

Re-design of the reference ``srcs/python/quiver/partition.py``:
``partition_feature_without_replication`` (partition.py:14-70, chunk-greedy,
chunk size 256 at partition.py:12), ``quiver_partition_feature``
(partition.py:73-143) and ``load_quiver_feature_partition``
(partition.py:146-173).

The algorithm is host-side/offline, so it stays numpy (the reference runs it
in torch on CPU/GPU): iterate id space in chunks; assign each chunk's nodes to
the partition whose access probability gain (own probability minus the other
partitions' average) is highest, balancing sizes.

Artifacts are saved with ``np.savez`` instead of ``torch.save`` but keep the
reference's file-role split: per-partition result + cache + a global
partition book.
"""

from __future__ import annotations

import os
from typing import List, Sequence, Tuple, Union

import numpy as np

CHUNK_SIZE = 256  # reference partition.py:12

QUIVER_PARTITION_FILE = "partition_res.npz"       # reference: partition_res.pth
QUIVER_CACHE_FILE = "cache_res.npz"               # reference: cache_res.pth
QUIVER_PARTITION_BOOK_FILE = "feature_partition_book.npz"


def partition_feature_without_replication(
    probs: Sequence[np.ndarray], chunk_size: int = CHUNK_SIZE
) -> Tuple[List[np.ndarray], np.ndarray]:
    """Greedy chunked assignment maximizing own-probability advantage
    (reference partition.py:14-70).

    probs: one access-probability vector per partition (from
    ``GraphSageSampler.sample_prob``), each [N].

    Returns (per-partition id arrays, partition_book [N] -> partition).
    The per-partition arrays are HEAT-ordered (hot nodes first — useful for
    cache-prefix placement); sort them ascending before use as a
    ``set_local_order``/``PartitionInfo`` local_order, whose rank space is
    ascending-id (reference feature.py:484-508).
    """
    probs = [np.asarray(p, dtype=np.float64) for p in probs]
    n_parts = len(probs)
    n = probs[0].shape[0]
    for p in probs:
        assert p.shape[0] == n
    prob_mat = np.stack(probs)  # [P, N]
    partition_book = np.full(n, -1, dtype=np.int32)
    res: List[List[np.ndarray]] = [[] for _ in range(n_parts)]
    sizes = np.zeros(n_parts, dtype=np.int64)

    # nodes any partition touches, in descending total probability — the
    # reference walks chunks of the raw id range; ordering by heat gives the
    # same result faster convergence-wise and stays deterministic
    total = prob_mat.sum(axis=0)
    touched = np.argsort(-total, kind="stable")
    touched = touched[total[touched] > 0]
    untouched = np.nonzero(total == 0)[0]

    for start in range(0, touched.shape[0], chunk_size):
        chunk = touched[start : start + chunk_size]
        sub = prob_mat[:, chunk]  # [P, C]
        # score per partition: own prob minus average of others
        # (reference partition.py:35-54)
        others = (sub.sum(axis=0, keepdims=True) - sub) / max(n_parts - 1, 1)
        gain = sub - others
        # balance: penalize the currently largest partitions
        gain = gain - (sizes[:, None] - sizes.min()) * 1e-9
        pick = np.argmax(gain, axis=0)
        for p in range(n_parts):
            ids = chunk[pick == p]
            if ids.size:
                res[p].append(ids)
                partition_book[ids] = p
                sizes[p] += ids.size
    # untouched nodes round-robin for balance (reference assigns rest evenly)
    if untouched.size:
        order = np.argsort(sizes, kind="stable")
        splits = np.array_split(untouched, n_parts)
        for p, ids in zip(order, splits):
            if ids.size:
                res[p].append(ids)
                partition_book[ids] = p
    out = [
        np.concatenate(r) if r else np.empty(0, dtype=np.int64) for r in res
    ]
    return out, partition_book


def quiver_partition_feature(
    probs: Sequence[np.ndarray],
    result_path: str,
    cache_memory_budget: Union[int, str] = 0,
    per_feature_size: int = 0,
    chunk_size: int = CHUNK_SIZE,
):
    """Partition + per-partition hot-cache selection, persisted to disk
    (reference partition.py:73-143)."""
    from .utils import parse_size

    os.makedirs(result_path, exist_ok=True)
    partitions, book = partition_feature_without_replication(probs, chunk_size)
    cache_budget = parse_size(cache_memory_budget)
    cache_rows = 0
    if cache_budget and per_feature_size:
        cache_rows = cache_budget // int(per_feature_size)
    caches = []
    for p, ids in enumerate(partitions):
        part_dir = os.path.join(result_path, f"partition_{p}")
        os.makedirs(part_dir, exist_ok=True)
        # hot cache for partition p: the hottest rows NOT owned by p
        # (reference caches remote-but-hot rows, partition.py:104-126)
        others = np.asarray(probs[p], dtype=np.float64).copy()
        others[ids] = 0
        cache_ids = np.argsort(-others, kind="stable")[:cache_rows]
        cache_ids = cache_ids[others[cache_ids] > 0]
        caches.append(cache_ids)
        np.savez(
            os.path.join(part_dir, QUIVER_PARTITION_FILE), partition_ids=ids
        )
        np.savez(os.path.join(part_dir, QUIVER_CACHE_FILE), cache_ids=cache_ids)
    np.savez(
        os.path.join(result_path, QUIVER_PARTITION_BOOK_FILE), partition_book=book
    )
    return partitions, caches, book


def load_quiver_feature_partition(partition_idx: int, result_path: str):
    """Load one partition's artifacts (reference partition.py:146-173)."""
    part_dir = os.path.join(result_path, f"partition_{partition_idx}")
    part = np.load(os.path.join(part_dir, QUIVER_PARTITION_FILE))
    cache = np.load(os.path.join(part_dir, QUIVER_CACHE_FILE))
    book = np.load(os.path.join(result_path, QUIVER_PARTITION_BOOK_FILE))
    return (
        part["partition_ids"],
        cache["cache_ids"],
        book["partition_book"],
    )

"""Checkpoint / resume for sampled-GNN training state.

The reference has NO library-level checkpointing (SURVEY.md section 5:
"absent from the library"; only benchmark scripts load Lightning checkpoints
for eval, train_quiver_multi_node.py:436-451, and offline artifacts are
torch.save'd files, partition.py:133-141). This module closes that gap with
an orbax-backed store for (params, opt_state, step, sampler RNG cursor), so
long multi-epoch runs survive preemption — table stakes on TPU pods.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import numpy as np


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


class CheckpointManager:
    """Thin orbax CheckpointManager wrapper keyed by step.

    save/restore operate on a pytree dict, e.g.::

        mgr = CheckpointManager("/tmp/run1", max_to_keep=3)
        mgr.save(step, {"params": params, "opt_state": opt_state,
                        "sampler_call": sampler._call})
        state = mgr.restore()           # latest, or restore(step)
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        ocp = _ocp()
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )

    def save(self, step: int, state: Dict[str, Any], wait: bool = True) -> None:
        ocp = _ocp()
        import jax

        # numpy SCALAR leaves (np.int64 step counters etc.) are rejected by
        # newer orbax StandardSave type validation; 0-d ndarrays round-trip
        state = jax.tree_util.tree_map(
            lambda x: np.asarray(x) if isinstance(x, np.generic) else x, state
        )
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, step: Optional[int] = None, template: Any = None) -> Any:
        ocp = _ocp()
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        if template is not None:
            return self._mgr.restore(step, args=ocp.args.StandardRestore(template))
        return self._mgr.restore(step)

    def flush(self) -> None:
        """Block until async saves (``save(..., wait=False)``) are durable."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()


def save_partition_artifacts(path: str, **arrays) -> None:
    """Persist offline artifacts (partition books, orders, preprocessed CSR)
    — the torch.save analog (reference preprocess.py:143-179)."""
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
    np.savez(path, **{k: np.asarray(v) for k, v in arrays.items()})


def load_partition_artifacts(path: str) -> Dict[str, np.ndarray]:
    data = np.load(path if path.endswith(".npz") else path + ".npz")
    return {k: data[k] for k in data.files}

"""DGL-style consumption surface: blocks (message-flow graphs) over
DenseSample.

The reference advertises PyG *and* DGL front ends; its DGL example
(/root/reference/examples/dgl/ogbn_products_sage_quiver.py:36-49) consumes
sampling output as a list of ``blocks`` where each block is an MFG with a
dst-prefix convention (``h_dst = h[:block.num_dst_nodes()]``) and layers are
called as ``layer(block, (h_src, h_dst))``.

`quiver_tpu.pyg.sage_sampler.DenseAdj` already IS that structure — targets
are the prefix of each hop's source n_id (DenseAdj docstring) — so the DGL
mapping is a thin adapter, not a port:

==============================  =======================================
DGL                             quiver_tpu
==============================  =======================================
``input_nodes``                 ``ds.n_id``
``output_nodes``                ``ds.n_id[:ds.batch_size]``
``blocks[l]``                   ``Block(ds.adjs[l], ...)`` (this module)
``block.num_dst_nodes()``       static target width of the hop
``block.num_src_nodes()``       static source width of the hop
``dglnn.SAGEConv(..., 'mean')``  :class:`DGLSAGEConv` — same
                                ``(block, (h_src, h_dst))`` call shape
``NodeDataLoader``              seed batches -> ``sampler.sample_dense``
==============================  =======================================

Widths here are STATIC (padded) — the XLA contract; masked lanes carry
zero weight in the aggregation, so semantics match DGL's ragged blocks.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from .pyg.sage_sampler import DenseAdj, DenseSample


class Block:
    """One message-flow graph (DGL ``dgl.to_block`` analog) wrapping a
    :class:`DenseAdj`. Registered as a pytree (the adj's arrays are
    children, ``num_src`` is static aux), so Blocks can be passed as jit
    ARGUMENTS — as ``examples/dgl_style_sage.py`` does with the adjs
    pytree. Do NOT close over a Block in jitted code: a closed-over Block
    embeds its arrays as compile-time constants and retraces per batch."""

    def __init__(self, adj: DenseAdj, num_src: int):
        self.adj = adj
        self._num_src = int(num_src)

    def num_dst_nodes(self) -> int:
        return self.adj.w_dst

    def num_src_nodes(self) -> int:
        return self._num_src


jax.tree_util.register_pytree_node(
    Block,
    lambda b: ((b.adj,), b._num_src),
    lambda num_src, children: Block(children[0], num_src),
)


def to_blocks(ds: DenseSample) -> Tuple[jax.Array, jax.Array, List[Block]]:
    """DGL dataloader triple ``(input_nodes, output_nodes, blocks)`` from a
    :class:`DenseSample` (reference DGL example consumes exactly this shape
    from its loader, ogbn_products_sage_quiver.py:120-131).

    Blocks are ordered outermost hop first — the order DGL feeds layers.
    Hop l's source width: the full n_id for the first block, the previous
    block's target width after that (each layer consumes the previous
    layer's output array).
    """
    blocks: List[Block] = []
    src_w = ds.n_id.shape[0]
    for adj in ds.adjs:
        blocks.append(Block(adj, src_w))
        src_w = adj.w_dst
    return ds.n_id, ds.n_id[: ds.batch_size], blocks


class DGLSAGEConv(nn.Module):
    """``dglnn.SAGEConv(..., aggregator_type='mean')`` call-compatible
    layer: ``conv(block, (h_src, h_dst))`` -> ``[num_dst, out_dim]``.
    Same math as `models.sage.SAGEConv` (fc_neigh(mean) + fc_self(h_dst));
    only the calling convention differs."""

    out_dim: int
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(
        self, block: Block, feat: Tuple[jax.Array, jax.Array]
    ) -> jax.Array:
        from .models.sage import masked_mean_aggregate

        h_src, h_dst = feat
        if self.dtype is not None:
            h_src = h_src.astype(self.dtype)
            h_dst = h_dst.astype(self.dtype)
        agg = masked_mean_aggregate(h_src, block.adj)
        h = nn.Dense(self.out_dim, dtype=self.dtype, name="fc_neigh")(agg)
        return h + nn.Dense(
            self.out_dim, use_bias=False, dtype=self.dtype, name="fc_self"
        )(h_dst)


class DGLStyleSAGE(nn.Module):
    """The reference DGL example's SAGE model, blocks-first
    (ogbn_products_sage_quiver.py:16-49): per layer,
    ``h_dst = h[:block.num_dst_nodes()]; h = layer(block, (h, h_dst))``
    with relu + dropout between layers."""

    hidden_dim: int
    out_dim: int
    num_layers: int = 3
    dropout: float = 0.5
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(
        self,
        blocks: Sequence[Block],
        x: jax.Array,
        *,
        train: bool = False,
    ) -> jax.Array:
        assert len(blocks) == self.num_layers, (len(blocks), self.num_layers)
        h = x
        for l, block in enumerate(blocks):
            h_dst = h[: block.num_dst_nodes()]
            dim = self.out_dim if l == self.num_layers - 1 else self.hidden_dim
            h = DGLSAGEConv(dim, dtype=self.dtype, name=f"layers_{l}")(
                block, (h, h_dst)
            )
            if l != self.num_layers - 1:
                h = jax.nn.relu(h)
                h = nn.Dropout(self.dropout, deterministic=not train)(h)
        return h.astype(jnp.float32)

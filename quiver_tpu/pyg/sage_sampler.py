"""GraphSAGE k-hop sampler — TPU-native re-design of the reference
``srcs/python/quiver/pyg/sage_sampler.py`` (GraphSageSampler at
sage_sampler.py:36-178).

Reference modes (sage_sampler.py:55-81) and their TPU mapping:

- ``GPU``  (graph resident in device memory)     -> ``"TPU"``: CSR in HBM,
  sampling + reindex run as fused XLA ops on-chip.
- ``UVA``  (graph in pinned host mem, GPU kernels read over PCIe) -> ``"HOST"``:
  no UVA exists on TPU; the graph stays in host DRAM and sampling runs in the
  native host engine (C++/numpy), feeding padded batches to the device. This
  preserves the capability (graph larger than HBM) the UVA mode existed for
  (SURVEY.md section 7.3 item 2).
- ``CPU``  -> ``"CPU"``: host sampling, results stay host-side.

Two output surfaces:

- :meth:`GraphSageSampler.sample_dense` — fully static-shape pytree
  (padded ``[S, k]`` adjacency + masks + counts), jittable end to end; this is
  what the TPU training loop consumes.
- :meth:`GraphSageSampler.sample` — reference/PyG-compatible
  ``(n_id, batch_size, [Adj])`` with ragged ``edge_index`` (host sync), so
  reference training scripts port line for line
  (sage_sampler.py:118-147).
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..utils import CSRTopo
from ..ops.sample import (
    pad_widths,
    sample_layer as _sample_layer_op,
    sample_prob as _sample_prob,
    tiled_sample_layer as _tiled_sample_layer_op,
    tiled_weighted_sample_layer as _tiled_weighted_sample_layer_op,
    weighted_sample_layer as _weighted_sample_layer_op,
)
from ..ops.reindex import local_reindex


class Adj(NamedTuple):
    """PyG-compatible adjacency (reference sage_sampler.py:21-28)."""

    edge_index: np.ndarray  # [2, nnz] (col=source, row=target local ids)
    e_id: np.ndarray        # empty — reference keeps it empty too (sage_sampler.py:143)
    size: Tuple[int, int]   # (n_src, n_dst)

    def to(self, *args, **kwargs):  # torch-API compat shim
        return self


class DenseAdj(NamedTuple):
    """Static-shape adjacency for one hop.

    ``cols[i, j]`` is the local id (into the *source* n_id of this hop) of the
    j-th sampled neighbor of target node i; ``mask`` marks real samples. The
    target nodes are always the prefix ``[:mask.shape[0]]`` of the source
    n_id, so dense GraphSAGE aggregation is a gather + masked mean.

    ``cols is None`` marks the STRUCTURAL layout of the fused (no-dedup)
    pipeline: neighbor (i, j) sits at source position ``W + j*W + i`` with
    ``W = mask.shape[0]``, so aggregation needs no gather at all — a slice +
    reshape replaces it (measured 2.3x faster than the equivalent iota-cols
    take on TPU: XLA does not recognize the pattern). ``None`` is a pytree
    aux value, so jitted code can branch on it in Python.
    """

    cols: Optional[jax.Array]  # [S, k] int32, or None (structural layout)
    mask: jax.Array   # [S, k] bool
    n_src: jax.Array  # scalar int32 — valid source-node count
    n_dst: jax.Array  # scalar int32 — valid target-node count

    @property
    def w_dst(self) -> int:
        """Static target-node width of this hop."""
        return self.mask.shape[0]

    def gather_src(self, x_src: jax.Array) -> jax.Array:
        """Neighbor features ``[W_dst, k, ...]`` from the hop-source array,
        honoring the layout: a slice+reshape for the structural (fused)
        layout, a gather for explicit cols."""
        w, k = self.mask.shape
        if self.cols is None:
            s = x_src[w : w * (1 + k)]
            return s.reshape((k, w) + x_src.shape[1:]).swapaxes(0, 1)
        return jnp.take(x_src, jnp.clip(self.cols, 0, x_src.shape[0] - 1), axis=0)


class DenseSample(NamedTuple):
    n_id: jax.Array          # [cap] padded unique node ids (global)
    count: jax.Array         # scalar int32 valid length of n_id
    batch_size: int
    adjs: Tuple[DenseAdj, ...]  # outermost hop first (reference reverses too)
    # dedup pipelines only (None elsewhere): the machinery that lets static
    # caps run TIGHT margins without silently changing sampling semantics.
    # cap_overflow: scalar int32, unique frontier nodes dropped by the caps
    # this batch (0 == bit-exact reference semantics); raw_counts: [L] int32
    # PRE-cap unique counts per hop (innermost-sampled last) — feed them to
    # `caps_from_counts` to recalibrate instead of re-probing.
    cap_overflow: Optional[jax.Array] = None
    raw_counts: Optional[jax.Array] = None


def sample_dense_fused(
    indptr: jax.Array,
    indices: jax.Array,
    key: jax.Array,
    seeds: jax.Array,
    sizes: Tuple[int, ...],
    sample_fn=None,
) -> DenseSample:
    """Fused multi-hop sample with NO per-layer dedup/reindex — the
    TPU-idiomatic hot path.

    The reference dedups every hop with a GPU hash table because UVA/PCIe
    bandwidth made repeated feature/topology reads expensive. On TPU the
    dedup itself is the expensive part (sort-based `unique` costs two
    O(W log W) sorts per hop on the MXU-starved sort unit), while the padded
    frontier is exactly the same width with or without dedup
    (W_{l+1} = W_l * (1+k)). Skipping dedup makes the local adjacency a
    STATIC index pattern — ``cols[i, j] = W_l + i*k + j`` — so the whole
    multihop pipeline is just degree lookups, Fisher-Yates draws and index
    gathers: zero sorts, zero scatters.

    Semantics: identical sampled-edge distribution; ``n_id`` may contain
    duplicate nodes (each occurrence carries the same feature row, so model
    outputs are bit-identical to the deduped pipeline up to float order).
    Use :func:`sample_dense_pure` when the unique-n_id contract matters
    (PyG-compat surface, cross-host dispatch).
    """
    if sample_fn is None:
        def sample_fn(cur, cur_valid, k, key):
            return _sample_layer_op(indptr, indices, cur, cur_valid, k, key)
    B = seeds.shape[0]
    cur = seeds
    cur_valid = jnp.ones((B,), bool)
    adjs: List[DenseAdj] = []
    prev_count = jnp.asarray(B, jnp.int32)
    for k in sizes:
        key, sub = jax.random.split(key)
        w = cur.shape[0]
        nbrs, valid = sample_fn(cur, cur_valid, k, sub)
        # transposed flatten: a [big, tiny] row-major flatten costs ~40 s of
        # TPU compile (lane-tile relayout); [k, w] -> flat is free. Neighbor
        # (i, j) lands at n_id position w + j*w + i — the structural layout
        # (cols=None) that lets aggregation run gather-free.
        n_id = jnp.concatenate([cur, nbrs.T.reshape(-1)])
        n_valid = jnp.concatenate([cur_valid, valid.T.reshape(-1)])
        count = n_valid.sum().astype(jnp.int32)
        adjs.append(DenseAdj(cols=None, mask=valid, n_src=count, n_dst=prev_count))
        cur, cur_valid, prev_count = n_id, n_valid, count
    return DenseSample(n_id=cur, count=prev_count, batch_size=B, adjs=tuple(adjs[::-1]))


def sample_and_gather_fused(
    indptr: jax.Array,
    indices: jax.Array,
    table: jax.Array,
    key: jax.Array,
    seeds: jax.Array,
    sizes: Tuple[int, ...],
    gather_fn=None,
    sample_fn=None,
) -> Tuple[DenseSample, jax.Array]:
    """Fused multi-hop sample with the FEATURE GATHER interleaved per hop.

    ``n_id`` is a concatenation of per-hop neighbor blocks, so the feature
    rows can be fetched hop by hop as each frontier materializes instead of
    in one big take at the end — XLA then overlaps hop l's (row-rate-bound)
    gather with hop l+1's sampling compute. Returns ``(ds, x)`` with
    ``x == table[clip(ds.n_id)]`` row for row (invalid lanes carry garbage
    rows that ``adj.mask`` gates out of every aggregation, exactly like the
    single-take formulation).

    ``gather_fn(table, ids) -> rows`` overrides the local HBM take — e.g.
    `quiver_tpu.parallel.collectives.sharded_gather` inside shard_map, so
    the ICI collective per hop overlaps with sampling the same way.
    """
    B = seeds.shape[0]
    if gather_fn is None:
        n_rows = table.shape[0]

        def gather_fn(tab, ids):
            return jnp.take(tab, jnp.clip(ids, 0, n_rows - 1), axis=0)
    if sample_fn is None:
        def sample_fn(cur, cur_valid, k, key):
            return _sample_layer_op(indptr, indices, cur, cur_valid, k, key)
    cur = seeds
    cur_valid = jnp.ones((B,), bool)
    adjs: List[DenseAdj] = []
    xs = [gather_fn(table, seeds)]
    prev_count = jnp.asarray(B, jnp.int32)
    for k in sizes:
        key, sub = jax.random.split(key)
        w = cur.shape[0]
        nbrs, valid = sample_fn(cur, cur_valid, k, sub)
        flat = nbrs.T.reshape(-1)
        xs.append(gather_fn(table, flat))
        n_id = jnp.concatenate([cur, flat])
        n_valid = jnp.concatenate([cur_valid, valid.T.reshape(-1)])
        count = n_valid.sum().astype(jnp.int32)
        adjs.append(DenseAdj(cols=None, mask=valid, n_src=count, n_dst=prev_count))
        cur, cur_valid, prev_count = n_id, n_valid, count
    ds = DenseSample(n_id=cur, count=prev_count, batch_size=B, adjs=tuple(adjs[::-1]))
    return ds, jnp.concatenate(xs, axis=0)


def sample_and_gather_dedup(
    indptr: jax.Array,
    indices: jax.Array,
    table: jax.Array,
    key: jax.Array,
    seeds: jax.Array,
    sizes: Tuple[int, ...],
    caps: Optional[Tuple[Optional[int], ...]] = None,
    gather_fn=None,
    sample_fn=None,
) -> Tuple[DenseSample, jax.Array]:
    """Reference-parity dedup sampling with a STRUCTURAL last hop — the fast
    formulation of the deduped e2e train step.

    The sampling DAG is identical to `sample_dense_pure` (each hop draws k
    neighbors of each node of the UNIQUE previous frontier — the reference's
    hash-table reindex contract, sage_sampler.py:133-145): hops 1..L-1 run
    dedup + sort-reindex exactly as `sample_dense_pure`. The LAST hop skips
    the reindex: its leaves stay in the sampled ``[W_{L-1}, k]`` layout and
    their feature rows are gathered straight from ``table`` into the
    structural (cols=None) block. Per (target, slot) the sampled edge and
    its feature row are exactly what the full-dedup pipeline feeds the
    model, so model outputs match up to float association; what changes is
    the data flow:

    - the leaf aggregation becomes a slice+reshape (2.3x faster than the
      equivalent take, PERF_NOTES.md) instead of a W_{L-1}*k_L-row gather
      from computed activations;
    - that gather's backward scatter disappears entirely — the structural
      leaf rows read the CONSTANT feature table, so no gradient flows;
    - the last (largest) reindex's sorts and the unique-leaf feature gather
      are replaced by one structural gather.

    Net on products shapes: ~1.0M gathered rows/step vs ~1.6M for gathering
    unique n_id + cols-aggregation. Returns ``(ds, x)``; ``ds.n_id`` is the
    hop-(L-1) unique frontier followed by the structural leaf block (NOT
    globally unique — this is the e2e-internal surface; the public sampler
    contract lives in `sample_dense_pure`/`GraphSageSampler.sample`).
    """
    if len(sizes) == 0:
        raise ValueError("sizes must name at least one hop")
    if gather_fn is None:
        n_rows = table.shape[0]

        def gather_fn(tab, ids):
            return jnp.take(tab, jnp.clip(ids, 0, n_rows - 1), axis=0)

    if sample_fn is None:
        def sample_fn(cur, cur_valid, k, key):
            return _sample_layer_op(indptr, indices, cur, cur_valid, k, key)

    B = seeds.shape[0]
    inner_caps = None if caps is None else tuple(caps[: len(sizes) - 1])
    widths = pad_widths(B, sizes[:-1], inner_caps)
    cur = seeds
    cur_valid = jnp.ones((B,), bool)
    adjs: List[DenseAdj] = []
    raws: List[jax.Array] = []
    overflow = jnp.asarray(0, jnp.int32)
    prev_count = jnp.asarray(B, jnp.int32)
    for l, k in enumerate(sizes[:-1]):
        key, sub = jax.random.split(key)
        nbrs, valid = sample_fn(cur, cur_valid, k, sub)
        res = local_reindex(cur, cur_valid, nbrs, valid)
        n_id, count = res.n_id, res.count
        raws.append(count)
        local_nbrs, nbr_valid = res.local_nbrs, res.nbr_valid
        if widths[l + 1] < n_id.shape[0]:
            cap = widths[l + 1]
            n_id = n_id[:cap]
            overflow = overflow + jnp.maximum(count - cap, 0)
            count = jnp.minimum(count, cap)
            nbr_valid = nbr_valid & (local_nbrs < cap)
        adjs.append(
            DenseAdj(cols=local_nbrs, mask=nbr_valid, n_src=count, n_dst=prev_count)
        )
        cur = n_id
        cur_valid = jnp.arange(n_id.shape[0], dtype=jnp.int32) < count
        prev_count = count
    # last hop: structural leaves, features straight off the table
    k = sizes[-1]
    key, sub = jax.random.split(key)
    nbrs, valid = sample_fn(cur, cur_valid, k, sub)
    flat = nbrs.T.reshape(-1)  # leaf (i, j) -> position W + j*W + i
    x = jnp.concatenate([gather_fn(table, cur), gather_fn(table, flat)], axis=0)
    n_src = prev_count + valid.sum().astype(jnp.int32)
    adjs.append(DenseAdj(cols=None, mask=valid, n_src=n_src, n_dst=prev_count))
    raws.append(n_src)  # structural leaves are never capped
    ds = DenseSample(
        n_id=jnp.concatenate([cur, flat]),
        count=n_src,
        batch_size=B,
        adjs=tuple(adjs[::-1]),
        cap_overflow=overflow,
        raw_counts=jnp.stack(raws),
    )
    return ds, x


def sample_dense_pure(
    indptr: jax.Array,
    indices: jax.Array,
    key: jax.Array,
    seeds: jax.Array,
    sizes: Tuple[int, ...],
    caps: Optional[Tuple[Optional[int], ...]] = None,
    sample_fn=None,
) -> DenseSample:
    """Pure, jittable multi-hop sample (static ``sizes``/``caps``).

    The reference's per-layer loop (sage_sampler.py:133-145) with the ragged
    hash-table reindex replaced by the static-shape sort reindex.

    ``sample_fn(cur, cur_valid, k, key) -> (nbrs, valid)`` overrides the
    local one-hop op — e.g. the collective
    `quiver_tpu.parallel.topology.sharded_sample_layer` when the CSR is
    row-sharded across the mesh (``indptr``/``indices`` may then be None).
    """
    if sample_fn is None:
        def sample_fn(cur, cur_valid, k, key):
            return _sample_layer_op(indptr, indices, cur, cur_valid, k, key)
    B = seeds.shape[0]
    widths = pad_widths(B, sizes, caps)
    cur = seeds
    cur_valid = jnp.ones((B,), bool)
    adjs: List[DenseAdj] = []
    raws: List[jax.Array] = []
    overflow = jnp.asarray(0, jnp.int32)
    prev_count = jnp.asarray(B, jnp.int32)
    for l, k in enumerate(sizes):
        key, sub = jax.random.split(key)
        nbrs, valid = sample_fn(cur, cur_valid, k, sub)
        res = local_reindex(cur, cur_valid, nbrs, valid)
        n_id, count = res.n_id, res.count
        raws.append(count)
        local_nbrs, nbr_valid = res.local_nbrs, res.nbr_valid
        if widths[l + 1] < n_id.shape[0]:
            cap = widths[l + 1]
            n_id = n_id[:cap]
            overflow = overflow + jnp.maximum(count - cap, 0)
            count = jnp.minimum(count, cap)
            nbr_valid = nbr_valid & (local_nbrs < cap)
        adjs.append(
            DenseAdj(cols=local_nbrs, mask=nbr_valid, n_src=count, n_dst=prev_count)
        )
        cur = n_id
        cur_valid = jnp.arange(n_id.shape[0], dtype=jnp.int32) < count
        prev_count = count
    return DenseSample(
        n_id=cur,
        count=prev_count,
        batch_size=B,
        adjs=tuple(adjs[::-1]),
        cap_overflow=overflow,
        raw_counts=jnp.stack(raws),
    )


import functools as _functools


@_functools.partial(jax.jit, static_argnames=("sizes",))
def _probe_hop_counts_scan(ip, ix, key0, batches, sizes):
    def body(_, i):
        ds = sample_dense_pure(
            ip, ix, jax.random.fold_in(key0, i), batches[i], sizes
        )
        return None, jnp.stack([a.n_src for a in ds.adjs[::-1]])

    _, counts = jax.lax.scan(
        body, None, jnp.arange(batches.shape[0], dtype=jnp.int32)
    )
    return counts


def probe_hop_counts(
    indptr: jax.Array,
    indices: jax.Array,
    key: jax.Array,
    seeds_all: jax.Array,
    sizes: Tuple[int, ...],
    sample_fn=None,
    cache: dict = None,
) -> np.ndarray:
    """Per-hop unique-frontier counts over ``m`` probe batches: ``[m, L]``.

    One jitted scan over the UNCAPPED dedup pipeline — one dispatch total,
    so probing is cheap even through a high-latency link (PERF_NOTES.md
    measurement discipline). The default flat-CSR path reuses one
    module-level compiled program across calls. A custom ``sample_fn``
    (the tiled DEFAULT layout and weighted samplers — caps MUST be
    calibrated under the distribution they will serve) closes over its own
    graph arrays, so its scan cannot live in the module-level cache; pass
    ``cache`` (any dict owned by the caller, keyed here by ``sizes``) to
    reuse the traced scan across calls — `GraphSageSampler.calibrate_caps`
    passes a per-sampler dict, which is sound because a sampler's layout /
    weighting / graph (everything ``sample_fn`` closes over) is fixed at
    construction. Without ``cache``, each call retraces.
    """
    seeds_all = jnp.asarray(seeds_all)
    if sample_fn is None:
        return np.asarray(
            _probe_hop_counts_scan(indptr, indices, key, seeds_all, tuple(sizes))
        )

    sizes_t = tuple(sizes)
    run = cache.get(sizes_t) if cache is not None else None
    if run is None:

        @jax.jit
        def run(key0, batches):
            def body(_, i):
                ds = sample_dense_pure(
                    None, None, jax.random.fold_in(key0, i), batches[i],
                    sizes_t, sample_fn=sample_fn,
                )
                return None, jnp.stack([a.n_src for a in ds.adjs[::-1]])

            _, counts = jax.lax.scan(
                body, None, jnp.arange(batches.shape[0], dtype=jnp.int32)
            )
            return counts

        if cache is not None:
            cache[sizes_t] = run

    return np.asarray(run(key, seeds_all))


def caps_from_counts(
    counts: np.ndarray,
    batch: int,
    sizes: Tuple[int, ...],
    margin: float = 1.2,
    granule: int = 4096,
) -> Tuple[int, ...]:
    """Static per-hop n_id caps from probed unique counts.

    ``max`` over the probe batches x ``margin`` safety factor, rounded up to
    ``granule`` (shape granularity keeps recompiles away when recalibrating),
    clipped to the uncapped worst case ``B*prod(1+k)``. This is the policy
    the round-2 bench hand-rolled (bench.py:275-286) promoted into the
    library — the reference needs no caps (ragged CUDA shapes); static-shape
    TPU pipelines do, so choosing them is the framework's job.
    """
    counts = np.asarray(counts).reshape(-1, len(sizes))
    worst = pad_widths(batch, sizes)[1:]
    caps = []
    for l in range(len(sizes)):
        need = int(np.max(counts[:, l])) * margin
        caps.append(int(min(-(-need // granule) * granule, worst[l])))
    return tuple(caps)


class GraphSageSampler:
    """K-hop sampler over a :class:`CSRTopo` (reference sage_sampler.py:36).

    Parameters
    ----------
    csr_topo : CSRTopo
    sizes : fanouts, outermost-first like PyG (e.g. ``[15, 10, 5]``)
    device : int, local device index for TPU mode (reference's GPU ordinal)
    mode : "TPU" | "HOST" | "CPU" (aliases: "GPU" -> TPU, "UVA" -> HOST,
        "ZERO_COPY"/"DMA" -> HOST/TPU)
    caps : optional per-layer static n_id budget (TPU-only knob; bounds padded
        growth for deep fanouts)
    seed : RNG seed; sampling is deterministic given (seed, call index)
    layout : "tiled" (default) | "flat" — TPU-mode graph layout. "tiled"
        stores edges 128-lane-aligned (`CSRTopo.to_device_tiled`) so the
        neighbor fetch rides 2-D row gathers (~1.4x the element-gather
        rate, measured) at ~2-3x flat-CSR HBM bytes; "flat" keeps the
        plain CSR (use when HBM is tight). Draw-identical on the same
        seed (weighted: when max_deg is a multiple of 128). Weighted
        tiled additionally tiles the edge weights
        (`to_device_tiled_weights`) so the [B, max_deg] weight window
        rides ceil(max_deg/128) row gathers per row instead of max_deg
        element gathers.
    dedup : True (default) dedups every hop like the reference's hash-table
        reindex; False uses the fused no-reindex hot path
        (`sample_dense_fused`) — fastest on TPU, n_id may repeat nodes
    auto_grow_caps : opt-in overflow ladder for TIGHT caps. When a dedup
        batch overflows its caps (``DenseSample.cap_overflow > 0`` — unique
        nodes would have been dropped), recalibrate the caps from that
        batch's pre-cap ``raw_counts`` (margin/granule from the last
        `calibrate_caps` call) and resample. Costs one host sync per
        ``sample_dense`` call and a recompile per cap change, so use with
        granule-rounded caps where regrowth is rare; the payoff is running
        margins like 1.1 instead of 1.2 — less padded gather width — while
        keeping exact reference sampling semantics.
    """

    MODE_ALIASES = {"GPU": "TPU", "UVA": "HOST", "ZERO_COPY": "HOST", "DMA": "TPU"}

    def __init__(
        self,
        csr_topo: CSRTopo,
        sizes: Sequence[int],
        device=0,
        mode: str = "TPU",
        caps: Optional[Sequence[Optional[int]]] = None,
        seed: int = 0,
        dedup: bool = True,
        weighted: bool = False,
        max_deg: int = 512,
        auto_grow_caps: bool = False,
        layout: str = "tiled",
    ):
        mode = self.MODE_ALIASES.get(mode, mode)
        if mode not in ("TPU", "HOST", "CPU"):
            raise ValueError(f"unsupported mode: {mode}")
        if layout not in ("tiled", "flat"):
            raise ValueError(f"unsupported layout: {layout}")
        self.csr_topo = csr_topo
        self.sizes = tuple(int(s) for s in sizes)
        self.caps = None if caps is None else tuple(caps)
        self.mode = mode
        self.device = device
        self.dedup = dedup
        self.weighted = weighted
        self.max_deg = int(max_deg)
        self.auto_grow_caps = bool(auto_grow_caps)
        # recalibration policy for the overflow ladder; updated by
        # calibrate_caps so regrowth uses the margin the caps were born with
        self.cap_margin = 1.2
        self.cap_granule = 4096
        if weighted:
            if csr_topo.edge_weights is None:
                raise ValueError(
                    "weighted=True needs CSRTopo(edge_weights=...) "
                    "(per-edge weights aligned with the COO input)"
                )
            # TPU mode: Gumbel-top-k device op. HOST/CPU: the native
            # engine's Efraimidis-Spirakis weighted k-subset (same
            # distribution; qt_sample_layer_weighted) — the reference has
            # no CPU weighted path at all (weight_sample is CUDA-only,
            # cuda_random.cu.hpp:177-221).
        self.layout = layout
        self._seed = seed
        self._call = 0
        self._dev_arrays = None
        self._dev_tiled = None
        self._w_dev = None
        # round-17 streaming binding (`bind_stream`): when set, the tiled
        # device graph is READ FROM THE STREAM at every sample/spec call
        # instead of the frozen CSRTopo cache — fenced graph deltas become
        # visible to the next draw without touching the key stream
        self._stream = None
        # round-19 temporal binding (`bind_temporal`): (source, recency)
        # — the source carries per-edge timestamps in the tile payload
        # lanes and every draw takes a per-seed query time t
        self._temporal = None
        # per-sampler probe-scan cache: under the default layout='tiled'
        # (and for weighted samplers) _engine() hands probe_hop_counts a
        # fresh sample_fn closure per call, so without this the jitted
        # probe scan would retrace on EVERY calibrate_caps call
        self._probe_scan_cache: dict = {}
        if mode == "TPU":
            self.lazy_init_quiver()
        self._host_engine = None

    def _device_obj(self):
        if isinstance(self.device, int):
            local = jax.local_devices()
            return local[self.device % len(local)]
        return None

    # -- streaming graph binding (round 17; quiver_tpu.stream) -----------
    @property
    def stream(self):
        """The bound `stream.StreamingTiledGraph`, or None (frozen
        graph). Serve engines read this to decide whether
        ``update_graph`` is supported."""
        return self._stream

    def bind_stream(self, stream) -> "GraphSageSampler":
        """Attach a `quiver_tpu.stream.StreamingTiledGraph`: every
        sample (split path), fused-spec build, and `lazy_init_quiver`
        then reads the stream's CURRENT device ``(bd, tiles)`` pair —
        array objects change at each fenced delta commit, shapes never
        do, so sealed AOT serve programs keep running (the engine
        rebinds their argument arrays via `BucketPrograms.rebind`).
        TPU-mode tiled uniform samplers only: HOST/CPU engines sample a
        host CSR the stream does not maintain, the flat layout has no
        pad lanes to append into, and weighted samplers would need the
        weight tiles streamed in lockstep (not built — stage weights
        with a rebuild instead)."""
        if self.mode != "TPU":
            raise TypeError("bind_stream needs mode='TPU' (device graph)")
        if self.layout != "tiled":
            raise TypeError(
                "bind_stream needs layout='tiled' — the flat CSR has no "
                "pad lanes to append into"
            )
        if self.weighted:
            raise TypeError(
                "streaming deltas keep the uniform tile map only; "
                "weighted samplers would need wtiles streamed in lockstep"
            )
        self._stream = stream
        self._dev_tiled = None
        # the cached probe scan (calibrate_caps) bakes the graph arrays
        # in as trace-time constants — sound for a frozen graph, stale
        # the moment this sampler reads a stream (re-keyed per commit
        # version in calibrate_caps)
        self._probe_scan_cache.clear()
        return self

    # -- temporal binding (round 19; quiver_tpu.workloads) ----------------
    @property
    def temporal(self):
        """``(source, recency)`` when this sampler draws temporally
        (`bind_temporal`), else None. The serve engines read this to pick
        the temporal serve-step shape (an extra per-seed query-time
        argument on every dispatch)."""
        return self._temporal

    def bind_temporal(self, source, recency: float = 0.0) -> "GraphSageSampler":
        """Attach a temporal graph: every draw then samples only edges
        with ``ts <= t`` (per-seed query times, a jit ARGUMENT of every
        dispatch — never a closure constant), recency-biased via the
        weighted sampler's Gumbel machinery
        (`ops.sample.tiled_temporal_sample_layer`;
        ``recency`` is the exponent of `ops.sample.temporal_edge_weights`,
        0 = uniform over the valid set).

        ``source`` is a `workloads.temporal.TemporalTiledGraph` (frozen
        graph + timestamps) or a `stream.StreamingTiledGraph` built with
        ``edge_ts=`` — the streaming case ALSO binds the stream
        (`bind_stream` semantics), so fenced ``update_graph`` commits
        make an arriving edge visible to the next ``t >= ts`` query and
        invisible below it. TPU-mode tiled uniform samplers with
        ``dedup=False`` only: the temporal pipeline threads each seed's
        own t down its frontier lineage, which needs the structural
        no-dedup layout (a dedup reindex would merge frontiers across
        requests with different query times)."""
        if self.mode != "TPU":
            raise TypeError("bind_temporal needs mode='TPU' (device graph)")
        if self.layout != "tiled":
            raise TypeError(
                "bind_temporal needs layout='tiled' — timestamps ride the "
                "tile payload lanes"
            )
        if self.weighted:
            raise TypeError(
                "temporal recency bias replaces static edge weights; "
                "bind_temporal needs weighted=False"
            )
        if self.dedup:
            raise TypeError(
                "temporal sampling threads per-seed query times down the "
                "frontier lineage — construct with dedup=False (the "
                "structural no-dedup pipeline)"
            )
        if not getattr(source, "temporal", False):
            raise TypeError(
                "bind_temporal wants a TemporalTiledGraph or a "
                "StreamingTiledGraph built with edge_ts= (got "
                f"{type(source).__name__})"
            )
        from ..stream import StreamingTiledGraph

        if isinstance(source, StreamingTiledGraph):
            # streaming temporal: the stream binding rides along so the
            # serve engines' update_graph/stage_edges find it
            self._stream = source
            self._dev_tiled = None
        self._temporal = (source, float(recency))
        self._probe_scan_cache.clear()
        return self

    def temporal_graph_arrays(self):
        """The CURRENT device ``(bd, tiles, ttiles)`` triple a temporal
        draw reads — re-read per call so fenced stream commits become
        visible to the next draw."""
        if self._temporal is None:
            raise TypeError("sampler has no temporal binding")
        return self._temporal[0].temporal_graph()

    def fused_graph_arrays(self):
        """The CURRENT device-graph pytree the fused serve programs take
        as their ``graph`` argument — temporal triple, streamed pair, or
        the frozen binding (`lazy_init_quiver`), in that precedence. The
        serve engines rebind sealed executables to this after a fenced
        graph commit."""
        if self._temporal is not None:
            return self.temporal_graph_arrays()
        if self._stream is not None:
            return self._stream.graph()
        return self.lazy_init_quiver()

    # -- device-graph binding (reference lazy_init_quiver, sage_sampler.py:98-113)
    def lazy_init_quiver(self):
        """Bind the graph to the device and return the binding: the
        ``(bd, tiles)`` pair under the default tiled layout (weighted
        samplers included — their weight tiles bind separately via
        ``to_device_tiled_weights``), the flat ``(indptr, indices)`` pair
        under ``layout='flat'``. Callers needing the flat pair regardless
        of layout should use ``self.csr_topo.to_device()``."""
        if self.layout == "tiled":
            if self._stream is not None:
                return self._stream.graph()
            if self._dev_tiled is None:
                self._dev_tiled = self.csr_topo.to_device_tiled(self._device_obj())
            return self._dev_tiled
        if self._dev_arrays is None:
            self._dev_arrays = self.csr_topo.to_device(self._device_obj())
        return self._dev_arrays

    def _host(self):
        if self._host_engine is None:
            from ..ops import cpu_kernels

            self._host_engine = cpu_kernels.HostSampler(
                self.csr_topo.indptr,
                self.csr_topo.indices,
                weights=self.csr_topo.edge_weights if self.weighted else None,
            )
        return self._host_engine

    def _next_key(self) -> jax.Array:
        key = jax.random.fold_in(jax.random.key(self._seed), self._call)
        self._call += 1
        return key

    def next_key(self) -> jax.Array:
        """Consume and return the next key of this sampler's deterministic
        stream WITHOUT running a sample — key i is exactly the key
        `sample_dense`'s i-th call would have drawn. The fused serve path
        (`inference.serve_step`) draws keys host-side in dispatch order and
        runs the sample itself inside the one pre-bound device program, so
        the key stream (and any replay of the dispatch log through a twin
        sampler) stays identical to the split sample/forward path."""
        if self.mode != "TPU":
            raise TypeError(
                "next_key() draws the TPU-mode jax key stream; HOST/CPU "
                "samplers derive their RNG seed inside sample_dense"
            )
        return self._next_key()

    def fused_sample_spec(self):
        """``(graph, bind, id_dtype)`` for building FUSED in-jit
        sample+gather+forward programs (`inference.make_serve_step`).

        ``graph`` is the device-array pytree the fused program must take as
        jit ARGUMENTS — never closure constants: big closure constants are
        the remote-compile trap (NEXT.md; bit round 5's probe script).
        ``bind(graph)`` rebuilds the one-hop ``sample_fn`` over the TRACED
        graph arrays inside the jit, mirroring `_engine()`'s eager
        closures. Raises TypeError when this sampler cannot be fused
        (HOST/CPU modes sample host-side; ``auto_grow_caps`` resizes caps
        mid-stream, which a pre-bound static-shape executable cannot
        follow)."""
        if self.mode != "TPU":
            raise TypeError("fused sampling needs mode='TPU' (device-resident graph)")
        if self.auto_grow_caps:
            raise TypeError(
                "auto_grow_caps resizes caps mid-stream; the fused serve "
                "program needs static caps (calibrate_caps first, or "
                "construct with auto_grow_caps=False)"
            )
        if self.layout == "tiled":
            bd, tiles = self.lazy_init_quiver()
            if self.weighted:
                wtiles = self.csr_topo.to_device_tiled_weights(self._device_obj())
                graph = (bd, tiles, wtiles)
                max_deg = self.max_deg

                def bind(g):
                    bd, tiles, wtiles = g

                    def sample_fn(cur, cur_valid, k, key):
                        return _tiled_weighted_sample_layer_op(
                            bd, tiles, wtiles, cur, cur_valid, k, key, max_deg
                        )

                    return sample_fn
            else:
                graph = (bd, tiles)

                def bind(g):
                    bd, tiles = g

                    def sample_fn(cur, cur_valid, k, key):
                        return _tiled_sample_layer_op(bd, tiles, cur, cur_valid, k, key)

                    return sample_fn
            return graph, bind, tiles.dtype
        indptr, indices = self.lazy_init_quiver()
        if self.weighted:
            if self._w_dev is None:
                self._w_dev = jnp.asarray(
                    np.asarray(self.csr_topo.edge_weights, np.float32)
                )
            graph = (indptr, indices, self._w_dev)
            max_deg = self.max_deg

            def bind(g):
                indptr, indices, w = g

                def sample_fn(cur, cur_valid, k, key):
                    return _weighted_sample_layer_op(
                        indptr, indices, w, cur, cur_valid, k, key, max_deg
                    )

                return sample_fn
        else:
            graph = (indptr, indices)

            def bind(g):
                indptr, indices = g

                def sample_fn(cur, cur_valid, k, key):
                    return _sample_layer_op(indptr, indices, cur, cur_valid, k, key)

                return sample_fn
        return graph, bind, indices.dtype

    def _weighted_sample_fn(self):
        """sample_fn closure routing one-hop draws through the weighted
        (Gumbel top-k) op; None when this sampler is uniform."""
        if not self.weighted:
            return None
        indptr, indices = self.lazy_init_quiver()
        if self._w_dev is None:
            self._w_dev = jnp.asarray(
                np.asarray(self.csr_topo.edge_weights, np.float32)
            )
        w, max_deg = self._w_dev, self.max_deg

        def sample_fn(cur, cur_valid, k, key):
            return _weighted_sample_layer_op(
                indptr, indices, w, cur, cur_valid, k, key, max_deg
            )

        return sample_fn

    def _engine(self):
        """(indptr, indices, sample_fn, id_dtype) for the dense pipelines.
        indptr/indices are None under the tiled layout — the sample_fn
        closure carries the (bd, tiles[, wtiles]) arrays instead."""
        if self.layout == "tiled":
            bd, tiles = self.lazy_init_quiver()
            if self.weighted:
                wtiles = self.csr_topo.to_device_tiled_weights(self._device_obj())
                max_deg = self.max_deg

                def sample_fn(cur, cur_valid, k, key):
                    return _tiled_weighted_sample_layer_op(
                        bd, tiles, wtiles, cur, cur_valid, k, key, max_deg
                    )
            elif self._stream is not None:
                # stream-bound: re-read the CURRENT device pair per draw
                # (a fenced commit swaps the array objects; binding them
                # into the closure once would sample the pre-delta graph
                # forever)
                stream = self._stream

                def sample_fn(cur, cur_valid, k, key):
                    bd_s, tiles_s = stream.graph()
                    return _tiled_sample_layer_op(
                        bd_s, tiles_s, cur, cur_valid, k, key
                    )
            else:
                def sample_fn(cur, cur_valid, k, key):
                    return _tiled_sample_layer_op(bd, tiles, cur, cur_valid, k, key)

            return None, None, sample_fn, tiles.dtype
        indptr, indices = self.lazy_init_quiver()
        if self.weighted:
            return indptr, indices, self._weighted_sample_fn(), indices.dtype
        return indptr, indices, None, indices.dtype

    # -- dense static-shape surface --------------------------------------
    def sample_dense(self, seeds, t=None) -> DenseSample:
        """Sample a padded, jittable mini-batch. TPU mode runs fully on
        device; HOST/CPU modes run the native host engine and pad.

        ``t`` (temporal samplers only — `bind_temporal`): per-seed query
        times, scalar or ``[B]``; every hop of a seed's expansion then
        draws only edges with ``ts <= t[seed]``. Consumes one key of the
        same deterministic stream as every other sample call."""
        if self._temporal is not None:
            if t is None:
                raise TypeError(
                    "temporal sampler needs a query time: "
                    "sample_dense(seeds, t=...)"
                )
            from ..workloads.temporal import temporal_sample_dense

            source, recency = self._temporal
            graph = self.temporal_graph_arrays()
            seeds = jnp.asarray(np.asarray(seeds), graph[1].dtype)
            tv = np.asarray(t, np.float32).reshape(-1)
            if tv.shape[0] == 1 and seeds.shape[0] != 1:
                tv = np.broadcast_to(tv, (seeds.shape[0],)).copy()
            if tv.shape[0] != seeds.shape[0]:
                raise ValueError(
                    f"t has {tv.shape[0]} entries for {seeds.shape[0]} seeds"
                )
            return temporal_sample_dense(
                graph, self._next_key(), seeds, jnp.asarray(tv),
                self.sizes, recency=recency, max_deg=self.max_deg,
            )
        if t is not None:
            raise TypeError(
                "t= is only meaningful on a temporal sampler "
                "(bind_temporal first)"
            )
        if self.mode == "TPU":
            indptr, indices, sample_fn, id_dtype = self._engine()
            seeds = jnp.asarray(np.asarray(seeds), id_dtype)
            if not self.dedup:
                return sample_dense_fused(
                    indptr, indices, self._next_key(), seeds, self.sizes,
                    sample_fn=sample_fn,
                )
            ds = sample_dense_pure(
                indptr, indices, self._next_key(), seeds, self.sizes, self.caps,
                sample_fn=sample_fn,
            )
            if self.auto_grow_caps and self.caps is not None:
                # overflow ladder: regrow caps from the observed pre-cap
                # counts and resample until nothing is dropped. raw_counts of
                # hop l+1 are measured under hop l's (possibly capped)
                # frontier, so one regrow can reveal more demand — iterate,
                # bounded (caps_from_counts clips at the uncapped worst case,
                # where overflow is impossible by construction).
                for _ in range(len(self.sizes) + 1):
                    if int(ds.cap_overflow) == 0:
                        break
                    grown = caps_from_counts(
                        np.asarray(ds.raw_counts)[None, :], seeds.shape[0],
                        self.sizes, margin=self.cap_margin,
                        granule=self.cap_granule,
                    )
                    # monotone merge: one batch's raw_counts must only ever
                    # RAISE caps — taking them wholesale would shrink hops
                    # that didn't overflow this batch (raw_counts are a
                    # single sample, not the calibrated max), ping-ponging
                    # caps and recompiling every few batches. None stays
                    # None: an uncapped hop cannot overflow, so capping it
                    # would force a shape change no overflow ever demanded.
                    self.caps = tuple(
                        None if o is None else max(o, n)
                        for o, n in zip(self.caps, grown)
                    )
                    ds = sample_dense_pure(
                        indptr, indices, self._next_key(), seeds, self.sizes,
                        self.caps, sample_fn=sample_fn,
                    )
                if int(ds.cap_overflow) > 0:
                    # ladder bound exhausted (per-key count fluctuation can
                    # outrun a small margin): surface it — the caller still
                    # sees cap_overflow, but silence here would contradict
                    # the "resample until nothing is dropped" contract
                    import warnings

                    warnings.warn(
                        f"auto_grow_caps: still dropping "
                        f"{int(ds.cap_overflow)} nodes after regrowth to "
                        f"caps={self.caps}; raise cap_margin/cap_granule",
                        RuntimeWarning,
                        stacklevel=2,
                    )
            return ds
        return self._host_sample_dense(np.asarray(seeds))

    def _host_sample_dense(self, seeds: np.ndarray) -> DenseSample:
        eng = self._host()
        rng_seed = (self._seed * 0x9E3779B1 + self._call) & 0x7FFFFFFF
        self._call += 1
        n_id, count, adjs = eng.sample_multilayer(
            seeds.astype(np.int64), self.sizes, rng_seed, self.caps
        )
        dense_adjs = tuple(
            DenseAdj(
                cols=jnp.asarray(a["cols"]),
                mask=jnp.asarray(a["mask"]),
                n_src=jnp.asarray(a["n_src"], jnp.int32),
                n_dst=jnp.asarray(a["n_dst"], jnp.int32),
            )
            for a in adjs[::-1]
        )
        return DenseSample(
            n_id=jnp.asarray(n_id),
            count=jnp.asarray(count, jnp.int32),
            batch_size=int(seeds.shape[0]),
            adjs=dense_adjs,
        )

    # -- reference/PyG-compatible surface ---------------------------------
    def sample(self, input_nodes):
        """Reference-compatible ``(n_id, batch_size, [Adj])``
        (sage_sampler.py:118-147). Ragged — forces a host sync; prefer
        :meth:`sample_dense` inside TPU training loops.

        Always uses the deduped pipeline: the ragged contract requires
        unique, prefix-valid n_id, which the fused path does not provide.
        """
        if self.mode == "TPU" and not self.dedup:
            indptr, indices, sample_fn, id_dtype = self._engine()
            seeds = jnp.asarray(np.asarray(input_nodes), id_dtype)
            ds = sample_dense_pure(
                indptr, indices, self._next_key(), seeds, self.sizes, self.caps,
                sample_fn=sample_fn,
            )
        else:
            ds = self.sample_dense(input_nodes)
        return dense_to_pyg(ds)

    def sample_layer(self, seeds, size: int):
        """One-hop sample (reference sage_sampler.py:83-96): returns ragged
        (neighbors, counts) on host."""
        if self.mode == "TPU":
            indptr, indices, fn, id_dtype = self._engine()
            seeds_d = jnp.asarray(np.asarray(seeds), id_dtype)
            if fn is None:
                nbrs, valid = _sample_layer_op(
                    indptr, indices, seeds_d, jnp.ones(seeds_d.shape, bool), size,
                    self._next_key(),
                )
            else:
                nbrs, valid = fn(
                    seeds_d, jnp.ones(seeds_d.shape, bool), size, self._next_key()
                )
            nbrs, valid = np.asarray(nbrs), np.asarray(valid)
        else:
            eng = self._host()
            rng_seed = (self._seed * 0x9E3779B1 + self._call) & 0x7FFFFFFF
            self._call += 1
            nbrs, valid = eng.sample_layer(np.asarray(seeds, np.int64), size, rng_seed)
        counts = valid.sum(axis=1)
        return nbrs[valid], counts

    def reindex(self, inputs, outputs, counts):
        """Reference-compatible reindex of a ragged one-hop result
        (sage_sampler.py:115-116): returns (n_id, row, col).

        The ragged->padded conversion is vectorized (row-major mask
        assignment matches the ragged concatenation order) — a per-row
        Python loop here was the compat surface's bottleneck at products
        batch sizes."""
        inputs = np.asarray(inputs)
        counts = np.asarray(counts, np.int64)
        S = inputs.shape[0]
        k = int(counts.max()) if S else 0
        padded = np.zeros((S, max(k, 1)), np.int64)
        mask = np.arange(max(k, 1))[None, :] < counts[:, None]
        padded[mask] = np.asarray(outputs)
        res = local_reindex(
            jnp.asarray(inputs), jnp.ones((S,), bool), jnp.asarray(padded), jnp.asarray(mask)
        )
        n_id = np.asarray(res.n_id)[: int(res.count)]
        rows = np.repeat(np.arange(S), counts)
        cols = np.asarray(res.local_nbrs)[np.asarray(res.nbr_valid)]
        return n_id, rows, cols

    # -- static-cap calibration (TPU-only concern; see caps_from_counts) --
    def calibrate_caps(
        self,
        probe_seeds,
        margin: float = 1.2,
        granule: int = 4096,
        set_caps: bool = True,
    ) -> Tuple[int, ...]:
        """Probe-batch calibration of the per-hop static n_id caps.

        ``probe_seeds``: [m, B] array (or list of m same-length batches) of
        representative seed batches — use >= 8 so the max is stable. Returns
        the caps and (by default) installs them on this sampler. Persist
        alongside other offline artifacts via
        ``checkpoint.save_partition_artifacts(path, caps=np.asarray(caps))``.
        """
        batches = np.stack([np.asarray(b) for b in probe_seeds])
        if batches.ndim != 2:
            raise ValueError(f"probe_seeds must be [m, B]; got {batches.shape}")
        if self.mode == "TPU":
            if self._stream is not None:
                # the cached probe scan closes over the stream's graph
                # arrays AS OF ITS TRACE — a delta commit leaves it
                # probing a stale graph, so the cache lives one stream
                # version only (probe_hop_counts keys entries by sizes;
                # the version marker coexists under its own key)
                ver = int(self._stream.version)
                if self._probe_scan_cache.get("stream_version") != ver:
                    self._probe_scan_cache.clear()
                    self._probe_scan_cache["stream_version"] = ver
            indptr, indices, sample_fn, id_dtype = self._engine()
            counts = probe_hop_counts(
                indptr, indices, self._next_key(),
                jnp.asarray(batches.astype(np.dtype(id_dtype))), self.sizes,
                sample_fn=sample_fn, cache=self._probe_scan_cache,
            )
        else:
            rows = []
            for b in batches:  # host engine: uncapped dense sample per batch
                saved = self.caps
                self.caps = None
                try:
                    ds = self._host_sample_dense(b)
                finally:
                    self.caps = saved
                rows.append([int(a.n_src) for a in ds.adjs[::-1]])
            counts = np.asarray(rows)
        caps = caps_from_counts(
            counts, batches.shape[1], self.sizes, margin=margin, granule=granule
        )
        self.cap_margin, self.cap_granule = float(margin), int(granule)
        if set_caps:
            self.caps = caps
        return caps

    # -- hot-probability propagation (reference sage_sampler.py:149-157) --
    def sample_prob(self, train_idx, total_node_count: int):
        # flat CSR regardless of sampling layout: neighbor_prob's
        # edge-parallel segment sum wants the plain (indptr, indices)
        indptr, indices = self.csr_topo.to_device(
            self._device_obj() if self.mode == "TPU" else None
        )
        return _sample_prob(
            indptr, indices, self.sizes, jnp.asarray(np.asarray(train_idx)), total_node_count
        )

    # -- multiprocess hand-off shims (reference sage_sampler.py:159-178) --
    def share_ipc(self):
        return (
            self.csr_topo, self.sizes, self.device, self.mode, self.caps,
            self._seed, self.dedup, self.weighted, self.max_deg,
            self.auto_grow_caps, self.layout,
        )

    @classmethod
    def lazy_from_ipc_handle(cls, ipc_handle):
        (csr_topo, sizes, device, mode, caps, seed, dedup, weighted, max_deg,
         auto_grow_caps, layout) = ipc_handle
        return cls(
            csr_topo, sizes, device=device, mode=mode, caps=caps, seed=seed,
            dedup=dedup, weighted=weighted, max_deg=max_deg,
            auto_grow_caps=auto_grow_caps, layout=layout,
        )


def dense_to_pyg(ds: DenseSample):
    """Convert a padded DenseSample to the reference's ragged
    ``(n_id, batch_size, [Adj])`` (host-side)."""
    count = int(ds.count)
    n_id = np.asarray(ds.n_id)[:count]
    adjs = []
    for adj in ds.adjs:
        mask = np.asarray(adj.mask)
        if adj.cols is None:  # structural layout: cols[i, j] = W + j*W + i
            w, k = mask.shape
            cols = w * (1 + np.arange(k))[None, :] + np.arange(w)[:, None]
        else:
            cols = np.asarray(adj.cols)
        rows = np.broadcast_to(np.arange(cols.shape[0])[:, None], cols.shape)
        edge_index = np.stack([cols[mask], rows[mask]]).astype(np.int64)
        adjs.append(
            Adj(
                edge_index=edge_index,
                e_id=np.empty((0,), np.int64),
                size=(int(adj.n_src), int(adj.n_dst)),
            )
        )
    return n_id, ds.batch_size, adjs

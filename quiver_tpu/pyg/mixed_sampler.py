"""Hybrid device+CPU adaptive sampling.

Re-design of the reference's ``MixedGraphSageSampler``/``SampleJob``
(srcs/python/quiver/pyg/sage_sampler.py:180-376): daemon CPU worker
processes drain a task queue (cpu_sampler_worker_loop, sage_sampler.py:198-205)
while the device samples inline; every epoch the task split between device
and CPU is re-decided from measured average sample times
(decide_task_num, sage_sampler.py:272-288).

TPU mapping: "device" sampling is the XLA pipeline on the chip (which is
also busy training, so shifting sampling work to host CPUs is exactly as
valuable as it was on GPU); "CPU" sampling is the native host engine
(`quiver_tpu.csrc`). Workers are SPAWNED processes (fork deadlocks under
the JAX runtime's threads) attaching the CSR arrays — and per-edge weights,
when weighted — through POSIX shared memory, replacing the reference's
torch shared memory (CSRTopo.share_memory_, utils.py:216-226). Queues are
strictly per-worker with daemon drainer threads feeding one in-process
inbox, so a worker death can never wedge the train loop; dead workers'
pending tasks are resubmitted to survivors and the pool re-heals at the
next epoch.
"""

from __future__ import annotations

import multiprocessing as mp
import queue as queue_mod
import time
from typing import Iterator, Optional, Sequence

import numpy as np

from ..utils import CSRTopo
from .sage_sampler import DenseSample, GraphSageSampler

# sentinel a worker (or shutdown) posts on its result queue so the parent's
# drainer thread retires instead of blocking on get() forever
_DRAIN_DONE = ("__qt_drain_done__",)


class SampleJob:
    """Abstract indexable, shuffleable task list (reference
    sage_sampler.py:180-195). Each task is a seed batch."""

    def __getitem__(self, index: int):
        raise NotImplementedError

    def shuffle(self) -> None:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError


class TrainSampleJob(SampleJob):
    """Canonical job: shuffle train ids, fixed-size seed batches."""

    def __init__(self, train_idx: np.ndarray, batch_size: int, seed: int = 0):
        self.train_idx = np.asarray(train_idx, np.int64).copy()
        self.batch_size = batch_size
        self._rng = np.random.default_rng(seed)

    def shuffle(self) -> None:
        self._rng.shuffle(self.train_idx)

    def __len__(self) -> int:
        return (len(self.train_idx) + self.batch_size - 1) // self.batch_size

    def __getitem__(self, index: int):
        lo = index * self.batch_size
        return self.train_idx[lo : lo + self.batch_size]


def _cpu_worker_loop(shm_names, shapes, sizes, caps, seed, task_q, result_q,
                     weights_shm=None):
    """Reference cpu_sampler_worker_loop (sage_sampler.py:198-205).

    Workers are spawned (fork deadlocks under the JAX runtime's threads) and
    attach the CSR arrays through POSIX shared memory — the analog of the
    reference sharing CSRTopo via torch shm (utils.py:216-226).
    ``weights_shm``: optional (name, shape) of a float32 per-edge weight
    array — workers then draw through the native weighted engine."""
    from multiprocessing import shared_memory

    from ..ops.cpu_kernels import HostSampler

    shms = [shared_memory.SharedMemory(name=n) for n in shm_names]
    indptr = np.ndarray(shapes[0], dtype=np.int64, buffer=shms[0].buf)
    indices = np.ndarray(shapes[1], dtype=np.int64, buffer=shms[1].buf)
    weights = None
    if weights_shm is not None:
        shms.append(shared_memory.SharedMemory(name=weights_shm[0]))
        weights = np.ndarray(weights_shm[1], np.float32, buffer=shms[-1].buf)
    eng = HostSampler(indptr, indices, weights=weights)
    try:
        while True:
            item = task_q.get()
            if item is None:
                return
            epoch, task_idx, seeds = item
            t0 = time.perf_counter()
            n_id, count, adjs = eng.sample_multilayer(
                np.asarray(seeds, np.int64), sizes, seed + epoch * 1009 + task_idx, caps
            )
            dt = time.perf_counter() - t0
            result_q.put((epoch, task_idx, n_id, count, adjs, dt))
    finally:
        try:
            result_q.put(_DRAIN_DONE)  # retire the parent's drainer thread
        except Exception:
            pass
        del eng, indptr, indices, weights
        for shm in shms:
            shm.close()


class MixedGraphSageSampler:
    """Adaptive device+CPU k-hop sampler (reference sage_sampler.py:207-376).

    mode: "TPU_CPU_MIXED" | "HOST_CPU_MIXED" | "TPU_ONLY" | "CPU_ONLY"
    (reference spellings GPU_CPU_MIXED / UVA_CPU_MIXED / GPU_ONLY /
    UVA_ONLY accepted).

    Iterating yields ``(task_idx, DenseSample)`` per task, one epoch per
    ``__iter__`` (job reshuffled each epoch like the reference).
    """

    MODE_ALIASES = {
        "GPU_CPU_MIXED": "TPU_CPU_MIXED",
        "UVA_CPU_MIXED": "HOST_CPU_MIXED",
        "GPU_ONLY": "TPU_ONLY",
        "UVA_ONLY": "TPU_ONLY",
    }

    def __init__(
        self,
        job: SampleJob,
        csr_topo: CSRTopo,
        sizes: Sequence[int],
        num_workers: int = 2,
        device: int = 0,
        mode: str = "TPU_CPU_MIXED",
        caps: Optional[Sequence[Optional[int]]] = None,
        seed: int = 0,
        auto_tune_workers: bool = False,
        device_share_target: float = 0.5,
        weighted: bool = False,
        max_deg: int = 512,
    ):
        mode = self.MODE_ALIASES.get(mode, mode)
        if mode not in ("TPU_CPU_MIXED", "HOST_CPU_MIXED", "TPU_ONLY", "CPU_ONLY"):
            raise ValueError(f"unsupported mode: {mode}")
        if mode == "CPU_ONLY" and num_workers < 1:
            raise ValueError("CPU_ONLY mode needs num_workers >= 1")
        if weighted and csr_topo.edge_weights is None:
            raise ValueError(
                "weighted=True needs CSRTopo(edge_weights=...) "
                "(per-edge weights aligned with the COO input)"
            )
        if weighted and mode == "TPU_CPU_MIXED" and num_workers > 0:
            # the TPU engine weights only each row's first max_deg edges
            # (its static lane window), the CPU engine weights ALL edges —
            # on a graph whose max degree exceeds max_deg, device-assigned
            # and CPU-assigned tasks would draw from different
            # distributions. HOST_CPU_MIXED is exempt: its "device" half
            # is the host native engine, which also weights all edges.
            graph_max_deg = int(np.max(np.diff(csr_topo.indptr))) if len(
                csr_topo.indptr) > 1 else 0
            if graph_max_deg > max_deg:
                raise ValueError(
                    f"weighted MIXED sampling needs max_deg >= the graph's "
                    f"max degree ({graph_max_deg}; got max_deg={max_deg}): "
                    f"the device engine weights only the first max_deg edges "
                    f"per row while CPU workers weight all edges, so the two "
                    f"halves of one epoch would sample different "
                    f"distributions. Raise max_deg, or use CPU_ONLY/TPU_ONLY."
                )
        if weighted and num_workers > 0 and ("MIXED" in mode or mode == "CPU_ONLY"):
            # fail HERE with the real reason: otherwise every spawned worker
            # dies on HostSampler's RuntimeError in a detached process and
            # the parent only sees a 120 s "workers stalled" timeout
            from ..ops.cpu_kernels import _load_native

            lib = _load_native()
            if lib is None or not hasattr(lib, "qt_sample_layer_weighted"):
                # mirror the exact worker-side requirement (a stale .so can
                # be native_available() yet lack the weighted entry point)
                raise RuntimeError(
                    "weighted CPU workers need the native engine's "
                    "qt_sample_layer_weighted (make -C quiver_tpu/csrc); "
                    "rebuild libquiver_cpu.so or use num_workers=0 / "
                    "mode='TPU_ONLY'"
                )
        self.job = job
        self.csr_topo = csr_topo
        self.sizes = tuple(int(s) for s in sizes)
        self.caps = None if caps is None else tuple(caps)
        self.num_workers = num_workers if "MIXED" in mode or mode == "CPU_ONLY" else 0
        self.mode = mode
        self.seed = seed
        self.weighted = bool(weighted)
        dev_mode = "HOST" if mode.startswith("HOST") else "TPU"
        self.device_sampler = (
            None
            if mode == "CPU_ONLY"
            else GraphSageSampler(
                csr_topo, sizes, device=device, mode=dev_mode, caps=caps,
                seed=seed, weighted=weighted, max_deg=max_deg,
            )
        )
        self._workers = []
        self._task_qs = None
        self._result_qs = None
        self._inbox = None
        # measured averages drive the adaptive split (reference
        # avg_device_time/avg_cpu_time, sage_sampler.py:262-270)
        self.avg_device_time = 0.0
        self.avg_cpu_time = 0.0
        self.auto_tune_workers = auto_tune_workers and "MIXED" in mode
        self.device_share_target = float(device_share_target)
        self.last_device_share = None  # measured split of the last epoch

    # -- worker lifecycle (reference lazy_init, sage_sampler.py:298-313) ----
    def _spawn_worker(self, slot: int) -> None:
        """Start (or REPLACE, with fresh queues — the dead one's may be
        poisoned) the worker in ``slot``, plus its DRAINER thread.

        The parent never reads a worker pipe directly: a producer killed
        mid-put leaves a PARTIAL message on which even ``get_nowait`` blocks
        forever (poll() sees data, ``_recv_bytes`` never completes —
        measured, see tests/test_mixed_sampler.py worker-death tests). Each
        worker's results are pumped by a daemon thread into one thread-safe
        in-process inbox; if a drainer wedges on a torn message it strands
        only that daemon thread, never the train loop."""
        import threading

        ctx = mp.get_context("spawn")
        self._task_qs[slot] = ctx.Queue()
        result_q = ctx.Queue()
        self._result_qs[slot] = result_q
        self._spawn_count = getattr(self, "_spawn_count", 0) + 1
        shm_names, shapes, weights_shm = self._worker_shm_args
        p = ctx.Process(
            target=_cpu_worker_loop,
            args=(
                shm_names,
                shapes,
                self.sizes,
                self.caps,
                self.seed + 7919 * self._spawn_count,
                self._task_qs[slot],
                result_q,
                weights_shm,
            ),
            daemon=True,
        )
        p.start()
        self._workers[slot] = p

        inbox = self._inbox

        def drain():
            try:
                while True:
                    item = result_q.get()
                    if item == _DRAIN_DONE:
                        return  # worker exited (or shutdown retired us)
                    inbox.put(item)
            except Exception:
                return  # queue closed/poisoned: this drainer retires

        threading.Thread(target=drain, daemon=True).start()

    def lazy_init(self) -> None:
        if self.num_workers == 0:
            return
        if self._workers:
            # heal the pool: respawn any worker that died (OOM-kill etc.)
            # so one bad epoch does not degrade every later one
            for slot, p in enumerate(self._workers):
                if not p.is_alive():
                    self._spawn_worker(slot)
            return
        from multiprocessing import shared_memory

        # ONE task queue AND one result queue per worker (the reference
        # round-robins per-worker queues, sage_sampler.py:306-311) — and the
        # failure-isolation property this build adds: a process killed while
        # using an mp.Queue can corrupt that queue (documented
        # multiprocessing hazard), so nothing may be SHARED between workers
        # — a death then poisons only the dead worker's own queues, and
        # worker-death recovery can reroute pending tasks to survivors
        self._task_qs = [None] * self.num_workers
        self._result_qs = [None] * self.num_workers
        self._workers = [None] * self.num_workers
        self._inbox = queue_mod.Queue()  # thread queue: uncorruptible
        self._shms = []
        shm_names, shapes = [], []
        arrays = [
            (self.csr_topo.indptr, np.int64),
            (self.csr_topo.indices, np.int64),
        ]
        if self.weighted:
            arrays.append((self.csr_topo.edge_weights, np.float32))
        for arr, dt in arrays:
            arr = np.ascontiguousarray(arr, dt)
            shm = shared_memory.SharedMemory(create=True, size=max(arr.nbytes, 1))
            np.ndarray(arr.shape, dt, buffer=shm.buf)[:] = arr
            self._shms.append(shm)
            shm_names.append(shm.name)
            shapes.append(arr.shape)
        weights_shm = (shm_names[2], shapes[2]) if self.weighted else None
        self._worker_shm_args = (shm_names[:2], shapes[:2], weights_shm)
        for w in range(self.num_workers):
            self._spawn_worker(w)

    def shutdown(self) -> None:
        if self._task_qs is not None:
            for q, p in zip(self._task_qs, self._workers):
                if p.is_alive():
                    q.put(None)
        for p in self._workers:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        # retire drainer threads of TERMINATED workers (a clean worker exit
        # already posted the sentinel itself); a drainer wedged on a torn
        # message from a killed worker stays parked — daemon, harmless
        for q in self._result_qs or []:
            try:
                q.put(_DRAIN_DONE)
            except Exception:
                pass
        # never let interpreter exit JOIN these queues' feeder threads: a
        # dead worker's task queue can hold unread buffered items (pipe
        # full, no reader), wedging multiprocessing's atexit finalizer
        # forever (reproduced: 12-passed suite hanging at _exit_function)
        for q in (self._task_qs or []) + (self._result_qs or []):
            try:
                q.cancel_join_thread()
            except Exception:
                pass
        self._workers = []
        self._task_qs = None
        self._result_qs = None
        self._inbox = None
        for shm in getattr(self, "_shms", []):
            try:
                shm.close()
                shm.unlink()
            except Exception:
                pass
        self._shms = []

    # -- adaptive split (reference decide_task_num, sage_sampler.py:272-288)
    def decide_task_num(self, total: int) -> int:
        """Number of tasks the device takes this epoch."""
        if self.mode == "CPU_ONLY":
            return 0
        if self.num_workers == 0 or self.mode == "TPU_ONLY":
            return total
        if self.avg_device_time <= 0 or self.avg_cpu_time <= 0:
            # first epoch: split evenly to get measurements
            return max(total // 2, 1)
        device_rate = 1.0 / self.avg_device_time
        cpu_rate = self.num_workers / self.avg_cpu_time
        share = device_rate / (device_rate + cpu_rate)
        return int(round(total * share))

    def _update_avg(self, attr: str, dt: float) -> None:
        prev = getattr(self, attr)
        setattr(self, attr, dt if prev == 0 else 0.9 * prev + 0.1 * dt)

    def suggest_num_workers(
        self,
        device_share_target: Optional[float] = None,
        max_workers: Optional[int] = None,
    ) -> int:
        """Worker count that pushes the device's task share down to
        ``device_share_target`` given the measured per-task averages.

        The device competes with TRAINING for the same chip (the reason the
        hybrid sampler exists, reference sage_sampler.py:207-230), so a
        lower device share frees step time; more workers only help while
        host cores are spare. From ``share = dev_rate/(dev_rate+cpu_rate)``
        and ``cpu_rate = w/avg_cpu``: ``w = avg_cpu*(1-t)/(t*avg_dev)``.
        """
        import os as _os

        t = self.device_share_target if device_share_target is None else device_share_target
        if self.avg_device_time <= 0 or self.avg_cpu_time <= 0 or not 0 < t < 1:
            return self.num_workers
        if max_workers is None:
            max_workers = max(_os.cpu_count() or 1, 1)
        w = self.avg_cpu_time * (1.0 - t) / (t * self.avg_device_time)
        return int(np.clip(round(w), 1, max_workers))

    def _maybe_retune_workers(self) -> None:
        """auto_tune_workers: re-spawn the worker pool between epochs when
        the measured averages call for a different size (the feedback loop
        the reference leaves manual)."""
        if not self.auto_tune_workers:
            return
        want = self.suggest_num_workers()
        if want != self.num_workers and self._workers:
            self.shutdown()
            self.num_workers = want

    def _to_dense(self, n_id, count, adjs) -> DenseSample:
        import jax.numpy as jnp

        from .sage_sampler import DenseAdj

        dense_adjs = tuple(
            DenseAdj(
                cols=jnp.asarray(a["cols"]),
                mask=jnp.asarray(a["mask"]),
                n_src=jnp.asarray(a["n_src"], jnp.int32),
                n_dst=jnp.asarray(a["n_dst"], jnp.int32),
            )
            for a in adjs[::-1]
        )
        return DenseSample(
            n_id=jnp.asarray(n_id),
            count=jnp.asarray(count, jnp.int32),
            batch_size=int(adjs[0]["n_dst"]) if adjs else 0,
            adjs=dense_adjs,
        )

    # -- epoch iterator (reference iter_sampler, sage_sampler.py:316-368) ---
    def __iter__(self) -> Iterator:
        self._maybe_retune_workers()
        self.lazy_init()
        self.job.shuffle()
        # stale-epoch fencing: an abandoned iterator (break/GeneratorExit)
        # may leave this epoch's tasks in flight; results are tagged with the
        # epoch and anything older is discarded on receipt
        self._epoch = getattr(self, "_epoch", 0) + 1
        epoch = self._epoch
        total = len(self.job)
        device_num = self.decide_task_num(total)
        self.last_device_share = device_num / max(total, 1)

        # per-task completion tracking enables WORKER-FAILURE RECOVERY (the
        # reference has none — a dead worker's in-flight task hung its
        # epoch): duplicates from resubmission are dropped on receipt
        pending: set = set(range(device_num, total))
        # EPOCH-scoped recovery state (inside recv_blocking it would reset
        # per call and re-trigger resubmission storms): the alive watermark,
        # the last PROGRESS stamp (refreshed on every received result — a
        # healthy-but-slow pool is not idle), and a 10 s floor between
        # steals bounding duplicated work
        recover = {
            "last_alive": len(self._workers),
            "last_progress": time.monotonic(),
            "last_resubmit": time.monotonic(),
        }

        def recv(block: bool):
            """Next NEW CPU result of THIS epoch from the drainer inbox, or
            None when nothing arrives (after ~2 s when blocking). The inbox
            is an in-process thread queue — worker death cannot corrupt it
            (the per-worker pipes are only ever read by disposable daemon
            drainer threads, see _spawn_worker)."""
            deadline = time.monotonic() + (2.0 if block else 0.0)
            while True:
                try:
                    timeout = max(deadline - time.monotonic(), 0.0)
                    item = self._inbox.get(timeout=timeout) if timeout else (
                        self._inbox.get_nowait()
                    )
                except queue_mod.Empty:
                    return None
                r_epoch, task_idx, n_id, count, adjs, dt = item
                if r_epoch != epoch or task_idx not in pending:
                    continue  # stale epoch, or duplicate after resubmit
                pending.discard(task_idx)
                recover["last_progress"] = time.monotonic()
                self._update_avg("avg_cpu_time", dt)
                return task_idx, self._to_dense(n_id, count, adjs)

        def submit(tasks):
            """Round-robin tasks over ALIVE workers' queues (the reference's
            per-worker dispatch, sage_sampler.py:306-311; per-worker queues
            also mean a killed worker cannot poison a sibling's queue)."""
            targets = [
                q for q, p in zip(self._task_qs, self._workers) if p.is_alive()
            ]
            if not targets:
                raise RuntimeError(
                    "all CPU sampler workers died (see worker stderr); "
                    f"{len(pending)} task(s) unfinished"
                )
            for i, t in enumerate(tasks):
                targets[i % len(targets)].put(
                    (epoch, t, np.asarray(self.job[t], np.int64))
                )

        def recv_blocking():
            """recv with failure recovery: if a worker DIED while tasks are
            pending — or the tail has been idle for a while (one slow
            worker hoarding its round-robin share) — every pending task is
            resubmitted round-robin to the live workers; duplicate answers
            are filtered in recv. If the whole pool is dead, fail
            immediately with the real reason instead of a long stall."""
            start = time.monotonic()
            while True:
                res = recv(block=True)
                if res is not None:
                    return res
                alive = sum(p.is_alive() for p in self._workers)
                if alive == 0:
                    raise RuntimeError(
                        "all CPU sampler workers died (see worker stderr); "
                        f"{len(pending)} task(s) unfinished"
                    )
                now = time.monotonic()
                died = alive < recover["last_alive"]
                # steal only when NOTHING has arrived for an idle window
                # (slow-but-healthy pools keep refreshing last_progress in
                # recv), rate-limited to the same window; the window scales
                # with the measured per-task time — capped at 90 s — so
                # legitimately slow tasks (huge fanouts, loaded host) don't
                # trigger resubmit storms, and the stall deadline scales
                # with the window so the steal always gets to fire first
                idle_s = min(max(10.0, 3.0 * self.avg_cpu_time), 90.0)
                idle_steal = (
                    now - recover["last_progress"] > idle_s
                    and now - recover["last_resubmit"] > idle_s
                )
                if died or idle_steal:
                    submit(sorted(pending))
                    recover["last_alive"] = alive
                    recover["last_resubmit"] = now
                if now - start > max(120.0, 4.0 * idle_s):
                    raise TimeoutError("CPU sampler workers stalled")

        try:
            if pending:
                submit(range(device_num, total))
            for t in range(device_num):
                t0 = time.perf_counter()
                ds = self.device_sampler.sample_dense(self.job[t])
                import jax

                jax.block_until_ready(ds.n_id)
                self._update_avg("avg_device_time", time.perf_counter() - t0)
                yield t, ds
                # drain any finished CPU results between device tasks
                while pending:
                    res = recv(block=False)
                    if res is None:
                        break
                    yield res
            while pending:
                yield recv_blocking()
        except Exception:
            # drain workers so the queue doesn't wedge (the reference's only
            # recovery logic, sage_sampler.py:361-368)
            self.shutdown()
            raise

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass

"""PyG-style sampler API (reference srcs/python/quiver/pyg/__init__.py)."""

from .sage_sampler import (
    Adj,
    DenseAdj,
    DenseSample,
    GraphSageSampler,
    dense_to_pyg,
    sample_dense_fused,
    sample_dense_pure,
)
from .mixed_sampler import MixedGraphSageSampler, SampleJob, TrainSampleJob

__all__ = [
    "Adj",
    "DenseAdj",
    "DenseSample",
    "GraphSageSampler",
    "MixedGraphSageSampler",
    "SampleJob",
    "TrainSampleJob",
    "dense_to_pyg",
    "sample_dense_fused",
    "sample_dense_pure",
]

"""PyG-style sampler API (reference srcs/python/quiver/pyg/__init__.py)."""

from .sage_sampler import (
    Adj,
    DenseAdj,
    DenseSample,
    GraphSageSampler,
    caps_from_counts,
    dense_to_pyg,
    probe_hop_counts,
    sample_and_gather_dedup,
    sample_and_gather_fused,
    sample_dense_fused,
    sample_dense_pure,
)
from .mixed_sampler import MixedGraphSageSampler, SampleJob, TrainSampleJob

__all__ = [
    "Adj",
    "DenseAdj",
    "DenseSample",
    "GraphSageSampler",
    "MixedGraphSageSampler",
    "SampleJob",
    "TrainSampleJob",
    "caps_from_counts",
    "dense_to_pyg",
    "probe_hop_counts",
    "sample_and_gather_dedup",
    "sample_and_gather_fused",
    "sample_dense_fused",
    "sample_dense_pure",
]

"""PyG-style sampler API (reference srcs/python/quiver/pyg/__init__.py)."""

from .sage_sampler import (
    Adj,
    DenseAdj,
    DenseSample,
    GraphSageSampler,
    dense_to_pyg,
    sample_dense_pure,
)

__all__ = [
    "Adj",
    "DenseAdj",
    "DenseSample",
    "GraphSageSampler",
    "dense_to_pyg",
    "sample_dense_pure",
]

"""ShardTensor — one logical ``[N, D]`` tensor spanning memory tiers.

TPU-native re-design of the reference's ShardTensor
(srcs/python/quiver/shard_tensor.py: Offset at :7, ShardTensorConfig at :35,
append at :75-95, from_cpu_tensor at :108-136, __getitem__ at :154-180) and its
CUDA twin (srcs/cpp/src/quiver/cuda/quiver_feature.cu:56-361 with the
multi-pointer gather kernel shard_tensor.cu.hpp:16-58).

Tier mapping (reference -> TPU):

- local GPU HBM shard            -> local TPU chip HBM (jax.Array on device)
- peer GPU HBM over NVLink (P2P) -> peer chip HBM over ICI: the eager path
  gathers on the owning chip and ships rows over ICI via ``jax.device_put``;
  the jit path (`quiver_tpu.parallel.collectives.sharded_gather`) does it
  inside ``shard_map`` with collectives;
- pinned host DRAM via UVA       -> host numpy (optionally mmap-backed); TPUs
  cannot read host memory from a kernel, so the host tier is gathered by the
  native C++ engine (`qt_gather_rows`) and shipped with one H2D copy.

Row ownership is a static offset book exactly like the reference's
``offset_list_`` (quiver_feature.cu:300-320); ``access_book`` degenerates on
TPU because every chip in a slice reaches every other over ICI.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Union

import numpy as np

import jax
import jax.numpy as jnp

from .utils import parse_size
from .ops import cpu_kernels

CPU_DEVICE = -1  # reference uses device == -1 for the pinned-CPU shard


def normalize_dtype(dtype) -> np.dtype:
    """One dtype-spelling normalizer for every tiered store ("bfloat16"
    strings resolve through jnp since numpy may not register the name)."""
    return np.dtype(jnp.bfloat16) if str(dtype) == "bfloat16" else np.dtype(dtype)


@dataclass
class Offset:
    """Row range [start, end) owned by one shard (reference shard_tensor.py:7)."""

    start: int
    end: int


@dataclass
class ShardTensorConfig:
    """Per-device HBM budget (reference shard_tensor.py:35-72).

    ``device_memory_budget`` maps local device rank -> bytes (int or "200M"
    style strings).
    """

    device_memory_budget: Dict[int, Union[int, str]] = field(default_factory=dict)

    def __post_init__(self):
        self.device_memory_budget = {
            int(d): parse_size(v) for d, v in self.device_memory_budget.items()
        }

    @property
    def device_list(self) -> List[int]:
        return sorted(self.device_memory_budget.keys())


def _device_of(rank: int):
    local = jax.local_devices()
    return local[rank % len(local)]


@jax.jit
def _gather_local(table: jax.Array, ids: jax.Array) -> jax.Array:
    return jnp.take(table, ids, axis=0)


@jax.jit
def _scatter_rows(out: jax.Array, pos: jax.Array, rows: jax.Array) -> jax.Array:
    # positions == out.shape[0] are padding; 'drop' discards them
    return out.at[pos].set(rows, mode="drop")


def _bucket(n: int, floor: int = 256) -> int:
    """Pad id-batch lengths to power-of-two buckets so the jitted gather and
    scatter programs are reused across calls (XLA recompiles per shape; an
    eager per-batch shape would recompile every step)."""
    b = floor
    while b < n:
        b <<= 1
    return b


class ShardTensor:
    """Logical row-sharded tensor with gather across tiers.

    ``append`` order defines the row ranges, like the reference (device shards
    first, then at most one host shard — shard_tensor.py:75-95 enforces the
    same layout).
    """

    def __init__(
        self,
        current_device: int = 0,
        shard_tensor_config: Optional[ShardTensorConfig] = None,
        dtype=np.float32,
    ):
        self.current_device = current_device
        self.config = shard_tensor_config or ShardTensorConfig({})
        # bfloat16 halves every tier (2x the hot rows per HBM byte); the
        # reference is float32-only (quiver_feature.cu:65-69)
        self.dtype = normalize_dtype(dtype)
        self.device_shards: List[tuple] = []  # (device_rank, jax.Array, Offset)
        self.cpu_tensor: Optional[np.ndarray] = None
        self.cpu_offset: Optional[Offset] = None
        # 4th tier (round 14): flat-file row shard below host DRAM,
        # read through an optional AsyncReadPool (pipeline.py)
        self.disk_shard = None  # tiers.DiskShard
        self.disk_offset: Optional[Offset] = None
        self.read_pool = None
        self._n_rows = 0
        self._dim: Optional[int] = None

    # ------------------------------------------------------------------ build
    def append(self, tensor, device: int) -> None:
        """Place ``tensor`` as the next row range on ``device``
        (-1 = host DRAM). Mirrors reference shard_tensor.py:75-95."""
        arr = np.asarray(tensor)
        if arr.ndim != 2:
            raise ValueError("ShardTensor shards must be 2-D")
        if self.disk_shard is not None:
            raise ValueError("the disk shard must be the final tier")
        if self._dim is None:
            self._dim = arr.shape[1]
        elif arr.shape[1] != self._dim:
            raise ValueError("shard dim mismatch")
        off = Offset(self._n_rows, self._n_rows + arr.shape[0])
        if device == CPU_DEVICE:
            if self.cpu_tensor is not None:
                raise ValueError("host shard already set")
            self.cpu_tensor = np.ascontiguousarray(arr.astype(self.dtype, copy=False))
            self.cpu_offset = off
        else:
            if self.cpu_tensor is not None:
                raise ValueError("device shards must precede the host shard")
            dev_arr = jax.device_put(
                jnp.asarray(arr).astype(self.dtype), _device_of(device)
            )
            self.device_shards.append((device, dev_arr, off))
        self._n_rows = off.end

    def append_disk(self, tensor, path: str, read_pool=None) -> None:
        """Spill ``tensor`` as the FINAL tier — a flat-file ``.npy`` row
        shard at ``path`` (round 14; the reference's mmap'd disk slice,
        feature.py:84-93, as a first-class shard-book tier). Rows are
        written at the STORE dtype, so a quantized store spills int8.
        Reads go through ``read_pool`` (`pipeline.AsyncReadPool`) when
        attached, else one synchronous page-cache gather."""
        from .tiers import DiskShard  # lazy: tiers imports this module

        arr = np.ascontiguousarray(
            np.asarray(tensor).astype(self.dtype, copy=False)
        )
        if arr.ndim != 2:
            raise ValueError("ShardTensor shards must be 2-D")
        if self.disk_shard is not None:
            raise ValueError("disk shard already set")
        if self._dim is None:
            self._dim = arr.shape[1]
        elif arr.shape[1] != self._dim:
            raise ValueError("shard dim mismatch")
        self.disk_shard = DiskShard.create(path, arr)
        self.disk_offset = Offset(self._n_rows, self._n_rows + arr.shape[0])
        self._n_rows = self.disk_offset.end
        if read_pool is not None:
            self.read_pool = read_pool

    @classmethod
    def new_from_cpu_tensor(
        cls,
        tensor,
        shard_tensor_config: ShardTensorConfig,
        current_device: int = 0,
        dtype=np.float32,
    ) -> "ShardTensor":
        """Budget-based split across device HBM shards + host tail
        (reference from_cpu_tensor, shard_tensor.py:108-136)."""
        self = cls(current_device, shard_tensor_config, dtype=dtype)
        arr = np.asarray(tensor)
        row_bytes = arr.shape[1] * self.dtype.itemsize
        cursor = 0
        for dev in self.config.device_list:
            budget = self.config.device_memory_budget[dev]
            rows = min(budget // row_bytes, arr.shape[0] - cursor)
            if rows <= 0:
                continue
            self.append(arr[cursor : cursor + rows], dev)
            cursor += rows
        if cursor < arr.shape[0]:
            self.append(arr[cursor:], CPU_DEVICE)
        return self

    from_cpu_tensor = new_from_cpu_tensor

    # ------------------------------------------------------------------ props
    @property
    def shape(self):
        return (self._n_rows, self._dim or 0)

    @property
    def size(self):
        return self._n_rows * (self._dim or 0)

    def device_ratio(self) -> float:
        dev_rows = sum(o.end - o.start for _, _, o in self.device_shards)
        return dev_rows / max(self._n_rows, 1)

    def tier_bytes(self) -> Dict[str, int]:
        """Actual byte footprint per tier at the STORED dtype — what the
        quantized capacity tables (`scaling.quant_fetch_table`) predict and
        tests verify: an int8 store's hot shard holds 4x the rows of an
        fp32 store in the same device bytes."""
        row = (self._dim or 0) * self.dtype.itemsize
        dev = sum((o.end - o.start) * row for _, _, o in self.device_shards)
        host = 0 if self.cpu_tensor is None else (
            (self.cpu_offset.end - self.cpu_offset.start) * row
        )
        disk = 0 if self.disk_shard is None else (
            (self.disk_offset.end - self.disk_offset.start) * row
        )
        return {"device": dev, "host": host, "disk": disk, "row": row}

    # ----------------------------------------------------------------- gather
    def __getitem__(self, ids) -> jax.Array:
        """Gather rows by global id onto ``current_device``.

        Eager multi-tier gather: per-shard local gather on the owning device
        (ICI transfer for peers, native host gather + one H2D for the host
        tier), then scatter-merge on the target. This is the TPU analog of the
        reference's single multi-pointer kernel (shard_tensor.cu.hpp:16-58) —
        the device<->device / device<->host boundary crossings that the CUDA
        kernel hid inside loads become explicit transfers here.
        """
        ids_np = np.asarray(ids).astype(np.int64).reshape(-1)
        n = ids_np.shape[0]
        target = _device_of(self.current_device)
        out = jnp.zeros((n, self._dim), self.dtype, device=target)

        def pad_sel(sel: np.ndarray, local: np.ndarray, pad_id: int):
            # pow2-bucketed padding; padded scatter positions point past the
            # output (mode='drop'), padded gather ids clamp to a valid row
            b = _bucket(sel.shape[0])
            pos = np.full(b, n, np.int32)
            pos[: sel.shape[0]] = sel
            loc = np.full(b, pad_id, np.int64)
            loc[: local.shape[0]] = local
            return pos, loc

        for dev_rank, table, off in self.device_shards:
            sel = np.nonzero((ids_np >= off.start) & (ids_np < off.end))[0]
            if sel.size == 0:
                continue
            pos, loc = pad_sel(sel, ids_np[sel] - off.start, 0)
            local_ids = jax.device_put(jnp.asarray(loc), _device_of(dev_rank))
            rows = _gather_local(table, local_ids)
            rows = jax.device_put(rows, target)  # rides ICI for peer chips
            out = _scatter_rows(out, jnp.asarray(pos), rows)
        if self.cpu_tensor is not None:
            off = self.cpu_offset
            sel = np.nonzero((ids_np >= off.start) & (ids_np < off.end))[0]
            if sel.size:
                # host tier: native parallel gather, then ONE padded H2D copy
                b = _bucket(sel.shape[0])
                pos = np.full(b, n, np.int32)
                pos[: sel.shape[0]] = sel
                rows_np = np.zeros((b, self._dim), self.dtype)
                rows_np[: sel.size] = cpu_kernels.gather_rows(
                    self.cpu_tensor, ids_np[sel] - off.start
                )
                rows = jax.device_put(jnp.asarray(rows_np), target)
                out = _scatter_rows(out, jnp.asarray(pos), rows)
        if self.disk_shard is not None:
            off = self.disk_offset
            sel = np.nonzero((ids_np >= off.start) & (ids_np < off.end))[0]
            if sel.size:
                # disk tier: pooled flat-file gather, then ONE padded H2D
                b = _bucket(sel.shape[0])
                pos = np.full(b, n, np.int32)
                pos[: sel.shape[0]] = sel
                rows_np = np.zeros((b, self._dim), self.dtype)
                rows_np[: sel.size] = self.disk_shard.read_rows(
                    ids_np[sel] - off.start, pool=self.read_pool
                )
                rows = jax.device_put(jnp.asarray(rows_np), target)
                out = _scatter_rows(out, jnp.asarray(pos), rows)
        return out

    # ------------------------------------------------------- ipc-compat shims
    def share_ipc(self):
        """Reference shard_tensor.py:190-210. One JAX process drives all local
        chips, so "IPC" is just handing over the pieces."""
        items = [
            dict(device=d, array=np.asarray(t), offset=(o.start, o.end))
            for d, t, o in self.device_shards
        ]
        disk_path = None if self.disk_shard is None else self.disk_shard.path
        return items, self.cpu_tensor, self.config, str(self.dtype), disk_path

    @classmethod
    def new_from_share_ipc(cls, ipc_handle, current_device: int = 0) -> "ShardTensor":
        items, cpu_tensor, config, *rest = ipc_handle
        self = cls(current_device, config, dtype=rest[0] if rest else np.float32)
        for item in items:
            self.append(item["array"], item["device"])
        if cpu_tensor is not None:
            self.append(cpu_tensor, CPU_DEVICE)
        if len(rest) > 1 and rest[1] is not None:
            # the disk tier re-opens by PATH (the flat file is the shared
            # medium — no bytes ride the handle)
            from .tiers import DiskShard

            self.disk_shard = DiskShard(rest[1])
            self.disk_offset = Offset(
                self._n_rows, self._n_rows + self.disk_shard.shape[0]
            )
            self._n_rows = self.disk_offset.end
        return self

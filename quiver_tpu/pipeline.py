"""Double-buffered sample -> tiered gather -> train pipeline.

The reference hides its host<->device latency in two ways the TPU cannot
copy: UVA kernels read pinned host memory directly (quiver.cu.hpp:16-26) and
CUDA streams overlap transfers with compute (stream_pool.hpp). The TPU-native
replacement (SURVEY.md section 7.3 item 5) is an explicit software pipeline:

- the jitted train step fuses the HOT gather (HBM-resident feature prefix)
  with the model fwd/bwd — one XLA program, nothing leaves the chip;
- COLD rows (the host-DRAM tail) are gathered by the native C++ engine
  (`qt_gather_rows`, csrc/quiver_cpu.cpp) and shipped with ONE async H2D
  copy per batch;
- a THREE-stage prefetch pipeline (sample+n_id-fetch thread, host cold-gather
  thread, H2D upload thread) runs batches i+1..i+3 while the device executes
  batch i's train step — the staged overlap that replaces CUDA streams. With
  the stages split, the per-batch wall clock converges to the SLOWEST stage
  (usually the H2D link) instead of the sum of all of them, which is what a
  single prefetch worker delivered (round-3 bench: 11% of non-link latency
  hidden; see VERDICT.md round 3 item 3).

The merge is in-jit: ``x = hot_gather(mapped) * is_hot`` then scatter the
prefetched cold rows into their slots (`mode="drop"` makes the padding
self-discarding). Cold batch length is bucketed to powers of two so the step
program is reused across batches (bounded recompiles).
"""

from __future__ import annotations

import concurrent.futures
from dataclasses import dataclass, field
from typing import Iterator, NamedTuple, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .comm import round_up_pow2
from .feature import Feature
from .pyg.sage_sampler import DenseSample, GraphSageSampler
from .trace import SpanRecorder, trace_scope


class AsyncReadPool:
    """Bounded worker pool for cold-tier DISK reads (round 14).

    The train pipeline's stage pools are one-worker-per-stage because the
    stages are inherently serial; disk reads are the opposite — each
    chunk is an independent page-cache/disk access, so a batch split
    across ``workers`` threads overlaps the page faults (the C read loop
    and the memmap fault path both release the GIL). `gather` is the
    synchronous surface the tier stores call per batch; `submit` returns
    a future for prefetch-shaped callers.

    Error contract (the mirror of this module's mid-epoch fix, round 7):
    a failing chunk read CANCELS every queued sibling chunk, observes
    every future (no "exception was never retrieved" at GC), and
    re-raises the first failure by submission order at the caller — a
    deterministic raise, never a hang. The pool survives the failure and
    keeps serving subsequent gathers.
    """

    def __init__(self, workers: int = 4, chunk_rows: int = 4096,
                 name: str = "qt-diskread"):
        if workers < 1:
            raise ValueError("AsyncReadPool needs >= 1 worker")
        if chunk_rows < 1:
            raise ValueError("chunk_rows must be >= 1")
        self.workers = int(workers)
        self.chunk_rows = int(chunk_rows)
        self._pool = concurrent.futures.ThreadPoolExecutor(workers, name)
        # plain ints under the GIL (same discipline as ServeStats fields)
        self.reads = 0       # chunk reads issued
        self.gathers = 0     # gather() batches served
        self.rows = 0
        self.bytes = 0
        self.errors = 0
        self.seconds = 0.0

    def _chunks(self, ids: np.ndarray):
        n = ids.shape[0]
        per = max(
            self.chunk_rows if n > self.workers * self.chunk_rows
            else -(-n // self.workers),
            1,
        )
        return [ids[i : i + per] for i in range(0, n, per)]

    def gather(self, read_block, local_ids: np.ndarray) -> np.ndarray:
        """``read_block(ids_chunk) -> rows`` fanned across the workers;
        returns the concatenated rows in input order."""
        import time as _time

        ids = np.asarray(local_ids, np.int64).reshape(-1)
        t0 = _time.monotonic()
        self.gathers += 1
        if ids.shape[0] == 0:
            return read_block(ids)
        chunks = self._chunks(ids)
        if len(chunks) == 1:
            # no pool hop for a batch one worker would serve anyway
            self.reads += 1
            out = read_block(chunks[0])
            self.rows += out.shape[0]
            self.bytes += out.nbytes
            self.seconds += _time.monotonic() - t0
            return out
        futs = [self._pool.submit(read_block, c) for c in chunks]
        self.reads += len(futs)
        error: Optional[BaseException] = None
        parts = []
        for f in futs:
            if error is not None:
                # first failure wins: cancel what has not started and
                # observe the rest so nothing logs at GC
                f.cancel()
                f.add_done_callback(
                    lambda fut: fut.cancelled() or fut.exception()
                )
                continue
            try:
                parts.append(f.result())
            except BaseException as exc:
                error = exc
        if error is not None:
            self.errors += 1
            raise error
        out = np.concatenate(parts, axis=0)
        self.rows += out.shape[0]
        self.bytes += out.nbytes
        self.seconds += _time.monotonic() - t0
        return out

    def submit(self, read_block, local_ids: np.ndarray):
        """Async single-chunk read (prefetch-shaped callers); the future
        resolves to the rows or raises the read's error."""
        return self._pool.submit(read_block, np.asarray(local_ids, np.int64))

    def stats(self) -> dict:
        return {
            "workers": self.workers,
            "gathers": self.gathers,
            "reads": self.reads,
            "rows": self.rows,
            "bytes": self.bytes,
            "errors": self.errors,
            "seconds": self.seconds,
        }

    def shutdown(self, wait: bool = True) -> None:
        self._pool.shutdown(wait=wait, cancel_futures=True)

    def __enter__(self) -> "AsyncReadPool":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()


class TieredBatch(NamedTuple):
    """Device-ready inputs for one pipelined step."""

    ds: DenseSample        # padded sample (adjs consumed by the model)
    mapped: jax.Array      # [W] int32 row ids in reordered (cache) space; -1 invalid
    cold_rows: jax.Array   # [C_b, D] prefetched host-tier rows (padded bucket)
    cold_pos: jax.Array    # [C_b] int32 slot in [0, W) for each cold row; W pads
    seeds: jax.Array       # [B] the batch's seed node ids (for labels)


class HostStaged(NamedTuple):
    """Host-side staging result (prepare_host) awaiting its H2D upload."""

    mapped: np.ndarray               # [W] int32, -1 invalid
    rows: Optional[np.ndarray]       # [C_b, D] cold rows, or None (no cold)
    pos: Optional[np.ndarray]        # [C_b] int32 slots, or None


def tiered_lookup(
    hot_table: jax.Array,
    mapped: jax.Array,
    cold_rows: jax.Array,
    cold_pos: jax.Array,
) -> jax.Array:
    """Jit-safe tiered feature assembly: HBM gather for hot rows + scatter of
    prefetched cold rows. The in-jit half of the reference's multi-pointer
    gather kernel (shard_tensor.cu.hpp:16-58) — the host-pointer branch
    arrives as ``cold_rows`` instead of being read through UVA."""
    hot_n = hot_table.shape[0]
    is_hot = (mapped >= 0) & (mapped < hot_n)
    x = jnp.take(hot_table, jnp.clip(mapped, 0, hot_n - 1), axis=0)
    x = x * is_hot[:, None].astype(x.dtype)
    if cold_rows.shape[0]:
        x = x.at[cold_pos].set(cold_rows, mode="drop")
    return x


class TieredFeaturePipeline:
    """Prepares :class:`TieredBatch` inputs for a tiered :class:`Feature`.

    Host-side per batch: remap ids through ``feature_order``, split hot/cold
    by the cache boundary, native-gather the cold rows, enqueue ONE async H2D
    copy. All device work this object dispatches is async; the caller's train
    step consumes the arrays without further host syncs.

    Round 18 (ROADMAP item 3b — train THROUGH the disk tier): the cold
    stage now spans the whole hierarchy. A static 4-tier feature
    (``disk_path`` without ``adaptive_tiers``) gathers its DRAM middle
    from the host tail and its cold tail from the flat-file
    `tiers.DiskShard` (through the feature's `AsyncReadPool`); an
    adaptive feature (``adaptive_tiers=True``) snapshots its
    `tiers.TierStore` placement at construction and routes each batch by
    it — HBM-resident rows ride the fused in-jit gather exactly like the
    round-3 hot prefix (``mapped`` then carries HBM SLOTS), DRAM/disk
    rows assemble host-side. Bytes are identical to an all-DRAM epoch by
    construction (the backing file is the same stored table), so epoch
    loss curves are bit-parity-pinned in tests/test_prefetch.py.

    ``prefetch=True`` adds the flush-ahead leg: the SAMPLE stage issues
    `AsyncReadPool` reads for a batch's disk-resident rows one stage
    before the gather stage consumes them (`tiers.PrefetchBuffer` — the
    exact ids, no closure walk needed: the sample already materialized
    ``n_id``), so the gather finds the bytes in DRAM staging. Strictly
    observe-only on bits, same contract as the serve engines.

    PLACEMENT FREEZE: an adaptive pipeline reads a placement SNAPSHOT
    (maps copied, table references pinned — jax arrays are immutable, so
    promotions cannot corrupt the pinned HBM view) taken at
    construction. Do not run `adapt_tiers`/`apply_placement` against the
    same store mid-epoch: a host-DRAM promotion mutates the store's
    ``host_cache`` in place, which the snapshot cannot defend against.
    Build a fresh pipeline after a placement batch instead.
    """

    def __init__(self, feature: Feature, device=None, prefetch: bool = False,
                 prefetch_max_rows: int = 8192):
        from .tiers import TIER_HBM, TIER_HOST

        self.feature = feature
        self.device = device or jax.local_devices()[0]
        self.dtype = getattr(feature, "dtype", np.dtype(np.float32))
        self._order = feature.feature_order  # old id -> stored row (or None)
        from .ops import cpu_kernels

        self._gather = cpu_kernels.gather_rows
        # true tier traffic (padding excluded), accumulated across prepare()
        self.cold_rows_seen = 0
        self.rows_seen = 0
        self.disk_rows_seen = 0
        self._prefetch = None  # tiers.PrefetchBuffer when enabled
        store = getattr(feature, "tier_store", None)
        if store is not None:
            # adaptive: freeze the placement (see docstring). The HBM
            # table reference is pinned — placement applies build NEW
            # arrays, never mutate this one.
            self.mode = "adaptive"
            self._store = store
            self._tier_of = store.placement.tier_of.copy()
            self._slot_of = store.placement.slot_of.copy()
            self._tier_hbm, self._tier_host = TIER_HBM, TIER_HOST
            self.hot_rows = store.placement.hbm_rows
            self.hot_table = (
                store.hbm_table if store.hbm_table is not None
                else jnp.zeros((0, feature.dim), self.dtype,
                               device=self.device)
            )
            self._host_cache = store.host_cache
            self._disk_read = None  # adaptive reads go through the store
            if prefetch:
                self._prefetch = store.enable_prefetch(
                    max_rows=prefetch_max_rows
                )
            return
        st = feature.shard_tensor
        if st is None:
            raise ValueError("feature not built; call from_cpu_tensor first")
        if len(st.device_shards) > 1:
            raise ValueError(
                "tiered pipeline expects one hot shard + optional host tail; "
                "use the mesh-sharded gather for clique-striped features"
            )
        self._store = None
        if st.device_shards:
            _, self.hot_table, off = st.device_shards[0]
            self.hot_rows = off.end - off.start
        else:
            self.hot_table = jnp.zeros((0, feature.dim), self.dtype, device=self.device)
            self.hot_rows = 0
        self.cold_np = st.cpu_tensor  # may be None (fully resident)
        self._disk = getattr(st, "disk_shard", None)
        if self._disk is not None:
            self.mode = "disk"
            self._disk_start = st.disk_offset.start
            self._disk_pool = getattr(st, "read_pool", None) \
                or getattr(feature, "read_pool", None)
            if prefetch:
                if self._disk_pool is None:
                    raise ValueError(
                        "prefetch needs an AsyncReadPool (build the "
                        "Feature with read_pool=/disk_read_workers=)"
                    )
                from .tiers import PrefetchBuffer

                self._prefetch = PrefetchBuffer(
                    lambda ids: self._disk.read_block(ids),
                    self._disk_pool, max_rows=prefetch_max_rows,
                )
                # attribution honesty (round-18 satellite): the feature's
                # observe-only tier counter reports staged disk rows as
                # `disk_prefetched`
                if hasattr(feature, "disk_staged"):
                    feature.disk_staged = self._prefetch.staged_mask
        else:
            self.mode = "dram"

    def prepare_host(
        self, ids: np.ndarray, valid_count: Optional[int] = None
    ) -> "HostStaged":
        """Pure-host half of staging: id remap + hot/cold split + native cold
        gather. No device calls — safe to run in a gather thread concurrently
        with another batch's H2D upload (:meth:`upload`).

        ``valid_count`` (= ``ds.count``) marks the padding tail: padding
        lanes carry garbage ids whose rows the model masks out anyway, so
        fetching them wastes cold-tier H2D — at products scale ~15% of the
        capped width, on a ~0.02-0.06 GB/s tunnel that is seconds per batch.
        """
        with trace_scope("pipeline.prepare_host"):
            ids = np.asarray(ids).astype(np.int64).reshape(-1)
            W = ids.shape[0]
            n_total = self.feature.shape[0]
            invalid = (ids < 0) | (ids >= n_total)
            if valid_count is not None and valid_count < W:
                invalid[valid_count:] = True
            safe = np.where(invalid, 0, ids)
            stored = self._order[safe] if self._order is not None else safe
            stored = np.where(invalid, -1, stored)
            self.rows_seen += W
            if self.mode == "adaptive":
                return self._prepare_adaptive(stored, W)
            mapped = stored.astype(np.int32)
            if self.cold_np is None and self.mode != "disk":
                return HostStaged(mapped, None, None)
            (cold_sel,) = np.nonzero(mapped >= self.hot_rows)
            if cold_sel.size == 0:
                # hot-dominated batch: skip the 256-row padded upload entirely
                # (the step program already specializes on the 0-size shape)
                return HostStaged(mapped, None, None)
            self.cold_rows_seen += int(cold_sel.shape[0])
            b = round_up_pow2(cold_sel.shape[0], floor=256)
            pos = np.full(b, W, np.int32)  # W == out-of-range -> dropped
            pos[: cold_sel.shape[0]] = cold_sel
            rows = np.zeros((b, self.feature.dim), self.dtype)
            cold_ids = mapped[cold_sel].astype(np.int64)
            with trace_scope("pipeline.cold_gather"):
                if self.mode == "disk":
                    host_sel = np.nonzero(cold_ids < self._disk_start)[0]
                    if host_sel.size and self.cold_np is not None:
                        rows[host_sel] = self._gather(
                            self.cold_np, cold_ids[host_sel] - self.hot_rows
                        )
                    disk_sel = np.nonzero(cold_ids >= self._disk_start)[0]
                    if disk_sel.size:
                        self.disk_rows_seen += int(disk_sel.size)
                        rows[disk_sel] = self._read_disk(
                            cold_ids[disk_sel] - self._disk_start
                        )
                else:
                    rows[: cold_sel.size] = self._gather(
                        self.cold_np, cold_ids - self.hot_rows
                    )
            return HostStaged(mapped, rows, pos)

    def _read_disk(self, local_ids: np.ndarray) -> np.ndarray:
        """Disk-tier rows for the static layout, staging-aware: rows the
        sample stage prefetched come out of DRAM, the rest through the
        pooled flat-file read — byte-identical either way."""
        def read(ids):
            return self._disk.read_rows(ids, pool=self._disk_pool)

        pf = self._prefetch
        if pf is None:
            return read(local_ids)
        return pf.take_or_read(local_ids, read)

    def _prepare_adaptive(self, stored: np.ndarray, W: int) -> "HostStaged":
        """Adaptive-placement staging against the frozen snapshot:
        ``mapped`` carries HBM SLOTS (the pinned hot table is
        slot-indexed), -1 elsewhere; DRAM/disk rows assemble host-side
        — DRAM from the store's cache slots, disk through
        `TierStore.gather`'s own staging-aware read path semantics
        (prefetched rows out of DRAM, the rest from the backing file)."""
        valid = stored >= 0
        safe = np.where(valid, stored, 0)
        tiers = self._tier_of[safe]
        is_hbm = valid & (tiers == self._tier_hbm)
        mapped = np.where(is_hbm, self._slot_of[safe], -1).astype(np.int32)
        (cold_sel,) = np.nonzero(valid & ~is_hbm)
        if cold_sel.size == 0:
            return HostStaged(mapped, None, None)
        self.cold_rows_seen += int(cold_sel.shape[0])
        b = round_up_pow2(cold_sel.shape[0], floor=256)
        pos = np.full(b, W, np.int32)
        pos[: cold_sel.shape[0]] = cold_sel
        rows = np.zeros((b, self.feature.dim), self.dtype)
        cold_ids = stored[cold_sel]
        cold_tiers = tiers[cold_sel]
        with trace_scope("pipeline.cold_gather"):
            host_sel = np.nonzero(cold_tiers == self._tier_host)[0]
            if host_sel.size and self._host_cache is not None:
                rows[host_sel] = self._gather(
                    self._host_cache, self._slot_of[cold_ids[host_sel]]
                )
            disk_sel = np.nonzero(cold_tiers != self._tier_host)[0]
            if disk_sel.size:
                self.disk_rows_seen += int(disk_sel.size)
                rows[disk_sel] = self._read_backing(cold_ids[disk_sel])
        return HostStaged(mapped, rows, pos)

    def _read_backing(self, stored_ids: np.ndarray) -> np.ndarray:
        """Adaptive disk rows: staged prefetch bytes first, backing-file
        reads for the rest (the store's read pool chunks them)."""
        store = self._store

        def read(ids):
            return store.backing.read_rows(ids, pool=store.read_pool)

        pf = self._prefetch
        if pf is None:
            return read(stored_ids)
        return pf.take_or_read(stored_ids, read)

    # -- flush-ahead prefetch (round 18; issued by the SAMPLE stage) -------

    @property
    def prefetch_stats(self) -> dict:
        return self._prefetch.stats() if self._prefetch is not None else {}

    def prefetch(self, ids: np.ndarray,
                 valid_count: Optional[int] = None) -> int:
        """Issue `AsyncReadPool` reads for the DISK-resident rows of a
        batch's ``n_id`` — called by the sample stage, one stage before
        the gather consumes them. Exact ids (the sample already
        materialized them), so nothing here is speculative; returns rows
        issued. Observe-only on bits."""
        pf = self._prefetch
        if pf is None:
            return 0
        ids = np.asarray(ids).astype(np.int64).reshape(-1)
        if valid_count is not None and valid_count < ids.shape[0]:
            ids = ids[:valid_count]
        n_total = self.feature.shape[0]
        ids = ids[(ids >= 0) & (ids < n_total)]
        if ids.size == 0:
            return 0
        stored = self._order[ids] if self._order is not None else ids
        if self.mode == "adaptive":
            disk = stored[self._tier_of[stored] > self._tier_host]
            return pf.issue(disk) if disk.size else 0
        local = stored[stored >= self._disk_start] - self._disk_start
        return pf.issue(local) if local.size else 0

    def cancel_prefetch(self) -> int:
        """Drop staged rows (mid-epoch error unwind / epoch end): see
        `tiers.PrefetchBuffer.cancel`."""
        return self._prefetch.cancel() if self._prefetch is not None else 0

    def upload(
        self, staged: "HostStaged"
    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """Device half of staging: the H2D copies. Runs in the upload thread
        so a 10-100 MB cold transfer overlaps the NEXT batch's host gather
        and the CURRENT batch's device step."""
        with trace_scope("pipeline.h2d"):
            mapped_dev = jax.device_put(staged.mapped, self.device)
            if staged.rows is None:
                cold_rows = jnp.zeros(
                    (0, self.feature.dim), self.dtype, device=self.device
                )
                cold_pos = jnp.zeros((0,), jnp.int32, device=self.device)
            else:
                cold_rows = jax.device_put(staged.rows, self.device)
                cold_pos = jax.device_put(staged.pos, self.device)
            return mapped_dev, cold_rows, cold_pos

    def prepare(
        self, n_id: jax.Array, valid_count: Optional[int] = None
    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """(mapped, cold_rows, cold_pos) for a padded n_id array — the
        single-threaded composition of :meth:`prepare_host` + :meth:`upload`
        (kept for direct callers; :class:`TrainPipeline` stages them on
        separate threads)."""
        return self.upload(self.prepare_host(np.asarray(n_id), valid_count))


@dataclass
class PipelineStats:
    batches: int = 0
    cold_rows: int = 0
    hot_rows: int = 0
    # mixed-sampler feedback (populated by run_epoch_iter when the source
    # is a MixedGraphSageSampler): measured per-task averages + the split
    # the sampler chose — the inputs to suggest_num_workers
    avg_device_sample_s: float = 0.0
    avg_cpu_sample_s: float = 0.0
    device_share: Optional[float] = None
    # measured stage spans (trace.SpanRecorder): (stage_name, t0, t1)
    # monotonic triples recorded around every stage body and every device
    # step. THE falsifiable overlap evidence — summarize with
    # `overlap_summary()`. The recorder snapshots before iterating, so the
    # summary is safe to read mid-epoch while stage threads still append.
    # Eagerly constructed: record() is called from all four stage threads,
    # and a lazy None-check init could race at the first batch and drop
    # the winner's early spans
    spans: SpanRecorder = field(default_factory=SpanRecorder)

    def record(self, stage: str, t0: float, t1: float) -> None:
        self.spans.record(stage, t0, t1)

    def overlap_summary(self) -> dict:
        """Measured concurrency of the recorded spans — see
        :meth:`quiver_tpu.trace.SpanRecorder.overlap_summary` (overlap_frac,
        hidden_frac_measured, per-stage busy seconds)."""
        return self.spans.overlap_summary() if self.spans else {}

    def register_metrics(self, registry=None,
                         prefix: str = "quiver_pipeline", labels=None):
        """Adapt these live pipeline counters into a
        `trace.MetricsRegistry` (created when not given) — the same
        adapter discipline as `ServeEngine.register_metrics`: callback-
        backed readers, nothing counted twice. ``overlap_frac`` is
        computed from the span recorder at exposition time (bounded ring,
        so a scrape stays cheap)."""
        from .trace import MetricsRegistry

        reg = registry if registry is not None else MetricsRegistry()
        reg.counter_fn(f"{prefix}_batches_total", lambda: self.batches,
                       "pipelined train batches", labels)
        reg.counter_fn(f"{prefix}_cold_rows_total", lambda: self.cold_rows,
                       "cold-tier rows fetched", labels)
        reg.counter_fn(f"{prefix}_hot_rows_total", lambda: self.hot_rows,
                       "hot-tier rows gathered", labels)
        reg.gauge_fn(f"{prefix}_overlap_frac",
                     lambda: self.overlap_summary().get("overlap_frac", 0.0),
                     "fraction of covered wall with >= 2 stages active",
                     labels)
        reg.gauge_fn(f"{prefix}_span_count", lambda: len(self.spans),
                     "stage spans in the recorder ring", labels)
        return reg


class TrainPipeline:
    """sample -> tiered gather -> step, with staged prefetch threads.

    ``step_fn(params, opt_state, key, batch: TieredBatch) -> (params,
    opt_state, loss)`` must be jitted by the caller (see
    :func:`make_tiered_train_step`). Three single-thread stages run ahead of
    the consuming step:

      1. sample: device sampling dispatch + the n_id/count D2H fetches
      2. gather: id remap + native host cold gather (pure host, GIL released
         inside the C engine)
      3. upload: the H2D copies (the link-bound leg)

    Each stage is its own one-worker executor processing batches FIFO, so
    batch i's upload, batch i+1's host gather, batch i+2's sampling, and
    batch i-1's device step all run concurrently — per-batch wall time
    converges to the slowest stage instead of their sum. ``depth`` extra
    chains are kept in flight beyond the 3 stage buffers to absorb jitter.
    """

    def __init__(
        self,
        sampler: GraphSageSampler,
        feature: Feature,
        step_fn,
        depth: int = 2,
        tiered: "TieredFeaturePipeline" = None,
        checkpoint=None,
        checkpoint_every: int = 0,
        measure_overlap: bool = False,
    ):
        self.sampler = sampler
        # callers that already built a TieredFeaturePipeline (e.g. to hand
        # its hot_table to make_tiered_train_step) pass it in — two
        # instances over one Feature would drift apart on stats
        self.tiered = tiered if tiered is not None else TieredFeaturePipeline(feature)
        self.step_fn = step_fn
        self.depth = max(depth, 1)
        self.stats = PipelineStats()
        # measure_overlap=True: sync each step's loss so the recorded
        # "step" span covers device execution — the falsifiable overlap
        # evidence (stats.overlap_summary). Costs one D2H sync per step,
        # so it is opt-in; when off, steps stay async and the recorded
        # span ("step_dispatch") covers only the dispatch.
        self.measure_overlap = bool(measure_overlap)
        # periodic preemption-safe state saves (checkpoint.CheckpointManager;
        # the reference has no library-level recovery story, SURVEY.md §5).
        # Saves are ASYNC (orbax background thread) so the train loop never
        # stalls on IO; _run flushes before returning.
        self.checkpoint = checkpoint
        self.checkpoint_every = int(checkpoint_every)
        if checkpoint is not None and self.checkpoint_every <= 0:
            raise ValueError("checkpoint given but checkpoint_every not set")
        if checkpoint is None and self.checkpoint_every > 0:
            raise ValueError("checkpoint_every set but no checkpoint manager")
        # resume numbering where the store left off: a fresh pipeline after
        # preemption must NOT re-save steps below the stored latest (orbax
        # accepts them silently and latest_step() would keep returning the
        # stale pre-crash state)
        self.global_step = (
            int(checkpoint.latest_step() or 0) if checkpoint is not None else 0
        )

    # --- the three stage bodies (each runs on its own single worker thread)

    def _sample_body(self, ds: DenseSample, seeds):
        """Stage 1: the D2H fetches that sync on device sampling."""
        # valid lanes form the n_id PREFIX only in the fully-deduped layout
        # (every adj carries explicit cols); structural (fused) samples
        # interleave invalid lanes, so the padding cut must be skipped there
        prefix_valid = all(a.cols is not None for a in ds.adjs)
        ids = np.asarray(ds.n_id)
        vc = int(ds.count) if prefix_valid else None
        if seeds is None:
            # the seed batch is always the n_id prefix (both pipelines)
            seeds = ids[: ds.batch_size]
        # flush-ahead prefetch (round 18): issue this batch's disk reads
        # NOW — the gather stage consumes them one stage later, so the
        # reads overlap the PREVIOUS batch's gather/upload/step instead
        # of sitting on the cold-gather critical path
        self.tiered.prefetch(ids, valid_count=vc)
        return ds, seeds, ids, vc

    def _gather_body(self, ds, seeds, ids, vc):
        """Stage 2: host remap + native cold gather (no device calls)."""
        before = self.tiered.cold_rows_seen
        host = self.tiered.prepare_host(ids, valid_count=vc)
        cold = self.tiered.cold_rows_seen - before
        self.stats.batches += 1
        self.stats.cold_rows += cold
        self.stats.hot_rows += host.mapped.shape[0] - cold
        return ds, seeds, host

    def _upload_body(self, ds, seeds, host) -> TieredBatch:
        """Stage 3: the H2D copies."""
        mapped, cold_rows, cold_pos = self.tiered.upload(host)
        return TieredBatch(
            ds=ds,
            mapped=mapped,
            cold_rows=cold_rows,
            cold_pos=cold_pos,
            seeds=jnp.asarray(np.asarray(seeds), jnp.int32),
        )

    def _stage_ds(self, ds: DenseSample, seeds=None) -> TieredBatch:
        """Single-threaded composition of all three stages (bootstrap and
        direct callers; the epoch loop stages them on separate threads)."""
        return self._upload_body(*self._gather_body(*self._sample_body(ds, seeds)))

    def _stage(self, seeds: np.ndarray) -> TieredBatch:
        return self._stage_ds(self.sampler.sample_dense(seeds), seeds)

    def register_metrics(self, registry=None,
                         prefix: str = "quiver_pipeline", labels=None):
        """`PipelineStats.register_metrics` plus the tiered feature
        pipeline's true-traffic counters (padding excluded)."""
        reg = self.stats.register_metrics(registry, prefix, labels)
        reg.counter_fn(f"{prefix}_tier_rows_seen_total",
                       lambda: self.tiered.rows_seen,
                       "rows through the tiered gather", labels)
        reg.counter_fn(f"{prefix}_tier_cold_rows_seen_total",
                       lambda: self.tiered.cold_rows_seen,
                       "rows answered by the cold tier", labels)
        reg.counter_fn(f"{prefix}_tier_disk_rows_seen_total",
                       lambda: self.tiered.disk_rows_seen,
                       "cold rows answered by the disk tier", labels)
        reg.counter_fn(
            f"{prefix}_tier_prefetch_issued_total",
            lambda: self.tiered.prefetch_stats.get("issued", 0),
            "disk rows issued flush-ahead by the sample stage", labels)
        reg.counter_fn(
            f"{prefix}_tier_prefetch_hits_total",
            lambda: self.tiered.prefetch_stats.get("hits", 0),
            "prefetched rows the gather stage consumed from staging",
            labels)
        return reg

    def export_chrome_trace(self, path: str, metadata=None):
        """Perfetto-loadable timeline of the recorded stage spans
        (sample / gather / upload / step lanes — the staged-overlap
        evidence as a picture instead of a fraction)."""
        from .trace import export_chrome_trace

        return export_chrome_trace(
            path, [("train_pipeline", self.stats.spans)], metadata
        )

    def run_epoch(
        self,
        seed_batches: Sequence[np.ndarray],
        params,
        opt_state,
        key: jax.Array,
    ):
        """Run one epoch over seed batches; returns (params, opt_state,
        losses list). Sampling, cold gather, and H2D for upcoming batches
        run on the stage threads while the device steps batch i."""
        return self._run(
            ((self.sampler.sample_dense(s), s) for s in seed_batches),
            params,
            opt_state,
            key,
        )

    def run_epoch_iter(self, samples, params, opt_state, key: jax.Array):
        """Train over an iterator of :class:`DenseSample`s — e.g. a
        `MixedGraphSageSampler` epoch, whose CPU worker processes then
        overlap with BOTH the cold-tier prefetch and the device steps.
        Accepts bare DenseSamples or the mixed sampler's
        ``(task_idx, DenseSample)`` pairs. All samples must share one padded
        shape (same sizes/batch/caps) so the step program is reused."""

        def pairs():
            for item in samples:
                # NB DenseSample is itself a (named) tuple — check it first
                ds = item if isinstance(item, DenseSample) else item[1]
                yield ds, None

        out = self._run(pairs(), params, opt_state, key)
        # feed the mixed sampler's measurements back into the stats so
        # callers can auto-tune (suggest_num_workers / auto_tune_workers)
        for attr, field in (
            ("avg_device_time", "avg_device_sample_s"),
            ("avg_cpu_time", "avg_cpu_sample_s"),
            ("last_device_share", "device_share"),
        ):
            if hasattr(samples, attr):
                setattr(self.stats, field, getattr(samples, attr))
        return out

    def _run(self, sample_pairs, params, opt_state, key: jax.Array):
        """The staged loop. ``sample_pairs`` yields (DenseSample, seeds)
        lazily; its work (the sampling dispatch) happens inside the SAMPLE
        thread's next() — generators refuse concurrent next(), and one
        thread per stage keeps delivery order FIFO. Each batch is a chain of
        three futures (sample -> gather -> upload); ``depth`` chains beyond
        the three stage buffers are kept in flight."""
        import collections

        it = iter(sample_pairs)
        losses = []
        spool = concurrent.futures.ThreadPoolExecutor(1, "qt-sample")
        gpool = concurrent.futures.ThreadPoolExecutor(1, "qt-gather")
        upool = concurrent.futures.ThreadPoolExecutor(1, "qt-upload")

        import time as _time

        def sample_next():
            t0 = _time.monotonic()
            item = next(it, None)
            if item is None:
                return None
            out = self._sample_body(*item)
            self.stats.record("sample", t0, _time.monotonic())
            return out

        def gather(fut):
            r = fut.result()
            if r is None:
                return None
            t0 = _time.monotonic()
            out = self._gather_body(*r)
            self.stats.record("gather", t0, _time.monotonic())
            return out

        def upload(fut):
            r = fut.result()
            if r is None:
                return None
            t0 = _time.monotonic()
            out = self._upload_body(*r)
            self.stats.record("upload", t0, _time.monotonic())
            return out

        q = collections.deque()
        try:

            def launch():
                f1 = spool.submit(sample_next)
                f2 = gpool.submit(gather, f1)
                q.append((f1, f2, upool.submit(upload, f2)))

            for _ in range(self.depth + 2):
                launch()
            while True:
                batch = q.popleft()[-1].result()
                if batch is None:
                    break
                launch()
                key, sub = jax.random.split(key)
                t0 = _time.monotonic()
                params, opt_state, loss = self.step_fn(params, opt_state, sub, batch)
                if self.measure_overlap:
                    # the span must cover device EXECUTION, not just the
                    # async dispatch — sync on the loss before closing it
                    loss = float(loss)
                    self.stats.record("step", t0, _time.monotonic())
                else:
                    self.stats.record("step_dispatch", t0, _time.monotonic())
                losses.append(loss)
                self.global_step += 1
                if (
                    self.checkpoint is not None
                    and self.global_step % self.checkpoint_every == 0
                ):
                    self.checkpoint.save(
                        self.global_step,
                        {"params": params, "opt_state": opt_state},
                        wait=False,
                    )
        except BaseException:
            # a stage (or the step) raised mid-epoch: cancel every QUEUED
            # stage future on all three pools so the blocking shutdown below
            # cannot sit behind batches nobody will consume, and mark EVERY
            # future of every in-flight chain as observed — including the
            # sample/gather futures, which can fail on their own (not just
            # unwind via CancelledError from a cancelled upstream) and
            # would otherwise log "exception was never retrieved" at GC.
            # The ORIGINAL exception then re-raises — the clean path's
            # shutdown alone would leave prefetched chains queued and the
            # caller guessing why the iterator died
            for pool in (spool, gpool, upool):
                pool.shutdown(wait=False, cancel_futures=True)
            while q:
                for f in q.popleft():
                    f.cancel()
                    f.add_done_callback(
                        lambda fut: fut.cancelled() or fut.exception()
                    )
            # flush-ahead reads issued for batches nobody will gather:
            # cancel + observe them so the unwind leaves no pool zombies
            # (the r7/r14 error contract extended to the prefetch leg)
            self.tiered.cancel_prefetch()
            raise
        finally:
            spool.shutdown(wait=True)
            gpool.shutdown(wait=True)
            upool.shutdown(wait=True)
            if self.checkpoint is not None:
                self.checkpoint.flush()
        return params, opt_state, [float(l) for l in losses]


def make_tiered_train_step(model, tx, labels: jax.Array, hot_table: jax.Array):
    """Jitted ``step(params, opt_state, key, batch)`` fusing the hot gather
    into fwd/bwd. ``labels``/``hot_table`` enter the jitted program as
    ARGUMENTS (closure capture would embed a million-row table as an XLA
    constant — minutes of compile, see bench.py)."""
    import optax

    hot_table = jnp.asarray(hot_table)
    labels = jnp.asarray(labels)

    @jax.jit
    def step(params, opt_state, key, hot, lab, batch: TieredBatch):
        x = tiered_lookup(hot, batch.mapped, batch.cold_rows, batch.cold_pos)
        y = jnp.take(lab, jnp.clip(batch.seeds, 0, lab.shape[0] - 1))

        def objective(p):
            logits = model.apply(
                p, x, batch.ds.adjs, train=True, rngs={"dropout": key}
            )
            ll = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(ll, y[:, None].astype(jnp.int32), axis=1)[:, 0]
            return nll.mean()

        loss, grads = jax.value_and_grad(objective)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    def bound(params, opt_state, key, batch: TieredBatch):
        return step(params, opt_state, key, hot_table, labels, batch)

    return bound

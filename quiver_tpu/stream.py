"""Streaming graph deltas: serve on a graph that changes under live
traffic (ROADMAP item 1, round 17).

Every layer built through round 16 — tiled sampling, fused one-dispatch
serving, the disk tier, replication, elastic resharding — assumes a frozen
CSR/tile map built once at ingest. The north-star workload (feeds, fraud
graphs) streams edges continuously, and the access-stream papers
(PyTorch-Direct, arxiv 2101.07956; GPU-side sampling invariants, arxiv
2009.06693) both argue the same discipline: mutation must ride the
existing GATHER-ONLY formulations, never reintroduce host-side rebuilds on
the hot path.

The 128-lane tile layout (`ops.sample.build_tiled_host`) makes that
possible almost for free. A node's edges live LANE-aligned in a
``[M, 128]`` tile table, so ceil-padding to 128 leaves ``cap - deg`` slack
pad lanes in every node's last tile row — lanes the degree mask already
gates out of every draw. An edge append is therefore:

- **pad-lane write** (the common case): put the new neighbor in the next
  slack lane and bump the node's degree — one tile-row write + one
  ``(base, deg)`` row write, no relayout, no shape change;
- **tile spill** (a node's allocated rows filled): relocate the node to
  fresh rows from a pre-reserved region at the table's tail (copy its old
  rows, bump ``base``), then write. The old rows become dead padding the
  degree mask never reads. Reserve exhaustion raises
  `StreamCapacityError` — capacity is planned like the sampler's static
  caps, never silently grown (a shape change would invalidate every
  AOT-sealed serve executable).

Deltas accumulate HOST-SIDE in a :class:`GraphDelta` buffer and land on
device as **batched tile swaps**: the touched tile rows (and bd rows) go
through one jitted bucketed row-scatter per commit
(`shard_tensor._scatter_rows` semantics — the same idiom the round-14 tier
promotions ride). Scatter-building big arrays is the compile trap
PERF_NOTES pins; a bounded ``[K, 128]`` row scatter into an EXISTING
same-shaped array is not. Every sampler path stays gather-only and
bit-replayable: the device arrays keep their shapes for the life of the
stream, so the sealed `inference.BucketPrograms` executables keep running
— `BucketPrograms.rebind` swaps the argument arrays, never recompiles.

Parity discipline (pinned in tests/test_stream.py): a draw from the
streamed ``(bd, tiles)`` is bit-equal to a draw from a tile table freshly
built over the materialized updated CSR (`to_csr_topo`) on the same key —
appends preserve per-row edge order (base edges first, arrivals after),
and `ops.sample._tiled_resolve` reads positions through the ``base``
indirection, so relocation changes no drawn bit. Frozen-graph replay is
bit-identical to delta-replay with an empty delta, and an appended edge is
visible to the NEXT sample after the commit returns (copy-all semantics:
any draw with fanout >= deg must include it).

`StreamingAdjacency` is the host bookkeeping half: the base CSR plus the
appended edges, with forward k-hop closures (the dist router's incremental
owner-shard extension) and reverse k-hop closures (the versioned-node-
stamp invalidation set — every seed whose k-hop expansion could reach a
changed row). The serve engines wire all of this through
``update_graph(delta)`` — see `serve.engine.ServeEngine.update_graph` and
docs/api.md "Streaming graphs".
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .ops.sample import LANE, build_tiled_host
from .shard_tensor import _bucket, _scatter_rows

# The batched tile-swap primitive: one bounded [K, ...] row scatter into
# an existing same-shaped device table, out-of-range positions dropped as
# padding (`shard_tensor._scatter_rows` — the round-14 promotion idiom,
# NOT the PERF_NOTES scatter-build trap). Named here because every delta
# consumer (tile sync below, `ClosureFeature.install_rows` in serve/dist)
# must commit through this one shape-stable path.
_swap_rows = _scatter_rows

__all__ = [
    "GraphDelta",
    "StreamCapacityError",
    "StreamingAdjacency",
    "StreamingTiledGraph",
    "validate_edge_ids",
]


class StreamCapacityError(RuntimeError):
    """The stream's reserved tile (or feature) rows are exhausted. The
    fix is capacity planning, not silent growth: growing the device
    arrays would change their shapes and invalidate every sealed AOT
    serve executable — rebuild the stream with a larger
    ``reserve_frac``/``reserve_tiles`` (the same contract as the
    sampler's static caps)."""


def validate_edge_ids(src, dst, n: Optional[int] = None,
                      what: str = "delta",
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Flatten an edge batch to matched int64 ``(src, dst)`` arrays and
    (when ``n`` is given) range-check every id against ``[0, n)`` — the
    one validation every staging/commit entry point shares, so a bad
    arrival raises AT ITS CALL SITE and never poisons a pending buffer
    (a commit failure re-stages the delta; an unvalidated bad edge would
    wedge every future ``update_graph``)."""
    src = np.asarray(src, np.int64).reshape(-1)
    dst = np.asarray(dst, np.int64).reshape(-1)
    if src.shape != dst.shape:
        raise ValueError(f"src {src.shape} / dst {dst.shape} mismatch")
    if n is not None:
        bad = (src < 0) | (src >= n) | (dst < 0) | (dst >= n)
        if bad.any():
            raise ValueError(
                f"{what} edge ids outside [0, {n}): "
                f"{np.stack([src[bad], dst[bad]], 1)[:4].tolist()}"
            )
    return src, dst


class GraphDelta:
    """Host-side edge-arrival buffer: ``(src, dst)`` pairs in arrival
    order, held as ndarray CHUNKS (one per staged batch — the ingest
    path is measured by bench's ``stream_append_s``, so no per-edge
    Python boxing). Accumulation is cheap and lock-free per instance
    (the serve engines guard their pending buffer with their own lock);
    nothing touches the device until a fenced ``update_graph``/``apply``
    commits the whole batch. Deterministic: two buffers fed the same
    arrivals apply identically."""

    __slots__ = ("_src", "_dst", "_ts", "_n")

    def __init__(self, src=None, dst=None, ts=None):
        self._src: List[np.ndarray] = []
        self._dst: List[np.ndarray] = []
        # per-edge timestamp chunks (round 19, temporal workloads): either
        # EVERY staged chunk carries timestamps or none does — a mixed
        # buffer could not commit into a temporal tile map deterministically
        self._ts: List[np.ndarray] = []
        self._n = 0
        if src is not None or dst is not None:
            if (src is None) != (dst is None):
                raise ValueError("src/dst lengths differ")
            self.add_edges(src, dst, ts=ts)

    def add_edge(self, src: int, dst: int, ts: Optional[float] = None) -> None:
        self.add_edges(
            np.asarray([src], np.int64), np.asarray([dst], np.int64),
            ts=None if ts is None else np.asarray([ts], np.float32),
        )

    def add_edges(self, src, dst, ts=None) -> None:
        src, dst = validate_edge_ids(src, dst)
        if src.size:
            if ts is not None:
                ts = np.asarray(ts, np.float32).reshape(-1)
                if ts.shape != src.shape:
                    raise ValueError(
                        f"ts {ts.shape} does not match edges {src.shape}"
                    )
            if self._n and (bool(self._ts) != (ts is not None)):
                raise ValueError(
                    "mixed timestamped and untimestamped edges in one "
                    "GraphDelta — a temporal stream needs a ts per edge"
                )
            # copies: the caller may reuse its arrival buffers after
            # staging, and staged chunks are never mutated in place (so
            # `extend` may share them across buffers)
            self._src.append(src.copy())
            self._dst.append(dst.copy())
            if ts is not None:
                self._ts.append(ts.copy())
            self._n += int(src.size)

    def extend(self, other: "GraphDelta") -> None:
        if self._n and other._n and bool(self._ts) != bool(other._ts):
            raise ValueError(
                "cannot merge timestamped and untimestamped GraphDeltas"
            )
        self._src.extend(other._src)
        self._dst.extend(other._dst)
        self._ts.extend(other._ts)
        self._n += other._n

    def __len__(self) -> int:
        return self._n

    def edges(self) -> Tuple[np.ndarray, np.ndarray]:
        """``(src, dst)`` int64 arrays in arrival order."""
        if not self._src:
            return np.empty(0, np.int64), np.empty(0, np.int64)
        return np.concatenate(self._src), np.concatenate(self._dst)

    def edges_ts(self) -> Optional[np.ndarray]:
        """Per-edge float32 timestamps in arrival order, or None when
        this buffer was staged without them (the pre-round-19 shape)."""
        if not self._ts:
            return None
        return np.concatenate(self._ts)

    def sources(self) -> np.ndarray:
        """Sorted unique source ids — the rows whose degree (and hence
        whose downstream draws) this delta changes. Destinations are new
        LEAVES: they change no other row's draw, so invalidation closures
        seed from sources only."""
        if not self._src:
            return np.empty(0, np.int64)
        return np.unique(np.concatenate(self._src))

    def clear(self) -> None:
        self._src.clear()
        self._dst.clear()
        self._n = 0


class StreamingAdjacency:
    """Host bookkeeping for a streaming graph: an immutable base CSR plus
    per-node appended-edge lists, answering the three questions the delta
    layer asks — current neighbors (in tile-lane order: base first,
    arrivals after), forward k-hop closures over the UPDATED graph (the
    dist router's incremental owner-mask extension), and reverse k-hop
    closures (the invalidation set: every node whose ``hops``-hop
    expansion could reach a changed row). Reverse adjacency of the base
    CSR is built once (O(E) counting sort); appended edges ride small
    per-node dicts, so a bounded delta batch costs O(batch), never
    O(E)."""

    def __init__(self, csr_topo, edge_ts=None):
        self.indptr = np.asarray(csr_topo.indptr, np.int64)
        self.indices = np.asarray(csr_topo.indices, np.int64)
        self.n = self.indptr.shape[0] - 1
        # round-19 temporal workloads: optional per-edge timestamps
        # aligned with the base CSR, plus per-node appended-ts lists kept
        # in lockstep with _extra (same lane order — draw parity and the
        # temporal replay oracle both ride it)
        self.edge_ts = (
            None if edge_ts is None
            else np.asarray(edge_ts, np.float32).reshape(-1)
        )
        if self.edge_ts is not None and (
            self.edge_ts.shape[0] != self.indices.shape[0]
        ):
            raise ValueError(
                f"edge_ts has {self.edge_ts.shape[0]} entries for "
                f"{self.indices.shape[0]} edges"
            )
        self._extra: Dict[int, List[int]] = {}
        self._extra_ts: Dict[int, List[float]] = {}
        self._rev_extra: Dict[int, List[int]] = {}
        self._n_extra = 0
        # reverse base CSR (counting sort, same construction as CSRTopo)
        order = np.argsort(self.indices, kind="stable")
        counts = np.bincount(self.indices, minlength=self.n)
        self.rev_indptr = np.zeros(self.n + 1, np.int64)
        np.cumsum(counts, out=self.rev_indptr[1:])
        src_per_edge = np.repeat(
            np.arange(self.n, dtype=np.int64),
            self.indptr[1:] - self.indptr[:-1],
        )
        self.rev_indices = src_per_edge[order]

    @property
    def extra_edges(self) -> int:
        return self._n_extra

    def add_edges(self, src, dst, ts=None) -> None:
        src, dst = validate_edge_ids(src, dst, self.n)
        if self.edge_ts is not None:
            if ts is None:
                raise ValueError(
                    "temporal adjacency (edge_ts set) needs a timestamp "
                    "per appended edge"
                )
            ts = np.asarray(ts, np.float32).reshape(-1)
            if ts.shape != src.shape:
                raise ValueError(f"ts {ts.shape} != edges {src.shape}")
        for i, (u, v) in enumerate(zip(src, dst)):
            self._extra.setdefault(int(u), []).append(int(v))
            if self.edge_ts is not None:
                self._extra_ts.setdefault(int(u), []).append(float(ts[i]))
            self._rev_extra.setdefault(int(v), []).append(int(u))
        self._n_extra += src.shape[0]

    def pop_edges(self, src, dst) -> None:
        """Reverse a JUST-APPLIED `add_edges(src, dst)` — the caller's
        rollback when a downstream capacity preflight fails after the
        adjacency already advanced (dist `update_graph` computes its
        closure plans over the updated view, then commits or rolls
        back). Only valid as the exact inverse of the last add: entries
        pop from the tails the add appended to."""
        src = np.asarray(src, np.int64).reshape(-1)
        dst = np.asarray(dst, np.int64).reshape(-1)
        for u, v in zip(src[::-1], dst[::-1]):
            self._extra[int(u)].pop()
            if self.edge_ts is not None:
                self._extra_ts[int(u)].pop()
            self._rev_extra[int(v)].pop()
        self._n_extra -= src.shape[0]

    def neighbors(self, node: int) -> np.ndarray:
        """Current adjacency of ``node`` in TILE-LANE order: the base CSR
        row first, appended arrivals after (the order `to_csr_topo`
        materializes and the tile writes preserve — draw parity rides
        it)."""
        node = int(node)
        base = self.indices[self.indptr[node]:self.indptr[node + 1]]
        extra = self._extra.get(node)
        if not extra:
            return base.copy()
        return np.concatenate([base, np.asarray(extra, np.int64)])

    def neighbors_ts(self, node: int) -> np.ndarray:
        """Per-edge timestamps of `neighbors(node)`, same lane order
        (base CSR ts first, appended arrival ts after). Temporal
        adjacencies only."""
        if self.edge_ts is None:
            raise ValueError("adjacency was built without edge_ts")
        node = int(node)
        base = self.edge_ts[self.indptr[node]:self.indptr[node + 1]]
        extra = self._extra_ts.get(node)
        if not extra:
            return base.copy()
        return np.concatenate([base, np.asarray(extra, np.float32)])

    def degree(self, node: int) -> int:
        node = int(node)
        return int(self.indptr[node + 1] - self.indptr[node]) + len(
            self._extra.get(node, ())
        )

    def forward_closure(self, seeds, hops: int) -> np.ndarray:
        """Bool [N] mask of nodes reachable from ``seeds`` within
        ``hops`` hops over the UPDATED graph (seeds included) — the
        incremental owner-shard extension input: k-hop closures are
        union-homomorphic, so a dist owner's new mask is old-mask OR
        this."""
        mask = np.zeros(self.n, bool)
        seeds = np.asarray(seeds, np.int64).reshape(-1)
        if seeds.size == 0:
            return mask
        mask[seeds] = True
        frontier = np.unique(seeds)
        for _ in range(max(int(hops), 0)):
            if frontier.size == 0:
                break
            nxt = self._expand(frontier, self.indptr, self.indices,
                               self._extra)
            nxt = nxt[~mask[nxt]]
            if nxt.size == 0:
                break
            mask[nxt] = True
            frontier = nxt
        return mask

    def reverse_closure(self, srcs, hops: int) -> np.ndarray:
        """Sorted ids of every node within ``hops`` REVERSE hops of
        ``srcs`` over the updated graph (srcs included) — the
        invalidation set: a seed's k-hop sample can only change if its
        expansion reaches a changed row, i.e. the seed lies in the
        changed rows' ``hops``-reverse closure."""
        srcs = np.unique(np.asarray(srcs, np.int64).reshape(-1))
        if srcs.size == 0:
            return srcs
        mask = np.zeros(self.n, bool)
        mask[srcs] = True
        frontier = srcs
        for _ in range(max(int(hops), 0)):
            if frontier.size == 0:
                break
            nxt = self._expand(frontier, self.rev_indptr, self.rev_indices,
                               self._rev_extra)
            nxt = nxt[~mask[nxt]]
            if nxt.size == 0:
                break
            mask[nxt] = True
            frontier = nxt
        return np.nonzero(mask)[0]

    @staticmethod
    def _expand(frontier, indptr, indices, extra):
        """One BFS hop: base-CSR rows vectorized, appended edges via the
        per-node dicts (bounded by the delta volume, never O(E))."""
        parts = []
        starts = indptr[frontier]
        ends = indptr[frontier + 1]
        widths = ends - starts
        if widths.sum() > 0:
            flat = np.concatenate([
                indices[s:e] for s, e in zip(starts, ends) if e > s
            ])
            parts.append(flat)
        if extra:
            ext = [extra[int(u)] for u in frontier if int(u) in extra]
            if ext:
                parts.append(np.concatenate(
                    [np.asarray(x, np.int64) for x in ext]
                ))
        if not parts:
            return np.array([], np.int64)
        return np.unique(np.concatenate(parts))

    def to_csr_topo(self):
        """Materialize the UPDATED graph as a fresh `CSRTopo` (base edges
        first per row, arrivals after — exactly the tile-lane order, so a
        sampler freshly built over the result draws bit-identically to
        the streamed tiles). This is the replay-oracle / rebuild surface,
        NOT the serving path — serving mutates tiles in place."""
        from .utils import CSRTopo

        if not self._extra:
            return CSRTopo(indptr=self.indptr.copy(),
                           indices=self.indices.copy())
        extra_deg = np.zeros(self.n, np.int64)
        for u, vs in self._extra.items():
            extra_deg[u] = len(vs)
        base_deg = self.indptr[1:] - self.indptr[:-1]
        new_deg = base_deg + extra_deg
        new_indptr = np.zeros(self.n + 1, np.int64)
        np.cumsum(new_deg, out=new_indptr[1:])
        new_indices = np.empty(int(new_indptr[-1]), np.int64)
        # base block copy: each row's base edges land at its new offset
        src_per_edge = np.repeat(np.arange(self.n, dtype=np.int64), base_deg)
        pos_in_row = np.arange(self.indices.shape[0], dtype=np.int64) - (
            np.repeat(self.indptr[:-1], base_deg)
        )
        new_indices[new_indptr[src_per_edge] + pos_in_row] = self.indices
        for u, vs in self._extra.items():
            lo = int(new_indptr[u] + base_deg[u])
            new_indices[lo:lo + len(vs)] = vs
        return CSRTopo(indptr=new_indptr, indices=new_indices)

    def to_temporal(self):
        """Materialize the UPDATED graph as ``(CSRTopo, edge_ts)`` with
        the timestamps in exactly `to_csr_topo`'s edge order (base edges
        first per row, arrivals after — the tile-lane order) — the
        temporal replay-oracle / rebuild surface. Temporal adjacencies
        only."""
        if self.edge_ts is None:
            raise ValueError("adjacency was built without edge_ts")
        topo = self.to_csr_topo()
        if not self._extra:
            return topo, self.edge_ts.copy()
        new_indptr = np.asarray(topo.indptr, np.int64)
        base_deg = self.indptr[1:] - self.indptr[:-1]
        new_ts = np.zeros(int(new_indptr[-1]), np.float32)
        src_per_edge = np.repeat(np.arange(self.n, dtype=np.int64), base_deg)
        pos_in_row = np.arange(self.indices.shape[0], dtype=np.int64) - (
            np.repeat(self.indptr[:-1], base_deg)
        )
        new_ts[new_indptr[src_per_edge] + pos_in_row] = self.edge_ts
        for u, vs in self._extra.items():
            lo = int(new_indptr[u] + base_deg[u])
            new_ts[lo:lo + len(vs)] = np.asarray(
                self._extra_ts.get(u, []), np.float32
            )
        return topo, new_ts


def _bucketed(idx: np.ndarray, rows: np.ndarray, sentinel: int,
              floor: int = 64) -> Tuple[np.ndarray, np.ndarray]:
    """Pad a row-swap batch to a power-of-two bucket so the jitted
    `shard_tensor._scatter_rows` commit (one bounded [K, ...] row
    scatter into an existing same-shaped device table — the round-14
    promotion idiom, NOT the PERF_NOTES scatter-build trap) compiles
    once per bucket, not once per delta size."""
    b = _bucket(idx.shape[0], floor=floor)
    pos = np.full(b, sentinel, np.int32)
    pos[: idx.shape[0]] = idx
    padded = np.zeros((b,) + rows.shape[1:], rows.dtype)
    padded[: idx.shape[0]] = rows
    return pos, padded


class StreamingTiledGraph:
    """The delta layer over the 128-lane tile layout: host ``(bd, tiles)``
    mirrors with reserved slack rows, in-place pad-lane appends + staged
    tile spills, and batched device tile swaps (module docstring has the
    design; docs/api.md "Streaming graphs" the contract).

    Parameters
    ----------
    csr_topo : CSRTopo — the ingest-time graph. Kept immutable; appended
        edges live in the stream's own state.
    reserve_tiles : explicit spare tile-row count for spills (default:
        ``ceil(reserve_frac * M)``, min 8). A spill relocates a node to
        ``old_rows + grow_tiles`` fresh rows from this reserve;
        exhaustion raises `StreamCapacityError` (plan capacity like
        sampler caps — shapes are frozen at construction).
    grow_tiles : extra tile rows granted per spill (>=1; each buys 128
        more slack lanes before the node spills again).
    device_arrays : build and maintain the device ``(bd, tiles)`` pair
        (the serving path). False = host bookkeeping only (the dist
        router's full-graph view costs no device HBM).
    id_dtype : tile dtype; defaults to the same `_best_id_dtype` rule as
        `CSRTopo.to_device_tiled`, so a streamed sampler and a frozen one
        run byte-identical programs.

    Thread safety: `apply`/`install_rows` mutate under one lock, but the
    serve engines additionally FENCE every commit (update_params-style
    drain) so no in-flight flush ever reads a half-applied batch — the
    lock only orders bare concurrent callers.
    """

    def __init__(self, csr_topo, reserve_tiles: Optional[int] = None,
                 reserve_frac: float = 0.5, grow_tiles: int = 1,
                 device_arrays: bool = True, id_dtype=None, edge_ts=None):
        from .utils import _best_id_dtype

        self.csr_topo = csr_topo
        self.adj = StreamingAdjacency(csr_topo, edge_ts=edge_ts)
        self.n = self.adj.n
        if id_dtype is None:
            id_dtype = _best_id_dtype(self.n + 1)
        bd, tiles = build_tiled_host(
            self.adj.indptr, self.adj.indices, id_dtype
        )
        m = tiles.shape[0]
        if reserve_tiles is None:
            reserve_tiles = max(8, int(np.ceil(float(reserve_frac) * m)))
        self.m_base = m
        self.m_cap = m + int(reserve_tiles)
        self.grow_tiles = max(int(grow_tiles), 1)
        self.bd = np.ascontiguousarray(bd)  # [N, 2] int32 (base, deg)
        self.tiles = np.zeros((self.m_cap, LANE), tiles.dtype)
        self.tiles[:m] = tiles
        # round-19 temporal payload: per-edge timestamps in a SECOND tile
        # table sharing the tile map byte for byte (the round-5 weights
        # trick) — appends/spills/installs mutate both under one lock and
        # one batched device swap per commit, so a committed edge and its
        # timestamp become drawable in the same `temporal_graph()` read
        self.ttiles: Optional[np.ndarray] = None
        if edge_ts is not None:
            _, tt = build_tiled_host(
                self.adj.indptr, self.adj.edge_ts, np.float32
            )
            self.ttiles = np.zeros((self.m_cap, LANE), np.float32)
            self.ttiles[:m] = tt
        deg = self.bd[:, 1].astype(np.int64)
        self.alloc_rows = (-(-deg // LANE)).astype(np.int32)  # rows held
        self._free_row = m
        self.version = 0
        # versioned node stamps: the graph version at which a node's row
        # last changed — the invalidation consumers (cache / replicas /
        # tier placement) compare against these instead of guessing
        self.node_version = np.zeros(self.n, np.int64)
        self.stats = {"pad_writes": 0, "tile_spills": 0, "installs": 0,
                      "tile_rows_swapped": 0, "bd_rows_swapped": 0,
                      "edges": 0}
        self._lock = threading.Lock()
        self._bd_dev = None
        self._tiles_dev = None
        self._tt_dev = None
        if device_arrays:
            import jax.numpy as jnp

            self._bd_dev = jnp.asarray(self.bd)
            self._tiles_dev = jnp.asarray(self.tiles)
            if self.ttiles is not None:
                self._tt_dev = jnp.asarray(self.ttiles)

    # ------------------------------------------------------------ reads
    @property
    def free_rows(self) -> int:
        return self.m_cap - self._free_row

    def _reserve_report_locked(self) -> Dict[str, object]:
        used = self._free_row - self.m_base
        free = self.m_cap - self._free_row
        commits = self.version
        per_commit = used / commits if commits else 0.0
        return {
            "tiles_base": self.m_base,
            "tiles_cap": self.m_cap,
            "reserve_tiles": self.m_cap - self.m_base,
            "reserve_used": used,
            "reserve_free": free,
            "commits": commits,
            "rows_per_commit": per_commit,
            # None = no consumption observed yet (or none at all): there
            # is nothing honest to project from
            "projected_commits_to_exhaustion": (
                free / per_commit if per_commit > 0 else None
            ),
            "tile_spills": self.stats["tile_spills"],
            "installs": self.stats["installs"],
        }

    def reserve_report(self) -> Dict[str, object]:
        """Live reserve budget (round-18 satellite — the r17 "capacity
        exhaustion is a planned hard error" leftover made diagnosable):
        tiles used / remaining, consumption rate per commit, and the
        projected commits left at that rate (None before any
        consumption). `StreamCapacityError` messages carry the same
        numbers, so the planned hard error names its own runway."""
        with self._lock:
            return self._reserve_report_locked()

    def _capacity_error(self, prefix: str) -> StreamCapacityError:
        """Build the planned hard error WITH the reserve diagnosis
        (caller holds ``_lock``)."""
        r = self._reserve_report_locked()
        proj = r["projected_commits_to_exhaustion"]
        return StreamCapacityError(
            f"{prefix} — reserve {r['reserve_used']}/{r['reserve_tiles']} "
            f"rows used over {r['commits']} commit(s) "
            f"({r['rows_per_commit']:.2f} rows/commit"
            + (f", ~{proj:.0f} commits of runway were left"
               if proj is not None else "")
            + "); rebuild the stream with a larger reserve_frac/"
            "reserve_tiles (shapes are frozen — see StreamingTiledGraph)"
        )

    @property
    def temporal(self) -> bool:
        """True when this stream carries per-edge timestamps (built with
        ``edge_ts=``) — `temporal_graph()` is then the sampling surface
        and every committed edge must arrive with a timestamp."""
        return self.ttiles is not None

    def graph(self):
        """The CURRENT device ``(bd, tiles)`` pair — what a stream-bound
        `GraphSageSampler` samples from (`bind_stream`). Array objects
        change at every commit; shapes never do."""
        if self._tiles_dev is None:
            raise ValueError(
                "stream was built with device_arrays=False (host "
                "bookkeeping only)"
            )
        return self._bd_dev, self._tiles_dev

    def temporal_graph(self):
        """The CURRENT device ``(bd, tiles, ttiles)`` triple — what a
        temporal-bound sampler (`GraphSageSampler.bind_temporal`) draws
        from. Same commit semantics as `graph()`: array objects change
        per fenced commit, shapes never."""
        if not self.temporal:
            raise ValueError(
                "stream was built without edge_ts (no timestamp payload)"
            )
        if self._tiles_dev is None:
            raise ValueError(
                "stream was built with device_arrays=False (host "
                "bookkeeping only)"
            )
        return self._bd_dev, self._tiles_dev, self._tt_dev

    def neighbors(self, node: int) -> np.ndarray:
        return self.adj.neighbors(node)

    def degree(self, node: int) -> int:
        return self.adj.degree(node)

    def to_csr_topo(self):
        return self.adj.to_csr_topo()

    def affected_seeds(self, srcs, hops: int) -> np.ndarray:
        """The invalidation set of changed rows ``srcs``: every node
        whose ``hops``-hop EXPANSION could reach one (reverse closure
        over the updated graph, srcs included). ``hops`` is the number of
        expansion hops — ``len(sizes) - 1`` for an L-layer sampler, since
        the final hop's frontier is gathered but never expanded."""
        return self.adj.reverse_closure(srcs, hops)

    # ----------------------------------------------------------- writes
    def preflight(self, delta: Optional[GraphDelta] = None,
                  installs: Optional[Sequence[Tuple[int, np.ndarray]]] = None,
                  ) -> int:
        """Validate a WHOLE batch — edge ids, install constraints, and
        reserve capacity (spills simulated in apply order) — without
        mutating anything. Returns the reserve rows the batch would
        consume; raises `StreamCapacityError`/`ValueError` exactly where
        `apply` would, BEFORE any state moves. `apply` runs this first,
        which is what makes a commit atomic: it either lands fully
        (host + device + version stamps) or leaves the stream untouched.
        Multi-stream callers (the dist router) preflight every stream
        before applying to any."""
        src, dst = delta.edges() if delta is not None else (
            np.array([], np.int64), np.array([], np.int64)
        )
        ts = delta.edges_ts() if delta is not None else None
        installs = self._normalize_installs(installs)
        with self._lock:
            return self._preflight_locked(src, dst, installs, ts)

    def _normalize_installs(self, installs):
        """Normalize install entries to ``(node, nbrs, ts_row|None)`` —
        temporal streams accept (and require) a per-neighbor timestamp
        row per install; non-temporal streams reject one."""
        out = []
        for entry in installs or ():
            if len(entry) == 2:
                node, nbrs = entry
                ts_row = None
            else:
                node, nbrs, ts_row = entry
            nbrs = np.asarray(nbrs, np.int64)
            if ts_row is not None:
                ts_row = np.asarray(ts_row, np.float32).reshape(-1)
            out.append((int(node), nbrs, ts_row))
        return out

    def _check_ts(self, src, ts, installs) -> None:
        """The temporal-arity contract, one place: a temporal stream
        takes exactly one timestamp per edge (appends AND installs); a
        non-temporal stream takes none."""
        if self.temporal:
            if src.size and (ts is None or ts.shape != src.shape):
                raise ValueError(
                    "temporal stream (edge_ts set) needs one timestamp "
                    "per appended edge — stage with "
                    "GraphDelta.add_edges(src, dst, ts=...)"
                )
            for node, nbrs, ts_row in installs:
                if nbrs.size and (ts_row is None
                                  or ts_row.shape[0] != nbrs.shape[0]):
                    raise ValueError(
                        f"temporal install for node {node} needs one "
                        f"timestamp per neighbor"
                    )
        else:
            if ts is not None or any(t is not None for _, _, t in installs):
                raise ValueError(
                    "edge timestamps staged into a non-temporal stream — "
                    "build StreamingTiledGraph(edge_ts=...) to carry them"
                )

    def _preflight_locked(self, src, dst, installs, ts=None) -> int:
        if src.size:
            validate_edge_ids(src, dst, self.n)
        self._check_ts(src, ts, installs)
        need = 0
        sim_alloc: Dict[int, int] = {}
        sim_deg: Dict[int, int] = {}
        for node, nbrs, _ts_row in installs:
            if not 0 <= node < self.n:
                raise ValueError(
                    f"install node {node} outside [0, {self.n})"
                )
            if nbrs.size and ((nbrs < 0) | (nbrs >= self.n)).any():
                # same contract as edge appends: a bad id raises here,
                # never lands in the tiles (clipped gathers would
                # silently read the last row otherwise)
                raise ValueError(
                    f"install neighbors of node {node} outside "
                    f"[0, {self.n}): "
                    f"{nbrs[(nbrs < 0) | (nbrs >= self.n)][:4].tolist()}"
                )
            if node in sim_deg:
                raise ValueError(
                    f"duplicate install for node {node} in one batch"
                )
            if int(self.bd[node, 1]) != 0:
                raise ValueError(
                    f"install_rows targets degree-0 rows only (node "
                    f"{node} has degree {int(self.bd[node, 1])}); use "
                    "apply() appends for materialized rows"
                )
            rows = -(-int(nbrs.size) // LANE)
            need += rows
            sim_alloc[node] = rows
            sim_deg[node] = int(nbrs.size)
        for u in src:
            u = int(u)
            d = sim_deg.get(u, int(self.bd[u, 1]))
            a = sim_alloc.get(u, int(self.alloc_rows[u]))
            if d >= a * LANE:
                a += self.grow_tiles
                need += a
                sim_alloc[u] = a
            sim_deg[u] = d + 1
        free = self.m_cap - self._free_row
        if need > free:
            raise self._capacity_error(
                f"tile reserve exhausted: batch needs {need} rows, "
                f"{free} free"
            )
        return need

    def apply(self, delta: GraphDelta,
              installs: Optional[Sequence[Tuple[int, np.ndarray]]] = None,
              ) -> Dict[str, int]:
        """Commit one delta batch: host pad-lane writes / spills /
        installs, then ONE batched device tile swap + one bd swap.
        ATOMIC: the whole batch is preflighted (ids, install
        constraints, reserve capacity) before any state moves, so a
        raising apply leaves host, device, versions, and the adjacency
        untouched. Returns the commit summary. Callers serving traffic
        go through ``engine.update_graph`` (which fences in-flight
        flushes first); the stream's own lock only orders bare
        concurrent callers."""
        src, dst = delta.edges() if delta is not None else (
            np.array([], np.int64), np.array([], np.int64)
        )
        ts = delta.edges_ts() if delta is not None else None
        installs = self._normalize_installs(installs)
        if src.size == 0 and not installs:
            return {"edges": 0, "pad_writes": 0, "tile_spills": 0,
                    "installs": 0, "tile_rows_swapped": 0,
                    "bd_rows_swapped": 0, "free_rows": self.free_rows,
                    "version": self.version}
        with self._lock:
            self._preflight_locked(src, dst, installs, ts)
            touched_tiles: set = set()
            touched_bd: set = set()
            pad_writes = spills = 0
            for node, nbrs, ts_row in installs:
                self._install_locked(node, nbrs, touched_tiles, touched_bd,
                                     ts_row=ts_row)
            if src.size:
                # adjacency bookkeeping feeds closures (ids validated by
                # the preflight above)
                self.adj.add_edges(src, dst, ts=ts)
                for i, (u, v) in enumerate(zip(src, dst)):
                    p, s = self._append_locked(
                        int(u), int(v), touched_tiles, touched_bd,
                        ts=None if ts is None else float(ts[i]),
                    )
                    pad_writes += p
                    spills += s
            self.version += 1
            changed = np.fromiter(touched_bd, np.int64, len(touched_bd))
            self.node_version[changed] = self.version
            n_tiles, n_bd = self._sync_device_locked(touched_tiles,
                                                     touched_bd)
            self.stats["pad_writes"] += pad_writes
            self.stats["tile_spills"] += spills
            self.stats["installs"] += len(installs)
            self.stats["edges"] += int(src.size)
            self.stats["tile_rows_swapped"] += n_tiles
            self.stats["bd_rows_swapped"] += n_bd
            return {"edges": int(src.size), "pad_writes": pad_writes,
                    "tile_spills": spills, "installs": len(installs),
                    "tile_rows_swapped": n_tiles, "bd_rows_swapped": n_bd,
                    "free_rows": self.free_rows, "version": self.version}

    def install_rows(self, rows: Sequence[Tuple[int, np.ndarray]]
                     ) -> Dict[str, int]:
        """Materialize full adjacency rows for nodes currently reading
        degree 0 — the dist router's incremental halo-closure extension
        (a node newly entering an owner's closure carries its WHOLE
        current edge list, not an append). One batched commit like
        `apply`."""
        return self.apply(None, installs=rows)

    # ------------------------------------------------------- internals
    def _append_locked(self, u: int, v: int, touched_tiles, touched_bd,
                       ts: Optional[float] = None):
        base = int(self.bd[u, 0])
        deg = int(self.bd[u, 1])
        cap = int(self.alloc_rows[u]) * LANE
        spilled = 0
        if deg >= cap:
            base = self._relocate_locked(u, touched_tiles)
            spilled = 1
        row = base + deg // LANE
        self.tiles[row, deg % LANE] = v
        if self.ttiles is not None:
            # the timestamp lands in the SAME (row, lane) as the edge —
            # one commit makes both drawable (arity checked by preflight)
            self.ttiles[row, deg % LANE] = ts
        self.bd[u, 1] = deg + 1
        touched_tiles.add(row)
        touched_bd.add(u)
        return 1 - spilled, spilled

    def _relocate_locked(self, u: int, touched_tiles) -> int:
        """Move node ``u`` to ``alloc + grow_tiles`` fresh rows from the
        reserve (copy its existing tiles, bump base). The old rows become
        dead padding the degree mask never reads — draws are unchanged
        because `ops.sample._tiled_resolve` only ever dereferences
        ``base + pos // 128`` for valid positions."""
        old_base = int(self.bd[u, 0])
        old_rows = int(self.alloc_rows[u])
        need = old_rows + self.grow_tiles
        if self._free_row + need > self.m_cap:
            raise self._capacity_error(
                f"tile reserve exhausted: node {u} needs {need} rows, "
                f"{self.m_cap - self._free_row} free"
            )
        new_base = self._free_row
        self._free_row += need
        if old_rows:
            self.tiles[new_base:new_base + old_rows] = (
                self.tiles[old_base:old_base + old_rows]
            )
            if self.ttiles is not None:
                self.ttiles[new_base:new_base + old_rows] = (
                    self.ttiles[old_base:old_base + old_rows]
                )
        touched_tiles.update(range(new_base, new_base + old_rows + 1))
        self.bd[u, 0] = new_base
        self.alloc_rows[u] = need
        return new_base

    def _install_locked(self, node: int, nbrs: np.ndarray, touched_tiles,
                        touched_bd, ts_row: Optional[np.ndarray] = None,
                        ) -> None:
        if not 0 <= node < self.n:
            raise ValueError(f"install node {node} outside [0, {self.n})")
        if int(self.bd[node, 1]) != 0:
            raise ValueError(
                f"install_rows targets degree-0 rows only (node {node} "
                f"has degree {int(self.bd[node, 1])}); use apply() "
                "appends for materialized rows"
            )
        if nbrs.size == 0:
            return
        need = -(-int(nbrs.size) // LANE)
        if self._free_row + need > self.m_cap:
            raise self._capacity_error(
                f"tile reserve exhausted installing node {node} "
                f"({need} rows needed, {self.m_cap - self._free_row} free)"
            )
        base = self._free_row
        self._free_row += need
        flat = self.tiles[base:base + need].reshape(-1)
        flat[: nbrs.size] = nbrs.astype(self.tiles.dtype)
        flat[nbrs.size:] = 0
        if self.ttiles is not None:
            tflat = self.ttiles[base:base + need].reshape(-1)
            tflat[: nbrs.size] = ts_row
            tflat[nbrs.size:] = 0
        self.bd[node, 0] = base
        self.bd[node, 1] = nbrs.size
        self.alloc_rows[node] = need
        touched_tiles.update(range(base, base + need))
        touched_bd.add(node)
        # bookkeeping: an installed row's neighbors enter the adjacency
        # view as "extras" over its empty base row (same lane order)
        self.adj._extra[node] = [int(x) for x in nbrs]
        if self.ttiles is not None:
            self.adj._extra_ts[node] = [float(x) for x in ts_row]
        for v in nbrs:
            self.adj._rev_extra.setdefault(int(v), []).append(node)
        self.adj._n_extra += int(nbrs.size)

    def _sync_device_locked(self, touched_tiles, touched_bd):
        n_tiles, n_bd = len(touched_tiles), len(touched_bd)
        if self._tiles_dev is None or (not n_tiles and not n_bd):
            return n_tiles, n_bd
        import jax.numpy as jnp

        if n_tiles:
            idx = np.fromiter(touched_tiles, np.int64, n_tiles)
            idx.sort()
            pos, rows = _bucketed(idx, self.tiles[idx], self.m_cap)
            self._tiles_dev = _scatter_rows(
                self._tiles_dev, jnp.asarray(pos), jnp.asarray(rows)
            )
            if self._tt_dev is not None:
                # the timestamp payload swaps the SAME touched rows in the
                # same commit — a draw can never see an edge without its ts
                tpos, trows = _bucketed(idx, self.ttiles[idx], self.m_cap)
                self._tt_dev = _scatter_rows(
                    self._tt_dev, jnp.asarray(tpos), jnp.asarray(trows)
                )
        if n_bd:
            idx = np.fromiter(touched_bd, np.int64, n_bd)
            idx.sort()
            pos, rows = _bucketed(idx, self.bd[idx], self.n)
            self._bd_dev = _scatter_rows(
                self._bd_dev, jnp.asarray(pos), jnp.asarray(rows)
            )
        return n_tiles, n_bd

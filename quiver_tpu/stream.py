"""Streaming graph deltas: serve on a graph that changes under live
traffic (ROADMAP item 1, round 17).

Every layer built through round 16 — tiled sampling, fused one-dispatch
serving, the disk tier, replication, elastic resharding — assumes a frozen
CSR/tile map built once at ingest. The north-star workload (feeds, fraud
graphs) streams edges continuously, and the access-stream papers
(PyTorch-Direct, arxiv 2101.07956; GPU-side sampling invariants, arxiv
2009.06693) both argue the same discipline: mutation must ride the
existing GATHER-ONLY formulations, never reintroduce host-side rebuilds on
the hot path.

The 128-lane tile layout (`ops.sample.build_tiled_host`) makes that
possible almost for free. A node's edges live LANE-aligned in a
``[M, 128]`` tile table, so ceil-padding to 128 leaves ``cap - deg`` slack
pad lanes in every node's last tile row — lanes the degree mask already
gates out of every draw. An edge append is therefore:

- **pad-lane write** (the common case): put the new neighbor in the next
  slack lane and bump the node's degree — one tile-row write + one
  ``(base, deg)`` row write, no relayout, no shape change;
- **tile spill** (a node's allocated rows filled): relocate the node to
  fresh rows from a pre-reserved region at the table's tail (copy its old
  rows, bump ``base``), then write. The old rows become dead padding the
  degree mask never reads. Reserve exhaustion raises
  `StreamCapacityError` — capacity is planned like the sampler's static
  caps, never silently grown (a shape change would invalidate every
  AOT-sealed serve executable).

Deltas accumulate HOST-SIDE in a :class:`GraphDelta` buffer and land on
device as **batched tile swaps**: the touched tile rows (and bd rows) go
through one jitted bucketed row-scatter per commit
(`shard_tensor._scatter_rows` semantics — the same idiom the round-14 tier
promotions ride). Scatter-building big arrays is the compile trap
PERF_NOTES pins; a bounded ``[K, 128]`` row scatter into an EXISTING
same-shaped array is not. Every sampler path stays gather-only and
bit-replayable: the device arrays keep their shapes for the life of the
stream, so the sealed `inference.BucketPrograms` executables keep running
— `BucketPrograms.rebind` swaps the argument arrays, never recompiles.

Parity discipline (pinned in tests/test_stream.py): a draw from the
streamed ``(bd, tiles)`` is bit-equal to a draw from a tile table freshly
built over the materialized updated CSR (`to_csr_topo`) on the same key —
appends preserve per-row edge order (base edges first, arrivals after),
and `ops.sample._tiled_resolve` reads positions through the ``base``
indirection, so relocation changes no drawn bit. Frozen-graph replay is
bit-identical to delta-replay with an empty delta, and an appended edge is
visible to the NEXT sample after the commit returns (copy-all semantics:
any draw with fanout >= deg must include it).

`StreamingAdjacency` is the host bookkeeping half: the base CSR plus the
appended edges, with forward k-hop closures (the dist router's incremental
owner-shard extension) and reverse k-hop closures (the versioned-node-
stamp invalidation set — every seed whose k-hop expansion could reach a
changed row). The serve engines wire all of this through
``update_graph(delta)`` — see `serve.engine.ServeEngine.update_graph` and
docs/api.md "Streaming graphs".

Round 21 (graph lifecycle, `quiver_tpu.lifecycle`) makes the stream live
forever — the tile map learns to SHRINK, under three distinct bit
disciplines (docs/api.md "Graph lifecycle" has the contract table):

- **edge deletion / timestamp update** (`GraphDelta.remove_edges` /
  `update_edges`): a deletion rewrites the node's lanes in place (the
  surviving edges shift left, preserving base-first-arrivals-after
  order), so the stream stays bit-equal to a graph FRESHLY BUILT without
  the edge — deletion parity is rebuild parity, the same oracle appends
  ride. Draw bits for touched rows change BY DESIGN (the Gumbel uniform
  stream is positional).
- **TTL retention** (`expire_edges`): expiry must NOT shift lanes — the
  per-lane uniform draw makes any shift a bit change, which would break
  the retention<->masking duality — so an expired edge's timestamp is
  overwritten with ``+inf`` (a masked lane write: invisible at every
  finite query t, exactly like a ``cutoff < ts`` band mask on the
  unexpired twin). Dead lanes are RE-USED by later appends to the same
  node (the adjacency replaces the entry in place, so rebuild parity
  still holds), which is what keeps a sliding-window working set's tile
  footprint flat.
- **compaction** (`plan_compaction`/`apply_compaction`): strictly
  observe-only on bits — it reclaims whole tile ROWS (spill-retired
  ranges, over-allocated tails, defrag relocations through the ``base``
  indirection), never lanes, because `ops.sample._tiled_resolve` reads
  positions through ``base`` and the degree mask: row placement is
  invisible to every draw.

Reserve exhaustion stops being terminal: `provision_reserve` grows the
tile tables by a whole bank (one shape change, one sealed-program
rebuild — never a per-commit recompile; see
`inference.BucketPrograms.reprovision`).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .ops.sample import LANE, build_tiled_host
from .shard_tensor import _bucket, _scatter_rows

# The batched tile-swap primitive: one bounded [K, ...] row scatter into
# an existing same-shaped device table, out-of-range positions dropped as
# padding (`shard_tensor._scatter_rows` — the round-14 promotion idiom,
# NOT the PERF_NOTES scatter-build trap). Named here because every delta
# consumer (tile sync below, `ClosureFeature.install_rows` in serve/dist)
# must commit through this one shape-stable path.
_swap_rows = _scatter_rows

__all__ = [
    "GraphDelta",
    "StreamCapacityError",
    "StreamingAdjacency",
    "StreamingTiledGraph",
    "validate_edge_ids",
]


class StreamCapacityError(RuntimeError):
    """The stream's reserved tile (or feature) rows are exhausted. The
    fix is capacity planning, not silent growth: growing the device
    arrays would change their shapes and invalidate every sealed AOT
    serve executable — rebuild the stream with a larger
    ``reserve_frac``/``reserve_tiles`` (the same contract as the
    sampler's static caps)."""


def validate_edge_ids(src, dst, n: Optional[int] = None,
                      what: str = "delta",
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Flatten an edge batch to matched int64 ``(src, dst)`` arrays and
    (when ``n`` is given) range-check every id against ``[0, n)`` — the
    one validation every staging/commit entry point shares, so a bad
    arrival raises AT ITS CALL SITE and never poisons a pending buffer
    (a commit failure re-stages the delta; an unvalidated bad edge would
    wedge every future ``update_graph``)."""
    src = np.asarray(src, np.int64).reshape(-1)
    dst = np.asarray(dst, np.int64).reshape(-1)
    if src.shape != dst.shape:
        raise ValueError(f"src {src.shape} / dst {dst.shape} mismatch")
    if n is not None:
        bad = (src < 0) | (src >= n) | (dst < 0) | (dst >= n)
        if bad.any():
            raise ValueError(
                f"{what} edge ids outside [0, {n}): "
                f"{np.stack([src[bad], dst[bad]], 1)[:4].tolist()}"
            )
    return src, dst


class GraphDelta:
    """Host-side edge-arrival buffer: ``(src, dst)`` pairs in arrival
    order, held as ndarray CHUNKS (one per staged batch — the ingest
    path is measured by bench's ``stream_append_s``, so no per-edge
    Python boxing). Accumulation is cheap and lock-free per instance
    (the serve engines guard their pending buffer with their own lock);
    nothing touches the device until a fenced ``update_graph``/``apply``
    commits the whole batch. Deterministic: two buffers fed the same
    arrivals apply identically."""

    __slots__ = ("_src", "_dst", "_ts", "_n",
                 "_rsrc", "_rdst", "_usrc", "_udst", "_uts")

    def __init__(self, src=None, dst=None, ts=None):
        self._src: List[np.ndarray] = []
        self._dst: List[np.ndarray] = []
        # per-edge timestamp chunks (round 19, temporal workloads): either
        # EVERY staged chunk carries timestamps or none does — a mixed
        # buffer could not commit into a temporal tile map deterministically
        self._ts: List[np.ndarray] = []
        self._n = 0
        # round-21 lifecycle: staged removals and timestamp updates, in
        # their own arrival order. One commit applies installs, then
        # appends, then removals, then updates — the fixed order every
        # preflight simulates, so "remove an edge this same batch
        # appended" validates exactly once, the same everywhere.
        self._rsrc: List[np.ndarray] = []
        self._rdst: List[np.ndarray] = []
        self._usrc: List[np.ndarray] = []
        self._udst: List[np.ndarray] = []
        self._uts: List[np.ndarray] = []
        if src is not None or dst is not None:
            if (src is None) != (dst is None):
                raise ValueError("src/dst lengths differ")
            self.add_edges(src, dst, ts=ts)

    def add_edge(self, src: int, dst: int, ts: Optional[float] = None) -> None:
        self.add_edges(
            np.asarray([src], np.int64), np.asarray([dst], np.int64),
            ts=None if ts is None else np.asarray([ts], np.float32),
        )

    def add_edges(self, src, dst, ts=None) -> None:
        src, dst = validate_edge_ids(src, dst)
        if src.size:
            if ts is not None:
                ts = np.asarray(ts, np.float32).reshape(-1)
                if ts.shape != src.shape:
                    raise ValueError(
                        f"ts {ts.shape} does not match edges {src.shape}"
                    )
            if self._n and (bool(self._ts) != (ts is not None)):
                raise ValueError(
                    "mixed timestamped and untimestamped edges in one "
                    "GraphDelta — a temporal stream needs a ts per edge"
                )
            # copies: the caller may reuse its arrival buffers after
            # staging, and staged chunks are never mutated in place (so
            # `extend` may share them across buffers)
            self._src.append(src.copy())
            self._dst.append(dst.copy())
            if ts is not None:
                self._ts.append(ts.copy())
            self._n += int(src.size)

    def remove_edge(self, src: int, dst: int) -> None:
        self.remove_edges(np.asarray([src], np.int64),
                          np.asarray([dst], np.int64))

    def remove_edges(self, src, dst) -> None:
        """Stage edge DELETIONS: each ``(src, dst)`` pair removes one
        occurrence of that edge (first in lane order) at commit time.
        All-or-none: the commit preflight validates every removal
        against the post-append adjacency and a single miss fails the
        whole batch before any state moves. A deletion rewrites the
        source row's lanes (survivors shift left), so the stream stays
        bit-equal to a graph freshly built WITHOUT the edge — touched
        rows' draws change by design and are invalidated like appends."""
        src, dst = validate_edge_ids(src, dst)
        if src.size:
            self._rsrc.append(src.copy())
            self._rdst.append(dst.copy())

    def update_edge(self, src: int, dst: int, ts: float) -> None:
        self.update_edges(np.asarray([src], np.int64),
                          np.asarray([dst], np.int64),
                          np.asarray([ts], np.float32))

    def update_edges(self, src, dst, ts) -> None:
        """Stage per-edge TIMESTAMP updates (temporal streams only —
        the timestamp is the one mutable per-edge payload a streamed
        tile map carries; plain streams have no weight tiles to write).
        Each pair retargets the first lane-order occurrence of
        ``(src, dst)``; timestamps must be finite (``+inf`` is the
        retention layer's expiry sentinel — see ``expire_edges``)."""
        src, dst = validate_edge_ids(src, dst)
        if ts is None:
            raise ValueError(
                "update_edges needs a timestamp per edge — the ts lane "
                "is the only mutable per-edge payload"
            )
        ts = np.asarray(ts, np.float32).reshape(-1)
        if ts.shape != src.shape:
            raise ValueError(f"ts {ts.shape} != edges {src.shape}")
        if ts.size and not np.isfinite(ts).all():
            raise ValueError(
                "non-finite edge timestamps staged — +inf is reserved "
                "as the retention expiry sentinel"
            )
        if src.size:
            self._usrc.append(src.copy())
            self._udst.append(dst.copy())
            self._uts.append(ts.copy())

    def extend(self, other: "GraphDelta") -> None:
        if self._n and other._n and bool(self._ts) != bool(other._ts):
            raise ValueError(
                "cannot merge timestamped and untimestamped GraphDeltas"
            )
        self._src.extend(other._src)
        self._dst.extend(other._dst)
        self._ts.extend(other._ts)
        self._n += other._n
        self._rsrc.extend(other._rsrc)
        self._rdst.extend(other._rdst)
        self._usrc.extend(other._usrc)
        self._udst.extend(other._udst)
        self._uts.extend(other._uts)

    @property
    def n_appends(self) -> int:
        return self._n

    def __len__(self) -> int:
        # total staged OPERATIONS: appends + removals + updates (the
        # engines use this for "is there anything to commit" and for
        # their delta_edges op counters)
        return self._n + sum(c.size for c in self._rsrc) + sum(
            c.size for c in self._usrc
        )

    def edges(self) -> Tuple[np.ndarray, np.ndarray]:
        """``(src, dst)`` int64 arrays in arrival order."""
        if not self._src:
            return np.empty(0, np.int64), np.empty(0, np.int64)
        return np.concatenate(self._src), np.concatenate(self._dst)

    def edges_ts(self) -> Optional[np.ndarray]:
        """Per-edge float32 timestamps in arrival order, or None when
        this buffer was staged without them (the pre-round-19 shape)."""
        if not self._ts:
            return None
        return np.concatenate(self._ts)

    def removals(self) -> Tuple[np.ndarray, np.ndarray]:
        """Staged removal pairs ``(src, dst)`` in arrival order."""
        if not self._rsrc:
            return np.empty(0, np.int64), np.empty(0, np.int64)
        return np.concatenate(self._rsrc), np.concatenate(self._rdst)

    def updates(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Staged timestamp updates ``(src, dst, ts)`` in arrival
        order."""
        if not self._usrc:
            return (np.empty(0, np.int64), np.empty(0, np.int64),
                    np.empty(0, np.float32))
        return (np.concatenate(self._usrc), np.concatenate(self._udst),
                np.concatenate(self._uts))

    def max_ts(self):
        """Largest staged timestamp (appends and updates), or None when
        nothing timestamped is staged — the commit clock the retention
        layer advances on (`lifecycle.RetentionPolicy`)."""
        parts = [c for c in self._ts if c.size] + [
            c for c in self._uts if c.size
        ]
        if not parts:
            return None
        return float(max(float(c.max()) for c in parts))

    def sources(self) -> np.ndarray:
        """Sorted unique source ids — the rows whose lanes (and hence
        whose downstream draws) this delta changes: append, removal, and
        update sources alike. Destinations are new LEAVES: they change
        no other row's draw, so invalidation closures seed from sources
        only."""
        parts = self._src + self._rsrc + self._usrc
        if not parts:
            return np.empty(0, np.int64)
        return np.unique(np.concatenate(parts))

    def clear(self) -> None:
        self._src.clear()
        self._dst.clear()
        self._ts.clear()
        self._n = 0
        self._rsrc.clear()
        self._rdst.clear()
        self._usrc.clear()
        self._udst.clear()
        self._uts.clear()


class StreamingAdjacency:
    """Host bookkeeping for a streaming graph: an immutable base CSR plus
    per-node appended-edge lists, answering the three questions the delta
    layer asks — current neighbors (in tile-lane order: base first,
    arrivals after), forward k-hop closures over the UPDATED graph (the
    dist router's incremental owner-mask extension), and reverse k-hop
    closures (the invalidation set: every node whose ``hops``-hop
    expansion could reach a changed row). Reverse adjacency of the base
    CSR is built once (O(E) counting sort); appended edges ride small
    per-node dicts, so a bounded delta batch costs O(batch), never
    O(E)."""

    def __init__(self, csr_topo, edge_ts=None):
        self.indptr = np.asarray(csr_topo.indptr, np.int64)
        self.indices = np.asarray(csr_topo.indices, np.int64)
        self.n = self.indptr.shape[0] - 1
        # round-19 temporal workloads: optional per-edge timestamps
        # aligned with the base CSR, plus per-node appended-ts lists kept
        # in lockstep with _extra (same lane order — draw parity and the
        # temporal replay oracle both ride it)
        self.edge_ts = (
            None if edge_ts is None
            else np.asarray(edge_ts, np.float32).reshape(-1)
        )
        if self.edge_ts is not None and (
            self.edge_ts.shape[0] != self.indices.shape[0]
        ):
            raise ValueError(
                f"edge_ts has {self.edge_ts.shape[0]} entries for "
                f"{self.indices.shape[0]} edges"
            )
        self._extra: Dict[int, List[int]] = {}
        self._extra_ts: Dict[int, List[float]] = {}
        self._rev_extra: Dict[int, List[int]] = {}
        self._n_extra = 0
        # round-21 lifecycle: once a row is deleted-from / expired /
        # ts-updated, its FULL lane list moves into an override (base
        # slice copied out + extras folded in — `_materialize`), and the
        # base CSR stops describing it. Keys here are disjoint from
        # `_extra` by construction. The REVERSE adjacency is never
        # shrunk by removals: reverse closures become supersets, which
        # only ever over-invalidates (safe; pinned in tests).
        self._override: Dict[int, List[int]] = {}
        self._override_ts: Dict[int, List[float]] = {}
        # reverse base CSR (counting sort, same construction as CSRTopo)
        order = np.argsort(self.indices, kind="stable")
        counts = np.bincount(self.indices, minlength=self.n)
        self.rev_indptr = np.zeros(self.n + 1, np.int64)
        np.cumsum(counts, out=self.rev_indptr[1:])
        src_per_edge = np.repeat(
            np.arange(self.n, dtype=np.int64),
            self.indptr[1:] - self.indptr[:-1],
        )
        self.rev_indices = src_per_edge[order]

    @property
    def extra_edges(self) -> int:
        # net appended-beyond-base count; clamped because a
        # deletion-heavy lifecycle can remove more base edges than were
        # ever appended
        return max(self._n_extra, 0)

    def add_edges(self, src, dst, ts=None) -> None:
        src, dst = validate_edge_ids(src, dst, self.n)
        if self.edge_ts is not None:
            if ts is None:
                raise ValueError(
                    "temporal adjacency (edge_ts set) needs a timestamp "
                    "per appended edge"
                )
            ts = np.asarray(ts, np.float32).reshape(-1)
            if ts.shape != src.shape:
                raise ValueError(f"ts {ts.shape} != edges {src.shape}")
        for i, (u, v) in enumerate(zip(src, dst)):
            self._append_one(
                int(u), int(v),
                ts=None if self.edge_ts is None else float(ts[i]),
            )

    def _append_one(self, u: int, v: int,
                    ts: Optional[float] = None) -> None:
        """Append one edge to ``u``'s lane tail — into the override list
        when the row is materialized, the extra list otherwise."""
        if u in self._override:
            self._override[u].append(v)
            if self.edge_ts is not None:
                self._override_ts[u].append(float(ts))
        else:
            self._extra.setdefault(u, []).append(v)
            if self.edge_ts is not None:
                self._extra_ts.setdefault(u, []).append(float(ts))
        self._rev_extra.setdefault(v, []).append(u)
        self._n_extra += 1

    def pop_edges(self, src, dst) -> None:
        """Reverse a JUST-APPLIED `add_edges(src, dst)` — the caller's
        rollback when a downstream capacity preflight fails after the
        adjacency already advanced (dist `update_graph` computes its
        closure plans over the updated view, then commits or rolls
        back). Only valid as the exact inverse of the last add: entries
        pop from the tails the add appended to."""
        src = np.asarray(src, np.int64).reshape(-1)
        dst = np.asarray(dst, np.int64).reshape(-1)
        for u, v in zip(src[::-1], dst[::-1]):
            u, v = int(u), int(v)
            if u in self._override:
                self._override[u].pop()
                if self.edge_ts is not None:
                    self._override_ts[u].pop()
            else:
                self._extra[u].pop()
                if self.edge_ts is not None:
                    self._extra_ts[u].pop()
            self._rev_extra[v].pop()
        self._n_extra -= src.shape[0]

    # ------------------------------------------------ lifecycle (r21)
    def _materialize(self, u: int) -> List[int]:
        """Fold ``u``'s base CSR slice and extras into a mutable
        override list (idempotent). Lane order is preserved exactly, so
        a materialized-but-untouched row answers every query the same as
        before — materialization itself changes no bit."""
        ov = self._override.get(u)
        if ov is not None:
            return ov
        base = self.indices[self.indptr[u]:self.indptr[u + 1]]
        ov = [int(x) for x in base] + self._extra.pop(u, [])
        self._override[u] = ov
        if self.edge_ts is not None:
            bts = self.edge_ts[self.indptr[u]:self.indptr[u + 1]]
            self._override_ts[u] = (
                [float(x) for x in bts] + self._extra_ts.pop(u, [])
            )
        return ov

    def remove_one(self, u: int, v: int) -> int:
        """Delete the first lane-order occurrence of ``(u, v)``; returns
        the lane position it held. Survivors shift left — the caller
        rewrites the row's tiles from the updated list. Raises KeyError
        semantics as ValueError when the edge is absent (commit-level
        all-or-none is the stream preflight's job)."""
        ov = self._materialize(u)
        try:
            p = ov.index(v)
        except ValueError:
            raise ValueError(f"edge ({u}, {v}) not present") from None
        del ov[p]
        if self.edge_ts is not None:
            del self._override_ts[u][p]
        self._n_extra -= 1
        return p

    def update_one(self, u: int, v: int, ts: float) -> int:
        """Retarget the first lane-order occurrence of ``(u, v)`` to a
        new timestamp; returns its lane position (the tile lane the
        caller rewrites). Temporal adjacencies only."""
        if self.edge_ts is None:
            raise ValueError("adjacency was built without edge_ts")
        ov = self._materialize(u)
        try:
            p = ov.index(v)
        except ValueError:
            raise ValueError(f"edge ({u}, {v}) not present") from None
        self._override_ts[u][p] = float(ts)
        return p

    def replace_at(self, u: int, p: int, v: int,
                   ts: Optional[float] = None) -> None:
        """Overwrite lane position ``p`` of ``u`` with a NEW edge —
        dead-lane reuse: the expired entry it replaces was already
        invisible to every draw, and replacing in place (instead of
        appending) is what keeps the adjacency in lane-lockstep with the
        tiles, so rebuild parity survives. The expired neighbor's
        reverse entry stays (reverse closures are supersets)."""
        ov = self._materialize(u)
        ov[p] = v
        if self.edge_ts is not None:
            self._override_ts[u][p] = float(ts)
        self._rev_extra.setdefault(v, []).append(u)

    def expire_node(self, u: int, cutoff: float) -> List[int]:
        """Mask every edge of ``u`` with ``ts <= cutoff`` by overwriting
        its timestamp with ``+inf`` (already-expired lanes hold +inf and
        never re-match). Returns the masked lane positions, ascending.
        NO lane shifts: expiry must stay the bit-dual of a
        ``cutoff < ts`` band mask, and the Gumbel uniform stream is
        positional."""
        if self.edge_ts is None:
            raise ValueError("adjacency was built without edge_ts")
        self._materialize(u)
        tsl = self._override_ts[u]
        pos = [p for p, t in enumerate(tsl) if t <= cutoff]
        for p in pos:
            tsl[p] = float("inf")
        return pos

    def neighbors(self, node: int) -> np.ndarray:
        """Current adjacency of ``node`` in TILE-LANE order: the base CSR
        row first, appended arrivals after (the order `to_csr_topo`
        materializes and the tile writes preserve — draw parity rides
        it). Materialized (lifecycle-touched) rows answer from their
        override list — same order contract."""
        node = int(node)
        ov = self._override.get(node)
        if ov is not None:
            return np.asarray(ov, np.int64)
        base = self.indices[self.indptr[node]:self.indptr[node + 1]]
        extra = self._extra.get(node)
        if not extra:
            return base.copy()
        return np.concatenate([base, np.asarray(extra, np.int64)])

    def neighbors_ts(self, node: int) -> np.ndarray:
        """Per-edge timestamps of `neighbors(node)`, same lane order
        (base CSR ts first, appended arrival ts after; expired lanes
        read ``+inf``). Temporal adjacencies only."""
        if self.edge_ts is None:
            raise ValueError("adjacency was built without edge_ts")
        node = int(node)
        ov = self._override_ts.get(node)
        if ov is not None:
            return np.asarray(ov, np.float32)
        base = self.edge_ts[self.indptr[node]:self.indptr[node + 1]]
        extra = self._extra_ts.get(node)
        if not extra:
            return base.copy()
        return np.concatenate([base, np.asarray(extra, np.float32)])

    def degree(self, node: int) -> int:
        node = int(node)
        ov = self._override.get(node)
        if ov is not None:
            return len(ov)
        return int(self.indptr[node + 1] - self.indptr[node]) + len(
            self._extra.get(node, ())
        )

    def forward_closure(self, seeds, hops: int) -> np.ndarray:
        """Bool [N] mask of nodes reachable from ``seeds`` within
        ``hops`` hops over the UPDATED graph (seeds included) — the
        incremental owner-shard extension input: k-hop closures are
        union-homomorphic, so a dist owner's new mask is old-mask OR
        this."""
        mask = np.zeros(self.n, bool)
        seeds = np.asarray(seeds, np.int64).reshape(-1)
        if seeds.size == 0:
            return mask
        mask[seeds] = True
        frontier = np.unique(seeds)
        for _ in range(max(int(hops), 0)):
            if frontier.size == 0:
                break
            nxt = self._expand(frontier, self.indptr, self.indices,
                               self._extra, self._override)
            nxt = nxt[~mask[nxt]]
            if nxt.size == 0:
                break
            mask[nxt] = True
            frontier = nxt
        return mask

    def reverse_closure(self, srcs, hops: int) -> np.ndarray:
        """Sorted ids of every node within ``hops`` REVERSE hops of
        ``srcs`` over the updated graph (srcs included) — the
        invalidation set: a seed's k-hop sample can only change if its
        expansion reaches a changed row, i.e. the seed lies in the
        changed rows' ``hops``-reverse closure."""
        srcs = np.unique(np.asarray(srcs, np.int64).reshape(-1))
        if srcs.size == 0:
            return srcs
        mask = np.zeros(self.n, bool)
        mask[srcs] = True
        frontier = srcs
        for _ in range(max(int(hops), 0)):
            if frontier.size == 0:
                break
            nxt = self._expand(frontier, self.rev_indptr, self.rev_indices,
                               self._rev_extra)
            nxt = nxt[~mask[nxt]]
            if nxt.size == 0:
                break
            mask[nxt] = True
            frontier = nxt
        return np.nonzero(mask)[0]

    @staticmethod
    def _expand(frontier, indptr, indices, extra, override=None):
        """One BFS hop: base-CSR rows vectorized, appended edges via the
        per-node dicts (bounded by the delta volume, never O(E)).
        Materialized rows (``override``, forward direction only) answer
        from their override lists instead of base+extra — the reverse
        direction has no overrides and stays a superset after
        removals."""
        if override:
            keep = np.fromiter(
                (int(u) not in override for u in frontier), bool,
                frontier.shape[0],
            )
            ov_nodes = frontier[~keep]
            frontier = frontier[keep]
        else:
            ov_nodes = None
        parts = []
        starts = indptr[frontier]
        ends = indptr[frontier + 1]
        widths = ends - starts
        if frontier.size and widths.sum() > 0:
            flat = np.concatenate([
                indices[s:e] for s, e in zip(starts, ends) if e > s
            ])
            parts.append(flat)
        if extra:
            ext = [extra[int(u)] for u in frontier if int(u) in extra]
            if ext:
                parts.append(np.concatenate(
                    [np.asarray(x, np.int64) for x in ext]
                ))
        if ov_nodes is not None and ov_nodes.size:
            ov = [override[int(u)] for u in ov_nodes if override[int(u)]]
            if ov:
                parts.append(np.concatenate(
                    [np.asarray(x, np.int64) for x in ov]
                ))
        if not parts:
            return np.array([], np.int64)
        return np.unique(np.concatenate(parts))

    def to_csr_topo(self):
        """Materialize the UPDATED graph as a fresh `CSRTopo` (base edges
        first per row, arrivals after — exactly the tile-lane order, so a
        sampler freshly built over the result draws bit-identically to
        the streamed tiles). This is the replay-oracle / rebuild surface,
        NOT the serving path — serving mutates tiles in place."""
        from .utils import CSRTopo

        if not self._extra and not self._override:
            return CSRTopo(indptr=self.indptr.copy(),
                           indices=self.indices.copy())
        base_deg = self.indptr[1:] - self.indptr[:-1]
        new_deg = base_deg.copy()
        for u, vs in self._extra.items():
            new_deg[u] += len(vs)
        for u, vs in self._override.items():
            new_deg[u] = len(vs)
        new_indptr = np.zeros(self.n + 1, np.int64)
        np.cumsum(new_deg, out=new_indptr[1:])
        new_indices = np.empty(int(new_indptr[-1]), np.int64)
        # base block copy: each non-overridden row's base edges land at
        # its new offset; materialized rows are written wholesale below
        src_per_edge = np.repeat(np.arange(self.n, dtype=np.int64), base_deg)
        pos_in_row = np.arange(self.indices.shape[0], dtype=np.int64) - (
            np.repeat(self.indptr[:-1], base_deg)
        )
        if self._override:
            keep = np.ones(self.n, bool)
            keep[np.fromiter(self._override.keys(), np.int64,
                             len(self._override))] = False
            sel = keep[src_per_edge]
            new_indices[new_indptr[src_per_edge[sel]] + pos_in_row[sel]] = (
                self.indices[sel]
            )
        else:
            new_indices[new_indptr[src_per_edge] + pos_in_row] = self.indices
        for u, vs in self._extra.items():
            lo = int(new_indptr[u] + base_deg[u])
            new_indices[lo:lo + len(vs)] = vs
        for u, vs in self._override.items():
            lo = int(new_indptr[u])
            new_indices[lo:lo + len(vs)] = vs
        return CSRTopo(indptr=new_indptr, indices=new_indices)

    def to_temporal(self):
        """Materialize the UPDATED graph as ``(CSRTopo, edge_ts)`` with
        the timestamps in exactly `to_csr_topo`'s edge order (base edges
        first per row, arrivals after — the tile-lane order) — the
        temporal replay-oracle / rebuild surface. Temporal adjacencies
        only."""
        if self.edge_ts is None:
            raise ValueError("adjacency was built without edge_ts")
        topo = self.to_csr_topo()
        if not self._extra and not self._override:
            return topo, self.edge_ts.copy()
        new_indptr = np.asarray(topo.indptr, np.int64)
        base_deg = self.indptr[1:] - self.indptr[:-1]
        new_ts = np.zeros(int(new_indptr[-1]), np.float32)
        src_per_edge = np.repeat(np.arange(self.n, dtype=np.int64), base_deg)
        pos_in_row = np.arange(self.indices.shape[0], dtype=np.int64) - (
            np.repeat(self.indptr[:-1], base_deg)
        )
        if self._override:
            keep = np.ones(self.n, bool)
            keep[np.fromiter(self._override.keys(), np.int64,
                             len(self._override))] = False
            sel = keep[src_per_edge]
            new_ts[new_indptr[src_per_edge[sel]] + pos_in_row[sel]] = (
                self.edge_ts[sel]
            )
        else:
            new_ts[new_indptr[src_per_edge] + pos_in_row] = self.edge_ts
        for u, vs in self._extra.items():
            lo = int(new_indptr[u] + base_deg[u])
            new_ts[lo:lo + len(vs)] = np.asarray(
                self._extra_ts.get(u, []), np.float32
            )
        # materialized rows carry their ts wholesale (expired lanes as
        # +inf — a rebuild over this surface reproduces the masked lanes
        # bit for bit, which is what deletion/retention parity pins)
        for u, tsl in self._override_ts.items():
            lo = int(new_indptr[u])
            new_ts[lo:lo + len(tsl)] = np.asarray(tsl, np.float32)
        return topo, new_ts


def _bucketed(idx: np.ndarray, rows: np.ndarray, sentinel: int,
              floor: int = 64) -> Tuple[np.ndarray, np.ndarray]:
    """Pad a row-swap batch to a power-of-two bucket so the jitted
    `shard_tensor._scatter_rows` commit (one bounded [K, ...] row
    scatter into an existing same-shaped device table — the round-14
    promotion idiom, NOT the PERF_NOTES scatter-build trap) compiles
    once per bucket, not once per delta size."""
    b = _bucket(idx.shape[0], floor=floor)
    pos = np.full(b, sentinel, np.int32)
    pos[: idx.shape[0]] = idx
    padded = np.zeros((b,) + rows.shape[1:], rows.dtype)
    padded[: idx.shape[0]] = rows
    return pos, padded


class StreamingTiledGraph:
    """The delta layer over the 128-lane tile layout: host ``(bd, tiles)``
    mirrors with reserved slack rows, in-place pad-lane appends + staged
    tile spills, and batched device tile swaps (module docstring has the
    design; docs/api.md "Streaming graphs" the contract).

    Parameters
    ----------
    csr_topo : CSRTopo — the ingest-time graph. Kept immutable; appended
        edges live in the stream's own state.
    reserve_tiles : explicit spare tile-row count for spills (default:
        ``ceil(reserve_frac * M)``, min 8). A spill relocates a node to
        ``old_rows + grow_tiles`` fresh rows from this reserve;
        exhaustion raises `StreamCapacityError` (plan capacity like
        sampler caps — shapes are frozen at construction).
    grow_tiles : extra tile rows granted per spill (>=1; each buys 128
        more slack lanes before the node spills again).
    device_arrays : build and maintain the device ``(bd, tiles)`` pair
        (the serving path). False = host bookkeeping only (the dist
        router's full-graph view costs no device HBM).
    id_dtype : tile dtype; defaults to the same `_best_id_dtype` rule as
        `CSRTopo.to_device_tiled`, so a streamed sampler and a frozen one
        run byte-identical programs.

    Thread safety: `apply`/`install_rows` mutate under one lock, but the
    serve engines additionally FENCE every commit (update_params-style
    drain) so no in-flight flush ever reads a half-applied batch — the
    lock only orders bare concurrent callers.
    """

    def __init__(self, csr_topo, reserve_tiles: Optional[int] = None,
                 reserve_frac: float = 0.5, grow_tiles: int = 1,
                 device_arrays: bool = True, id_dtype=None, edge_ts=None):
        from .utils import _best_id_dtype

        self.csr_topo = csr_topo
        self.adj = StreamingAdjacency(csr_topo, edge_ts=edge_ts)
        self.n = self.adj.n
        if id_dtype is None:
            id_dtype = _best_id_dtype(self.n + 1)
        bd, tiles = build_tiled_host(
            self.adj.indptr, self.adj.indices, id_dtype
        )
        m = tiles.shape[0]
        if reserve_tiles is None:
            reserve_tiles = max(8, int(np.ceil(float(reserve_frac) * m)))
        self.m_base = m
        self.m_cap = m + int(reserve_tiles)
        self.grow_tiles = max(int(grow_tiles), 1)
        self.bd = np.ascontiguousarray(bd)  # [N, 2] int32 (base, deg)
        self.tiles = np.zeros((self.m_cap, LANE), tiles.dtype)
        self.tiles[:m] = tiles
        # round-19 temporal payload: per-edge timestamps in a SECOND tile
        # table sharing the tile map byte for byte (the round-5 weights
        # trick) — appends/spills/installs mutate both under one lock and
        # one batched device swap per commit, so a committed edge and its
        # timestamp become drawable in the same `temporal_graph()` read
        self.ttiles: Optional[np.ndarray] = None
        if edge_ts is not None:
            _, tt = build_tiled_host(
                self.adj.indptr, self.adj.edge_ts, np.float32
            )
            self.ttiles = np.zeros((self.m_cap, LANE), np.float32)
            self.ttiles[:m] = tt
        deg = self.bd[:, 1].astype(np.int64)
        self.alloc_rows = (-(-deg // LANE)).astype(np.int32)  # rows held
        # free tile rows as a sorted, coalescing range list — first-fit
        # from the LOWEST start (deterministic). Starts as the whole
        # reserve; compaction releases reclaimed rows back here, and
        # `provision_reserve` appends whole new banks.
        self._free_ranges: List[List[int]] = (
            [[m, self.m_cap - m]] if self.m_cap > m else []
        )
        # rows vacated by spill relocations park here (NOT freed at
        # relocate time — r17 semantics: the reserve report counts them
        # as consumed) until a compaction releases them
        self._retired: List[Tuple[int, int]] = []
        self._retired_rows = 0
        # expired (masked, ts=+inf) lane positions per node, ascending —
        # appends re-use the lowest dead lane before growing the degree
        self._dead: Dict[int, List[int]] = {}
        self._dead_lanes = 0
        # per-node min finite edge ts (+inf when none): makes
        # `expire_edges(cutoff)` an O(expiring) scan, not O(N * deg)
        self._min_ts: Optional[np.ndarray] = None
        if edge_ts is not None:
            self._min_ts = np.full(self.n, np.inf, np.float32)
            base_deg = (self.adj.indptr[1:] - self.adj.indptr[:-1])
            np.minimum.at(
                self._min_ts,
                np.repeat(np.arange(self.n, dtype=np.int64), base_deg),
                self.adj.edge_ts,
            )
        self.version = 0
        # versioned node stamps: the graph version at which a node's row
        # last changed — the invalidation consumers (cache / replicas /
        # tier placement) compare against these instead of guessing
        self.node_version = np.zeros(self.n, np.int64)
        self.stats = {"pad_writes": 0, "tile_spills": 0, "installs": 0,
                      "tile_rows_swapped": 0, "bd_rows_swapped": 0,
                      "edges": 0,
                      # round-21 lifecycle counters
                      "edges_deleted": 0, "edges_expired": 0,
                      "ts_updates": 0, "lanes_reused": 0,
                      "tiles_reclaimed": 0, "compactions": 0,
                      "provisions": 0}
        self._lock = threading.Lock()
        self._bd_dev = None
        self._tiles_dev = None
        self._tt_dev = None
        # zero-stall (round 24) double buffer: commits run with
        # defer_publish=True build the post-commit device arrays HERE
        # (basing on staged-if-present, so apply + expire in one commit
        # accumulate), leaving the live ``_*_dev`` refs — what `graph()`
        # serves and in-flight flushes hold — untouched until `publish()`
        # flips them in O(1)
        self._staged_bd = None
        self._staged_tiles = None
        self._staged_tt = None
        if device_arrays:
            import jax.numpy as jnp

            self._bd_dev = jnp.asarray(self.bd)
            self._tiles_dev = jnp.asarray(self.tiles)
            if self.ttiles is not None:
                self._tt_dev = jnp.asarray(self.ttiles)

    # -------------------------------------------------- row allocator
    @staticmethod
    def _take(ranges: List[List[int]], k: int) -> Optional[int]:
        """First-fit ``k`` contiguous rows from the LOWEST-start free
        range (deterministic); None when no single range fits. The
        preflight simulates allocation on a copy with this same
        function, so "enough total rows but too fragmented" fails there,
        not mid-commit."""
        for r in ranges:
            if r[1] >= k:
                start = r[0]
                r[0] += k
                r[1] -= k
                if r[1] == 0:
                    ranges.remove(r)
                return start
        return None

    @staticmethod
    def _put(ranges: List[List[int]], start: int, k: int) -> None:
        """Return ``k`` rows at ``start`` to a free list, keeping it
        sorted and coalescing with adjacent ranges."""
        if k <= 0:
            return
        i = 0
        while i < len(ranges) and ranges[i][0] < start:
            i += 1
        ranges.insert(i, [start, k])
        if i + 1 < len(ranges) and (
            ranges[i][0] + ranges[i][1] == ranges[i + 1][0]
        ):
            ranges[i][1] += ranges[i + 1][1]
            del ranges[i + 1]
        if i > 0 and ranges[i - 1][0] + ranges[i - 1][1] == ranges[i][0]:
            ranges[i - 1][1] += ranges[i][1]
            del ranges[i]

    def _release_locked(self, start: int, k: int) -> None:
        """Free ``k`` rows at ``start`` AND zero their host mirror, so a
        later reallocation's device sync ships bytes identical to a
        fresh reserve row (released device rows keep stale bytes until
        then — unreachable: the degree mask gates every read)."""
        if k <= 0:
            return
        self.tiles[start:start + k] = 0
        if self.ttiles is not None:
            self.ttiles[start:start + k] = 0
        self._put(self._free_ranges, start, k)

    # ------------------------------------------------------------ reads
    @property
    def free_rows(self) -> int:
        return sum(r[1] for r in self._free_ranges)

    @property
    def _free_row(self) -> int:
        # compatibility view of the pre-r21 bump pointer: rows consumed
        # so far, measured from the table base (== the old next-free-row
        # watermark whenever nothing has been reclaimed)
        return self.m_cap - self.free_rows

    def _reserve_report_locked(self) -> Dict[str, object]:
        free = self.free_rows
        used = max((self.m_cap - self.m_base) - free, 0)
        commits = self.version
        per_commit = used / commits if commits else 0.0
        deg = self.bd[:, 1].astype(np.int64)
        tight = -(-deg // LANE)
        alloc = self.alloc_rows.astype(np.int64)
        deg_sum = int(deg.sum())
        trimmable = int(np.maximum(alloc - tight, 0).sum())
        return {
            "tiles_base": self.m_base,
            "tiles_cap": self.m_cap,
            "reserve_tiles": self.m_cap - self.m_base,
            "reserve_used": used,
            "reserve_free": free,
            "commits": commits,
            "rows_per_commit": per_commit,
            # None = no consumption observed yet (or none at all): there
            # is nothing honest to project from
            "projected_commits_to_exhaustion": (
                free / per_commit if per_commit > 0 else None
            ),
            "tile_spills": self.stats["tile_spills"],
            "installs": self.stats["installs"],
            # round-21 lifecycle fields (exported as gauges by
            # `serve.engine.register_stream_reserve`):
            # slack lanes inside held rows — over-allocation from spill
            # growth and deletions, the compaction trim target
            "fragmented_lanes": int(alloc.sum()) * LANE - deg_sum,
            # rows a compaction pass could hand back to the free list
            # right now: spill-retired ranges + trimmable tails
            "reclaimable_tiles": self._retired_rows + trimmable,
            # expired (masked) lanes as a fraction of live lane content —
            # the append path re-uses these before consuming new rows
            "dead_lane_frac": (
                self._dead_lanes / deg_sum if deg_sum else 0.0
            ),
        }

    def reserve_report(self) -> Dict[str, object]:
        """Live reserve budget (round-18 satellite — the r17 "capacity
        exhaustion is a planned hard error" leftover made diagnosable):
        tiles used / remaining, consumption rate per commit, and the
        projected commits left at that rate (None before any
        consumption). `StreamCapacityError` messages carry the same
        numbers, so the planned hard error names its own runway."""
        with self._lock:
            return self._reserve_report_locked()

    def _capacity_error(self, prefix: str) -> StreamCapacityError:
        """Build the planned hard error WITH the reserve diagnosis
        (caller holds ``_lock``)."""
        r = self._reserve_report_locked()
        proj = r["projected_commits_to_exhaustion"]
        return StreamCapacityError(
            f"{prefix} — reserve {r['reserve_used']}/{r['reserve_tiles']} "
            f"rows used over {r['commits']} commit(s) "
            f"({r['rows_per_commit']:.2f} rows/commit"
            + (f", ~{proj:.0f} commits of runway were left"
               if proj is not None else "")
            + "); reclaim rows with compaction "
            "(plan_compaction/apply_compaction), grow the bank with "
            "provision_reserve (one sealed-program rebuild), or rebuild "
            "the stream with a larger reserve_frac/reserve_tiles"
        )

    @property
    def temporal(self) -> bool:
        """True when this stream carries per-edge timestamps (built with
        ``edge_ts=``) — `temporal_graph()` is then the sampling surface
        and every committed edge must arrive with a timestamp."""
        return self.ttiles is not None

    def graph(self):
        """The CURRENT device ``(bd, tiles)`` pair — what a stream-bound
        `GraphSageSampler` samples from (`bind_stream`). Array objects
        change at every commit; shapes never do."""
        if self._tiles_dev is None:
            raise ValueError(
                "stream was built with device_arrays=False (host "
                "bookkeeping only)"
            )
        return self._bd_dev, self._tiles_dev

    def temporal_graph(self):
        """The CURRENT device ``(bd, tiles, ttiles)`` triple — what a
        temporal-bound sampler (`GraphSageSampler.bind_temporal`) draws
        from. Same commit semantics as `graph()`: array objects change
        per fenced commit, shapes never."""
        if not self.temporal:
            raise ValueError(
                "stream was built without edge_ts (no timestamp payload)"
            )
        if self._tiles_dev is None:
            raise ValueError(
                "stream was built with device_arrays=False (host "
                "bookkeeping only)"
            )
        return self._bd_dev, self._tiles_dev, self._tt_dev

    def neighbors(self, node: int) -> np.ndarray:
        return self.adj.neighbors(node)

    def degree(self, node: int) -> int:
        return self.adj.degree(node)

    def to_csr_topo(self):
        return self.adj.to_csr_topo()

    def affected_seeds(self, srcs, hops: int) -> np.ndarray:
        """The invalidation set of changed rows ``srcs``: every node
        whose ``hops``-hop EXPANSION could reach one (reverse closure
        over the updated graph, srcs included). ``hops`` is the number of
        expansion hops — ``len(sizes) - 1`` for an L-layer sampler, since
        the final hop's frontier is gathered but never expanded."""
        return self.adj.reverse_closure(srcs, hops)

    # ----------------------------------------------------------- writes
    def preflight(self, delta: Optional[GraphDelta] = None,
                  installs: Optional[Sequence[Tuple[int, np.ndarray]]] = None,
                  ) -> int:
        """Validate a WHOLE batch — edge ids, install constraints, and
        reserve capacity (spills simulated in apply order) — without
        mutating anything. Returns the reserve rows the batch would
        consume; raises `StreamCapacityError`/`ValueError` exactly where
        `apply` would, BEFORE any state moves. `apply` runs this first,
        which is what makes a commit atomic: it either lands fully
        (host + device + version stamps) or leaves the stream untouched.
        Multi-stream callers (the dist router) preflight every stream
        before applying to any."""
        src, dst = delta.edges() if delta is not None else (
            np.array([], np.int64), np.array([], np.int64)
        )
        ts = delta.edges_ts() if delta is not None else None
        removals = delta.removals() if delta is not None else None
        updates = delta.updates() if delta is not None else None
        installs = self._normalize_installs(installs)
        with self._lock:
            return self._preflight_locked(src, dst, installs, ts,
                                          removals, updates)

    def _normalize_installs(self, installs):
        """Normalize install entries to ``(node, nbrs, ts_row|None)`` —
        temporal streams accept (and require) a per-neighbor timestamp
        row per install; non-temporal streams reject one."""
        out = []
        for entry in installs or ():
            if len(entry) == 2:
                node, nbrs = entry
                ts_row = None
            else:
                node, nbrs, ts_row = entry
            nbrs = np.asarray(nbrs, np.int64)
            if ts_row is not None:
                ts_row = np.asarray(ts_row, np.float32).reshape(-1)
            out.append((int(node), nbrs, ts_row))
        return out

    def _check_ts(self, src, ts, installs) -> None:
        """The temporal-arity contract, one place: a temporal stream
        takes exactly one timestamp per edge (appends AND installs); a
        non-temporal stream takes none."""
        if self.temporal:
            if src.size and (ts is None or ts.shape != src.shape):
                raise ValueError(
                    "temporal stream (edge_ts set) needs one timestamp "
                    "per appended edge — stage with "
                    "GraphDelta.add_edges(src, dst, ts=...)"
                )
            for node, nbrs, ts_row in installs:
                if nbrs.size and (ts_row is None
                                  or ts_row.shape[0] != nbrs.shape[0]):
                    raise ValueError(
                        f"temporal install for node {node} needs one "
                        f"timestamp per neighbor"
                    )
        else:
            if ts is not None or any(t is not None for _, _, t in installs):
                raise ValueError(
                    "edge timestamps staged into a non-temporal stream — "
                    "build StreamingTiledGraph(edge_ts=...) to carry them"
                )
        if ts is not None and ts.size and not np.isfinite(ts).all():
            raise ValueError(
                "non-finite appended timestamps — +inf is reserved as "
                "the retention expiry sentinel (expire_edges)"
            )
        for node, _nbrs, ts_row in installs:
            if ts_row is not None and ts_row.size and (
                not np.isfinite(ts_row).all()
            ):
                raise ValueError(
                    f"non-finite install timestamps for node {node} — "
                    "+inf is reserved as the retention expiry sentinel"
                )

    def _preflight_locked(self, src, dst, installs, ts=None,
                          removals=None, updates=None) -> int:
        if src.size:
            validate_edge_ids(src, dst, self.n)
        self._check_ts(src, ts, installs)
        rsrc, rdst = removals if removals is not None else (
            np.empty(0, np.int64), np.empty(0, np.int64)
        )
        usrc, udst, uts = updates if updates is not None else (
            np.empty(0, np.int64), np.empty(0, np.int64),
            np.empty(0, np.float32),
        )
        if rsrc.size:
            validate_edge_ids(rsrc, rdst, self.n, what="removal")
        if usrc.size:
            validate_edge_ids(usrc, udst, self.n, what="update")
            if not self.temporal:
                raise ValueError(
                    "timestamp updates staged into a non-temporal "
                    "stream — streamed tiles carry no weight payload; "
                    "the ts lane (edge_ts=...) is the one mutable "
                    "per-edge field"
                )
        # removal/update existence, simulated in APPLY ORDER (installs,
        # appends, removals, updates) over per-(u, v) occurrence counts —
        # all-or-none: one missing edge fails the whole batch here
        if rsrc.size or usrc.size:
            pairs = set(zip(rsrc.tolist(), rdst.tolist())) | set(
                zip(usrc.tolist(), udst.tolist())
            )
            inst_rows = {node: nbrs for node, nbrs, _ in installs}
            avail: Dict[Tuple[int, int], int] = {}
            rows_cache: Dict[int, np.ndarray] = {}
            for (u, v) in pairs:
                if u not in rows_cache:
                    rows_cache[u] = (
                        inst_rows[u] if u in inst_rows
                        else self.adj.neighbors(u)
                    )
                avail[(u, v)] = int((rows_cache[u] == v).sum())
            for u, v in zip(src.tolist(), dst.tolist()):
                if (u, v) in avail:
                    avail[(u, v)] += 1
            for u, v in zip(rsrc.tolist(), rdst.tolist()):
                avail[(u, v)] -= 1
                if avail[(u, v)] < 0:
                    raise ValueError(
                        f"removal of absent edge ({u}, {v}) — the whole "
                        "batch is rejected (all-or-none), nothing was "
                        "applied"
                    )
            for u, v in zip(usrc.tolist(), udst.tolist()):
                if avail[(u, v)] <= 0:
                    raise ValueError(
                        f"timestamp update of absent edge ({u}, {v}) — "
                        "the whole batch is rejected (all-or-none), "
                        "nothing was applied"
                    )
        # reserve capacity: simulate the allocator EXACTLY (same
        # first-fit walk apply will take, on a scratch copy of the free
        # ranges) — with reclamation the free pool fragments, and
        # "enough total rows but no contiguous fit" must fail here, not
        # mid-commit
        need = 0
        sim_ranges = [r[:] for r in self._free_ranges]
        sim_alloc: Dict[int, int] = {}
        sim_deg: Dict[int, int] = {}
        sim_dead: Dict[int, int] = {}
        for node, nbrs, _ts_row in installs:
            if not 0 <= node < self.n:
                raise ValueError(
                    f"install node {node} outside [0, {self.n})"
                )
            if nbrs.size and ((nbrs < 0) | (nbrs >= self.n)).any():
                # same contract as edge appends: a bad id raises here,
                # never lands in the tiles (clipped gathers would
                # silently read the last row otherwise)
                raise ValueError(
                    f"install neighbors of node {node} outside "
                    f"[0, {self.n}): "
                    f"{nbrs[(nbrs < 0) | (nbrs >= self.n)][:4].tolist()}"
                )
            if node in sim_deg:
                raise ValueError(
                    f"duplicate install for node {node} in one batch"
                )
            if int(self.bd[node, 1]) != 0:
                raise ValueError(
                    f"install_rows targets degree-0 rows only (node "
                    f"{node} has degree {int(self.bd[node, 1])}); use "
                    "apply() appends for materialized rows"
                )
            if nbrs.size == 0:
                sim_deg[node] = 0
                sim_alloc[node] = int(self.alloc_rows[node])
                continue
            # a deleted-to-zero row re-installing releases its old rows
            # first, exactly as _install_locked will
            old = int(self.alloc_rows[node])
            if old:
                self._put(sim_ranges, int(self.bd[node, 0]), old)
            rows = -(-int(nbrs.size) // LANE)
            need += rows
            if self._take(sim_ranges, rows) is None:
                raise self._capacity_error(
                    f"tile reserve exhausted: install of node {node} "
                    f"needs {rows} contiguous rows, "
                    f"{sum(r[1] for r in sim_ranges)} free"
                )
            sim_alloc[node] = rows
            sim_deg[node] = int(nbrs.size)
            sim_dead[node] = 0
        for u in src:
            u = int(u)
            dead = sim_dead.get(u, len(self._dead.get(u, ())))
            if dead > 0:
                # the append re-uses an expired lane: no degree growth,
                # no spill risk
                sim_dead[u] = dead - 1
                continue
            sim_dead[u] = 0
            d = sim_deg.get(u, int(self.bd[u, 1]))
            a = sim_alloc.get(u, int(self.alloc_rows[u]))
            if d >= a * LANE:
                a += self.grow_tiles
                need += a
                if self._take(sim_ranges, a) is None:
                    raise self._capacity_error(
                        f"tile reserve exhausted: batch needs {need} "
                        f"rows ({a} contiguous for node {u}), "
                        f"{sum(r[1] for r in sim_ranges)} free"
                    )
                sim_alloc[u] = a
            sim_deg[u] = d + 1
        return need

    def apply(self, delta: GraphDelta,
              installs: Optional[Sequence[Tuple[int, np.ndarray]]] = None,
              defer_publish: bool = False,
              ) -> Dict[str, int]:
        """Commit one delta batch: host pad-lane writes / spills /
        installs, then ONE batched device tile swap + one bd swap.
        ATOMIC: the whole batch is preflighted (ids, install
        constraints, reserve capacity) before any state moves, so a
        raising apply leaves host, device, versions, and the adjacency
        untouched. Returns the commit summary. Callers serving traffic
        go through ``engine.update_graph`` (which fences in-flight
        flushes first, or — zero-stall mode — passes
        ``defer_publish=True`` so the new device arrays stage without
        touching what `graph()` serves until `publish()`); the stream's
        own lock only orders bare concurrent callers."""
        src, dst = delta.edges() if delta is not None else (
            np.array([], np.int64), np.array([], np.int64)
        )
        ts = delta.edges_ts() if delta is not None else None
        removals = delta.removals() if delta is not None else (
            np.empty(0, np.int64), np.empty(0, np.int64)
        )
        updates = delta.updates() if delta is not None else (
            np.empty(0, np.int64), np.empty(0, np.int64),
            np.empty(0, np.float32),
        )
        rsrc, rdst = removals
        usrc, udst, uts = updates
        installs = self._normalize_installs(installs)
        if (src.size == 0 and not installs and rsrc.size == 0
                and usrc.size == 0):
            return {"edges": 0, "pad_writes": 0, "tile_spills": 0,
                    "installs": 0, "tile_rows_swapped": 0,
                    "bd_rows_swapped": 0, "free_rows": self.free_rows,
                    "version": self.version, "edges_deleted": 0,
                    "ts_updates": 0, "lanes_reused": 0}
        with self._lock:
            self._preflight_locked(src, dst, installs, ts,
                                   removals, updates)
            touched_tiles: set = set()
            touched_bd: set = set()
            pad_writes = spills = reused = 0
            for node, nbrs, ts_row in installs:
                self._install_locked(node, nbrs, touched_tiles, touched_bd,
                                     ts_row=ts_row)
            # per-edge: the adjacency and the tiles advance in lockstep
            # (an append that re-uses a dead lane REPLACES the adjacency
            # entry instead of appending — lane order stays shared, which
            # is what keeps rebuild parity through the whole lifecycle).
            # Ids were validated by the preflight above.
            for i, (u, v) in enumerate(zip(src, dst)):
                p, s, r = self._append_locked(
                    int(u), int(v), touched_tiles, touched_bd,
                    ts=None if ts is None else float(ts[i]),
                )
                pad_writes += p
                spills += s
                reused += r
            if rsrc.size:
                for u, v in zip(rsrc, rdst):
                    self.adj.remove_one(int(u), int(v))
                for u in np.unique(rsrc):
                    self._rewrite_node_locked(int(u), touched_tiles,
                                              touched_bd)
            for u, v, t in zip(usrc, udst, uts):
                self._update_one_locked(int(u), int(v), float(t),
                                        touched_tiles, touched_bd)
            self.version += 1
            changed = np.fromiter(touched_bd, np.int64, len(touched_bd))
            self.node_version[changed] = self.version
            n_tiles, n_bd = self._sync_device_locked(
                touched_tiles, touched_bd, defer=defer_publish)
            self.stats["pad_writes"] += pad_writes
            self.stats["tile_spills"] += spills
            self.stats["installs"] += len(installs)
            self.stats["edges"] += int(src.size)
            self.stats["edges_deleted"] += int(rsrc.size)
            self.stats["ts_updates"] += int(usrc.size)
            self.stats["lanes_reused"] += reused
            self.stats["tile_rows_swapped"] += n_tiles
            self.stats["bd_rows_swapped"] += n_bd
            return {"edges": int(src.size), "pad_writes": pad_writes,
                    "tile_spills": spills, "installs": len(installs),
                    "tile_rows_swapped": n_tiles, "bd_rows_swapped": n_bd,
                    "free_rows": self.free_rows, "version": self.version,
                    "edges_deleted": int(rsrc.size),
                    "ts_updates": int(usrc.size), "lanes_reused": reused}

    def install_rows(self, rows: Sequence[Tuple[int, np.ndarray]]
                     ) -> Dict[str, int]:
        """Materialize full adjacency rows for nodes currently reading
        degree 0 — the dist router's incremental halo-closure extension
        (a node newly entering an owner's closure carries its WHOLE
        current edge list, not an append). One batched commit like
        `apply`."""
        return self.apply(None, installs=rows)

    # -------------------------------------------------- lifecycle (r21)
    def expire_edges(self, cutoff, defer_publish: bool = False
                     ) -> Dict[str, object]:
        """TTL retention commit: mask every edge with ``ts <= cutoff``
        by overwriting its timestamp lane with ``+inf`` — NO lane
        shifts, so the expired stream stays the exact bit-dual of the
        unexpired stream queried with a ``cutoff < ts <= t`` band mask
        (the r19 masking's natural dual; pinned in
        tests/test_lifecycle.py). Masked lanes become the dead pool
        later appends re-use. One batched device ttile swap; bumps the
        version and stamps touched nodes (their draws at any t change),
        so the engines' invalidation consumers fire exactly as for
        appends. ``cutoff`` is snapped to the float32 grid — window
        arithmetic must follow the `quantize_t` f32 rule."""
        if not self.temporal:
            raise ValueError(
                "expire_edges needs a temporal stream (edge_ts=...) — "
                "a plain stream has no timestamps to retire"
            )
        cutoff = np.float32(cutoff)
        with self._lock:
            cand = np.nonzero(self._min_ts <= cutoff)[0]
            if cand.size == 0:
                return {"edges_expired": 0, "nodes": 0,
                        "version": self.version, "tile_rows_swapped": 0,
                        "sources": np.empty(0, np.int64)}
            touched_tiles: set = set()
            touched_bd: set = set()
            n_exp = 0
            for u in cand:
                u = int(u)
                pos = self.adj.expire_node(u, float(cutoff))
                if not pos:
                    # stale min (shouldn't persist — reindex below keeps
                    # it exact); recompute defensively
                    self._reindex_node_ts_locked(
                        u, self.adj.neighbors_ts(u))
                    continue
                base = int(self.bd[u, 0])
                for p in pos:
                    self.ttiles[base + p // LANE, p % LANE] = np.inf
                    touched_tiles.add(base + p // LANE)
                touched_bd.add(u)
                n_exp += len(pos)
                self._reindex_node_ts_locked(u, self.adj.neighbors_ts(u))
            self.version += 1
            changed = np.fromiter(touched_bd, np.int64, len(touched_bd))
            self.node_version[changed] = self.version
            n_tiles, n_bd = self._sync_device_locked(
                touched_tiles, touched_bd, defer=defer_publish)
            self.stats["edges_expired"] += n_exp
            self.stats["tile_rows_swapped"] += n_tiles
            self.stats["bd_rows_swapped"] += n_bd
            return {"edges_expired": n_exp, "nodes": len(touched_bd),
                    "version": self.version, "tile_rows_swapped": n_tiles,
                    "sources": np.sort(changed)}

    def plan_compaction(self, max_moves: int = 0) -> Dict[str, object]:
        """Snapshot a reclamation plan — built OFF-FENCE (only the
        stream lock, no traffic drain): spill-retired ranges to release,
        over-allocated rows to trim (``alloc > ceil(deg/128)``), and up
        to ``max_moves`` defrag relocations (highest-based nodes first).
        Every per-node entry carries the node's version stamp;
        `apply_compaction` skips entries whose row committed in between
        (stale) — the LSM discipline: plan cheap, validate at flip."""
        with self._lock:
            plan: Dict[str, object] = {
                "retired": [tuple(r) for r in self._retired],
                "planned_at": self.version,
            }
            deg = self.bd[:, 1].astype(np.int64)
            tight = -(-deg // LANE)
            slack = self.alloc_rows.astype(np.int64) - tight
            plan["trims"] = [
                (int(u), int(self.node_version[u]))
                for u in np.nonzero(slack > 0)[0]
            ]
            moves: List[Tuple[int, int]] = []
            if max_moves:
                order = np.argsort(self.bd[:, 0], kind="stable")[::-1]
                for u in order:
                    if len(moves) >= int(max_moves):
                        break
                    u = int(u)
                    if self.alloc_rows[u] and int(self.bd[u, 0]):
                        moves.append((u, int(self.node_version[u])))
            plan["moves"] = moves
            return plan

    def apply_compaction(self, plan: Dict[str, object],
                         defer_publish: bool = False) -> Dict[str, int]:
        """Apply a `plan_compaction` plan: release retired ranges, trim
        over-allocated tails, relocate planned nodes downward (verbatim
        row copies through the ``base`` indirection). STRICTLY
        observe-only on bits — no version bump, no node-version stamps,
        no draw changes (pinned: logits and dispatch logs identical with
        compaction on/off). Engines fence the flip
        (`engine.compact_graph`); stale per-node entries are skipped."""
        with self._lock:
            freed = trims = 0
            touched_tiles: set = set()
            touched_bd: set = set()
            for rng in plan.get("retired", ()):
                rng = (int(rng[0]), int(rng[1]))
                if rng in self._retired:
                    self._retired.remove(rng)
                    self._retired_rows -= rng[1]
                    self._release_locked(rng[0], rng[1])
                    freed += rng[1]
            for u, ver in plan.get("trims", ()):
                u = int(u)
                if int(self.node_version[u]) != int(ver):
                    continue  # raced a commit — the next plan retries
                deg = int(self.bd[u, 1])
                tight = -(-deg // LANE)
                alloc = int(self.alloc_rows[u])
                if alloc > tight:
                    base = int(self.bd[u, 0])
                    self._release_locked(base + tight, alloc - tight)
                    self.alloc_rows[u] = tight
                    freed += alloc - tight
                    trims += 1
            moved = 0
            for u, ver in plan.get("moves", ()):
                u = int(u)
                if int(self.node_version[u]) != int(ver):
                    continue
                rows = int(self.alloc_rows[u])
                base = int(self.bd[u, 0])
                if rows == 0:
                    continue
                new = self._take(self._free_ranges, rows)
                if new is None or new >= base:
                    if new is not None:
                        # no downward fit — put the trial back
                        self._put(self._free_ranges, new, rows)
                    continue
                self.tiles[new:new + rows] = self.tiles[base:base + rows]
                if self.ttiles is not None:
                    self.ttiles[new:new + rows] = (
                        self.ttiles[base:base + rows]
                    )
                self.bd[u, 0] = new
                self._release_locked(base, rows)
                touched_tiles.update(range(new, new + rows))
                touched_bd.add(u)
                moved += 1
            n_tiles, n_bd = self._sync_device_locked(
                touched_tiles, touched_bd, defer=defer_publish)
            self.stats["tiles_reclaimed"] += freed
            self.stats["compactions"] += 1
            self.stats["tile_rows_swapped"] += n_tiles
            self.stats["bd_rows_swapped"] += n_bd
            return {"tiles_reclaimed": freed, "trims": trims,
                    "moves": moved, "tile_rows_swapped": n_tiles,
                    "free_rows": self.free_rows}

    def compact(self, max_moves: int = 0) -> Dict[str, int]:
        """Plan + apply in one call (bare callers; engines split the
        two around their fence)."""
        return self.apply_compaction(self.plan_compaction(max_moves))

    def provision_reserve(self, tiles: int) -> Dict[str, object]:
        """Grow the tile tables by a whole BANK of ``tiles`` rows — the
        one sanctioned shape change. Host mirrors reallocate, the new
        bank joins the free pool, and (when device arrays exist) fresh
        device tables upload. Sealed AOT executables bound to the old
        shapes must be rebuilt ONCE per provision event
        (`inference.BucketPrograms.reprovision` — never
        recompile-per-commit); `serve.engine.ServeEngine.
        provision_reserve` fences and does both sides."""
        bank = int(tiles)
        if bank <= 0:
            raise ValueError(f"provision_reserve needs tiles > 0, got "
                             f"{tiles}")
        with self._lock:
            old_cap = self.m_cap
            self.m_cap = old_cap + bank
            new_tiles = np.zeros((self.m_cap, LANE), self.tiles.dtype)
            new_tiles[:old_cap] = self.tiles
            self.tiles = new_tiles
            if self.ttiles is not None:
                new_tt = np.zeros((self.m_cap, LANE), np.float32)
                new_tt[:old_cap] = self.ttiles
                self.ttiles = new_tt
            self._put(self._free_ranges, old_cap, bank)
            self.stats["provisions"] += 1
            if self._tiles_dev is not None:
                import jax.numpy as jnp

                # a full re-upload supersedes any staged (defer_publish)
                # arrays — their shapes are the OLD bank size; drop them
                self._staged_bd = None
                self._staged_tiles = None
                self._staged_tt = None
                self._tiles_dev = jnp.asarray(self.tiles)
                if self.ttiles is not None:
                    self._tt_dev = jnp.asarray(self.ttiles)
            return self._reserve_report_locked()

    # ------------------------------------------------------- internals
    def _append_locked(self, u: int, v: int, touched_tiles, touched_bd,
                       ts: Optional[float] = None):
        """One edge append, advancing adjacency and tiles together.
        Returns ``(pad_writes, spills, lanes_reused)``. A node with dead
        (expired) lanes re-uses the LOWEST one first: the new edge takes
        the masked position (adjacency entry replaced in place, degree
        unchanged) — no reserve consumption, which is what keeps a
        sliding-window workload's tile footprint flat."""
        dead = self._dead.get(u)
        if dead:
            p = dead.pop(0)
            if not dead:
                del self._dead[u]
            self._dead_lanes -= 1
            base = int(self.bd[u, 0])
            row = base + p // LANE
            self.tiles[row, p % LANE] = v
            # dead lanes exist only on temporal streams (expiry made them)
            self.ttiles[row, p % LANE] = ts
            self.adj.replace_at(u, p, v, ts=ts)
            self._min_ts[u] = min(float(self._min_ts[u]), float(ts))
            touched_tiles.add(row)
            touched_bd.add(u)
            return 0, 0, 1
        self.adj._append_one(u, v, ts=ts)
        base = int(self.bd[u, 0])
        deg = int(self.bd[u, 1])
        cap = int(self.alloc_rows[u]) * LANE
        spilled = 0
        if deg >= cap:
            base = self._relocate_locked(u, touched_tiles)
            spilled = 1
        row = base + deg // LANE
        self.tiles[row, deg % LANE] = v
        if self.ttiles is not None:
            # the timestamp lands in the SAME (row, lane) as the edge —
            # one commit makes both drawable (arity checked by preflight)
            self.ttiles[row, deg % LANE] = ts
            self._min_ts[u] = min(float(self._min_ts[u]), float(ts))
        self.bd[u, 1] = deg + 1
        touched_tiles.add(row)
        touched_bd.add(u)
        return 1 - spilled, spilled, 0

    def _relocate_locked(self, u: int, touched_tiles) -> int:
        """Move node ``u`` to ``alloc + grow_tiles`` fresh rows from the
        free pool (copy its existing tiles, bump base). The old rows
        become dead padding the degree mask never reads — draws are
        unchanged because `ops.sample._tiled_resolve` only ever
        dereferences ``base + pos // 128`` for valid positions. The
        vacated rows park in ``_retired`` (still counted as consumed —
        r17 semantics) until a compaction releases them."""
        old_base = int(self.bd[u, 0])
        old_rows = int(self.alloc_rows[u])
        need = old_rows + self.grow_tiles
        new_base = self._take(self._free_ranges, need)
        if new_base is None:
            raise self._capacity_error(
                f"tile reserve exhausted: node {u} needs {need} "
                f"contiguous rows, {self.free_rows} free"
            )
        if old_rows:
            self.tiles[new_base:new_base + old_rows] = (
                self.tiles[old_base:old_base + old_rows]
            )
            if self.ttiles is not None:
                self.ttiles[new_base:new_base + old_rows] = (
                    self.ttiles[old_base:old_base + old_rows]
                )
            self._retired.append((old_base, old_rows))
            self._retired_rows += old_rows
        touched_tiles.update(range(new_base, new_base + old_rows + 1))
        self.bd[u, 0] = new_base
        self.alloc_rows[u] = need
        return new_base

    def _rewrite_node_locked(self, u: int, touched_tiles,
                             touched_bd) -> None:
        """Re-emit node ``u``'s lanes from its (just-mutated) adjacency
        — the deletion shift: survivors pack left in lane order,
        trailing lanes zero. Dead-lane positions and the min-ts index
        are recomputed from the shifted timestamp row."""
        base = int(self.bd[u, 0])
        rows = int(self.alloc_rows[u])
        nbrs = self.adj.neighbors(u)
        d = int(nbrs.size)
        tvals = None
        if rows:
            flat = self.tiles[base:base + rows].reshape(-1)
            flat[:d] = nbrs.astype(self.tiles.dtype)
            flat[d:] = 0
            if self.ttiles is not None:
                tvals = self.adj.neighbors_ts(u)
                tflat = self.ttiles[base:base + rows].reshape(-1)
                tflat[:d] = tvals
                tflat[d:] = 0
            touched_tiles.update(range(base, base + rows))
        self.bd[u, 1] = d
        touched_bd.add(u)
        if self.ttiles is not None:
            if tvals is None:
                tvals = np.empty(0, np.float32)
            self._reindex_node_ts_locked(u, tvals)

    def _reindex_node_ts_locked(self, u: int, tvals: np.ndarray) -> None:
        """Rebuild ``u``'s dead-lane list and min-ts entry from its
        current timestamp row."""
        old = self._dead.pop(u, None)
        if old:
            self._dead_lanes -= len(old)
        deadpos = np.nonzero(np.isinf(tvals))[0]
        if deadpos.size:
            self._dead[u] = deadpos.tolist()
            self._dead_lanes += int(deadpos.size)
        finite = tvals[np.isfinite(tvals)]
        self._min_ts[u] = finite.min() if finite.size else np.inf

    def _update_one_locked(self, u: int, v: int, t: float,
                           touched_tiles, touched_bd) -> None:
        """Retarget one edge's timestamp lane (first lane-order
        occurrence of ``(u, v)``). A formerly-dead lane given a finite
        ts comes back to life (leaves the re-use pool)."""
        p = self.adj.update_one(u, v, t)
        base = int(self.bd[u, 0])
        row = base + p // LANE
        self.ttiles[row, p % LANE] = t
        touched_tiles.add(row)
        touched_bd.add(u)
        # recompute (not just min): the update may have MOVED the row's
        # minimum up, and a stale min would re-scan this node at every
        # expiry; this also drops lane p from the dead list if the
        # update revived it
        self._reindex_node_ts_locked(u, self.adj.neighbors_ts(u))

    def _install_locked(self, node: int, nbrs: np.ndarray, touched_tiles,
                        touched_bd, ts_row: Optional[np.ndarray] = None,
                        ) -> None:
        if not 0 <= node < self.n:
            raise ValueError(f"install node {node} outside [0, {self.n})")
        if int(self.bd[node, 1]) != 0:
            raise ValueError(
                f"install_rows targets degree-0 rows only (node {node} "
                f"has degree {int(self.bd[node, 1])}); use apply() "
                "appends for materialized rows"
            )
        if nbrs.size == 0:
            return
        # a deleted-to-zero row re-installing hands its old rows back
        # first (they hold nothing a draw can reach)
        old_rows = int(self.alloc_rows[node])
        if old_rows:
            self._release_locked(int(self.bd[node, 0]), old_rows)
            self.alloc_rows[node] = 0
        need = -(-int(nbrs.size) // LANE)
        base = self._take(self._free_ranges, need)
        if base is None:
            raise self._capacity_error(
                f"tile reserve exhausted installing node {node} "
                f"({need} contiguous rows needed, {self.free_rows} free)"
            )
        flat = self.tiles[base:base + need].reshape(-1)
        flat[: nbrs.size] = nbrs.astype(self.tiles.dtype)
        flat[nbrs.size:] = 0
        if self.ttiles is not None:
            tflat = self.ttiles[base:base + need].reshape(-1)
            tflat[: nbrs.size] = ts_row
            tflat[nbrs.size:] = 0
        self.bd[node, 0] = base
        self.bd[node, 1] = nbrs.size
        self.alloc_rows[node] = need
        touched_tiles.update(range(base, base + need))
        touched_bd.add(node)
        # bookkeeping: an installed row's neighbors enter the adjacency
        # view as "extras" over its empty base row (same lane order) —
        # or replace the override list wholesale when the row was
        # already materialized by a lifecycle op
        if node in self.adj._override:
            self.adj._override[node] = [int(x) for x in nbrs]
            if self.ttiles is not None:
                self.adj._override_ts[node] = [float(x) for x in ts_row]
        else:
            self.adj._extra[node] = [int(x) for x in nbrs]
            if self.ttiles is not None:
                self.adj._extra_ts[node] = [float(x) for x in ts_row]
        for v in nbrs:
            self.adj._rev_extra.setdefault(int(v), []).append(node)
        self.adj._n_extra += int(nbrs.size)
        if self._min_ts is not None:
            finite = ts_row[np.isfinite(ts_row)]
            self._min_ts[node] = finite.min() if finite.size else np.inf

    def _sync_device_locked(self, touched_tiles, touched_bd,
                            defer: bool = False):
        n_tiles, n_bd = len(touched_tiles), len(touched_bd)
        if self._tiles_dev is None or (not n_tiles and not n_bd):
            return n_tiles, n_bd
        import jax.numpy as jnp

        if not defer and self._staged_tiles is not None:
            # a deferred commit was never published (defensive — engine
            # commit locks serialize this away): fold it in first so the
            # scatter below bases on the newest bits
            self._publish_locked()
        if defer:
            # base on staged-if-present: apply + retention-expire inside
            # one zero-stall commit accumulate into ONE flip
            base_tiles = (self._staged_tiles if self._staged_tiles
                          is not None else self._tiles_dev)
            base_tt = (self._staged_tt if self._staged_tt is not None
                       else self._tt_dev)
            base_bd = (self._staged_bd if self._staged_bd is not None
                       else self._bd_dev)
        else:
            base_tiles, base_tt, base_bd = (
                self._tiles_dev, self._tt_dev, self._bd_dev
            )
        if n_tiles:
            idx = np.fromiter(touched_tiles, np.int64, n_tiles)
            idx.sort()
            pos, rows = _bucketed(idx, self.tiles[idx], self.m_cap)
            base_tiles = _scatter_rows(
                base_tiles, jnp.asarray(pos), jnp.asarray(rows)
            )
            if base_tt is not None:
                # the timestamp payload swaps the SAME touched rows in the
                # same commit — a draw can never see an edge without its ts
                tpos, trows = _bucketed(idx, self.ttiles[idx], self.m_cap)
                base_tt = _scatter_rows(
                    base_tt, jnp.asarray(tpos), jnp.asarray(trows)
                )
        if n_bd:
            idx = np.fromiter(touched_bd, np.int64, n_bd)
            idx.sort()
            pos, rows = _bucketed(idx, self.bd[idx], self.n)
            base_bd = _scatter_rows(
                base_bd, jnp.asarray(pos), jnp.asarray(rows)
            )
        if defer:
            self._staged_tiles = base_tiles
            self._staged_tt = base_tt
            self._staged_bd = base_bd
        else:
            self._tiles_dev = base_tiles
            self._tt_dev = base_tt
            self._bd_dev = base_bd
        return n_tiles, n_bd

    def _publish_locked(self) -> bool:
        if self._staged_tiles is None and self._staged_bd is None:
            return False
        if self._staged_tiles is not None:
            self._tiles_dev = self._staged_tiles
            self._tt_dev = self._staged_tt
        if self._staged_bd is not None:
            self._bd_dev = self._staged_bd
        self._staged_bd = None
        self._staged_tiles = None
        self._staged_tt = None
        return True

    def publish(self) -> bool:
        """Flip the staged (defer_publish) device arrays live: O(1) ref
        assignment under the stream lock — the zero-stall commit's only
        serving-visible moment. Flushes sealed before the flip keep the
        old array objects (immutable; `_scatter_rows` copies on write)
        and complete bit-exactly against their epoch. Returns True when
        something was staged."""
        with self._lock:
            return self._publish_locked()

"""Fused dequant-on-gather lookups for quantized feature tables.

Every function here traces into the CALLER's jitted program (none is
jitted itself): the gather touches encoded rows + per-row side entries
and decodes in-register, so the f32 table never exists anywhere — not in
HBM, not on the H2D wire, not as an XLA temp bigger than the gathered
batch. This is the quantized twin of ``pipeline.tiered_lookup`` /
``collectives.sharded_gather``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .codecs import get_codec


def _side_lookup(mapped, scale, zero):
    """Per-lane scale/zero from the full [N_stored] side tables (clip keeps
    invalid lanes in range; their rows are masked by the caller)."""
    n = scale.shape[0]
    safe = jnp.clip(mapped, 0, n - 1)
    return jnp.take(scale, safe), jnp.take(zero, safe)


def gather_dequant(codec, payload, ids, scale=None, zero=None):
    """Fused gather + decode from a fully device-resident encoded table.

    payload: ``[N, D]`` encoded rows; scale/zero: ``[N]`` f32 side tables
    (codecs without side tables pass None). ids: any int shape — clipped
    into range exactly like ``Feature.lookup_padded`` (the jit contract;
    use :meth:`Feature.validate_ids` when silent clipping is not wanted).
    Returns f32 rows ``[..., D]``.
    """
    codec = get_codec(codec)
    n = payload.shape[0]
    q = jnp.take(payload, jnp.clip(ids, 0, n - 1), axis=0)
    if scale is not None:
        s, z = _side_lookup(ids, scale, zero)
        return codec.dequant(q, s, z)
    return codec.dequant(q)


def quantized_tiered_lookup(
    codec,
    hot_payload: jax.Array,
    mapped: jax.Array,
    cold_payload: jax.Array,
    cold_pos: jax.Array,
    scale: Optional[jax.Array] = None,
    zero: Optional[jax.Array] = None,
) -> jax.Array:
    """Quantized twin of :func:`quiver_tpu.pipeline.tiered_lookup`.

    The assembly stays ENCODED end to end: gather encoded hot rows from
    HBM, scatter the prefetched encoded cold rows (which crossed the H2D
    wire at codec width) into their lanes, THEN decode the merged [W, D]
    block once — dequant-after-scatter, so hot and cold lanes share one
    decode and the program holds no f32 temp wider than the batch. Side
    entries come from the device-resident [N_stored] tables indexed by
    ``mapped`` (cold rows never ship scale/zero over the wire).

    mapped: [W] stored-row ids, -1 invalid (the pipeline's contract);
    cold_payload/cold_pos: the staged cold rows in storage dtype. Lanes
    whose ``mapped`` points past the hot prefix MUST be covered by
    ``cold_pos`` (the pipeline guarantees it); uncovered cold lanes decode
    to the row's zero-point, not to 0.
    """
    codec = get_codec(codec)
    hot_n = hot_payload.shape[0]
    valid = mapped >= 0
    is_hot = valid & (mapped < hot_n)
    q = jnp.take(hot_payload, jnp.clip(mapped, 0, hot_n - 1), axis=0)
    q = q * is_hot[:, None].astype(q.dtype)
    if cold_payload.shape[0]:
        q = q.at[cold_pos].set(cold_payload, mode="drop")
    if scale is not None:
        s, z = _side_lookup(mapped, scale, zero)
        x = codec.dequant(q, s, z)
    else:
        x = codec.dequant(q)
    return x * valid[:, None].astype(x.dtype)


def sharded_dequant_gather(
    codec, payload_block, ids, axis_name, scale=None, zero=None
):
    """Global-id gather from an ICI-row-striped ENCODED table, inside
    shard_map — the quantized twin of ``collectives.sharded_gather``.

    The psum rides the encoded payload (int8 moves 4x fewer ICI bytes than
    f32 per gathered row); scale/zero are replicated per chip ([N_global]
    f32, ~2% of an fp32 table at D=100) and applied AFTER the collective.
    Summing encoded partials is exact: every non-owner contributes zeros.
    Out-of-range ids return zero rows (matching sharded_gather).
    """
    # lazy: pulling quiver_tpu.parallel at import time would drag the whole
    # train-step machinery into `import quiver_tpu`
    from ..parallel.collectives import sharded_gather

    codec = get_codec(codec)
    q = sharded_gather(payload_block, ids, axis_name)
    if scale is None:
        return codec.dequant(q)
    n = scale.shape[0]
    ok = (ids >= 0) & (ids < n)
    s, z = _side_lookup(ids, scale, zero)
    x = codec.dequant(q, s, z)
    return x * ok[..., None].astype(x.dtype)


def make_quantized_train_step(
    model, tx, labels: jax.Array, hot_payload: jax.Array,
    scale: Optional[jax.Array] = None, zero: Optional[jax.Array] = None,
    codec="int8",
):
    """Jitted ``step(params, opt_state, key, batch)`` with the fused
    dequant-gather inside fwd/bwd — the quantized twin of
    :func:`quiver_tpu.pipeline.make_tiered_train_step` (consumes the same
    :class:`TieredBatch`; the batch's ``cold_rows`` arrive in storage
    dtype from a ``TieredFeaturePipeline`` built over a
    :class:`QuantizedFeature`). Tables/labels enter as jit ARGUMENTS —
    closure capture would bake them in as XLA constants (see bench.py).
    """
    import optax

    codec = get_codec(codec)
    hot_payload = jnp.asarray(hot_payload)
    labels = jnp.asarray(labels)

    @jax.jit
    def step(params, opt_state, key, hot, s, z, lab, batch):
        x = quantized_tiered_lookup(
            codec, hot, batch.mapped, batch.cold_rows, batch.cold_pos, s, z
        )
        y = jnp.take(lab, jnp.clip(batch.seeds, 0, lab.shape[0] - 1))

        def objective(p):
            logits = model.apply(
                p, x, batch.ds.adjs, train=True, rngs={"dropout": key}
            )
            ll = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(ll, y[:, None].astype(jnp.int32), axis=1)[:, 0]
            return nll.mean()

        loss, grads = jax.value_and_grad(objective)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    def bound(params, opt_state, key, batch):
        return step(params, opt_state, key, hot_payload, scale, zero, labels, batch)

    return bound

"""QuantizedFeature — the tiered feature store over encoded rows.

Composes with the existing :class:`quiver_tpu.feature.Feature` rather than
reimplementing it: the degree-descending reorder happens HERE (so the
per-row side tables stay aligned with the stored row order), then an inner
``Feature`` tiers the ENCODED payload through the unchanged machinery —
hot HBM prefix (``device_replicate``), ICI-striped clique
(``p2p_clique_replicate``), cold host tail, budget math and IPC shims all
reused with ``dtype = codec.storage_dtype``. Every tier therefore holds
encoded rows, and the hot prefix covers up to
``codec.capacity_multiplier(D)``x the rows the same HBM budget bought in
fp32 (int8 at D=100: ~3.7x, realized at full residency — see the
capacity-accounting note below).

The wrapper quacks like ``Feature`` where the pipeline reads it
(``shard_tensor``/``feature_order``/``dim``/``shape``/``dtype``), so
``TieredFeaturePipeline(QuantizedFeature(...))`` works unchanged: the host
cold gather runs the dtype-agnostic native byte engine over the encoded
tail and the H2D upload ships storage-dtype rows — wire bytes shrink by
the same factor. The train step decodes after the scatter
(:func:`quiver_tpu.quant.lookup.quantized_tiered_lookup`).

Capacity accounting: the per-row side tables (fp32 scale/zero over ALL N
rows, int8 only) are device-replicated — at 8 B/row they are ~2% of an
fp32 table at D=100 — so cold lookups never ship scale over the wire.
Their full-N footprint is charged against ``device_cache_size`` FIRST
(they are resident regardless of hot fraction); the remaining budget
buys hot payload rows. :meth:`side_table_bytes` reports the footprint;
the amortized per-row multiplier ``codec.capacity_multiplier(D)`` (what
``scaling.quant_fetch_table`` tabulates) is realized at full residency.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import numpy as np

import jax
import jax.numpy as jnp

from ..feature import Feature
from ..shard_tensor import _device_of
from ..utils import CSRTopo, IciTopo, parse_size, reindex_feature
from .codecs import QuantizedRows, get_codec
from .lookup import gather_dequant, quantized_tiered_lookup


class QuantizedFeature:
    """Tiered ``[N, D]`` feature store holding CODEC-ENCODED rows.

    Constructor mirrors :class:`Feature` plus ``codec`` (a registry name —
    ``"fp32"`` | ``"bf16"`` | ``"int8"`` — or any object satisfying the
    codec contract, see ``quant.codecs``).
    """

    def __init__(
        self,
        codec: Union[str, object] = "int8",
        rank: int = 0,
        device_list: Optional[Sequence[int]] = None,
        device_cache_size: Union[int, str] = 0,
        cache_policy: str = "device_replicate",
        csr_topo: Optional[CSRTopo] = None,
        host_memory_budget: Union[int, str] = 0,
        disk_path: Optional[str] = None,
        adaptive_tiers: bool = False,
        disk_read_workers: int = 4,
        read_pool=None,
    ):
        self.codec = get_codec(codec)
        # round-14 disk tier: passed straight to the inner Feature, so the
        # spilled tail (and the adaptive backing file) hold ENCODED rows —
        # cold rows are int8 on disk AND on the wire. The fp32 side tables
        # stay device-resident over all N (unchanged accounting below).
        self.host_memory_budget = host_memory_budget
        self.disk_path = disk_path
        self.adaptive_tiers = bool(adaptive_tiers)
        self.disk_read_workers = int(disk_read_workers)
        self.read_pool = read_pool
        self.rank = rank
        self.device_list = list(device_list) if device_list else [rank]
        self.device_cache_size = parse_size(device_cache_size)
        if cache_policy == "ici_replicate":
            cache_policy = "p2p_clique_replicate"
        self.cache_policy = cache_policy
        self.csr_topo = csr_topo
        self.feature_order: Optional[np.ndarray] = None
        self._inv_order: Optional[np.ndarray] = None
        self.inner: Optional[Feature] = None
        self._n = 0
        self._dim: Optional[int] = None
        self._scale_np: Optional[np.ndarray] = None
        self._zero_np: Optional[np.ndarray] = None
        self._scale_dev = None
        self._zero_dev = None
        self._order_dev = None
        # observe-only workload tap (round 13): same contract as
        # Feature.tier_counter — eager gathers attribute rows per tier of
        # the INNER (encoded) shard book
        self.tier_counter = None
        # round-14 row-access tap (see Feature.row_tap)
        self.row_tap = None

    # ------------------------------------------------------------------ build
    def from_cpu_tensor(self, cpu_tensor) -> None:
        """Ingest the f32 table: reorder (degree-descending when a
        ``csr_topo`` is attached), encode, then tier the encoded payload
        through an inner ``Feature``."""
        arr = np.asarray(cpu_tensor, np.float32)
        if arr.ndim != 2:
            raise ValueError("features must be [N, D]")
        self._n, self._dim = arr.shape
        # honest HBM accounting: the per-row side tables span ALL N rows
        # regardless of hot fraction (cold dequant-after-scatter reads them
        # on device), so their full footprint is charged against the budget
        # FIRST; the remainder buys hot payload rows. The amortized
        # codec.row_bytes multiplier (3.70x at int8/D=100) is realized at
        # full residency; small budgets pay the fixed side cost up front.
        side_total = self.codec.side_bytes_per_row * self._n
        if 0 < self.device_cache_size < side_total:
            # a stated budget the side tables alone overflow is a config
            # error, not a 0-hot-rows store: .scale/.zero would still put
            # the full tables on device, silently exceeding the budget.
            # (device_cache_size=0 stays the explicit all-cold opt-in —
            # side tables ride along on first use, as documented.)
            raise ValueError(
                f"device_cache_size ({self.device_cache_size} B) cannot even "
                f"hold the {self.codec.name} codec's device-resident side "
                f"tables ({int(side_total)} B for N={self._n}); raise the "
                "budget or use a sideless codec (bf16)"
            )
        payload_row_bytes = self._dim * np.dtype(self.codec.storage_dtype).itemsize
        cache_rows = min(
            int(max(0.0, self.device_cache_size - side_total) // payload_row_bytes),
            self._n,
        )
        if self.csr_topo is not None:
            # same hot-ratio policy as Feature.from_cpu_tensor, with rows
            # priced at the CODEC's row bytes — the capacity multiplier is
            # exactly what widens this ratio
            if self.cache_policy == "p2p_clique_replicate":
                clique = IciTopo.detect().get_clique(self.rank)
                ratio = min(cache_rows * len(clique), self._n) / max(self._n, 1)
            else:
                ratio = cache_rows / max(self._n, 1)
            arr, order = reindex_feature(self.csr_topo, arr, ratio)
            self.feature_order = order
            self.csr_topo.feature_order = order
            self._inv_order = None
        enc = self.codec.encode(arr)
        # the inner Feature re-derives cache_rows from ITS row bytes, so
        # hand it exactly cache_rows * payload bytes (csr_topo=None: the
        # reorder already happened here, against quant-priced capacity)
        inner = Feature(
            rank=self.rank,
            device_list=self.device_list,
            device_cache_size=cache_rows * payload_row_bytes,
            cache_policy=self.cache_policy,
            csr_topo=None,
            dtype=self.codec.storage_dtype,
            host_memory_budget=self.host_memory_budget,
            disk_path=self.disk_path,
            adaptive_tiers=self.adaptive_tiers,
            disk_read_workers=self.disk_read_workers,
            read_pool=self.read_pool,
        )
        inner.from_cpu_tensor(enc.payload)
        self.inner = inner
        self._scale_np = None if enc.scale is None else np.asarray(enc.scale, np.float32)
        self._zero_np = None if enc.zero is None else np.asarray(enc.zero, np.float32)
        self._scale_dev = self._zero_dev = self._order_dev = None

    # ------------------------------------------------------------- delegation
    # the attribute surface TieredFeaturePipeline and tests read; the
    # pipeline stages encoded rows without knowing the table is quantized
    @property
    def shard_tensor(self):
        return None if self.inner is None else self.inner.shard_tensor

    @property
    def tier_store(self):
        """The inner store's adaptive `tiers.TierStore` (None when
        static) — placement moves operate on ENCODED rows."""
        return None if self.inner is None else self.inner.tier_store

    @property
    def disk_staged(self):
        """Flush-ahead staging mask hook (round 18), delegated to the
        inner Feature: whoever runs the prefetch installs it through the
        wrapper and the ENCODED store's attribution reports
        ``disk_prefetched`` truthfully (staged rows are encoded rows —
        the staging buffer holds codec-width bytes)."""
        return None if self.inner is None else self.inner.disk_staged

    @disk_staged.setter
    def disk_staged(self, fn):
        if self.inner is None:
            raise ValueError("disk_staged needs a built feature "
                             "(call from_cpu_tensor first)")
        self.inner.disk_staged = fn

    def tier_bytes(self):
        """Live ENCODED-payload bytes per tier (see
        `Feature.tier_bytes`); side tables are reported separately by
        :meth:`side_table_bytes` — together they are the full device
        charge, and demotions shrink the payload term immediately."""
        return {} if self.inner is None else self.inner.tier_bytes()

    def stored_rows_of(self, node_ids) -> np.ndarray:
        """Node id -> stored (encoded) row; -1 out of range. The outer
        wrapper owns the reorder, so the map lives HERE, not on the
        inner Feature (whose order is None by construction)."""
        ids = np.asarray(node_ids).astype(np.int64).reshape(-1)
        oob = (ids < 0) | (ids >= self._n)
        stored = np.where(oob, 0, ids)
        if self.feature_order is not None:
            stored = self.feature_order[stored]
        return np.where(oob, -1, stored)

    def node_ids_of_stored(self, stored) -> np.ndarray:
        """Stored row -> node id (inverse of the outer reorder)."""
        stored = np.asarray(stored, np.int64).reshape(-1)
        if self.feature_order is None:
            return stored
        if getattr(self, "_inv_order", None) is None:
            inv = np.full(self._n, -1, np.int64)
            inv[self.feature_order] = np.arange(self._n, dtype=np.int64)
            self._inv_order = inv
        return self._inv_order[stored]

    @property
    def dtype(self):
        return np.dtype(self.codec.storage_dtype)

    @property
    def shape(self):
        return (self._n, self._dim)

    @property
    def dim(self) -> int:
        return self._dim or 0

    def size(self, axis: int) -> int:
        return self.shape[axis]

    @property
    def hot_rows(self) -> int:
        """Rows resident in this handle's HBM shards (the hot prefix;
        LIVE placement count for adaptive stores)."""
        if self.tier_store is not None:
            return self.tier_store.placement.counts()["hbm"]
        st = self.shard_tensor
        if st is None:
            return 0
        return sum(o.end - o.start for _, _, o in st.device_shards)

    def side_table_bytes(self) -> int:
        """Device-resident side-table footprint (0 for sideless codecs)."""
        if self._scale_np is None:
            return 0
        return self._scale_np.nbytes + self._zero_np.nbytes

    # ------------------------------------------------------------ side tables
    @property
    def scale(self):
        """[N_stored] f32 scale table on this rank's device (None if the
        codec has no side tables)."""
        if self._scale_np is None:
            return None
        if self._scale_dev is None:
            self._scale_dev = jax.device_put(
                jnp.asarray(self._scale_np), _device_of(self.rank)
            )
        return self._scale_dev

    @property
    def zero(self):
        if self._zero_np is None:
            return None
        if self._zero_dev is None:
            self._zero_dev = jax.device_put(
                jnp.asarray(self._zero_np), _device_of(self.rank)
            )
        return self._zero_dev

    # ----------------------------------------------------------------- lookup
    def __getitem__(self, node_idx) -> jax.Array:
        """Eager tiered gather + decode by ORIGINAL node id: encoded rows
        cross every tier boundary (ICI / H2D) at codec width, decode runs
        on device over the gathered batch only. Invalid ids yield zero
        rows (same contract as ``Feature.__getitem__``)."""
        ids = np.asarray(node_idx).astype(np.int64).reshape(-1)
        invalid = (ids < 0) | (ids >= self._n)
        safe = np.where(invalid, 0, ids)
        stored = self.feature_order[safe] if self.feature_order is not None else safe
        if self.tier_counter is not None:
            if self.tier_store is not None:
                split = self.tier_store.tier_split(stored[~invalid])
                for tier, nn in split.items():
                    if nn:
                        self.tier_counter.hit(nn, tier=tier)
            else:
                from ..feature import attribute_gather_tiers

                attribute_gather_tiers(
                    self.inner.shard_tensor, self.rank, stored,
                    self.tier_counter, valid=~invalid,
                    staged=self.inner.disk_staged,
                )
        if self.row_tap is not None:
            self.row_tap(stored[~invalid])
        q = self.inner.gather_stored(stored)
        if self._scale_np is not None:
            s = jnp.asarray(self._scale_np[stored])
            z = jnp.asarray(self._zero_np[stored])
            x = self.codec.dequant(q, s, z)
        else:
            x = self.codec.dequant(q)
        if invalid.any():
            x = x * jnp.asarray(~invalid, x.dtype)[:, None]
        return x

    def lookup_padded(
        self, node_idx: jax.Array, valid: Optional[jax.Array] = None
    ) -> jax.Array:
        """Jit-friendly fused dequant-gather for fully HBM-resident tables
        (same residency requirement and id-CLIP semantics as
        ``Feature.lookup_padded``; see ``validate_ids`` for the strict
        opt-in check)."""
        st = self.shard_tensor
        if st is None or st.cpu_tensor is not None or len(st.device_shards) != 1:
            raise ValueError(
                "lookup_padded needs a fully HBM-resident feature; "
                "use __getitem__ (tiered) or the quantized pipeline"
            )
        table = st.device_shards[0][1]
        ids = node_idx
        if self.feature_order is not None:
            if self._order_dev is None:
                self._order_dev = jnp.asarray(self.feature_order)
            ids = jnp.take(
                self._order_dev,
                jnp.clip(ids, 0, self._order_dev.shape[0] - 1),
            )
        rows = gather_dequant(self.codec, table, ids, self.scale, self.zero)
        if valid is not None:
            rows = rows * valid[:, None].astype(rows.dtype)
        return rows

    def validate_ids(self, node_idx) -> np.ndarray:
        """Opt-in strict id validation (host-side); see
        :meth:`Feature.validate_ids`."""
        from ..feature import validate_lookup_ids

        return validate_lookup_ids(node_idx, self._n)

    def decode_rows(self, node_idx) -> np.ndarray:
        """Host-side oracle decode by ORIGINAL node id (numpy end to end;
        the bit-for-bit reference the fused paths are tested against).
        Requires the encoded payload to be host-reachable only through the
        shard book — it re-gathers via ``__getitem__`` semantics on host
        tiers; intended for tests/debugging, not the hot path."""
        ids = np.asarray(node_idx).astype(np.int64).reshape(-1)
        invalid = (ids < 0) | (ids >= self._n)
        safe = np.where(invalid, 0, ids)
        stored = self.feature_order[safe] if self.feature_order is not None else safe
        # gather through the tiers (disk/adaptive included), then host math
        q = np.asarray(self.inner.gather_stored(stored))
        enc = QuantizedRows(
            q,
            None if self._scale_np is None else self._scale_np[stored],
            None if self._zero_np is None else self._zero_np[stored],
        )
        x = self.codec.decode(enc)
        if not x.flags.writeable:
            # identity decodes (fp32) hand back the read-only jax view
            x = x.copy()
        x[invalid] = 0.0
        return x

"""quiver_tpu.quant — quantized feature store (compressed hot/cold cache
with fused dequant-on-gather).

Pieces:

- ``codecs``: the codec registry (``fp32`` baseline, ``bf16`` cast,
  ``int8`` per-row affine) and the pluggable :class:`Codec` contract.
- ``QuantizedFeature``: the tiered store holding encoded rows in every
  tier (hot HBM prefix / ICI stripe / cold host tail), composed over the
  unchanged :class:`quiver_tpu.Feature`.
- ``lookup``: the in-jit fused paths — ``gather_dequant`` (resident
  tables), ``quantized_tiered_lookup`` (hot gather + encoded cold
  scatter, one decode), ``sharded_dequant_gather`` (encoded psum over
  ICI), ``make_quantized_train_step`` (drop-in for
  ``make_tiered_train_step``).

Byte/capacity accounting lives in
``quiver_tpu.parallel.scaling.quant_fetch_table``; the synthetic
fp32-vs-int8 training probe is ``scripts/quant_probe.py``.
"""

from .codecs import (
    CODECS,
    Bf16Codec,
    Codec,
    Int8Codec,
    QuantizedRows,
    get_codec,
    register_codec,
)
from .feature import QuantizedFeature
from .lookup import (
    gather_dequant,
    make_quantized_train_step,
    quantized_tiered_lookup,
    sharded_dequant_gather,
)

__all__ = [
    "CODECS",
    "Bf16Codec",
    "Codec",
    "Int8Codec",
    "QuantizedFeature",
    "QuantizedRows",
    "gather_dequant",
    "get_codec",
    "make_quantized_train_step",
    "quantized_tiered_lookup",
    "register_codec",
    "sharded_dequant_gather",
]

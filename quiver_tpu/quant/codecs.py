"""Feature codecs — per-row compression for the tiered feature store.

The paper's split is "sampling is latency-critical, feature collection is
bandwidth-critical" (SURVEY.md section 2); every byte model in this repo
(NEXT.md item 2, scaling.py) says the non-compute share of the step is
dominated by feature fetches. On TPU the cheapest byte is the one never
gathered: storing encoded rows in every tier simultaneously

- multiplies the effective HBM hot-cache capacity (more rows hot ->
  fewer cold host gathers at all),
- shrinks the HBM bytes each fused gather touches, and
- shrinks the H2D wire bytes of the cold prefetch path
  (PyTorch-Direct, arXiv 2101.07956, and the GPU-initiated direct-storage
  work, arXiv 2306.16384, attack the same wall on GPUs).

A codec is storage-layout only: training still consumes float32 rows.
Dequantization composes into the caller's jitted program (gather encoded
rows + per-row side entries, decode in-register) — the encoded table is
never materialized as f32.

Codec contract (duck-typed; see :class:`Codec`):

- ``name``: registry key.
- ``storage_dtype``: numpy dtype of the encoded ``[N, D]`` payload — this
  is what every tier (HBM shard, ICI stripe, host tail, H2D wire) holds.
- ``bytes_per_elem``: payload bytes per element (wire-true ``trace.gbps``
  accounting).
- ``side_bytes_per_row``: bytes of per-row side tables (int8: fp32 scale +
  zero = 8). Side tables stay device-resident (they are ~2% of an fp32
  row at D=100) and never ride the H2D wire.
- ``encode(arr) -> QuantizedRows`` (host, numpy in / numpy out).
- ``decode(enc) -> np.ndarray`` — the host-side oracle; the in-jit path
  must match it bit-for-bit (tests/test_quant.py pins this).
- ``dequant(q, scale, zero)`` — the in-jit decode; plain jnp ops so it
  traces into the caller's program (NOT jitted itself).

Register custom codecs with :func:`register_codec`; anything satisfying
the contract works end to end (QuantizedFeature, the pipeline, the
scaling tables all go through the registry).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Union

import numpy as np

import jax
import jax.numpy as jnp

from ..shard_tensor import normalize_dtype


class QuantizedRows(NamedTuple):
    """Encoded rows + per-row side tables (a pytree: jit-traversable).

    payload: ``[N, D]`` array in the codec's storage dtype.
    scale/zero: ``[N]`` float32 per-row affine tables, or None for codecs
    without side tables (fp32, bf16).
    """

    payload: Any
    scale: Optional[Any] = None
    zero: Optional[Any] = None


class Codec:
    """Base codec: the fp32 identity (useful as the baseline row of every
    byte table, and as the template for custom codecs)."""

    name = "fp32"
    storage_dtype = np.dtype(np.float32)
    bytes_per_elem = 4.0
    side_bytes_per_row = 0.0

    def row_bytes(self, dim: int) -> float:
        """Total stored bytes per row (payload + side tables) — the unit of
        hot-cache capacity accounting."""
        return self.bytes_per_elem * dim + self.side_bytes_per_row

    def capacity_multiplier(self, dim: int) -> float:
        """How many encoded rows fit where one fp32 row did."""
        return (4.0 * dim) / self.row_bytes(dim)

    # ---------------------------------------------------------------- encode
    def encode(self, arr) -> QuantizedRows:
        return QuantizedRows(np.ascontiguousarray(arr, np.float32))

    # ----------------------------------------------------------- host decode
    def decode(self, enc: QuantizedRows) -> np.ndarray:
        return np.asarray(enc.payload, np.float32)

    # -------------------------------------------------------- in-jit decode
    def dequant(self, q, scale=None, zero=None):
        """Decode gathered rows inside the caller's jitted program.

        ``q``: ``[..., D]`` encoded rows; ``scale``/``zero``: per-row side
        entries broadcast over the last axis (``[...]``-shaped), or None.
        """
        return q.astype(jnp.float32)


class Bf16Codec(Codec):
    """Lossless-ish bfloat16 cast: 2x capacity, no side tables. bf16 keeps
    f32's exponent range, so the cast never overflows — error is pure
    mantissa rounding (rel ~2^-8), which GNN training shrugs off (the
    existing ``Feature(dtype="bfloat16")`` tier relies on the same fact)."""

    name = "bf16"
    storage_dtype = normalize_dtype("bfloat16")
    bytes_per_elem = 2.0
    side_bytes_per_row = 0.0

    def encode(self, arr) -> QuantizedRows:
        return QuantizedRows(
            np.ascontiguousarray(np.asarray(arr, np.float32).astype(self.storage_dtype))
        )

    def decode(self, enc: QuantizedRows) -> np.ndarray:
        return np.asarray(enc.payload).astype(np.float32)


class Int8Codec(Codec):
    """Per-row affine int8: ``x ~ (q - zero) * scale`` with fp32 scale and
    fp32 zero-POINT (q-space) side tables. 4x payload compression; max abs
    error per element is ``~row_span / 508`` (q spans [-127, 127] over the
    row's [min, max]) PLUS a few ulps of the row's magnitude — the fp32
    output-representability floor, which only matters for rows whose
    offset is huge relative to their span (|rmin| >> span: the q-space
    zero is then large and its own fp32 rounding costs ~ulp(|row|) in
    value space; measured <= 0.51*scale + ~2.5*ulp, pinned in tests).
    A value-space offset (``q*s + rmin``) would shave that ulp term but
    its decode is mul-then-add, which XLA contracts into an FMA under jit
    (measured on the CPU backend, survives lax.optimization_barrier) —
    breaking the bit-for-bit host/jit parity this codec guarantees; rows
    that close to the fp32 floor gain nothing from any f32-output codec.

    The decode is deliberately sub-then-mul, NOT mul-then-add: XLA fuses
    ``q*s + z`` into an FMA under jit (measured 1-ulp drift vs numpy on
    the CPU backend), while ``(q - z) * s`` admits no value-changing
    fusion — so the in-jit fused dequant-gather matches the host decode
    BIT-FOR-BIT on every backend (tests/test_quant.py pins it).

    Safe when rows are not heavy-tailed WITHIN a row (the span sets the
    grid): degree-normalized embeddings, one-hot-ish floats, and standard
    feature matrices all qualify; rows mixing O(1) and O(1e4) magnitudes
    do not — use bf16 there. docs/api.md carries the guidance table.
    """

    name = "int8"
    storage_dtype = np.dtype(np.int8)
    bytes_per_elem = 1.0
    side_bytes_per_row = 8.0  # fp32 scale + fp32 zero-point

    def encode(self, arr) -> QuantizedRows:
        arr = np.ascontiguousarray(arr, np.float32)
        rmin = arr.min(axis=1)
        rmax = arr.max(axis=1)
        span = rmax - rmin
        pos = span > 0
        scale = np.where(pos, span / np.float32(254.0), np.float32(1.0)).astype(
            np.float32
        )
        with np.errstate(divide="ignore"):
            inv = np.where(pos, np.float32(254.0) / span, np.float32(0.0)).astype(
                np.float32
            )
        q = np.clip(
            np.rint((arr - rmin[:, None]) * inv[:, None]) - 127.0, -127, 127
        ).astype(np.int8)
        # zero-point in q-space: decode(-127) lands on ~rmin. Constant rows
        # (span 0) store q=0, scale=1, zero=-rmin -> decode EXACTLY rmin
        zero = np.where(
            pos, np.float32(-127.0) - rmin / scale, -rmin
        ).astype(np.float32)
        q[~pos] = 0
        return QuantizedRows(q, scale, zero)

    def decode(self, enc: QuantizedRows) -> np.ndarray:
        q = np.asarray(enc.payload)
        scale = np.asarray(enc.scale, np.float32)
        zero = np.asarray(enc.zero, np.float32)
        return (q.astype(np.float32) - zero[..., None]) * scale[..., None]

    def dequant(self, q, scale=None, zero=None):
        if scale is None or zero is None:
            raise ValueError("int8 dequant needs per-row scale and zero tables")
        return (q.astype(jnp.float32) - zero[..., None]) * scale[..., None]


CODECS = {c.name: c for c in (Codec(), Bf16Codec(), Int8Codec())}


def register_codec(codec) -> None:
    """Add a custom codec to the registry (overwrites an existing name)."""
    CODECS[codec.name] = codec


def get_codec(codec: Union[str, Codec]):
    """Resolve a codec name (or pass through an instance)."""
    if isinstance(codec, str):
        try:
            return CODECS[codec]
        except KeyError:
            raise ValueError(
                f"unknown codec {codec!r}; registered: {sorted(CODECS)}"
            ) from None
    return codec

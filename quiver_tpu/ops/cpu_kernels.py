"""Host-side (CPU) sampling engine.

TPU-native replacement for the reference's two host/graph-too-big paths:

- ``quiver<T, CPU>`` OpenMP-style sampler (include/quiver/quiver.cpu.hpp:57-102:
  parallel degree pass + per-seed ``std::sample``) -> the native C++ engine in
  ``quiver_tpu/csrc/quiver_cpu.cpp`` (std::thread parallel, per-thread
  mt19937, partial Fisher-Yates), loaded via ctypes;
- the UVA mode (GPU kernels reading pinned host memory,
  quiver.cu.hpp:16-26) -> "HOST" mode: the graph stays in host DRAM, this
  engine samples it, and padded batches stream to the TPU. TPUs cannot map
  host memory into kernels, so host-side sampling + async H2D is the
  replacement (SURVEY.md section 7.3 item 2).

A pure-numpy fallback keeps everything working when the native lib is not
built; outputs are bit-identical in shape/masking to the TPU path so models
consume either interchangeably.
"""

from __future__ import annotations

import ctypes
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

SENTINEL = np.iinfo(np.int64).max

_LIB = None
_LIB_TRIED = False


def _load_native():
    """Load libquiver_cpu.so, building it on first use if a toolchain is
    around (see csrc/Makefile); else None and numpy fallbacks apply."""
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    csrc = os.path.join(here, "csrc")
    if not os.path.exists(os.path.join(csrc, "libquiver_cpu.so")) and os.path.exists(
        os.path.join(csrc, "Makefile")
    ):
        import subprocess

        try:
            subprocess.run(
                ["make", "-C", csrc],
                check=False,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
                timeout=120,
            )
        except Exception:
            pass
    for cand in (
        os.path.join(csrc, "libquiver_cpu.so"),
        os.path.join(here, "libquiver_cpu.so"),
    ):
        if os.path.exists(cand):
            try:
                lib = ctypes.CDLL(cand)
                lib.qt_sample_layer.argtypes = [
                    ctypes.c_void_p,  # indptr int64*
                    ctypes.c_void_p,  # indices int64*
                    ctypes.c_int64,   # num_nodes
                    ctypes.c_void_p,  # seeds int64*
                    ctypes.c_int64,   # batch
                    ctypes.c_int64,   # k
                    ctypes.c_uint64,  # rng seed
                    ctypes.c_void_p,  # out neighbors int64* [B*k]
                    ctypes.c_void_p,  # out valid uint8* [B*k]
                ]
                lib.qt_sample_layer.restype = None
                lib.qt_gather_rows.argtypes = [
                    ctypes.c_void_p,  # src float32* [N, D]
                    ctypes.c_int64,   # N
                    ctypes.c_int64,   # D
                    ctypes.c_void_p,  # ids int64* [B]
                    ctypes.c_int64,   # B
                    ctypes.c_void_p,  # out float32* [B, D]
                ]
                lib.qt_gather_rows.restype = None
                try:
                    lib.qt_sample_layer_weighted.argtypes = [
                        ctypes.c_void_p,  # indptr int64*
                        ctypes.c_void_p,  # indices int64*
                        ctypes.c_void_p,  # weights float32* (CSR edge order)
                        ctypes.c_int64,   # num_nodes
                        ctypes.c_void_p,  # seeds int64*
                        ctypes.c_int64,   # batch
                        ctypes.c_int64,   # k
                        ctypes.c_uint64,  # rng seed
                        ctypes.c_void_p,  # out neighbors int64* [B*k]
                        ctypes.c_void_p,  # out valid uint8* [B*k]
                    ]
                    lib.qt_sample_layer_weighted.restype = None
                except AttributeError:
                    pass  # stale .so; uniform native path still works
                try:
                    lib.qt_gather_rows_bytes.argtypes = [
                        ctypes.c_void_p,  # src bytes*
                        ctypes.c_int64,   # N rows
                        ctypes.c_int64,   # row bytes
                        ctypes.c_void_p,  # ids int64*
                        ctypes.c_int64,   # batch
                        ctypes.c_void_p,  # out bytes*
                    ]
                    lib.qt_gather_rows_bytes.restype = None
                except AttributeError:
                    pass  # stale .so; f32 gather + numpy fallback still work
                try:
                    lib.qt_reindex.argtypes = [
                        ctypes.c_void_p,  # head int64* [seed_count]
                        ctypes.c_int64,   # seed_count
                        ctypes.c_void_p,  # nbrs int64* [total]
                        ctypes.c_void_p,  # mask uint8* [total]
                        ctypes.c_int64,   # total
                        ctypes.c_void_p,  # out n_id int64* [seed_count+total]
                        ctypes.c_void_p,  # out count int64*
                        ctypes.c_void_p,  # out local int32* [total]
                    ]
                    lib.qt_reindex.restype = None
                except AttributeError:
                    # stale .so from before qt_reindex existed: the numpy
                    # reindex fallback still applies, sampling stays native
                    pass
                _LIB = lib
            except OSError:
                _LIB = None
            break
    return _LIB


def native_available() -> bool:
    return _load_native() is not None


def _np_sample_layer(
    indptr: np.ndarray,
    indices: np.ndarray,
    seeds: np.ndarray,
    k: int,
    seed: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """Numpy fallback for one-hop sampling; exact k-subset w/o replacement,
    copy-all when deg <= k (reference cuda_random.cu.hpp:33-38 semantics)."""
    rng = np.random.default_rng(seed)
    B = seeds.shape[0]
    nbrs = np.zeros((B, k), np.int64)
    valid = np.zeros((B, k), bool)
    # mirror the native guard (csrc/quiver_cpu.cpp): out-of-range seeds
    # produce an invalid (deg=0) row instead of wrapping/raising
    node_count = indptr.shape[0] - 1
    in_range = (seeds >= 0) & (seeds < node_count)
    safe = np.where(in_range, seeds, 0)
    starts = indptr[safe]
    degs = np.where(in_range, indptr[safe + 1] - starts, 0)
    for i in range(B):
        deg = int(degs[i])
        if deg <= 0:
            continue
        start = int(starts[i])
        if deg <= k:
            nbrs[i, :deg] = indices[start : start + deg]
            valid[i, :deg] = True
        else:
            pos = rng.choice(deg, size=k, replace=False)
            nbrs[i] = indices[start + pos]
            valid[i] = True
    return nbrs, valid


def host_reindex(
    seeds: np.ndarray,
    seed_count: int,
    nbrs: np.ndarray,
    mask: np.ndarray,
) -> Tuple[np.ndarray, int, np.ndarray, np.ndarray]:
    """Host mirror of :func:`quiver_tpu.ops.reindex.local_reindex`: returns
    (n_id_unpadded, count, local_nbrs [S,k], nbr_valid). Valid seeds keep
    slots 0..seed_count-1 VERBATIM (duplicates included, reference
    reindex.cu.hpp min-index contract: lookups resolve to the first slot
    holding a value); unique new neighbors follow in ascending-id order —
    the same contract as the device op, so outputs are bit-identical."""
    S, k = nbrs.shape
    seeds = np.asarray(seeds, np.int64)
    head = seeds[:seed_count]
    lib = _load_native()
    if lib is not None and hasattr(lib, "qt_reindex"):
        total = S * k
        head_c = np.ascontiguousarray(head, np.int64)
        nbrs_c = np.ascontiguousarray(nbrs, np.int64)
        mask_c = np.ascontiguousarray(mask, np.uint8)
        n_id_buf = np.empty(seed_count + total, np.int64)
        count_buf = np.zeros(1, np.int64)
        local = np.empty(total, np.int32)
        lib.qt_reindex(
            head_c.ctypes.data, seed_count, nbrs_c.ctypes.data,
            mask_c.ctypes.data, total, n_id_buf.ctypes.data,
            count_buf.ctypes.data, local.ctypes.data,
        )
        count = int(count_buf[0])
        return n_id_buf[:count], count, local.reshape(S, k), mask
    nbr_vals = nbrs[mask]
    new = np.setdiff1d(nbr_vals, head)  # sorted unique, seed values excluded
    count = seed_count + new.shape[0]
    n_id = np.concatenate([head, new])

    # canonical id: first seed slot holding the value, else the rank slot
    local_new = seed_count + np.clip(
        np.searchsorted(new, nbrs), 0, max(new.shape[0] - 1, 0)
    )
    if seed_count > 0:
        uq_s, first_slot = np.unique(head, return_index=True)
        pc = np.clip(np.searchsorted(uq_s, nbrs), 0, uq_s.shape[0] - 1)
        in_seeds = uq_s[pc] == nbrs
        local = np.where(in_seeds, first_slot[pc], local_new)
    else:
        local = local_new
    local_nbrs = np.where(mask, local, 0).astype(np.int32)
    return n_id, count, local_nbrs, mask


class HostSampler:
    """Stateful host engine bound to one CSR graph (reference
    ``CPUQuiver``, srcs/cpp/src/quiver/quiver.cpp:11-38).

    ``weights`` (optional, float32, CSR edge order — e.g.
    ``CSRTopo.edge_weights``) switches every draw to the weighted k-subset
    engine (`qt_sample_layer_weighted`, same Efraimidis-Spirakis/Gumbel
    distribution as the device op). Weighted mode requires the native lib
    (no numpy fallback — the per-row weighted loop would be minutes-slow
    at scale, and silence would hide it)."""

    def __init__(
        self,
        indptr: np.ndarray,
        indices: np.ndarray,
        weights: Optional[np.ndarray] = None,
    ):
        self.indptr = np.ascontiguousarray(indptr, np.int64)
        self.indices = np.ascontiguousarray(indices, np.int64)
        self._lib = _load_native()
        self.weights = None
        if weights is not None:
            if self._lib is None or not hasattr(self._lib, "qt_sample_layer_weighted"):
                raise RuntimeError(
                    "weighted host sampling needs the native engine "
                    "(make -C quiver_tpu/csrc); rebuild libquiver_cpu.so"
                )
            self.weights = np.ascontiguousarray(weights, np.float32)
            if self.weights.shape[0] != self.indices.shape[0]:
                raise ValueError(
                    f"weights has {self.weights.shape[0]} entries for "
                    f"{self.indices.shape[0]} edges"
                )

    @property
    def node_count(self) -> int:
        return self.indptr.shape[0] - 1

    def sample_layer(self, seeds: np.ndarray, k: int, seed: int):
        seeds = np.ascontiguousarray(seeds, np.int64)
        if self._lib is not None:
            B = seeds.shape[0]
            nbrs = np.empty((B, k), np.int64)
            valid_u8 = np.empty((B, k), np.uint8)
            # one arg list for both ABIs: the weighted entry point takes the
            # identical signature with the weights pointer inserted third
            args = [
                self.indptr.ctypes.data,
                self.indices.ctypes.data,
                self.node_count,
                seeds.ctypes.data,
                B,
                k,
                ctypes.c_uint64(seed),
                nbrs.ctypes.data,
                valid_u8.ctypes.data,
            ]
            if self.weights is not None:
                args.insert(2, self.weights.ctypes.data)
                self._lib.qt_sample_layer_weighted(*args)
            else:
                self._lib.qt_sample_layer(*args)
            return nbrs, valid_u8.astype(bool)
        return _np_sample_layer(self.indptr, self.indices, seeds, k, seed)

    def sample_multilayer(
        self,
        seeds: np.ndarray,
        sizes: Sequence[int],
        seed: int,
        caps: Optional[Sequence[Optional[int]]] = None,
    ) -> Tuple[np.ndarray, int, List[Dict]]:
        """Multi-hop sample with the same static padding as the device path
        (single width source: `quiver_tpu.ops.sample.pad_widths`)."""
        from .sample import pad_widths

        B = seeds.shape[0]
        widths = pad_widths(B, sizes, caps)
        width = B
        cur = np.ascontiguousarray(seeds, np.int64)
        cur_count = B
        adjs: List[Dict] = []
        for l, k in enumerate(sizes):
            # sample only the valid prefix; pad the rest
            nbrs_v, valid_v = self.sample_layer(cur[:cur_count], k, seed + l * 1000003)
            nbrs = np.zeros((width, k), np.int64)
            mask = np.zeros((width, k), bool)
            nbrs[:cur_count] = nbrs_v
            mask[:cur_count] = valid_v
            n_id, count, local_nbrs, mask = host_reindex(cur, cur_count, nbrs, mask)
            new_width = widths[l + 1]
            if count > new_width:
                n_id = n_id[:new_width]
                count = new_width
                mask = mask & (local_nbrs < new_width)
            adjs.append(
                dict(cols=local_nbrs, mask=mask, n_src=count, n_dst=cur_count)
            )
            cur = np.full(new_width, SENTINEL, np.int64)
            cur[:count] = n_id
            cur_count = count
            width = new_width
        return cur, cur_count, adjs

    def gather_rows(self, table: np.ndarray, ids: np.ndarray) -> np.ndarray:
        """Parallel host feature gather (cold-tier analog of
        quiver_tensor_gather's host-pointer branch, shard_tensor.cu.hpp:44-55);
        dtype-agnostic via the byte-row engine — see module-level
        :func:`gather_rows`."""
        return gather_rows(table, ids)


def gather_rows(table: np.ndarray, ids: np.ndarray) -> np.ndarray:
    """Module-level host gather using the native lib when possible.

    Dtype-agnostic: any C-contiguous 2-D table goes through the native
    byte-row engine (`qt_gather_rows_bytes`) — bf16 cold tiers included
    (the reference's gather kernel is float32-only,
    quiver_feature.cu:65-69). Out-of-range ids (negative or >= N) return
    zero rows — one contract on EVERY path: the native byte/f32 engines
    zero-fill in C, and the numpy fallback masks invalid ids and zeroes
    their rows so behavior does not depend on which .so is loaded."""
    lib = _load_native()
    ids = np.ascontiguousarray(ids, np.int64)
    plain = (
        table.ndim == 2
        and table.flags.c_contiguous
        and not table.dtype.hasobject  # object rows are PyObject* — memcpy
        #                                would skip refcounting (crash at GC)
    )
    if lib is not None and plain and hasattr(lib, "qt_gather_rows_bytes"):
        out = np.empty((ids.shape[0], table.shape[1]), table.dtype)
        lib.qt_gather_rows_bytes(
            table.ctypes.data,
            table.shape[0],
            table.shape[1] * table.itemsize,
            ids.ctypes.data,
            ids.shape[0],
            out.ctypes.data,
        )
        return out
    if lib is not None and plain and table.dtype == np.float32:
        # stale .so predating qt_gather_rows_bytes: the f32 entry point is
        # still there — keep the hot cold-tier path multi-threaded (and its
        # zero-OOB contract) instead of silently dropping to numpy
        out = np.empty((ids.shape[0], table.shape[1]), np.float32)
        lib.qt_gather_rows(
            table.ctypes.data,
            table.shape[0],
            table.shape[1],
            ids.ctypes.data,
            ids.shape[0],
            out.ctypes.data,
        )
        return out
    # numpy fallback: enforce the same zero-row contract as the native
    # paths (fancy indexing would instead raise on ids >= N and silently
    # wrap negative ids to end-relative rows)
    if table.shape[0] == 0:
        # degenerate zero-row table: every id is out of range, and the
        # np.where(ok, ids, 0) trick below would still index row 0 of an
        # empty table (IndexError) where the native engines zero-fill
        return np.zeros((ids.shape[0], table.shape[1]), table.dtype)
    ok = (ids >= 0) & (ids < table.shape[0])
    if ok.all():
        return np.ascontiguousarray(table[ids])
    out = table[np.where(ok, ids, 0)]
    out[~ok] = 0
    return out

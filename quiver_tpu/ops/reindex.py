"""Dedup + local-id rewrite ("reindex") with static shapes.

Re-design of the reference's GPU ordered hash table
(``include/quiver/reindex.cu.hpp``: DeviceOrderedHashTable atomicCAS insert
keeping the *minimum input index* per key, reindex.cu.hpp:120-139) and the
``reindex_kernel``/``FillWithDuplicates`` pipeline (quiver_sample.cu:202-255,
18-63).

The contract the reference establishes (and PyG relies on):

- ``n_id[:num_seeds] == seeds`` — seeds keep their slots VERBATIM, in order,
  duplicates included (reference reindex.cu.hpp writes seeds straight into
  the output; a duplicate seed still owns its slot while lookups resolve to
  the first slot holding the value);
- the remaining unique nodes follow, each exactly once;
- every sampled neighbor is rewritten to the canonical local id of its value.

The reference orders the non-seed tail by first occurrence (hash insert
order); here the tail is ordered by ascending node id instead — an
implementation detail no consumer depends on (features/labels are always
gathered *through* ``n_id``), chosen because it keeps the whole pass in
sorted space.

TPU cost/compile model (measured on v5e):

- a 1M-element sort RUNS in ~0.3-0.7 ms while a 1M scatter/gather runs in
  ~5-8 ms — so sorts are the only data-movement primitive here, including a
  key-sort standing in for the inverse permutation (never scatter);
- XLA's TPU compile time for million-element 1-D sort/cumsum/scan ops is
  pathological (~12-60 s EACH), while 2-D row ops compile in ~1 s and
  identical sort signatures compile once per shape. The first sort uses
  a (key + 2 payloads) signature (it needs both origin position and seed
  slot); the second and third use a slimmer (key + 1 payload) signature —
  1/3 less data movement per pass, one extra cached compile. All running
  sums/scans are blocked into [rows, 1024] two-level form.

Per hop: three sorts (two signatures) + blocked cumsums + elementwise work,
O(W log W) with tiny constants, fully static shapes, jittable.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

_BLOCK = 1024


def _sort3(key: jax.Array, a: jax.Array, b: jax.Array):
    """Stable sort by ``key`` carrying two payloads (the first pass, which
    genuinely needs both origin position and seed slot)."""
    return lax.sort((key, a, b), num_keys=1, is_stable=True)


def _sort2(key: jax.Array, a: jax.Array):
    """Stable sort by ``key`` carrying ONE payload — the second and third
    reindex passes need only one, and the slimmer tuple moves 1/3 less
    data per pass (measured 6.7 -> 5.8 ms on the 811k hop-3 reindex;
    the extra compiled sort signature is a one-time cache entry)."""
    return lax.sort((key, a), num_keys=1, is_stable=True)


def _blocked(x: jax.Array, fill) -> Tuple[jax.Array, int]:
    """Reshape [W] -> [R, 1024], padding the tail with ``fill``."""
    W = x.shape[0]
    R = -(-W // _BLOCK)
    pad = R * _BLOCK - W
    if pad:
        x = jnp.concatenate([x, jnp.full((pad,), fill, x.dtype)])
    return x.reshape(R, _BLOCK), W


def blocked_cumsum(x: jax.Array) -> jax.Array:
    """Inclusive 1-D cumsum as row-cumsum + row-carry (compiles ~17x faster
    than the 1-D op at W=1M on TPU)."""
    x2, W = _blocked(x, 0)
    row = jnp.cumsum(x2, axis=1)
    carry = jnp.cumsum(row[:, -1])
    carry = jnp.concatenate([jnp.zeros((1,), carry.dtype), carry[:-1]])
    return (row + carry[:, None]).reshape(-1)[:W]


def propagate_group_start(is_start: jax.Array, val: jax.Array) -> jax.Array:
    """For each position t, the ``val`` of the latest position <= t with
    ``is_start`` set — broadcasts a group start's value down its group
    without a gather. Blockwise "latest start wins" associative scan:
    within-row pair scan, tiny cross-row carry scan, elementwise merge."""
    n = val.shape[0]
    pos = jnp.where(is_start, jnp.arange(n, dtype=jnp.int32), -1)
    pos2, _ = _blocked(pos, -1)
    val2, _ = _blocked(val, 0)

    def combine(x, y):
        px, vx = x
        py, vy = y
        take_y = py >= px
        return jnp.where(take_y, py, px), jnp.where(take_y, vy, vx)

    p_row, v_row = lax.associative_scan(combine, (pos2, val2), axis=1)
    pc, vc = lax.associative_scan(combine, (p_row[:, -1], v_row[:, -1]))
    p_prev = jnp.concatenate([jnp.full((1,), -1, jnp.int32), pc[:-1]])
    v_prev = jnp.concatenate([jnp.zeros((1,), val.dtype), vc[:-1]])
    keep = p_row >= p_prev[:, None]
    out = jnp.where(keep, v_row, v_prev[:, None])
    return out.reshape(-1)[:n]


class ReindexResult(NamedTuple):
    n_id: jax.Array        # [cap] node ids: valid seeds verbatim, then unique
                           # new neighbors ascending; sentinel padding
    count: jax.Array       # scalar int32: number of valid entries in n_id
    local_seeds: jax.Array  # [S] output slot of each seed (-1 where invalid)
    local_nbrs: jax.Array  # [S, k] canonical local id of each sampled neighbor
    nbr_valid: jax.Array   # [S, k] validity mask (propagated from sampling)


@functools.partial(jax.jit, static_argnames=())
def local_reindex(
    seeds: jax.Array,
    seed_valid: jax.Array,
    nbrs: jax.Array,
    nbr_valid: jax.Array,
) -> ReindexResult:
    """Build ``n_id`` (valid seeds verbatim, then unique new neighbors
    ascending) and rewrite neighbors to canonical local ids.

    Matches ``TorchQuiver::reindex_single`` semantics
    (quiver_sample.cu:305-357) including duplicate seeds: each valid seed
    keeps its own slot, lookups resolve to the first slot with the value.

    ``seeds`` is [S]; ``nbrs`` is [S, k]. cap = S + S*k.
    """
    S = seeds.shape[0]
    k = nbrs.shape[1]
    W = S + S * k
    idt = jnp.promote_types(seeds.dtype, nbrs.dtype)
    sentinel = jnp.asarray(jnp.iinfo(idt).max, idt)

    seed_valid = seed_valid.astype(bool)
    # output slot of each valid seed (compacted; identity for prefix-valid)
    seed_slot = blocked_cumsum(seed_valid.astype(jnp.int32)) - 1
    n_seed = seed_valid.sum().astype(jnp.int32)

    # Flatten [S, k] TRANSPOSED: XLA's TPU compile time for a [big, tiny]
    # row-major flatten is pathological (~40 s at S=180k, k=5 — a lane-tile
    # relayout), while [k, S] -> flat is layout-preserving (<1 s). Neighbor
    # (i, j) lands at position S + j*S + i; order within the flat array is
    # irrelevant to the contract (ties resolve by slot payload, not
    # position).
    all_nodes = jnp.concatenate([
        jnp.where(seed_valid, seeds.astype(idt), sentinel),
        jnp.where(nbr_valid, nbrs.astype(idt), sentinel).T.reshape(-1),
    ])
    pos = jnp.arange(W, dtype=jnp.int32)
    # payload 2: a seed's output slot, or S for neighbors/invalid
    slotpay = jnp.concatenate([
        jnp.where(seed_valid, seed_slot, S),
        jnp.full((S * k,), S, jnp.int32),
    ])
    sv, order, spay = _sort3(all_nodes, pos, slotpay)

    is_start = jnp.concatenate([jnp.ones((1,), bool), sv[1:] != sv[:-1]])
    valid_sorted = sv != sentinel
    from_seed = order < S

    # new unique = group start that is a (valid) neighbor (stable sort puts
    # any seed with the value first); slots follow the seed block in sorted
    # (ascending id) order — rank is a cumsum, not a second sort
    new_unique = is_start & valid_sorted & ~from_seed
    rank = blocked_cumsum(new_unique.astype(jnp.int32)) - 1
    id_if_start = jnp.where(from_seed, spay, n_seed + rank)
    canonical = propagate_group_start(is_start, id_if_start)

    # back to input order: sort by original position (the inverse
    # permutation as a key-sort — scatters are ~15x a sort on TPU)
    _, local_all = _sort2(order, canonical)
    # n_id: sort values by output slot (valid seeds -> their slot, new
    # uniques -> their rank slot, everything else -> past the end)
    outkey = jnp.where(
        valid_sorted & from_seed,
        spay,
        jnp.where(new_unique, n_seed + rank, W),
    )
    outval = jnp.where(outkey < W, sv, sentinel)
    _, n_id = _sort2(outkey, outval)

    count = n_seed + new_unique.sum().astype(jnp.int32)
    return ReindexResult(
        n_id=n_id,
        count=count,
        local_seeds=jnp.where(seed_valid, seed_slot, -1),
        local_nbrs=local_all[S:].reshape(k, S).T,
        nbr_valid=nbr_valid,
    )


def reindex_single(
    seeds: jax.Array, inputs: jax.Array, counts=None
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Free-function analog of the reference's standalone ``reindex_single``
    (quiver_sample.cu:305-357): given seeds and their sampled neighbors,
    return (n_id, count, local_ids_of_inputs).

    ``inputs`` is either a padded ``[S, k]`` matrix, or the reference's
    FLAT ragged concatenation — in which case ``counts`` (neighbors per
    seed, the shape the reference call sites actually pass) is REQUIRED
    unless the flat length happens to be uniform: a flat ragged list whose
    length is coincidentally divisible by S must not be silently gridded.
    Returned local ids for a ragged input are the positions of the real
    (unpadded) entries, in input order.
    """
    S = seeds.shape[0]
    if inputs.ndim == 2:
        res = local_reindex(
            seeds, jnp.ones((S,), bool), inputs, jnp.ones(inputs.shape, bool)
        )
        return res.n_id, res.count, res.local_nbrs.reshape(-1)
    if counts is None:
        if inputs.shape[0] % S != 0:
            raise ValueError(
                f"flat ragged neighbor list (len {inputs.shape[0]}, {S} "
                f"seeds): pass counts= (neighbors per seed) — guessing a "
                f"uniform [S, k] grid would mis-assign neighbors"
            )
        flat = inputs.reshape(S, -1)
        res = local_reindex(
            seeds, jnp.ones((S,), bool), flat, jnp.ones(flat.shape, bool)
        )
        return res.n_id, res.count, res.local_nbrs.reshape(-1)
    counts = np.asarray(counts)
    if counts.shape[0] != S or int(counts.sum()) != inputs.shape[0]:
        raise ValueError(
            f"counts {counts.shape}/{int(counts.sum())} inconsistent with "
            f"{S} seeds and {inputs.shape[0]} flat neighbors"
        )
    k = max(int(counts.max()), 1) if S else 1
    flat_np = np.asarray(inputs)
    padded = np.zeros((S, k), flat_np.dtype)
    mask = np.zeros((S, k), bool)
    off = 0
    for i, c in enumerate(counts):
        padded[i, : int(c)] = flat_np[off : off + int(c)]
        mask[i, : int(c)] = True
        off += int(c)
    res = local_reindex(seeds, jnp.ones((S,), bool), jnp.asarray(padded), jnp.asarray(mask))
    return res.n_id, res.count, np.asarray(res.local_nbrs)[np.asarray(mask)]

"""Dedup + local-id rewrite ("reindex") with static shapes.

Re-design of the reference's GPU ordered hash table
(``include/quiver/reindex.cu.hpp``: DeviceOrderedHashTable atomicCAS insert
keeping the *minimum input index* per key, reindex.cu.hpp:120-139) and the
``reindex_kernel``/``FillWithDuplicates`` pipeline (quiver_sample.cu:202-255,
18-63).

The contract the reference establishes (and PyG relies on):

- ``n_id[:num_seeds] == seeds`` — seeds keep their slots, in order;
- the remaining unique nodes follow in first-occurrence order;
- every input element is rewritten to its local id in ``n_id``.

On TPU, open-addressing hash tables are a poor fit (scatter-heavy, atomics);
the XLA-native formulation is sort-based: ``jnp.unique`` with a static
``size=`` cap, then a segment-min of input positions to recover
first-occurrence order. Invalid (padding) slots carry a ``sentinel`` value and
are pushed to the tail. Everything is jittable with static shapes.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class ReindexResult(NamedTuple):
    n_id: jax.Array        # [cap] unique node ids, seeds first, sentinel-padded
    count: jax.Array       # scalar int32: number of valid entries in n_id
    local_seeds: jax.Array  # [S] local id of each seed (== arange(S) for valid, unique seeds)
    local_nbrs: jax.Array  # [S, k] local id of each sampled neighbor
    nbr_valid: jax.Array   # [S, k] validity mask (propagated from sampling)


@functools.partial(jax.jit, static_argnames=())
def local_reindex(
    seeds: jax.Array,
    seed_valid: jax.Array,
    nbrs: jax.Array,
    nbr_valid: jax.Array,
) -> ReindexResult:
    """Build ``n_id`` (seeds first, then first-occurrence-ordered unique
    neighbors) and rewrite seeds/neighbors to local ids.

    Matches ``TorchQuiver::reindex_single`` semantics
    (quiver_sample.cu:305-357) for valid, duplicate-free seeds.

    ``seeds`` is [S]; ``nbrs`` is [S, k]. cap = S + S*k.
    """
    S = seeds.shape[0]
    k = nbrs.shape[1]
    cap = S + S * k
    idt = jnp.promote_types(seeds.dtype, nbrs.dtype)
    sentinel = jnp.asarray(jnp.iinfo(idt).max, idt)

    all_nodes = jnp.concatenate([
        jnp.where(seed_valid, seeds.astype(idt), sentinel),
        jnp.where(nbr_valid, nbrs.astype(idt), sentinel).reshape(-1),
    ])
    all_valid = jnp.concatenate([seed_valid, nbr_valid.reshape(-1)])

    uniq, inv = jnp.unique(all_nodes, return_inverse=True, size=cap, fill_value=sentinel)
    # first-occurrence position per unique value; invalid inputs pushed past cap
    pos = jnp.where(all_valid, jnp.arange(cap, dtype=jnp.int32), cap)
    first = jnp.full((cap,), cap, jnp.int32).at[inv].min(pos)
    order = jnp.argsort(first)            # stable; valid uniques in input order
    rank = jnp.zeros((cap,), jnp.int32).at[order].set(jnp.arange(cap, dtype=jnp.int32))
    local_all = jnp.take(rank, inv)
    n_id = jnp.take(uniq, order)
    count = (first < cap).sum().astype(jnp.int32)
    return ReindexResult(
        n_id=n_id,
        count=count,
        local_seeds=local_all[:S],
        local_nbrs=local_all[S:].reshape(S, k),
        nbr_valid=nbr_valid,
    )


def reindex_single(seeds: jax.Array, inputs: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Free-function analog of the reference's standalone ``reindex_single``
    (quiver_sample.cu:305-357): given seeds and a flat neighbor array (one
    row already implied), return (n_id, count, local_ids_of_inputs)."""
    S = seeds.shape[0]
    flat = inputs.reshape(S, -1) if inputs.ndim == 1 and inputs.shape[0] % S == 0 else inputs
    if flat.ndim == 1:
        flat = flat[None, :]
    res = local_reindex(
        seeds,
        jnp.ones((S,), bool),
        flat,
        jnp.ones(flat.shape, bool),
    )
    return res.n_id, res.count, res.local_nbrs.reshape(-1)

"""Device-side k-hop neighbor sampling, XLA/TPU-native.

Re-design of the reference's CUDA sampling pipeline
(``srcs/cpp/src/quiver/cuda/quiver_sample.cu:134-200`` sample_kernel and the
warp-per-row reservoir kernel ``include/quiver/cuda_random.cu.hpp:7-69``).

The reference pipeline is ragged: per-seed degree pass -> cap -> exclusive scan
-> ragged output buffer. XLA demands static shapes, so the TPU design returns a
dense padded ``[B, k]`` neighbor matrix plus a validity mask:

- ``deg <= k``  -> copy-all (positions ``0..deg-1`` valid), matching the
  copy-all branch of the reference kernel (cuda_random.cu.hpp:33-38);
- ``deg > k``   -> an exact uniform k-subset without replacement, matching the
  reservoir-sampling branch (cuda_random.cu.hpp:40-60) in distribution.

The without-replacement draw uses a vectorised *partial Fisher-Yates* over a
virtual ``arange(deg)`` permutation: slot values below ``k`` live in a dense
``head`` array, swaps landing at ``j >= k`` are recorded in a k-entry override
table (at most one new override per step). This is O(k^2) vector work per row
(k <= 32 in practice) with fully static shapes — no per-row data-dependent
control flow, so the whole thing fuses into a handful of XLA ops.

All functions are jittable; the padded output feeds the dense reindex pass
(`quiver_tpu.ops.reindex`) and the padded-[B,k] GraphSAGE aggregation
(`quiver_tpu.models.sage`), which turns sparse segment ops into dense
reshape+mean — the TPU-friendly formulation (SURVEY.md section 7.1).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

LANE = 128  # native int32 lane width — the tile-layout row size


def pad_widths(batch: int, sizes, caps=None):
    """Static padded n_id widths per hop: ``W_{l+1} = min(cap_l, W_l*(1+k_l))``.

    Single source of truth for the shape contract shared by the device
    pipeline (`quiver_tpu.pyg.sage_sampler.sample_dense_pure`) and the host
    engine (`quiver_tpu.ops.cpu_kernels.HostSampler.sample_multilayer`) —
    their outputs must be bit-identical in shape/masking.
    """
    widths = [int(batch)]
    for l, k in enumerate(sizes):
        w = widths[-1] * (1 + int(k))
        if caps is not None and caps[l] is not None:
            w = min(w, int(caps[l]))
        widths.append(w)
    return widths


def row_windows(indptr: jax.Array, s: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(row start, degree) for CLIPPED node ids ``s`` — as ONE dim-2 gather
    instead of two element gathers. TPU gathers are descriptor-rate bound
    and width-invariant up to ~128 lanes (PERF_NOTES.md), so pairing
    (indptr[i], indptr[i+1]) into an [N, 2] table halves the degree-lookup
    descriptors (measured 43.6 -> 41.5 ms on the products e2e step). The
    stack is loop-invariant: CSE'd across hops and hoisted out of epoch
    scans. The ONE implementation — every sampler (uniform, weighted,
    sharded) goes through it."""
    pp = jnp.stack([indptr[:-1], indptr[1:]], axis=1)
    both = jnp.take(pp, s, axis=0)
    return both[:, 0], (both[:, 1] - both[:, 0]).astype(jnp.int32)


def fisher_yates_positions(key: jax.Array, deg: jax.Array, k: int) -> Tuple[jax.Array, jax.Array]:
    """Draw, for each row ``b``, ``min(deg[b], k)`` distinct positions in
    ``[0, deg[b])``.

    Returns ``(pos, valid)`` with ``pos`` int32 ``[B, k]`` and ``valid`` bool
    ``[B, k]``. For rows with ``deg <= k`` positions are ``0..deg-1`` in order
    (copy-all semantics). For ``deg > k`` positions are an exact uniform
    k-subset, in random order.
    """
    deg = deg.astype(jnp.int32)
    B = deg.shape[0]
    ar_k = jnp.arange(k, dtype=jnp.int32)

    if k == 0:
        return (jnp.zeros((B, 0), jnp.int32), jnp.zeros((B, 0), bool))

    us = jax.random.uniform(key, (k, B))

    def step(state, inp):
        head, tail_j, tail_v, cnt = state
        i, u = inp
        span = jnp.maximum(deg - i, 1)
        j = i + (u * span.astype(u.dtype)).astype(jnp.int32)
        j = jnp.minimum(j, jnp.maximum(deg - 1, 0))
        in_head = j < k
        # one-hot select, NOT take_along_axis: a per-row dynamic lane read
        # lowers to a B-descriptor gather per scan step (~5 ms/hop at
        # products hop-3 shape — measured, scripts/probe_fetch_final.py);
        # the one-hot compare+sum is pure VPU work
        head_val = jnp.where(ar_k[None, :] == j[:, None], head, 0).sum(axis=1)
        match = tail_j == j[:, None]  # [B, k]
        has_match = match.any(axis=1)
        tail_val = jnp.where(has_match, jnp.where(match, tail_v, 0).sum(axis=1), j)
        val_j = jnp.where(in_head, head_val, tail_val)
        val_i = head[:, i]
        # a[j] = a[i]
        onehot_j = (ar_k[None, :] == j[:, None]) & in_head[:, None]
        head = jnp.where(onehot_j, val_i[:, None], head)
        # a[i] = a[j] (slot i is never drawn again but keep the permutation honest)
        head = head.at[:, i].set(val_j)
        slot = jnp.where(has_match, jnp.argmax(match, axis=1).astype(jnp.int32), cnt)
        write_tail = ~in_head
        onehot_s = (ar_k[None, :] == slot[:, None]) & write_tail[:, None]
        tail_j = jnp.where(onehot_s, j[:, None], tail_j)
        tail_v = jnp.where(onehot_s, val_i[:, None], tail_v)
        cnt = cnt + (write_tail & ~has_match).astype(jnp.int32)
        return (head, tail_j, tail_v, cnt), val_j

    init = (
        jnp.broadcast_to(ar_k, (B, k)),
        jnp.full((B, k), -1, jnp.int32),
        jnp.zeros((B, k), jnp.int32),
        jnp.zeros((B,), jnp.int32),
    )
    _, outs = lax.scan(step, init, (ar_k, us))
    pos = outs.T  # [B, k]
    # copy-all override for low-degree rows (reference cuda_random.cu.hpp:33-38)
    pos = jnp.where(deg[:, None] <= k, ar_k[None, :], pos)
    valid = ar_k[None, :] < jnp.minimum(deg, k)[:, None]
    return pos, valid


def gumbel_topk_positions(
    key: jax.Array, deg: jax.Array, k: int, weight_rows: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """Weighted without-replacement k-subset per row via Gumbel top-k.

    The XLA formulation of the reference's ``weight_sample`` kernel
    (cuda_random.cu.hpp:177-221): drawing k items without replacement with
    probability proportional to weights (successive/Plackett-Luce sampling)
    is exactly taking the top-k of ``log w_i + Gumbel(0,1)`` — no sequential
    draw loop, one sort-free `lax.top_k`.

    weight_rows: ``[B, W]`` per-row candidate weights (garbage beyond
    ``deg[b]`` is masked). Rows with ``deg <= k`` return all their
    candidates (copy-all, like the uniform sampler). Returns ``(pos, valid)``
    with positions into ``[0, W)``.
    """
    B, W = weight_rows.shape
    if k == 0:
        return (jnp.zeros((B, 0), jnp.int32), jnp.zeros((B, 0), bool))
    u = jax.random.uniform(key, (B, W), minval=1e-20, maxval=1.0)
    g = -jnp.log(-jnp.log(u))
    w = jnp.maximum(weight_rows.astype(jnp.float32), 0.0)
    scores = jnp.where(
        (jnp.arange(W, dtype=jnp.int32)[None, :] < deg[:, None]) & (w > 0),
        jnp.log(jnp.maximum(w, 1e-30)) + g,
        -jnp.inf,
    )
    vals, pos = lax.top_k(scores, k)
    n_valid = jnp.minimum(deg, k)
    # zero-weight candidates are never valid draws; count only finite
    # scores — read off top_k's OWN values (a take_along_axis here would
    # lower to a B*k-descriptor gather; the values are already in hand)
    finite = vals > -jnp.inf
    valid = (jnp.arange(k, dtype=jnp.int32)[None, :] < n_valid[:, None]) & finite
    return pos.astype(jnp.int32), valid


@functools.partial(jax.jit, static_argnames=("k", "max_deg"))
def weighted_sample_layer(
    indptr: jax.Array,
    indices: jax.Array,
    weights: jax.Array,
    seeds: jax.Array,
    seed_valid: jax.Array,
    k: int,
    key: jax.Array,
    max_deg: int = 512,
) -> Tuple[jax.Array, jax.Array]:
    """One-hop WEIGHTED neighbor sample (reference quiver.cu.hpp:61-82
    bucketed weights + cuda_random.cu.hpp:177-221 weight_sample).

    ``weights`` [E] edge weights aligned with ``indices``. Static-shape
    tradeoff: each row considers its first ``min(deg, max_deg)`` neighbors
    (one ``[B, max_deg]`` lane window instead of the reference's dynamic
    bucket machinery) — set ``max_deg`` >= the graph's max degree for exact
    semantics; heavier-degree tails are truncated and a row's sample then
    comes from its first ``max_deg`` edges.
    """
    n = indptr.shape[0] - 1
    s = jnp.clip(seeds, 0, n - 1).astype(indptr.dtype)
    ptr, deg = row_windows(indptr, s)
    deg = jnp.where(seed_valid, jnp.minimum(deg, max_deg), 0)
    lanes = ptr[:, None] + jnp.arange(max_deg, dtype=ptr.dtype)[None, :]
    lanes = jnp.clip(lanes, 0, indices.shape[0] - 1)
    w_rows = jnp.take(weights, lanes)
    pos, valid = gumbel_topk_positions(key, deg, k, w_rows)
    # NOT take_along_axis (a [B, k] per-row dynamic lane read lowers to a
    # B*k-descriptor gather — the round-5 trap, PERF_NOTES.md grep rule) and
    # not even the one-hot compare+sum: the lane window is AFFINE in the
    # drawn position (lanes[b, p] == clip(ptr[b] + p)), so the select is
    # plain address arithmetic — zero descriptors, bit-identical flat ids
    flat = jnp.clip(
        ptr[:, None] + pos.astype(ptr.dtype),
        0,
        jnp.asarray(indices.shape[0] - 1, ptr.dtype),
    )
    nbrs = jnp.take(indices, flat)
    return nbrs, valid


def _tiled_bd_lookup(bd, seeds, seed_valid):
    """(base, deg) rows for clipped seeds; deg zeroed where invalid."""
    n = bd.shape[0]
    s = jnp.clip(seeds, 0, n - 1).astype(jnp.int32)
    both = jnp.take(bd, s, axis=0)
    return both[:, 0], jnp.where(seed_valid, both[:, 1], 0)


def _tiled_resolve(tiles, base, pos, k):
    """Resolve drawn positions to neighbor ids through the tile table:
    k-split row gathers + one-hot lane selects (k separate [B]-row
    gathers measured faster than one [B*k]: probe_tiled_variants 6.2 vs
    7.1 ms; one-hot instead of take_along_axis — the descriptor trap,
    probe_fetch_final). Shared by the uniform and weighted tiled layers
    so the fetch pattern is tuned in ONE place."""
    rows = base[:, None] + lax.shift_right_logical(pos, LANE.bit_length() - 1)
    rows = jnp.clip(rows, 0, tiles.shape[0] - 1)
    lane = jnp.bitwise_and(pos, LANE - 1)
    ar = jnp.arange(LANE, dtype=jnp.int32)
    cols = []
    for j in range(k):
        win = jnp.take(tiles, rows[:, j], axis=0)
        oh = lane[:, j][:, None] == ar[None, :]
        cols.append(jnp.where(oh, win, 0).sum(axis=1))
    return jnp.stack(cols, axis=1).astype(tiles.dtype)


@functools.partial(jax.jit, static_argnames=("k", "max_deg"))
def tiled_weighted_sample_layer(
    bd: jax.Array,
    tiles: jax.Array,
    wtiles: jax.Array,
    seeds: jax.Array,
    seed_valid: jax.Array,
    k: int,
    key: jax.Array,
    max_deg: int = 512,
) -> Tuple[jax.Array, jax.Array]:
    """Weighted one-hop sample over the tile layout.

    ``wtiles`` is the weights array laid out with the SAME tile map as
    ``tiles`` (`build_tiled_host(indptr, weights, np.float32)`), so each
    row's first ``ceil(max_deg/128)`` weight tiles arrive as row gathers
    — ~128x fewer descriptors than the flat path's [B, max_deg] lane
    window — and chosen positions resolve like `tiled_sample_layer`.
    Draw-identical to :func:`weighted_sample_layer` on the same key when
    ``max_deg`` is a multiple of 128 (same [B, max_deg] Gumbel shape,
    same scores, same top-k). Same truncation semantics: each row
    considers its first ``min(deg, max_deg)`` edges.
    """
    base, deg = _tiled_bd_lookup(bd, seeds, seed_valid)
    deg = jnp.minimum(deg, max_deg)
    w_rows = _tiled_payload_window(base, wtiles, max_deg)
    pos, valid = gumbel_topk_positions(key, deg, k, w_rows)
    return _tiled_resolve(tiles, base, pos, k), valid


def _tiled_payload_window(base, ptiles, max_deg: int):
    """Each row's first ``ceil(max_deg/128)`` PAYLOAD tiles as one
    ``[B, T*128]`` window: T per-row tile fetches, k-split style — a
    [B, T] 3-D gather compiles pathologically, see `_tiled_resolve`.
    The ONE payload-window fetch (weights and timestamps both ride it;
    the temporal-vs-weighted bit-parity pin depends on the two never
    diverging)."""
    T = -(-max_deg // LANE)
    m_rows = ptiles.shape[0]
    parts = []
    for t in range(T):
        tr = jnp.clip(base + t, 0, m_rows - 1)
        parts.append(jnp.take(ptiles, tr, axis=0))
    return jnp.concatenate(parts, axis=1)  # [B, T*128] >= max_deg


@functools.partial(jax.jit, static_argnames=("k",))
def sample_layer(
    indptr: jax.Array,
    indices: jax.Array,
    seeds: jax.Array,
    seed_valid: jax.Array,
    k: int,
    key: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """One-hop sample for every valid seed.

    Equivalent of ``TorchQuiver::sample_neighbor`` (quiver_sample.cu:113-132):
    degree lookup, position draw, neighbor gather — all dense.

    Parameters
    ----------
    indptr : [N+1] int array in HBM
    indices : [E] int array in HBM
    seeds : [B] int array (garbage allowed where ``~seed_valid``)
    seed_valid : [B] bool
    k : static fanout

    Returns
    -------
    nbrs : [B, k] same dtype as ``indices``; garbage where invalid
    valid : [B, k] bool
    """
    n = indptr.shape[0] - 1
    s = jnp.clip(seeds, 0, n - 1).astype(indptr.dtype)
    ptr, deg = row_windows(indptr, s)
    deg = jnp.where(seed_valid, deg, 0)
    pos, valid = fisher_yates_positions(key, deg, k)
    flat = ptr[:, None] + pos.astype(ptr.dtype)
    flat = jnp.clip(flat, 0, jnp.asarray(indices.shape[0] - 1, ptr.dtype))
    nbrs = jnp.take(indices, flat)
    return nbrs, valid


def build_tiled_host(
    indptr: "np.ndarray", indices: "np.ndarray", id_dtype=None
) -> Tuple["np.ndarray", "np.ndarray"]:
    """Host-side build of the LANE-aligned edge-tile layout.

    Each node's edge list is copied to start at a 128-lane row boundary
    of a ``[M, 128]`` tile table; a ``[N, 2]`` (tile_base, degree) int32
    table replaces indptr for sampling. Sampled position ``p`` of node
    ``i`` then lives at tile row ``base[i] + p // 128``, lane ``p % 128``
    — so the neighbor fetch becomes 2-D ROW gathers (measured ~115-145M
    rows/s on v5e) + an in-register one-hot lane select, instead of
    one-element gathers (~45-90M/s): scripts/probe_rowgather_width.py,
    probe_tiled_variants.py, probe_fetch_final.py. Exact for every
    degree — no copy-all/hub split. Memory: ceil-padding to 128 costs
    ~(E + 64*N)/E x the flat CSR (products: 1.45 GB vs 0.49 GB).

    Replaces the flat-CSR read path of the reference's sample_kernel
    (srcs/cpp/src/quiver/cuda/quiver_sample.cu:134-200) — GPU warps read
    ragged rows through UVA/HBM fine, TPU DMA wants tiled rows.

    Returns ``(bd, tiles)``: bd ``[N, 2]`` int32, tiles ``[M, 128]`` of
    ``id_dtype`` (int32 when node ids fit).
    """
    import numpy as np

    if id_dtype is None:
        from ..utils import _best_id_dtype

        id_dtype = _best_id_dtype(indptr.shape[0])  # node ids, not edge ids
    bd, M = tiled_base_host(indptr)
    base = bd[:, 0].astype(np.int64)
    deg = bd[:, 1].astype(np.int64)
    tiles = np.zeros((M, LANE), np.dtype(id_dtype))
    out_pos = (
        np.repeat(base * LANE, deg)
        + np.arange(len(indices), dtype=np.int64)
        - np.repeat(indptr[:-1].astype(np.int64), deg)
    )
    tiles.reshape(-1)[out_pos] = indices.astype(id_dtype, copy=False)
    return bd, tiles


@jax.jit
def build_tiled_device(
    indices: jax.Array, row_start: jax.Array, row_width: jax.Array
) -> jax.Array:
    """Build the ``[M, 128]`` tile table ON DEVICE from a flat indices
    array already in HBM (the host build + H2D of `build_tiled_host`
    costs ~25-45 s of tile-table transfer through a thin link; this is
    one [M, 128] gather on-chip, ~seconds).

    ``row_start``/``row_width``: per-TILE-ROW flat edge offset and valid
    lane count, host-computed by `tiled_rowmap_host` (cheap [M] numpy
    work, ~20 MB upload). Deliberately gather-only: the scatter/scan
    formulation of this build compiled pathologically on TPU (>25 min —
    big 1-D scatters, the same wall ops/reindex.py documents for 1-D
    million-element ops).
    """
    e = indices.shape[0]
    lanes = jnp.arange(LANE, dtype=row_start.dtype)
    g = row_start[:, None] + lanes[None, :]
    vals = jnp.take(indices, jnp.clip(g, 0, e - 1))
    return jnp.where(lanes[None, :] < row_width[:, None], vals, 0)


def tiled_base_host(indptr) -> Tuple["np.ndarray", int]:
    """Host half of the tile build: ``(bd [N,2] int32, m_rows)``."""
    import numpy as np

    deg = np.diff(indptr).astype(np.int64)
    rows_per = -(-deg // LANE)
    base = np.zeros(len(deg) + 1, np.int64)
    np.cumsum(rows_per, out=base[1:])
    if base[-1] > np.iinfo(np.int32).max:
        raise ValueError(f"tile row count {base[-1]} exceeds int32")
    bd = np.stack([base[:-1].astype(np.int32), deg.astype(np.int32)], axis=1)
    return bd, max(int(base[-1]), 1)


def tiled_rowmap_host(indptr):
    """Per-tile-row (flat_edge_start, valid_lane_count) for
    `build_tiled_device`: row r of the tile table holds edges
    ``[start[r], start[r] + width[r])`` of its owner node. Row
    accounting comes from `tiled_base_host` — one definition of the
    base/degree math."""
    import numpy as np

    bd, M = tiled_base_host(indptr)
    base = bd[:, 0].astype(np.int64)
    deg = bd[:, 1].astype(np.int64)
    rows_per = -(-deg // LANE)
    owner = np.repeat(np.arange(len(deg), dtype=np.int64), rows_per)
    if owner.shape[0] == 0:  # empty graph: one all-padding row
        return np.zeros(1, np.int64), np.zeros(1, np.int32)
    t = np.arange(M, dtype=np.int64) - base[owner]
    start = indptr[:-1][owner] + t * LANE
    width = np.minimum(indptr[1:][owner] - start, LANE).astype(np.int32)
    return start, width


def temporal_edge_weights(ts: jax.Array, recency: float) -> jax.Array:
    """Recency weight per edge from its timestamp: ``exp(recency * ts)``
    — the Plackett-Luce weight the temporal sampler hands the SAME
    Gumbel top-k the weighted sampler rides (a draw then prefers recent
    edges with half-life ``ln(2)/recency`` in timestamp units;
    ``recency=0`` is uniform over the valid set, exactly 1.0 per edge).
    The query time ``t`` never enters the weight — ``exp(recency*(ts-t))``
    differs from this by a per-row constant factor, which top-k ignores —
    so at ``t=inf`` a temporal draw IS a weighted draw over these
    weights, bit for bit (the frozen==temporal-at-t=inf parity pin in
    tests/test_temporal.py). One definition shared by the device layer,
    the host-masked oracle, and `recency weight-tile` builds, so the
    float32 exp is always the same elementwise op on the same inputs.
    Timestamps must keep ``recency * ts`` within float32 exp range
    (|x| < ~87); scale epochs accordingly."""
    if recency == 0.0:
        return jnp.ones_like(ts, jnp.float32)
    return jnp.exp(jnp.float32(recency) * ts.astype(jnp.float32))


def temporal_weight_rows(
    ts_rows: jax.Array, t: jax.Array, recency: float, cutoff=None
) -> jax.Array:
    """The masked weight window of a temporal draw: recency weights where
    ``ts <= t`` (per-row query times ``t`` [B] broadcast over lanes),
    0 elsewhere — zero weight is exactly how `gumbel_topk_positions`
    already excludes a candidate, so "sample edges with ts <= t" costs
    ONE where. Shared by `tiled_temporal_sample_layer` and the host-
    masked oracle (`workloads.temporal.host_masked_oracle`): both build
    their ``[B, W]`` timestamp windows differently (tile fetch vs host
    CSR slices) but weight them through this one function, which is what
    makes the oracle a bit-parity pin on the tile path.

    ``cutoff`` (scalar, optional) additionally excludes ``ts <= cutoff``
    — the sliding-window band mask ``cutoff < ts <= t``. This is the
    bit-dual of round-21 retention: `stream.expire_edges(cutoff)`
    rewrites expired lanes' ts to ``+inf`` (masked here by ``ts <= t``
    at any finite t), and because the Gumbel uniform stream is
    positional and weights agree lane-for-lane on the survivors, an
    expired stream draws bit-identically to its unexpired twin queried
    through this band (pinned in tests/test_lifecycle.py)."""
    w = temporal_edge_weights(ts_rows, recency)
    keep = ts_rows.astype(jnp.float32) <= t[:, None]
    if cutoff is not None:
        keep = keep & (
            ts_rows.astype(jnp.float32) > jnp.float32(cutoff)
        )
    return jnp.where(keep, w, 0.0)


@functools.partial(jax.jit, static_argnames=("k", "max_deg", "recency"))
def tiled_temporal_sample_layer(
    bd: jax.Array,
    tiles: jax.Array,
    ttiles: jax.Array,
    seeds: jax.Array,
    seed_valid: jax.Array,
    k: int,
    key: jax.Array,
    t: jax.Array,
    max_deg: int = 512,
    recency: float = 0.0,
    cutoff=None,
) -> Tuple[jax.Array, jax.Array]:
    """TEMPORAL one-hop sample over the tile layout (ROADMAP item 4):
    draw k neighbors per seed among edges with ``ts <= t``, recency-
    biased via the existing Gumbel machinery. ``cutoff`` (optional
    traced scalar) narrows the draw to the ``cutoff < ts <= t`` band —
    the retention duality surface (`temporal_weight_rows`).

    ``ttiles`` is the per-edge timestamp payload laid out with the SAME
    tile map as ``tiles`` (`build_tiled_host(indptr, edge_ts,
    np.float32)`) — timestamps ride the payload lanes exactly like the
    round-5 edge weights, so the fetch is the weighted layer's fetch
    verbatim and positions resolve through the same `_tiled_resolve`.
    ``t`` is a ``[B]`` float32 of per-SEED query times — a traced jit
    ARGUMENT, never a static constant (the NEXT.md rule: one compiled
    program serves every query time), so multi-hop pipelines thread each
    request's own t down its frontier lineage
    (`workloads.temporal.temporal_sample_dense`).

    Draw semantics: among a row's first ``min(deg, max_deg)`` edges,
    every edge with ``ts <= t[row]`` scores ``log w + Gumbel`` with
    ``w = temporal_edge_weights(ts, recency)``; edges beyond t (or
    recency-underflowed to weight 0) are excluded exactly like
    zero-weight edges in the weighted sampler. At ``t = +inf`` the mask
    passes everything and the draw is BIT-EQUAL to
    `tiled_weighted_sample_layer` over weight tiles
    ``temporal_edge_weights(ttiles, recency)`` on the same key — the
    frozen-graph parity pin. Rows whose valid-edge count is below k
    return all their valid edges (copy-all, like every sampler here)."""
    base, deg = _tiled_bd_lookup(bd, seeds, seed_valid)
    deg = jnp.minimum(deg, max_deg)
    ts_rows = _tiled_payload_window(base, ttiles, max_deg)
    w_rows = temporal_weight_rows(ts_rows, t.astype(jnp.float32), recency,
                                  cutoff=cutoff)
    pos, valid = gumbel_topk_positions(key, deg, k, w_rows)
    return _tiled_resolve(tiles, base, pos, k), valid


@functools.partial(jax.jit, static_argnames=("k",))
def tiled_sample_layer(
    bd: jax.Array,
    tiles: jax.Array,
    seeds: jax.Array,
    seed_valid: jax.Array,
    k: int,
    key: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """One-hop sample over the LANE-aligned tile layout (`build_tiled_host`).

    Draw-identical to :func:`sample_layer` on the same key (same
    Fisher-Yates positions; only the fetch path differs): positions are
    resolved via k 2-D row gathers + one-hot lane selects. Measured at
    products hop-3 shape: fetch 6.5 vs 9.0 ms (scripts/probe_fetch_final.py).
    """
    base, deg = _tiled_bd_lookup(bd, seeds, seed_valid)
    pos, valid = fisher_yates_positions(key, deg, k)
    return _tiled_resolve(tiles, base, pos, k), valid


def neighbor_prob(
    indptr: jax.Array,
    indices: jax.Array,
    prob: jax.Array,
    k: int,
    *,
    edge_chunk: int = 1 << 22,
) -> jax.Array:
    """One step of sampling-probability propagation.

    Equivalent of ``cal_neighbor_prob``/``cal_next``
    (quiver_sample.cu:100-111, cuda_random.cu.hpp:71-104): given P(node is in
    the sampled frontier) per node, propagate to neighbors — each sampled node
    u touches neighbor v with probability ``min(k/deg(u), 1)``, accumulated as
    ``next[v] += prob[u] * min(k/deg(u), 1)``.

    In XLA this is a flat edge-parallel segment-sum over the CSR (the TPU-native
    replacement for the atomicAdd kernel). Chunked over edges with a
    ``lax.fori_loop`` so the traced program holds ONE chunk body regardless of
    graph size (an unrolled Python loop would bake 15+ scatter-adds into the
    graph at products scale, worse at papers100M scale).
    """
    n = indptr.shape[0] - 1
    e = indices.shape[0]
    if e == 0:
        return jnp.zeros((n,), jnp.float32)
    deg = (indptr[1:] - indptr[:-1]).astype(jnp.float32)
    w = prob * jnp.minimum(k / jnp.maximum(deg, 1.0), 1.0)  # weight per src node
    chunk = min(edge_chunk, e)
    nchunks = -(-e // chunk)

    def body(c, out):
        # chunks cover [c*chunk, (c+1)*chunk); the final chunk's start is
        # clamped so the static-size slice stays in bounds, and lanes the
        # previous chunk already covered are masked out
        start_u = c * chunk
        start = jnp.minimum(start_u, e - chunk)
        eidx = start + jnp.arange(chunk, dtype=indptr.dtype)
        fresh = eidx >= start_u
        # edge i belongs to row searchsorted(indptr, i, 'right')-1
        src = jnp.searchsorted(indptr, eidx, side="right") - 1
        dst = lax.dynamic_slice(indices, (start,), (chunk,))
        dst = jnp.where(fresh, dst, n)  # n is out of range -> dropped
        return out.at[dst].add(jnp.where(fresh, jnp.take(w, src), 0.0), mode="drop")

    return lax.fori_loop(0, nchunks, body, jnp.zeros((n,), jnp.float32))


def sample_prob(
    indptr: jax.Array,
    indices: jax.Array,
    sizes,
    train_idx: jax.Array,
    num_nodes: Optional[int] = None,
) -> jax.Array:
    """Multi-layer hot-probability estimate (reference sage_sampler.py:149-157).

    Seeds get probability 1; each hop propagates with `neighbor_prob`. The
    result drives degree-free hot/cold placement and the offline partitioner.
    """
    n = num_nodes if num_nodes is not None else indptr.shape[0] - 1
    prob = jnp.zeros((n,), jnp.float32).at[train_idx].set(1.0)
    last = prob
    for k in sizes:
        nxt = neighbor_prob(indptr, indices, last, k)
        prob = prob + nxt
        last = nxt
    return prob

"""Device + host compute kernels (sampling, reindex, gather)."""

from . import cpu_kernels, reindex, sample

__all__ = ["cpu_kernels", "reindex", "sample"]

"""Graph lifecycle (round 21, ROADMAP item 2): the policy layer that
makes a `stream.StreamingTiledGraph` live forever — deletes, TTL
retention, background tile compaction, and reserve re-provisioning, all
riding the `update_graph` commit machinery on both engines. Since round
24 those commits are ZERO-STALL by default: the post-commit device
arrays build off-fence (``defer_publish=True`` staging) and flip under
the engine's dispatch lock only — retention expiry and compaction ride
the same staged flip, while re-provisioning (an executable aval swap)
always takes the full fenced path. ``fenced_commits=True`` restores the
round-23 drain.

The mechanisms live in `quiver_tpu.stream` (they mutate tile state and
must share its lock); this module holds the DETERMINISTIC POLICIES that
decide *when* each one runs, so the decisions are replayable from the
commit stream alone:

- `RetentionPolicy(window=W)` — sliding-window TTL: at a commit whose
  clock (the delta's max staged timestamp) is ``t_commit``, expire every
  edge with ``ts <= t_commit - W``. The subtraction is FLOAT32 (the
  `quantize_t` grid rule from NEXT.md: timestamps live on the f32 grid,
  so window arithmetic must too — a float64 cutoff could straddle a
  lane's f32 ts and expire on one host but not another). Expiry is a
  masked ``ts -> +inf`` lane write, the exact bit-dual of querying the
  unexpired stream through a ``cutoff < ts <= t`` band mask
  (`ops.sample.temporal_weight_rows(cutoff=...)`), pinned in
  tests/test_lifecycle.py.
- `CompactionPolicy` — LSM-style background reclamation: trigger a
  `plan_compaction`/`apply_compaction` pair when the reserve report
  shows at least ``min_reclaimable`` reclaimable tile rows. Plans build
  OFF-FENCE; the apply flips under the engine fence like an r16
  migration and is strictly observe-only on bits (no draw changes, no
  invalidation).
- `ProvisionPolicy` — grow the tile bank by whole banks when free rows
  sink below a floor (or reactively on `StreamCapacityError`), paying
  exactly one sealed-program rebuild per event
  (`inference.BucketPrograms.reprovision`) — never recompile-per-commit.

Every policy is a pure function of observable state (commit clock,
reserve report) with no wall-clock or RNG input, which is what keeps
deletion-era dispatch logs replayable: `replay_fleet_oracle`/
`replay_temporal_log` snapshot topology per version — since round 24
each dispatch-log row carries its sealed ``graph_version`` stamp, so
commits racing in-flight flushes replay per epoch — and the policies
re-derive the same expiry/compaction decisions from the same stream.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

__all__ = [
    "RetentionPolicy",
    "CompactionPolicy",
    "ProvisionPolicy",
    "retention_cutoff",
]


def retention_cutoff(t_commit: float, window: float) -> float:
    """The sliding-window expiry cutoff ``t_commit - window`` computed
    ON THE FLOAT32 GRID (both operands snapped to f32, subtraction in
    f32, result returned as the exact f32 value) — the same discipline
    as `workloads.serving.quantize_t`: edge timestamps are f32 lanes,
    and a float64 cutoff sitting between two adjacent f32 values could
    classify a lane differently than the f32 comparison the duality
    test (and a second host) performs."""
    return float(np.float32(np.float32(t_commit) - np.float32(window)))


class RetentionPolicy:
    """Deterministic sliding-window TTL retention for temporal streams.

    ``window`` is in timestamp units. Each commit advances the policy's
    clock to the largest timestamp it has seen (monotone — a late,
    out-of-order arrival never moves the cutoff backwards), and
    `cutoff_for` yields the expiry cutoff the engine passes to
    `StreamingTiledGraph.expire_edges` — or None when nothing new could
    expire (the cutoff hasn't advanced past the last one applied, so
    the O(nodes-touched) expiry scan is skipped).

    Deterministic and replayable: the cutoff is a pure f32 function of
    the committed timestamps; two replicas fed the same commit stream
    expire identical lane sets."""

    def __init__(self, window: float):
        if not (float(window) > 0.0) or not np.isfinite(window):
            raise ValueError(
                f"retention window must be positive and finite, got "
                f"{window}"
            )
        self.window = float(np.float32(window))
        self._clock: Optional[float] = None
        self._last_cutoff: Optional[float] = None

    def observe(self, t_commit: Optional[float]) -> None:
        """Advance the policy clock to ``t_commit`` (monotone max)."""
        if t_commit is None:
            return
        t = float(np.float32(t_commit))
        if self._clock is None or t > self._clock:
            self._clock = t

    def cutoff_for(self, t_commit: Optional[float] = None
                   ) -> Optional[float]:
        """Observe ``t_commit`` and return the cutoff to expire at, or
        None when the window hasn't advanced since the last expiry."""
        self.observe(t_commit)
        if self._clock is None:
            return None
        cut = retention_cutoff(self._clock, self.window)
        if self._last_cutoff is not None and cut <= self._last_cutoff:
            return None
        return cut

    def mark_expired(self, cutoff: float) -> None:
        """Record that expiry ran at ``cutoff`` (the engine calls this
        after `expire_edges` commits)."""
        if self._last_cutoff is None or cutoff > self._last_cutoff:
            self._last_cutoff = float(np.float32(cutoff))

    def state(self) -> Dict[str, Optional[float]]:
        return {"window": self.window, "clock": self._clock,
                "last_cutoff": self._last_cutoff}


class CompactionPolicy:
    """When to run a compaction pass: once the reserve report shows at
    least ``min_reclaimable`` reclaimable tile rows (spill-retired
    ranges + trimmable tails). ``max_moves`` bounds optional defrag
    relocations per pass (0 = reclaim only, never move live rows).
    Pure function of the report — no clock, no RNG."""

    def __init__(self, min_reclaimable: int = 8, max_moves: int = 0):
        self.min_reclaimable = max(int(min_reclaimable), 1)
        self.max_moves = max(int(max_moves), 0)

    def should_compact(self, report: Dict[str, object]) -> bool:
        return int(report.get("reclaimable_tiles", 0)) >= (
            self.min_reclaimable
        )


class ProvisionPolicy:
    """When (and by how much) to grow the tile bank: provision
    ``bank_tiles`` fresh rows whenever free rows sink below
    ``min_free_tiles``. Growing by whole banks keeps the r17 contract
    honest — shapes change at provision events only, each paying ONE
    sealed-program rebuild, so the per-commit path still never
    recompiles."""

    def __init__(self, bank_tiles: int, min_free_tiles: int = 0):
        if int(bank_tiles) <= 0:
            raise ValueError(
                f"bank_tiles must be positive, got {bank_tiles}"
            )
        self.bank_tiles = int(bank_tiles)
        self.min_free_tiles = max(int(min_free_tiles), 0)

    def should_provision(self, report: Dict[str, object]) -> bool:
        return int(report.get("reserve_free", 0)) < self.min_free_tiles

"""Dataset ingestion + realistic synthetic graphs.

The reference proves itself on OGB datasets (ogbn-products epoch times and
the ~0.787 GraphSAGE accuracy anchor,
examples/multi_gpu/pyg/ogb-products/dist_sampling_ogb_products_quiver.py:1;
power-law skew justification docs/Introduction_en.md:77-80: >avg-degree
nodes are 31.3% of products' nodes but touch 76.8% of edges). This image has
no dataset egress, so this module provides:

- :func:`load_npz` / :func:`save_npz` — an ``.npz`` interchange format so a
  real OGB download (exported with ``save_npz`` anywhere ogb is installed)
  drops straight into the examples;
- :func:`synthetic_powerlaw` — a generator matching a target power-law
  degree profile (products-like by default) including *in*-degree skew via
  degree-proportional destination sampling, so cache-hit behaviour under
  degree-ordered placement is realistic, unlike a uniform random graph;
- :func:`cache_hit_rate` — the skew-realistic cache measurement the
  reference runs as test_partition.py:66-100 (cache-hit CDFs).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

# ogbn-products scale (docs/Introduction_en.md / OGB reference numbers)
PRODUCTS = dict(n_nodes=2_449_029, n_edges=61_859_140, feat_dim=100, classes=47,
                train_nodes=196_615)
REDDIT = dict(n_nodes=232_965, n_edges=114_615_892, feat_dim=602, classes=41,
              train_nodes=153_431)


def save_npz(path: str, edge_index: np.ndarray, features: np.ndarray,
             labels: np.ndarray, train_idx: np.ndarray, **extra) -> None:
    """Write the interchange format the examples consume (run this next to
    an ``ogb.nodeproppred.NodePropPredDataset`` to export a real dataset)."""
    np.savez_compressed(
        path, edge_index=edge_index, features=features, labels=labels,
        train_idx=train_idx, **extra,
    )


def load_npz(path: str) -> Dict[str, np.ndarray]:
    """Load an exported dataset: {edge_index [2,E], features [N,D],
    labels [N], train_idx [T], (optional valid_idx/test_idx)}."""
    data = np.load(path)
    out = {k: data[k] for k in data.files}
    for k in ("edge_index", "features", "labels", "train_idx"):
        if k not in out:
            raise ValueError(f"dataset {path} missing required array {k!r}")
    return out


def _powerlaw_csr_arrays(n_nodes, n_edges, alpha, seed, max_deg_frac):
    """(indptr, indices) of a power-law graph, built directly in CSR order
    (no edge sort needed: src = repeat(arange, deg) is already grouped)."""
    rng = np.random.default_rng(seed)
    raw = rng.pareto(alpha, n_nodes) + 1.0
    raw = np.minimum(raw, raw.sum() * max_deg_frac)  # clip mega-hubs
    deg = np.maximum((raw / raw.sum() * n_edges).astype(np.int64), 1)
    diff = int(deg.sum() - n_edges)
    if diff > 0:
        idx = rng.choice(n_nodes, diff, replace=True, p=deg / deg.sum())
        np.subtract.at(deg, idx, 1)
        deg = np.maximum(deg, 0)
    elif diff < 0:
        idx = rng.integers(0, n_nodes, -diff)
        np.add.at(deg, idx, 1)
    indptr = np.zeros(n_nodes + 1, np.int64)
    np.cumsum(deg, out=indptr[1:])
    # degree-proportional destinations via inverse-CDF on the degree mass
    cdf = np.cumsum(deg.astype(np.float64))
    cdf /= cdf[-1]
    e = int(indptr[-1])
    indices = np.searchsorted(cdf, rng.random(e), side="right").astype(np.int64)
    np.minimum(indices, n_nodes - 1, out=indices)
    return indptr, indices, rng


def powerlaw_csr(n_nodes: int, n_edges: int, alpha: float = 1.35, seed: int = 0,
                 max_deg_frac: float = 0.01):
    """CSR arrays of a products-like power-law graph without materializing
    (or sorting) an edge list — cheap enough for products scale in benches."""
    indptr, indices, _ = _powerlaw_csr_arrays(n_nodes, n_edges, alpha, seed, max_deg_frac)
    return indptr, indices


def synthetic_powerlaw(
    n_nodes: int,
    n_edges: int,
    alpha: float = 1.35,
    dim: int = 0,
    classes: int = 0,
    train_frac: float = 0.08,
    seed: int = 0,
    max_deg_frac: float = 0.01,
    label_signal: float = 1.5,
):
    """Power-law graph with products-like degree skew.

    Out-degrees follow a Pareto(alpha) profile scaled to ``n_edges`` total;
    destinations are drawn degree-proportionally (preferential attachment
    flavour) so in-degree is skewed too — the property that makes
    degree-ordered hot caching work on real graphs. ``alpha=1.35`` lands
    near products' published skew (top ~30% of nodes owning ~77% of edges).

    Returns (edge_index [2,E], features [N,dim] or None, labels [N] or
    None, train_idx).
    """
    indptr, dst, rng = _powerlaw_csr_arrays(n_nodes, n_edges, alpha, seed, max_deg_frac)
    deg = np.diff(indptr)
    src = np.repeat(np.arange(n_nodes, dtype=np.int64), deg)
    edge_index = np.stack([src, dst])

    features = labels = None
    if dim:
        features = rng.standard_normal((n_nodes, dim)).astype(np.float32)
    if classes:
        labels = rng.integers(0, classes, n_nodes).astype(np.int32)
        if dim:
            # make labels learnable: nudge a class-dependent direction.
            # `label_signal` sets task difficulty — accuracy-anchor runs use
            # a value tuned to land AWAY from 1.0 so regressions can move
            # the number (round-3 verdict item 8)
            basis = rng.standard_normal((classes, dim)).astype(np.float32)
            features += basis[labels] * label_signal
    train_idx = rng.choice(n_nodes, max(int(n_nodes * train_frac), 1), replace=False)
    return edge_index, features, labels, train_idx


def synthetic_community(
    n_nodes: int,
    communities: int = 4,
    avg_deg: int = 10,
    inter_frac: float = 0.05,
    dim: int = 16,
    feature_signal: float = 0.0,
    train_frac: float = 0.5,
    seed: int = 0,
):
    """Stochastic-block-model-flavoured graph: edges land inside the node's
    community except an ``inter_frac`` leak. With ``feature_signal=0`` the
    features are pure noise, so only the STRUCTURE carries the labels —
    the honest benchmark for unsupervised/structural embedding methods
    (examples/graph_sage_unsup.py); raise it to mix in a supervised-style
    class nudge.

    Returns (edge_index [2,E], features [N,dim], labels [N], train_idx).
    """
    rng = np.random.default_rng(seed)
    # one boundary array drives BOTH labels and edge blocks, so intra-
    # community edges stay intra even when communities don't divide n
    bounds = (np.arange(communities + 1, dtype=np.int64) * n_nodes) // communities
    labels = (
        np.searchsorted(bounds, np.arange(n_nodes), side="right") - 1
    ).astype(np.int32)
    src = np.repeat(np.arange(n_nodes, dtype=np.int64), avg_deg)
    lab_src = labels[src].astype(np.int64)
    width = (bounds[lab_src + 1] - bounds[lab_src]).astype(np.float64)
    dst = bounds[lab_src] + (rng.random(src.shape[0]) * width).astype(np.int64)
    leak = rng.random(src.shape[0]) < inter_frac
    dst[leak] = rng.integers(0, n_nodes, int(leak.sum()))
    edge_index = np.stack([src, np.minimum(dst, n_nodes - 1)])
    features = rng.standard_normal((n_nodes, dim)).astype(np.float32)
    if feature_signal:
        basis = rng.standard_normal((communities, dim)).astype(np.float32)
        features += basis[labels] * feature_signal
    train_idx = rng.choice(
        n_nodes, max(int(n_nodes * train_frac), 1), replace=False
    )
    return edge_index, features, labels, train_idx


def products_like(scale: float = 1.0, dim: Optional[int] = None,
                  classes: Optional[int] = None, seed: int = 0):
    """products-shaped graph at ``scale`` (1.0 = full 2.45M nodes / 61.9M
    edges). Smaller scales keep the degree profile for hermetic tests."""
    n = max(int(PRODUCTS["n_nodes"] * scale), 10)
    e = max(int(PRODUCTS["n_edges"] * scale), 20)
    return synthetic_powerlaw(
        n, e,
        dim=PRODUCTS["feat_dim"] if dim is None else dim,
        classes=PRODUCTS["classes"] if classes is None else classes,
        train_frac=PRODUCTS["train_nodes"] / PRODUCTS["n_nodes"],
        seed=seed,
    )


def edge_skew(edge_index: np.ndarray, n_nodes: int, node_frac: float = 0.2):
    """Fraction of edges owned by the top ``node_frac`` of nodes by degree
    (products: top 31.3% own 76.8%, docs/Introduction_en.md:77-80)."""
    deg = np.bincount(edge_index[0], minlength=n_nodes)
    top = np.sort(deg)[::-1][: max(int(n_nodes * node_frac), 1)]
    return float(top.sum()) / max(float(deg.sum()), 1.0)


def cache_hit_rate(
    csr_topo,
    gathered_ids: Sequence[np.ndarray],
    cache_ratio: float,
) -> float:
    """Hit rate of a degree-ordered hot prefix of size ``cache_ratio * N``
    against observed gather batches (reference test_partition.py:66-100
    measures the same CDF). ``csr_topo.feature_order`` must be set (Feature
    attaches it) or degrees are used directly."""
    n = csr_topo.node_count
    cache_rows = int(n * cache_ratio)
    if csr_topo.feature_order is not None:
        order = np.asarray(csr_topo.feature_order)
        hits = total = 0
        for ids in gathered_ids:
            ids = np.asarray(ids)
            ids = ids[(ids >= 0) & (ids < n)]
            hits += int((order[ids] < cache_rows).sum())
            total += ids.size
    else:
        deg = np.asarray(csr_topo.degree)
        hot = np.zeros(n, bool)
        hot[np.argsort(deg)[::-1][:cache_rows]] = True
        hits = total = 0
        for ids in gathered_ids:
            ids = np.asarray(ids)
            ids = ids[(ids >= 0) & (ids < n)]
            hits += int(hot[ids].sum())
            total += ids.size
    return hits / max(total, 1)

"""Core graph-topology containers and helpers.

TPU-native re-design of the reference's ``srcs/python/quiver/utils.py``
(CSRTopo at utils.py:120, Topo/p2pCliqueTopo at utils.py:54-107,
reindex_by_config at utils.py:230-248, parse_size at utils.py:260-281,
init_p2p at utils.py:251-257).

Key departures from the reference:

- Topology lives in host numpy arrays (the TPU analog of pageable/pinned host
  memory) and is materialised into device HBM on demand (`to_device`), instead
  of the reference's UVA ``cudaHostRegister`` mapping — TPUs cannot read host
  memory from inside a kernel, so the "UVA" tier becomes host-side sampling and
  the "GPU" tier becomes HBM-resident CSR (see SURVEY.md section 7.3).
- ids default to int32 on device when the graph fits (faster gathers on TPU);
  int64 is kept for >2B-edge graphs (ogbn-papers100M scale).
- The NVLink-clique `Topo` becomes `IciTopo`: introspection of the JAX device
  mesh, where every chip in a TPU slice is one "clique" (all-to-all ICI),
  replacing cudaDeviceCanAccessPeer probing.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import List, Optional, Sequence, Union

import numpy as np


def parse_size(sz: Union[int, str, float]) -> int:
    """Parse a human byte size like ``"200M"``, ``"4GB"``, ``"1.5g"`` to bytes.

    Mirrors reference ``utils.py:260-281`` (parse_size) but accepts fractional
    values and an optional trailing "B".
    """
    if isinstance(sz, (int, np.integer)):
        return int(sz)
    if isinstance(sz, float):
        return int(sz)
    s = str(sz).strip().upper()
    m = re.fullmatch(r"([0-9]*\.?[0-9]+)\s*([KMGT]?)B?", s)
    if not m:
        raise ValueError(f"Cannot parse size: {sz!r}")
    value = float(m.group(1))
    unit = m.group(2)
    mult = {"": 1, "K": 1 << 10, "M": 1 << 20, "G": 1 << 30, "T": 1 << 40}[unit]
    return int(value * mult)


def _best_id_dtype(max_value: int) -> np.dtype:
    """int32 when every index fits, else int64 (papers100M-scale edges)."""
    return np.dtype(np.int32) if max_value < 2**31 - 1 else np.dtype(np.int64)


class CSRTopo:
    """CSR graph topology container (reference ``utils.py:120-248``).

    Construct from an edge_index COO pair (2 x E) or from (indptr, indices).
    Arrays are held as host numpy; `to_device()` returns jnp copies placed in
    TPU HBM for device-mode sampling.

    Attributes
    ----------
    indptr : np.ndarray [N+1]
    indices : np.ndarray [E]
    eid : optional np.ndarray [E] original edge ids (reference keeps these for
        edge-feature lookup; ``Adj.e_id`` is empty in the reference snapshot,
        sage_sampler.py:143, but we keep the slot)
    feature_order : optional np.ndarray [N] new_order permutation produced by
        `reindex_by_config` / `Feature.from_cpu_tensor` (reference
        utils.py:171-186)
    """

    def __init__(
        self,
        edge_index=None,
        indptr=None,
        indices=None,
        eid=None,
        num_nodes: Optional[int] = None,
        edge_weights=None,
    ):
        if edge_index is not None:
            edge_index = np.asarray(edge_index)
            if edge_index.shape[0] != 2:
                raise ValueError("edge_index must be [2, E]")
            src = np.asarray(edge_index[0], dtype=np.int64)
            dst = np.asarray(edge_index[1], dtype=np.int64)
            n = int(num_nodes) if num_nodes is not None else int(
                max(src.max(initial=-1), dst.max(initial=-1)) + 1
            )
            # COO -> CSR via counting sort on rows (reference uses scipy
            # csr_matrix, utils.py:110-117; counting sort avoids the scipy dep
            # and preserves a stable order of neighbors within a row).
            order = np.argsort(src, kind="stable")
            src_sorted = src[order]
            self.indptr = np.zeros(n + 1, dtype=np.int64)
            counts = np.bincount(src_sorted, minlength=n)
            np.cumsum(counts, out=self.indptr[1:])
            self.indices = dst[order]
            self.eid = order.astype(np.int64)  # original edge id per CSR slot
            # optional per-edge weights for the weighted sampler
            # (reference quiver.cu.hpp:61-82); stored CSR-aligned
            if edge_weights is None:
                self.edge_weights = None
            else:
                ew = np.asarray(edge_weights, np.float32)
                if ew.shape != src.shape:
                    raise ValueError(
                        f"edge_weights shape {ew.shape} != edge count "
                        f"{src.shape} of edge_index"
                    )
                self.edge_weights = ew[order]
        elif indptr is not None and indices is not None:
            self.indptr = np.ascontiguousarray(np.asarray(indptr, dtype=np.int64))
            self.indices = np.ascontiguousarray(np.asarray(indices, dtype=np.int64))
            self.eid = None if eid is None else np.asarray(eid, dtype=np.int64)
            self.edge_weights = (
                None
                if edge_weights is None
                else np.asarray(edge_weights, np.float32)
            )
            if num_nodes is not None and num_nodes + 1 > self.indptr.shape[0]:
                pad = np.full(num_nodes + 1 - self.indptr.shape[0], self.indptr[-1])
                self.indptr = np.concatenate([self.indptr, pad])
        else:
            raise ValueError("need edge_index or (indptr, indices)")
        if self.edge_weights is not None and self.edge_weights.shape != self.indices.shape:
            raise ValueError(
                f"edge_weights shape {self.edge_weights.shape} != indices "
                f"shape {self.indices.shape}"
            )
        self._feature_order: Optional[np.ndarray] = None
        self._device_cache = None
        self._tiled_cache = None

    @property
    def feature_order(self) -> Optional[np.ndarray]:
        return self._feature_order

    @feature_order.setter
    def feature_order(self, order) -> None:
        self._feature_order = np.asarray(order, dtype=np.int64)

    @property
    def degree(self) -> np.ndarray:
        """Out-degree per node (reference utils.py:189-195)."""
        return self.indptr[1:] - self.indptr[:-1]

    @property
    def node_count(self) -> int:
        return self.indptr.shape[0] - 1

    @property
    def edge_count(self) -> int:
        return self.indices.shape[0]

    def __getstate__(self):
        # device arrays don't cross process boundaries; children re-bind
        # lazily (the reference reshares topology via torch shm and re-runs
        # lazy_init_quiver in the child, sage_sampler.py:98-113)
        state = self.__dict__.copy()
        state["_device_cache"] = None
        state["_tiled_cache"] = None
        state["_wtiled_cache"] = None
        return state

    def share_memory_(self):
        """No-op compat shim (reference utils.py:216-226).

        JAX drives every local chip from one process; numpy arrays passed to
        worker processes for CPU sampling go through OS fork/pickle instead of
        torch shared memory.
        """
        return self

    def to_device(self, device=None, id_dtype=None):
        """Materialise (indptr, indices) as jnp arrays in HBM.

        Returns a cached (indptr_dev, indices_dev) pair. ``id_dtype`` defaults
        to int32 when indices fit (TPU gathers are cheaper on int32).
        """
        import jax
        import jax.numpy as jnp

        if id_dtype is None:
            id_dtype = _best_id_dtype(max(self.edge_count, self.node_count + 1))
        if np.dtype(id_dtype) == np.int64 and not jax.config.jax_enable_x64:
            # jnp.asarray would SILENTLY wrap int64 -> int32 here (jax
            # default); >2^31 ids would corrupt instead of erroring
            raise ValueError(
                "graph needs int64 ids on device but jax x64 is disabled "
                "(ids would silently wrap to int32): enable it via "
                'jax.config.update("jax_enable_x64", True) before first jax '
                "use, or keep the graph host-side with mode='HOST' (the "
                "native engine is int64 end to end)"
            )
        key = (str(device), np.dtype(id_dtype).name)
        if self._device_cache is not None and self._device_cache[0] == key:
            return self._device_cache[1]
        indptr = jnp.asarray(self.indptr.astype(id_dtype))
        indices = jnp.asarray(self.indices.astype(id_dtype))
        if device is not None:
            indptr = jax.device_put(indptr, device)
            indices = jax.device_put(indices, device)
        self._device_cache = (key, (indptr, indices))
        return self._device_cache[1]

    def to_device_tiled(self, device=None, id_dtype=None):
        """Materialise the 128-lane-aligned tile layout in HBM:
        ``(bd [N, 2] int32, tiles [M, 128])`` — see
        `quiver_tpu.ops.sample.build_tiled_host`. The TPU-mode sampler's
        default graph layout: neighbor fetches ride 2-D row gathers
        (~1.4-2x the one-element gather rate) at the cost of ceil-padding
        each node's edge list to 128 lanes (~2-3x flat-CSR bytes on
        power-law graphs; pass ``layout='flat'`` to the sampler when HBM
        is tight)."""
        import jax

        import jax.numpy as jnp

        from .ops.sample import build_tiled_host

        if id_dtype is None:
            id_dtype = _best_id_dtype(self.node_count + 1)
        if np.dtype(id_dtype) == np.int64 and not jax.config.jax_enable_x64:
            raise ValueError(
                "graph needs int64 node ids on device but jax x64 is "
                "disabled — see CSRTopo.to_device"
            )
        key = ("tiled", str(device), np.dtype(id_dtype).name)
        if getattr(self, "_tiled_cache", None) is not None and self._tiled_cache[0] == key:
            return self._tiled_cache[1]
        bd_np, tiles_np = build_tiled_host(self.indptr, self.indices, id_dtype)
        bd = jnp.asarray(bd_np)
        tiles = jnp.asarray(tiles_np)
        if device is not None:
            bd = jax.device_put(bd, device)
            tiles = jax.device_put(tiles, device)
        self._tiled_cache = (key, (bd, tiles))
        return self._tiled_cache[1]

    def to_device_tiled_weights(self, device=None):
        """Edge weights in the SAME tile map as `to_device_tiled`'s edge
        tiles (``[M, 128]`` f32) — the weighted sampler's lane windows
        then ride row gathers too (`ops.sample.tiled_weighted_sample_layer`)."""
        import jax

        import jax.numpy as jnp

        from .ops.sample import build_tiled_host

        if self.edge_weights is None:
            raise ValueError("no edge_weights on this CSRTopo")
        key = ("wtiled", str(device))
        if getattr(self, "_wtiled_cache", None) is not None and self._wtiled_cache[0] == key:
            return self._wtiled_cache[1]
        _, wtiles_np = build_tiled_host(
            self.indptr, self.edge_weights, np.float32
        )
        wtiles = jnp.asarray(wtiles_np)
        if device is not None:
            wtiles = jax.device_put(wtiles, device)
        self._wtiled_cache = (key, wtiles)
        return wtiles


def heat_reorder(
    edge_index,
    num_nodes: Optional[int] = None,
    features=None,
    labels=None,
    index_sets=(),
    heat=None,
):
    """Renumber the WHOLE id space heat-descending, so the hot prefix
    convention of `shard_feature_hot_cold` / `sharded_gather_hot_cold`
    ("rows < hot_rows are the replicated tier") holds for graph, features,
    labels and index sets alike — the ONE implementation of that convention.

    ``heat``: per-node hotness scores; default is in+out degree. Pass
    measured access probabilities (`GraphSageSampler.sample_prob`) for the
    reference's prob-driven placement (mag240m preprocess.py:117-179).

    Returns ``(edge_index_r, features_r, labels_r, sets_r, order, inv)``
    with ``order[new_id] = old_id`` and ``inv[old_id] = new_id``; pass-
    through ``None`` for absent features/labels. (`reindex_by_config` /
    `Feature.from_cpu_tensor` reorder only the TABLE and translate ids at
    lookup; this reorders the id space itself, which collective gathers
    need — they test hotness by raw id.)"""
    edge_index = np.asarray(edge_index)
    n = int(num_nodes) if num_nodes is not None else int(edge_index.max()) + 1
    if heat is None:
        heat = np.bincount(edge_index[0], minlength=n) + np.bincount(
            edge_index[1], minlength=n
        )
    else:
        heat = np.asarray(heat)
        if heat.shape[0] != n:
            raise ValueError(f"heat has {heat.shape[0]} entries for {n} nodes")
    order = np.argsort(-heat, kind="stable").astype(np.int64)
    inv = np.empty(n, np.int64)
    inv[order] = np.arange(n)
    edge_r = inv[edge_index]
    feats_r = None if features is None else np.asarray(features)[order]
    labels_r = None if labels is None else np.asarray(labels)[order]
    sets_r = tuple(inv[np.asarray(s)] for s in index_sets)
    return edge_r, feats_r, labels_r, sets_r, order, inv


def show_tensor_info(x, name: str = "", file=None) -> str:
    """Debug dump of an array's identity — the TPU analog of the
    reference's ``show_tensor_info`` (srcs/cpp/src/quiver/cpu/tensor.cpp:
    74-95: dtype/shape/device/data pointer). Handles jax arrays (device +
    sharding), numpy arrays (memmap path included), and anything exposing
    shape/dtype. Returns the line (also printed)."""
    parts = [name or type(x).__name__]
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    parts.append(f"shape={tuple(shape) if shape is not None else '?'}")
    parts.append(f"dtype={dtype}")
    nbytes = getattr(x, "nbytes", None)
    if nbytes is not None:
        parts.append(f"nbytes={nbytes:,}")
    if isinstance(x, np.memmap):
        parts.append(f"memmap={getattr(x, 'filename', '?')}")
    elif isinstance(x, np.ndarray):
        parts.append("host=numpy")
    else:
        sharding = getattr(x, "sharding", None)
        if sharding is not None:
            devs = getattr(x, "devices", None)
            parts.append(f"sharding={sharding}")
            if callable(devs):
                parts.append(f"devices={sorted(str(d) for d in devs())}")
        committed = getattr(x, "committed", None)
        if committed is not None:
            parts.append(f"committed={committed}")
    line = " ".join(str(p) for p in parts)
    print(line, file=file)
    return line


def reindex_by_config(adj_csr: CSRTopo, graph_feature, gpu_portion: float, seed: int = 0):
    """Degree-descending hot/cold reorder (reference ``utils.py:230-248``).

    Sort nodes by out-degree descending, randomly shuffle the hot prefix
    (top ``gpu_portion`` fraction) to load-balance striped placement, and
    return ``(permuted_feature, prev_order)`` where ``prev_order`` maps
    old node id -> position in the permuted feature ("feature_order").

    The hot-prefix shuffle is seeded (default 0) so cache placement — and
    any performance comparison across runs — is reproducible; pass a
    different ``seed`` to resample the striping.
    """
    if not 0.0 <= gpu_portion <= 1.0:
        raise ValueError("gpu_portion must be in [0, 1]")
    node_count = adj_csr.node_count
    split = int(node_count * gpu_portion)
    perm_range = np.random.default_rng(seed).permutation(split)
    degree = adj_csr.degree
    # descending degree order; stable for determinism on ties
    prev_order = np.argsort(-degree, kind="stable")
    prev_order[:split] = prev_order[perm_range]
    new_order = np.empty(node_count, dtype=np.int64)
    new_order[prev_order] = np.arange(node_count, dtype=np.int64)
    if graph_feature is not None:
        graph_feature = np.asarray(graph_feature)[prev_order]
    return graph_feature, new_order


def reindex_feature(graph: CSRTopo, feature, ratio: float, seed: int = 0):
    """Reference ``utils.py:230`` companion used by Feature; returns
    (reordered_feature, feature_order)."""
    feature, new_order = reindex_by_config(graph, feature, ratio, seed=seed)
    return feature, new_order


@dataclass
class IciTopo:
    """TPU replacement for the NVLink p2p-clique `Topo` (reference
    ``utils.py:54-107`` + Bron-Kerbosch find_cliques utils.py:8-33).

    On a TPU slice every local chip is connected over ICI, so clique discovery
    degenerates to "all local devices form one clique per slice". We keep the
    same info surface: `get_clique(rank)`, `info()`.
    """

    cliques: List[List[int]]

    @staticmethod
    def detect(devices: Optional[Sequence] = None) -> "IciTopo":
        import jax

        devs = list(devices) if devices is not None else jax.local_devices()
        by_slice = {}
        for i, d in enumerate(devs):
            slice_idx = getattr(d, "slice_index", 0) or 0
            by_slice.setdefault(slice_idx, []).append(i)
        return IciTopo(cliques=[sorted(v) for _, v in sorted(by_slice.items())])

    def get_clique_id(self, device_rank: int) -> int:
        for cid, clique in enumerate(self.cliques):
            if device_rank in clique:
                return cid
        raise KeyError(device_rank)

    def get_clique(self, device_rank: int) -> List[int]:
        return self.cliques[self.get_clique_id(device_rank)]

    @property
    def p2p_clique(self):  # reference-compatible spelling
        return {i: c for i, c in enumerate(self.cliques)}

    def info(self) -> str:
        lines = ["Device ICI Topology:"]
        for cid, clique in enumerate(self.cliques):
            lines.append(f"  clique {cid}: devices {clique} (all-to-all ICI)")
        return "\n".join(lines)


# Reference-compatible alias (`p2pCliqueTopo`, __init__.py:6).
p2pCliqueTopo = IciTopo
Topo = IciTopo


def force_virtual_cpu_devices(n_devices: int) -> None:
    """Force an ``n_devices`` virtual CPU mesh regardless of which
    accelerator plugin registered first.

    Env vars alone (``JAX_PLATFORMS``/``XLA_FLAGS``) lose once a site hook
    has imported jax and an accelerator plugin won platform selection; only
    ``jax.config.update`` is authoritative, and an already-initialized
    backend must be cleared so the new device count is re-read. Used by the
    test conftest, the driver's multichip dryrun, and the examples'
    ``QUIVER_VIRTUAL_DEVICES`` knob.
    """
    import os
    import re as _re

    xla_flags = _re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        os.environ.get("XLA_FLAGS", ""),
    )
    os.environ["XLA_FLAGS"] = (
        xla_flags + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    def _apply():
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", n_devices)
        except AttributeError:  # older jax: the XLA_FLAGS env (above) rules
            pass

    def _clear():
        # reset initialized backends (e.g. a TPU plugin) so the
        # platform/device-count config is re-read on next use
        try:
            import jax.extend.backend

            jax.extend.backend.clear_backends()
        except Exception:  # pragma: no cover
            from jax._src import xla_bridge

            xla_bridge._clear_backends()
        jax.clear_caches()

    try:
        _apply()
    except RuntimeError:
        _clear()
        _apply()
    if len(jax.devices()) != n_devices or jax.devices()[0].platform != "cpu":
        _clear()
        _apply()
    assert len(jax.devices()) == n_devices and jax.devices()[0].platform == "cpu", (
        f"could not force {n_devices} virtual CPU devices; got {jax.devices()}"
    )


def axis_size_compat(axis_name):
    """`lax.axis_size` across the API drift (inside shard_map/pmap only):
    older jax has no ``lax.axis_size``; ``psum(1, axis)`` is the documented
    equivalent and constant-folds to a Python int at trace time, so the
    result is usable in static shapes either way."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def shard_map_compat(f, *, mesh, in_specs, out_specs, check_vma=False):
    """`jax.shard_map` across the API drift, the ONE spelling every caller
    (library, tests, scripts) goes through: jax >= 0.6 exposes top-level
    ``jax.shard_map(..., check_vma=)``; older releases only have
    ``jax.experimental.shard_map.shard_map(..., check_rep=)`` — same knob,
    renamed. Passing the new name to an old build is a TypeError before
    tracing, so the fallback is exact."""
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    kw = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    try:
        return sm(f, check_vma=check_vma, **kw)
    except TypeError:
        return sm(f, check_rep=check_vma, **kw)


def init_p2p(device_list: Optional[List[int]] = None) -> None:
    """Compat no-op (reference utils.py:251-257 / quiver_feature.cu:363-406).

    TPU chips in a slice are always mutually reachable over ICI; there is no
    peer-access switch to flip. Kept so reference scripts port unchanged.
    """
    return None


def can_device_access_peer(a: int, b: int) -> bool:
    """ICI reachability probe (reference quiver_feature.cu:407-413): true when
    both ranks sit on the same TPU slice."""
    topo = IciTopo.detect()
    try:
        return topo.get_clique_id(a) == topo.get_clique_id(b)
    except KeyError:
        return False

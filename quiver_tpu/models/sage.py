"""GraphSAGE for TPU — dense padded aggregation.

The reference trains plain PyG ``SAGEConv`` stacks
(examples/pyg/reddit_quiver.py, examples/multi_gpu/pyg/ogb-products/
dist_sampling_ogb_products_quiver.py: 2-3 layer SAGEConv, hidden 256,
accuracy anchor ~0.787 on ogbn-products). On TPU the sampler emits padded
``[S, k]`` neighbor matrices (see ``quiver_tpu.pyg.sage_sampler.DenseAdj``),
which turns the sparse segment-mean aggregation into a dense gather +
masked mean — a reshape away from MXU-friendly matmuls (SURVEY.md 7.1).

Semantics match PyG SAGEConv(mean): ``out = lin_l(mean_j x_j) + lin_r(x_i)``.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..pyg.sage_sampler import DenseAdj


def masked_mean_aggregate(x_src: jax.Array, adj: DenseAdj) -> jax.Array:
    """Mean of valid sampled neighbors per target node.

    x_src: [W_src, D] embeddings of this hop's source n_id.
    Returns [W_dst, D]. For the fused pipeline's structural layout
    (``adj.cols is None``) this is a slice+reshape — no gather at all
    (2.3x faster than the equivalent take on TPU).
    """
    gathered = adj.gather_src(x_src)                  # [W_dst, k, D]
    m = adj.mask[..., None].astype(x_src.dtype)
    s = (gathered * m).sum(axis=1)
    cnt = jnp.maximum(adj.mask.sum(axis=1, keepdims=True), 1).astype(x_src.dtype)
    return s / cnt


class SAGEConv(nn.Module):
    """One GraphSAGE layer (PyG SAGEConv, mean aggregator).

    ``dtype`` is the COMPUTE dtype (e.g. ``jnp.bfloat16`` to run the
    matmuls on the MXU's native precision); params stay float32 (flax
    ``param_dtype`` default) — the standard TPU mixed-precision recipe."""

    out_dim: int
    use_bias: bool = True
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x_src: jax.Array, adj: DenseAdj) -> jax.Array:
        if self.dtype is not None:
            x_src = x_src.astype(self.dtype)
        w_dst = adj.w_dst
        x_dst = x_src[:w_dst]  # targets are the prefix of the source n_id
        agg = masked_mean_aggregate(x_src, adj)
        h = nn.Dense(
            self.out_dim, use_bias=self.use_bias, dtype=self.dtype, name="lin_l"
        )(agg)
        h = h + nn.Dense(
            self.out_dim, use_bias=False, dtype=self.dtype, name="lin_r"
        )(x_dst)
        return h


class GraphSAGE(nn.Module):
    """Multi-layer GraphSAGE matching the reference example models
    (examples/pyg/reddit_quiver.py SAGE class: relu + dropout between
    layers, log_softmax head is left to the loss).

    ``dtype=jnp.bfloat16`` runs every layer's compute in bf16 (params and
    returned logits stay float32, so losses/optimizers are unchanged) —
    the feature gather itself is row-rate-bound and dtype-invariant
    (PERF_NOTES.md), so this buys matmul time and activation memory, not
    gather time."""

    hidden_dim: int
    out_dim: int
    num_layers: int = 2
    dropout: float = 0.5
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        adjs: Tuple[DenseAdj, ...],
        *,
        train: bool = False,
    ) -> jax.Array:
        assert len(adjs) == self.num_layers, (len(adjs), self.num_layers)
        for i, adj in enumerate(adjs):
            dim = self.out_dim if i == self.num_layers - 1 else self.hidden_dim
            x = SAGEConv(dim, dtype=self.dtype, name=f"conv{i}")(x, adj)
            if i != self.num_layers - 1:
                x = jax.nn.relu(x)
                x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return x.astype(jnp.float32)

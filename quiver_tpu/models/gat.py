"""GAT for TPU — dense padded attention over sampled neighbors.

Parity with the reference's GAT training example
(examples/multi_gpu/pyg/reddit/dist_sampling_reddit_gat.py uses PyG GATConv).
The padded ``[S, k]`` sampler output makes attention a dense masked softmax
over the k sampled neighbors — batched [S, H, k] scores feed the VPU/MXU with
no segment ops.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..pyg.sage_sampler import DenseAdj


class GATConv(nn.Module):
    """Single GAT layer (PyG GATConv semantics, mean of heads optional).

    out[i] = sum_j alpha_ij * (W x_j), alpha over sampled neighbors + self.
    ``dtype`` is the compute dtype (params stay float32; attention softmax
    always runs float32 for stability).
    """

    out_dim: int
    heads: int = 1
    concat: bool = True
    negative_slope: float = 0.2
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x_src: jax.Array, adj: DenseAdj) -> jax.Array:
        h, d = self.heads, self.out_dim
        if self.dtype is not None:
            x_src = x_src.astype(self.dtype)
        w_dst = adj.w_dst
        x_dst = x_src[:w_dst]

        proj = nn.Dense(h * d, use_bias=False, dtype=self.dtype, name="lin")
        hs = proj(x_src).reshape(-1, h, d)          # [W_src, H, D]
        hd = hs[:w_dst]                              # [W_dst, H, D]

        a_src = self.param("att_src", nn.initializers.glorot_uniform(), (1, h, d))
        a_dst = self.param("att_dst", nn.initializers.glorot_uniform(), (1, h, d))
        a_src = a_src.astype(hs.dtype)
        a_dst = a_dst.astype(hs.dtype)

        hn = adj.gather_src(hs)                      # [W_dst, k, H, D]
        e_src = (hn * a_src[None]).sum(-1)           # [W_dst, k, H]
        e_dst = (hd * a_dst).sum(-1)                 # [W_dst, H]
        # self-attention edge (PyG adds self loops; the sampler's target node
        # is its own extra neighbor here)
        e_self = e_dst + (hd * a_src[0]).sum(-1)     # [W_dst, H]
        e = jax.nn.leaky_relu(
            e_src + e_dst[:, None, :], self.negative_slope
        )                                            # [W_dst, k, H]
        e_self = jax.nn.leaky_relu(e_self, self.negative_slope)

        mask = adj.mask[:, :, None]
        neg = jnp.asarray(-1e9, e.dtype)
        e = jnp.where(mask, e, neg)
        all_e = jnp.concatenate([e, e_self[:, None, :]], axis=1)  # [W_dst, k+1, H]
        # softmax in f32 regardless of compute dtype: bf16 exp/normalize
        # loses attention mass on long tails
        alpha = jax.nn.softmax(all_e.astype(jnp.float32), axis=1).astype(hs.dtype)
        vals = jnp.concatenate([hn, hd[:, None]], axis=1)         # [W_dst, k+1, H, D]
        out = (alpha[..., None] * vals).sum(axis=1)               # [W_dst, H, D]
        if self.concat:
            return out.reshape(w_dst, h * d)
        return out.mean(axis=1)


class GAT(nn.Module):
    """Multi-layer GAT matching the reference example shape: concat heads on
    hidden layers, mean heads on the output layer."""

    hidden_dim: int
    out_dim: int
    heads: int = 4
    num_layers: int = 2
    dropout: float = 0.5
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(
        self, x: jax.Array, adjs: Tuple[DenseAdj, ...], *, train: bool = False
    ) -> jax.Array:
        assert len(adjs) == self.num_layers
        for i, adj in enumerate(adjs):
            last = i == self.num_layers - 1
            x = GATConv(
                out_dim=self.out_dim if last else self.hidden_dim,
                heads=1 if last else self.heads,
                concat=not last,
                dtype=self.dtype,
                name=f"gat{i}",
            )(x, adj)
            if not last:
                x = jax.nn.elu(x)
                x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return x.astype(jnp.float32)

"""Reference-parity model zoo (GraphSAGE, GAT) in flax."""

from .sage import SAGEConv, GraphSAGE, masked_mean_aggregate
from .gat import GAT, GATConv

__all__ = ["GAT", "GATConv", "SAGEConv", "GraphSAGE", "masked_mean_aggregate"]

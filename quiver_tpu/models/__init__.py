"""Reference-parity model zoo (GraphSAGE, GAT) in flax."""

from .sage import SAGEConv, GraphSAGE, masked_mean_aggregate

__all__ = ["SAGEConv", "GraphSAGE", "masked_mean_aggregate"]

"""Reference-parity model zoo (GraphSAGE, GAT, GCN) in flax."""

from .sage import SAGEConv, GraphSAGE, masked_mean_aggregate
from .gat import GAT, GATConv
from .gcn import GCN, GCNConv

__all__ = [
    "GAT", "GATConv", "GCN", "GCNConv", "SAGEConv", "GraphSAGE",
    "masked_mean_aggregate",
]

"""GCN for TPU — dense padded graph convolution over sampled neighbors.

Rounds out the model zoo (SAGE, GAT, GCN) for users coming from the
reference's PyG/DGL ecosystems (the reference's own examples train SAGE and
GAT; GCN is the third standard consumer of the same sampler output —
`dgl.nn.GraphConv` / `torch_geometric.nn.GCNConv`).

Mini-batch GCN on sampled blocks follows DGL's GraphConv conventions:

- ``norm="right"`` (default): mean over incoming messages including the
  self-loop — on TPU this is the cheap form (mask + sum + divide; no
  scatter at all).
- ``norm="both"``: symmetric 1/sqrt(d_i d_j) with degrees counted WITHIN
  the sampled block (DGL's block semantics ON THE DEDUP LAYOUT; the fused
  structural layout duplicates src nodes per edge, so out-degrees are all
  1 there — see the in-code note). The src-side out-degree count needs one
  scatter-add per layer over the hop's source width; scatters are the
  expensive primitive on TPU (PERF_NOTES.md) — prefer "right" unless
  parity with a DGL norm='both' training run matters.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..pyg.sage_sampler import DenseAdj


class GCNConv(nn.Module):
    """One GCN layer over a :class:`DenseAdj` (self-loop included)."""

    out_dim: int
    norm: str = "right"
    use_bias: bool = True
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x_src: jax.Array, adj: DenseAdj) -> jax.Array:
        if self.norm not in ("right", "both"):
            raise ValueError(f"unknown norm: {self.norm!r}")
        if self.dtype is not None:
            x_src = x_src.astype(self.dtype)
        w_dst = adj.w_dst
        x_dst = x_src[:w_dst]
        gathered = adj.gather_src(x_src)              # [W_dst, k, D]
        m = adj.mask[..., None].astype(x_src.dtype)
        deg_in = adj.mask.sum(axis=1).astype(x_src.dtype)  # sampled in-degree
        if self.norm == "right":
            # mean over {self} + sampled in-neighbors
            s = (gathered * m).sum(axis=1) + x_dst
            agg = s / (deg_in + 1.0)[:, None]
        else:
            # within-block symmetric norm: src out-degree by scatter count,
            # accumulated in f32 ALWAYS (a bf16 accumulator saturates at 256,
            # silently under-counting hub nodes)
            if adj.cols is None:
                # structural layout: every src lane is a per-edge COPY, so
                # its within-block out-degree is exactly 1. NOTE this makes
                # norm="both" normalize differently than the dedup layout
                # (where a node feeding many dst rows counts them all) —
                # use the dedup pipeline when DGL-block norm='both'
                # semantics matter.
                deg_out = jnp.ones(x_src.shape[0], jnp.float32)
            else:
                deg_out = jnp.zeros(x_src.shape[0], jnp.float32).at[
                    adj.cols.reshape(-1)
                ].add(adj.mask.reshape(-1).astype(jnp.float32), mode="drop")
            deg_out = deg_out.astype(x_src.dtype)
            # self-loops count on both sides
            inv_dst = jax.lax.rsqrt(deg_in + 1.0)
            inv_src_all = jax.lax.rsqrt(deg_out + 1.0)
            inv_src = adj.gather_src(inv_src_all[:, None])[..., 0]  # [W_dst, k]
            s = (gathered * m * inv_src[..., None]).sum(axis=1)
            # self edge contributes x_i / d_i: one rsqrt here, one in the
            # final dst scaling below
            s = s + x_dst * inv_dst[:, None]
            agg = s * inv_dst[:, None]
        return nn.Dense(
            self.out_dim, use_bias=self.use_bias, dtype=self.dtype, name="lin"
        )(agg)


class GCN(nn.Module):
    """Multi-layer GCN with the zoo's conventions (relu + dropout between
    layers; bf16 compute via ``dtype``; f32 logits out)."""

    hidden_dim: int
    out_dim: int
    num_layers: int = 2
    dropout: float = 0.5
    norm: str = "right"
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        adjs: Tuple[DenseAdj, ...],
        *,
        train: bool = False,
    ) -> jax.Array:
        assert len(adjs) == self.num_layers, (len(adjs), self.num_layers)
        for i, adj in enumerate(adjs):
            dim = self.out_dim if i == self.num_layers - 1 else self.hidden_dim
            x = GCNConv(dim, norm=self.norm, dtype=self.dtype, name=f"conv{i}")(x, adj)
            if i != self.num_layers - 1:
                x = jax.nn.relu(x)
                x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return x.astype(jnp.float32)

"""Row-sharded graph topology over the device mesh.

The reference scales the *graph* past one device's memory with UVA: the CSR
lives in pinned host DRAM and GPU kernels read it over PCIe
(srcs/cpp/src/quiver/cuda/quiver_sample.cu:361-421 ZERO_COPY register;
benchmarks/ogbn-papers100M/train_quiver_multi_node.py runs 100M+ nodes that
way). The TPU-native equivalent keeps the CSR *in HBM* but row-shards it
across the mesh, so total graph capacity scales with chip count and every
topology read rides ICI/DCN collectives instead of PCIe:

- each shard owns a CONTIGUOUS row range (edge-balanced, so the big
  ``indices`` array splits evenly even on power-law graphs where
  degree-ordered hot rows concentrate at low ids);
- one-hop sampling becomes a collective: every chip draws neighbors for the
  frontier rows it owns (degree-0 elsewhere) and a ``psum`` over the
  topology axes assembles the full ``[W, k]`` neighbor matrix — the same
  owner-exclusive-contribution pattern as
  `quiver_tpu.parallel.collectives.sharded_gather`, riding the same axes.

The alternative formulation — route each frontier id to its owner with a
targeted all_to_all — is NOT better under XLA's static shapes: per-(owner)
request budgets must be provisioned for the worst-case skew, which on
degree-ordered power-law graphs is the full frontier width (the same
analysis as the grouped feature gather, see NEXT.md round-2 note), so the
lane count matches the all_gather/psum formulation while adding sorts.

Two shard LAYOUTS share all of the collective machinery above:

- ``layout="flat"`` (`ShardedTopology`): each shard keeps its contiguous CSR
  block as a local indptr + flat indices array and resolves drawn positions
  with one-element gathers (`ops.sample.row_windows`);
- ``layout="tiled"`` (`TiledShardedTopology`): each shard's block is rebuilt
  into the 128-lane tile layout of `ops.sample.build_tiled_host` — a local
  ``(base, degree)`` table plus a ``[M, 128]`` tile table — so position
  resolution rides 2-D ROW gathers + one-hot lane selects, the fetch shape
  behind the single-chip 2.58x fused-SEPS win (PERF_NOTES.md "ROUND-5").
  The collective payloads are IDENTICAL between layouts (same ``[W, k]``
  neighbor/valid return, same frontier all_gather); only the local HBM
  fetch shape changes — `sampling_comm_bytes(layout=...)` models both.
  Tiled is the TPU-mode default (`resolve_topology_layout`), matching the
  single-chip ``GraphSageSampler(layout="tiled")`` default; SCALING.md
  carries the flat-vs-tiled comparison.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..utils import axis_size_compat
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.sample import (
    LANE,
    _tiled_bd_lookup,
    _tiled_resolve,
    build_tiled_host,
    fisher_yates_positions,
    pad_widths,
    row_windows,
)


class ShardedTopology(NamedTuple):
    """Device-resident row-sharded CSR (see `shard_topology_rows`).

    ``indptr``  [P, R_max+1] — per-shard LOCAL indptr (offsets into the
                shard's own indices block), edge-padded so padding rows read
                as degree 0;
    ``indices`` [P, E_pad]   — per-shard neighbor block, zero-padded;
    ``row_start`` [P+1]      — global row boundaries (replicated; shard p
                owns rows ``row_start[p]:row_start[p+1]``).
    """

    indptr: jax.Array
    indices: jax.Array
    row_start: jax.Array

    @property
    def n_shards(self) -> int:
        return self.indptr.shape[0]

    def specs(self, feat_axes) -> "ShardedTopology":
        """shard_map in_specs pytree for this topology striped over
        ``feat_axes`` (row_start is replicated)."""
        return topology_specs(feat_axes)


def topology_specs(feat_axes) -> "ShardedTopology":
    """The ONE place the ShardedTopology shard_map spec layout lives: CSR
    blocks striped over ``feat_axes``, row boundaries replicated."""
    return ShardedTopology(
        indptr=P(feat_axes, None), indices=P(feat_axes, None), row_start=P()
    )


class TiledShardedTopology(NamedTuple):
    """Row-sharded CSR in the 128-lane TILE layout (`build_tiled_topology_shards`).

    ``bd``    [P, R_max, 2] int32 — per-shard LOCAL (tile_base, degree)
              table (`ops.sample.tiled_base_host` of the shard's block),
              row-padded so rows past the shard's range read as degree 0;
    ``tiles`` [P, M_max, 128] — per-shard tile tables (`build_tiled_host`
              of the block), tile-count-padded so the blocks stack;
    ``row_start`` [P+1]      — global row boundaries (replicated; shard p
              owns rows ``row_start[p]:row_start[p+1]``), same contract
              as `ShardedTopology`.
    """

    bd: jax.Array
    tiles: jax.Array
    row_start: jax.Array

    @property
    def n_shards(self) -> int:
        return self.bd.shape[0]

    def specs(self, feat_axes) -> "TiledShardedTopology":
        """shard_map in_specs pytree for this topology striped over
        ``feat_axes`` (row_start is replicated)."""
        return tiled_topology_specs(feat_axes)


def tiled_topology_specs(feat_axes) -> "TiledShardedTopology":
    """`topology_specs` for the tiled layout: bd/tile blocks striped over
    ``feat_axes``, row boundaries replicated."""
    return TiledShardedTopology(
        bd=P(feat_axes, None, None),
        tiles=P(feat_axes, None, None),
        row_start=P(),
    )


def resolve_topology_layout(layout: Optional[str]) -> str:
    """Default the sharded-topology layout per backend: ``None`` means
    "tiled" on TPU (matching the single-chip `GraphSageSampler` TPU
    default) and "flat" elsewhere (virtual CPU meshes keep the layout the
    hermetic tests were seeded with unless they opt in explicitly)."""
    if layout is None:
        layout = "tiled" if jax.default_backend() == "tpu" else "flat"
    if layout not in ("flat", "tiled"):
        raise ValueError(f"unsupported topology layout: {layout!r}")
    return layout


def partition_rows_by_edges(indptr: np.ndarray, n_shards: int) -> np.ndarray:
    """Contiguous row boundaries with ~equal edges per shard.

    Returns ``row_start`` [n_shards+1] with ``row_start[0]=0`` and
    ``row_start[-1]=N``. Row ranges may be empty on pathological graphs
    (one row owning nearly all edges); the sampler handles that (degree-0
    ownership elsewhere).
    """
    indptr = np.asarray(indptr)
    n = indptr.shape[0] - 1
    e = int(indptr[-1])
    targets = (np.arange(1, n_shards) * e) // n_shards
    cuts = np.searchsorted(indptr, targets, side="left")
    row_start = np.concatenate([[0], cuts, [n]]).astype(np.int64)
    return np.maximum.accumulate(row_start)  # enforce monotone under ties


def build_topology_shards(
    indptr: np.ndarray,
    indices: np.ndarray,
    n_shards: int,
    pad_multiple: int = 512,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host-side shard construction: (indptr_blocks, indices_blocks,
    row_start) as stacked numpy arrays (see `ShardedTopology`)."""
    indptr = np.asarray(indptr)
    indices = np.asarray(indices)
    row_start = partition_rows_by_edges(indptr, n_shards)
    r_max = int(np.max(row_start[1:] - row_start[:-1])) if n_shards else 0
    r_max = max(r_max, 1)
    e_pad = 0
    for p in range(n_shards):
        e_pad = max(e_pad, int(indptr[row_start[p + 1]] - indptr[row_start[p]]))
    e_pad = max(-(-e_pad // pad_multiple) * pad_multiple, pad_multiple)
    ptr_dt = np.int32 if e_pad < 2**31 else np.int64
    indptr_blocks = np.zeros((n_shards, r_max + 1), ptr_dt)
    indices_blocks = np.zeros((n_shards, e_pad), indices.dtype)
    for p in range(n_shards):
        lo, hi = int(row_start[p]), int(row_start[p + 1])
        local = (indptr[lo : hi + 1] - indptr[lo]).astype(ptr_dt)
        indptr_blocks[p, : hi - lo + 1] = local
        # edge-pad: rows past this shard's range read as degree 0
        indptr_blocks[p, hi - lo + 1 :] = local[-1] if local.size else 0
        blk = indices[int(indptr[lo]) : int(indptr[hi])]
        indices_blocks[p, : blk.shape[0]] = blk
    rs_dt = np.int32 if int(row_start[-1]) < 2**31 else np.int64
    return indptr_blocks, indices_blocks, row_start.astype(rs_dt)


def build_tiled_topology_shards(
    indptr: np.ndarray,
    indices: np.ndarray,
    n_shards: int,
    pad_multiple: int = 8,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host-side TILED shard construction: (bd_blocks, tiles_blocks,
    row_start) as stacked numpy arrays (see `TiledShardedTopology`).

    Row boundaries come from the same `partition_rows_by_edges` split as
    the flat build, and each shard's contiguous block is rebuilt with
    `build_tiled_host` on its LOCAL indptr — so a shard's tile table holds
    exactly the edges of its flat indices block, in the same per-row
    order (the parity tests lean on this). Per-shard tile counts are
    padded to the max (rounded up to ``pad_multiple`` tile rows) so the
    blocks stack into one ``[P, M_max, 128]`` device array; bd blocks are
    row-padded with degree-0 entries so out-of-range lookups draw nothing.
    """
    indptr = np.asarray(indptr)
    indices = np.asarray(indices)
    row_start = partition_rows_by_edges(indptr, n_shards)
    r_max = int(np.max(row_start[1:] - row_start[:-1])) if n_shards else 0
    r_max = max(r_max, 1)
    blocks = []
    for p in range(n_shards):
        lo, hi = int(row_start[p]), int(row_start[p + 1])
        local_ptr = (indptr[lo : hi + 1] - indptr[lo]).astype(np.int64)
        local_idx = indices[int(indptr[lo]) : int(indptr[hi])]
        blocks.append(build_tiled_host(local_ptr, local_idx, indices.dtype))
    m_max = max(max(t.shape[0] for _, t in blocks), 1)
    m_max = -(-m_max // pad_multiple) * pad_multiple
    bd_blocks = np.zeros((n_shards, r_max, 2), np.int32)
    tiles_blocks = np.zeros((n_shards, m_max, LANE), indices.dtype)
    for p, (bd, tiles) in enumerate(blocks):
        bd_blocks[p, : bd.shape[0]] = bd
        tiles_blocks[p, : tiles.shape[0]] = tiles
    rs_dt = np.int32 if int(row_start[-1]) < 2**31 else np.int64
    return bd_blocks, tiles_blocks, row_start.astype(rs_dt)


def shard_topology_rows(
    mesh: Mesh,
    topo,
    axes: Optional[Tuple[str, ...]] = None,
    layout: Optional[str] = None,
) -> Union["ShardedTopology", "TiledShardedTopology"]:
    """Place a `CSRTopo` row-sharded over the mesh's feature axes.

    Each device ends up holding ONLY its contiguous CSR block (~E/P edges;
    edge-balanced), so total graph capacity scales with chip count — the
    papers100M axis the reference serves with UVA (quiver_sample.cu:361-421).

    ``axes`` defaults to the mesh's feature axes ((host, ici) on a 3-axis
    mesh, else (ici,)); the blocks are replicated over the remaining axes.

    ``layout`` picks the per-shard block format: "flat" (`ShardedTopology`)
    or "tiled" (`TiledShardedTopology`, the 128-lane tile layout). ``None``
    resolves per backend (`resolve_topology_layout`: tiled on TPU). Pair
    with the same ``layout`` on `make_sharded_topo_train_step`.
    """
    from .train import mesh_axes

    layout = resolve_topology_layout(layout)
    if axes is None:
        _, axes, _ = mesh_axes(mesh)
    axes = (axes,) if isinstance(axes, str) else tuple(axes)
    n_shards = 1
    for a in axes:
        n_shards *= mesh.shape[a]
    rep = NamedSharding(mesh, P())
    if layout == "tiled":
        bd_b, tiles_b, row_start = build_tiled_topology_shards(
            topo.indptr, topo.indices, n_shards
        )
        blk3 = NamedSharding(mesh, P(axes, None, None))
        return TiledShardedTopology(
            bd=jax.device_put(jnp.asarray(bd_b), blk3),
            tiles=jax.device_put(jnp.asarray(tiles_b), blk3),
            row_start=jax.device_put(jnp.asarray(row_start), rep),
        )
    indptr_b, indices_b, row_start = build_topology_shards(
        topo.indptr, topo.indices, n_shards
    )
    blk_sharding = NamedSharding(mesh, P(axes, None))
    return ShardedTopology(
        indptr=jax.device_put(jnp.asarray(indptr_b), blk_sharding),
        indices=jax.device_put(jnp.asarray(indices_b), blk_sharding),
        row_start=jax.device_put(jnp.asarray(row_start), rep),
    )


def _flat_axis_index(axes: Tuple[str, ...]):
    idx = lax.axis_index(axes[0])
    for a in axes[1:]:
        idx = idx * axis_size_compat(a) + lax.axis_index(a)
    return idx


def _psum_assemble(nbrs, valid, axes):
    """Owner-exclusive full assembly: shard contributions are zeros off
    the owner, so a psum over the striping axes IS the gather."""
    return lax.psum(nbrs, axes), lax.psum(valid, axes) > 0


def _grouped_collective_sample(partial_fn, cur, cur_valid, k, axes, group_axis, via):
    """The ONE grouped-sample implementation both shard layouts ride:
    all_gather the per-group frontiers over ``group_axis``, draw once via
    ``partial_fn(all_cur, all_valid) -> (nbrs, valid_int32)`` (a layout's
    un-reduced shard contribution at the gathered width), then return each
    group its own ``[W, k]`` slice through one of the two spellings —
    ``via="scatter"`` psum_scatters the ``[G, W, k]`` partials over the
    group axis (ring cost (G-1)/G) and psums the remaining striping axes at
    width W; ``via="psum"`` is the round-3 full-psum+slice spelling (2x the
    group-axis bytes, G x the other axes' width — kept selectable for the
    SCALING.md comparison)."""
    h = axis_size_compat(group_axis)
    w = cur.shape[0]
    all_cur = lax.all_gather(cur, group_axis).reshape(-1)
    all_valid = lax.all_gather(cur_valid, group_axis).reshape(-1)
    if via == "psum" or group_axis not in axes:
        nbrs, valid = _psum_assemble(*partial_fn(all_cur, all_valid), axes)
        me = lax.axis_index(group_axis)
        return nbrs.reshape(h, w, k)[me], valid.reshape(h, w, k)[me]
    if via != "scatter":
        raise ValueError(f"unknown via {via!r}")
    nbrs, valid = partial_fn(all_cur, all_valid)
    nbrs = lax.psum_scatter(
        nbrs.reshape(h, w, k), group_axis, scatter_dimension=0, tiled=False
    )
    valid = lax.psum_scatter(
        valid.reshape(h, w, k), group_axis, scatter_dimension=0, tiled=False
    )
    other = tuple(a for a in axes if a != group_axis)
    if other:
        nbrs = lax.psum(nbrs, other)
        valid = lax.psum(valid, other)
    return nbrs, valid > 0


def sharded_sample_layer(
    indptr_blk: jax.Array,
    indices_blk: jax.Array,
    row_start: jax.Array,
    cur: jax.Array,
    cur_valid: jax.Array,
    k: int,
    key: jax.Array,
    axes,
) -> Tuple[jax.Array, jax.Array]:
    """Collective one-hop sample from a row-sharded CSR (inside shard_map).

    ``cur`` must be identical across every axis in ``axes`` (use
    `sharded_sample_layer_grouped` when a striping axis carries different
    frontiers). Each shard draws neighbors for the frontier rows whose
    global id falls in its ``row_start`` range — everything else reads as
    degree 0 — and the psum over ``axes`` assembles the full result, since
    row ownership is exclusive. Same contract as
    `quiver_tpu.ops.sample.sample_layer`: ``(nbrs [W, k], valid [W, k])``
    with global neighbor ids.
    """
    axes = (axes,) if isinstance(axes, str) else tuple(axes)
    nbrs, valid = _sample_layer_partial(
        indptr_blk, indices_blk, row_start, cur, cur_valid, k, key, axes
    )
    return _psum_assemble(nbrs, valid, axes)


def _sample_layer_partial(
    indptr_blk, indices_blk, row_start, cur, cur_valid, k, key, axes
):
    """This shard's un-reduced contribution to a one-hop sample: neighbors
    for the frontier rows it owns, zeros elsewhere. Callers choose the
    reduction (full psum, or scatter-over-group then psum)."""
    idx = _flat_axis_index(axes)
    start = jnp.take(row_start, idx)
    end = jnp.take(row_start, idx + 1)
    r_max = indptr_blk.shape[0] - 1
    e_pad = indices_blk.shape[0]
    local = (cur - start).astype(jnp.int32)
    mine = cur_valid & (cur >= start) & (cur < end)
    s = jnp.clip(local, 0, r_max - 1)
    ptr, deg = row_windows(indptr_blk, s)
    deg = jnp.where(mine, deg, 0)
    pos, valid = fisher_yates_positions(key, deg, k)
    flat = jnp.clip(ptr[:, None] + pos.astype(ptr.dtype), 0, e_pad - 1)
    nbrs = jnp.take(indices_blk, flat)
    nbrs = jnp.where(valid, nbrs, 0)
    return nbrs, valid.astype(jnp.int32)


def _tiled_sample_layer_partial(
    bd_blk, tiles_blk, row_start, cur, cur_valid, k, key, axes
):
    """`_sample_layer_partial` over the TILE layout: the owner test and the
    Fisher-Yates draw are identical (same key, same per-row degree — the
    draw is bit-equal to the flat path's), only position resolution differs:
    tile-row gathers + one-hot lane selects through `_tiled_resolve` instead
    of flat element gathers, the same fetch shape as the single-chip
    `tiled_sample_layer`."""
    idx = _flat_axis_index(axes)
    start = jnp.take(row_start, idx)
    end = jnp.take(row_start, idx + 1)
    local = (cur - start).astype(jnp.int32)
    mine = cur_valid & (cur >= start) & (cur < end)
    base, deg = _tiled_bd_lookup(bd_blk, local, mine)
    pos, valid = fisher_yates_positions(key, deg, k)
    nbrs = _tiled_resolve(tiles_blk, base, pos, k)
    nbrs = jnp.where(valid, nbrs, 0)
    return nbrs, valid.astype(jnp.int32)


def tiled_sharded_sample_layer(
    bd_blk: jax.Array,
    tiles_blk: jax.Array,
    row_start: jax.Array,
    cur: jax.Array,
    cur_valid: jax.Array,
    k: int,
    key: jax.Array,
    axes,
) -> Tuple[jax.Array, jax.Array]:
    """`sharded_sample_layer` over the TILE shard layout
    (`TiledShardedTopology`): same contract, same owner-exclusive psum
    assembly, bit-identical draws on the same key — the shard-local fetch
    rides 2-D row gathers instead of element gathers."""
    axes = (axes,) if isinstance(axes, str) else tuple(axes)
    nbrs, valid = _tiled_sample_layer_partial(
        bd_blk, tiles_blk, row_start, cur, cur_valid, k, key, axes
    )
    return _psum_assemble(nbrs, valid, axes)


def sharded_sample_layer_grouped(
    indptr_blk: jax.Array,
    indices_blk: jax.Array,
    row_start: jax.Array,
    cur: jax.Array,
    cur_valid: jax.Array,
    k: int,
    key: jax.Array,
    axes,
    group_axis: str,
    via: str = "scatter",
) -> Tuple[jax.Array, jax.Array]:
    """`sharded_sample_layer` for frontiers that DIFFER across ``group_axis``
    (one of the striping axes, typically "host" — data-parallel groups span
    it, so each host's frontier is distinct). Grouped machinery and both
    ``via`` return-trip spellings live in `_grouped_collective_sample`.
    """
    axes = (axes,) if isinstance(axes, str) else tuple(axes)

    def partial_fn(all_cur, all_valid):
        return _sample_layer_partial(
            indptr_blk, indices_blk, row_start, all_cur, all_valid, k, key, axes
        )

    return _grouped_collective_sample(
        partial_fn, cur, cur_valid, k, axes, group_axis, via
    )


def tiled_sharded_sample_layer_grouped(
    bd_blk: jax.Array,
    tiles_blk: jax.Array,
    row_start: jax.Array,
    cur: jax.Array,
    cur_valid: jax.Array,
    k: int,
    key: jax.Array,
    axes,
    group_axis: str,
    via: str = "scatter",
) -> Tuple[jax.Array, jax.Array]:
    """`sharded_sample_layer_grouped` over the TILE shard layout: identical
    grouped machinery and ``via`` spellings (`_grouped_collective_sample`),
    tiled shard-local fetches."""
    axes = (axes,) if isinstance(axes, str) else tuple(axes)

    def partial_fn(all_cur, all_valid):
        return _tiled_sample_layer_partial(
            bd_blk, tiles_blk, row_start, all_cur, all_valid, k, key, axes
        )

    return _grouped_collective_sample(
        partial_fn, cur, cur_valid, k, axes, group_axis, via
    )


def gather_comm_bytes(
    mesh: Mesh,
    width: int,
    dim: int,
    cold_budget: Optional[int] = None,
    feat_bytes: int = 4,
    id_bytes: int = 4,
    via: str = "scatter",
) -> Dict[str, float]:
    """Per-gather collective-byte model (ring costs, same conventions as
    `sampling_comm_bytes`) for ONE feature gather of ``width`` ids on a
    multi-host mesh — the number that makes the replicated-hot win
    quantitative: with ``cold_budget`` set (the `sharded_gather_hot_cold`
    layout) only the cold lanes ride the DCN leg, so DCN bytes scale by
    ``cold_budget / width`` ≈ the hot-tier miss rate.

    ``via`` mirrors `sharded_gather_grouped`: "scatter" (the default
    implementation — psum_scatter the [H, W, D] partials over host, then an
    ici psum at width W) or "psum" (round-3 full psum + slice: 2x the DCN
    row bytes and H x the ici width; see the SCALING.md round-4 table).
    """
    from .train import mesh_axes

    _, feat_axes, _ = mesh_axes(mesh)
    has_host = "host" in mesh.axis_names
    hostsz = mesh.shape["host"] if has_host else 1
    out = {"ici_bytes": 0.0, "dcn_bytes": 0.0}

    def add_psum(n_elems, axes):
        for a in axes:
            sz = mesh.shape[a]
            if sz == 1:
                continue
            b = 2.0 * (sz - 1) / sz * n_elems * feat_bytes
            out["dcn_bytes" if a == "host" else "ici_bytes"] += b

    def add_grouped_rows(w):
        """Return-trip bytes for a grouped gather of w rows per group."""
        if via == "scatter":
            # psum_scatter [H, w, D] over host + psum [w, D] over ici
            out["dcn_bytes"] += (hostsz - 1) / hostsz * hostsz * w * dim * feat_bytes
            add_psum(w * dim, ici_axes)
        else:
            add_psum(w * hostsz * dim, feat_axes)

    ici_axes = tuple(a for a in feat_axes if a != "host")
    if not has_host:
        add_psum(width * dim, feat_axes)
    elif cold_budget is None:
        # grouped: all_gather W ids over host, then the row return trip
        out["dcn_bytes"] += (hostsz - 1) / hostsz * width * hostsz * id_bytes
        add_grouped_rows(width)
    else:
        # hot: ICI-only psum at full width (per host)
        add_psum(width * dim, ici_axes)
        # cold: grouped path at the budgeted width
        out["dcn_bytes"] += (hostsz - 1) / hostsz * cold_budget * hostsz * id_bytes
        add_grouped_rows(cold_budget)
    out["total_bytes"] = out["ici_bytes"] + out["dcn_bytes"]
    return out


def sampling_comm_bytes(
    mesh: Mesh,
    sizes: Sequence[int],
    batch_per_group: int,
    feature_dim: int = 0,
    caps: Optional[Sequence[Optional[int]]] = None,
    id_bytes: int = 4,
    feat_bytes: int = 4,
    via: str = "scatter",
    layout: str = "flat",
) -> Dict[str, float]:
    """Static per-step collective-traffic model for the sharded-topology
    train step — the ICI/DCN byte accounting the multichip artifacts log.

    Counts, per training step and per chip, the bytes each collective moves
    over ICI (within a host) and DCN (the host axis), using the ring model
    (psum ≈ 2(P-1)/P × payload, all_gather ≈ (P-1)/P × gathered payload,
    psum_scatter ≈ (P-1)/P × payload; a multi-axis psum decomposes into a
    per-axis ring each paying its own (A-1)/A factor on the FULL payload,
    ICI legs first). Hop widths follow `pad_widths`; ``feature_dim > 0``
    adds the per-hop sharded feature-gather of the fused pipeline. ``via``
    selects the grouped return-trip spelling the step uses ("scatter" =
    the implementation default; "psum" = the round-3 spelling, kept for the
    SCALING.md comparison). This is a *model* — on real hardware XLA may
    pick other algorithms — but it makes relative layout costs comparable
    without a pod.

    ``layout`` ("flat" | "tiled", the `ShardedTopology` vs
    `TiledShardedTopology` shard formats) does NOT change the collective
    accounting — both layouts move the identical ``[W, k]`` neighbor/valid
    return and frontier all_gather — but it changes the shard-LOCAL HBM
    fetch shape, reported as two extra keys: ``hbm_descriptors`` (gather
    descriptors issued per chip per step: one per frontier row for the
    degree/base lookup plus one per drawn position) and ``hbm_fetch_bytes``
    (bytes those descriptors move: 128-lane tile rows under "tiled",
    single elements under "flat"). Descriptor COUNTS match between layouts;
    what differs is the bytes per descriptor and — the reason tiled wins —
    the issue RATE: TPU row gathers stream ~1.4-2.6x faster than element
    gathers (PERF_NOTES.md; `scaling.sharded_fetch_table` applies the
    measured rates).
    """
    from .train import mesh_axes

    _, feat_axes, _ = mesh_axes(mesh)
    has_host = "host" in mesh.axis_names
    hostsz = mesh.shape["host"] if has_host else 1
    out: Dict[str, float] = {"ici_bytes": 0.0, "dcn_bytes": 0.0}
    widths = pad_widths(batch_per_group, sizes, caps)
    ici_axes = tuple(a for a in feat_axes if a != "host")

    def add_psum(n_elems: int, elem_bytes: int, axes=None):
        # per-axis rings over the striping axes; payload does not shrink
        for a in (feat_axes if axes is None else axes):
            sz = mesh.shape[a]
            if sz == 1:
                continue
            b = 2.0 * (sz - 1) / sz * n_elems * elem_bytes
            out["dcn_bytes" if a == "host" else "ici_bytes"] += b

    def add_all_gather_host(n_elems: int, elem_bytes: int):
        if hostsz > 1:
            out["dcn_bytes"] += (hostsz - 1) / hostsz * n_elems * hostsz * elem_bytes

    def add_grouped(per_group_elems: int, elem_bytes: int):
        """Return trip of a grouped collective, per_group_elems per group."""
        if not has_host or via == "psum":
            add_psum(per_group_elems * hostsz, elem_bytes)
        else:
            # psum_scatter [H, w] over host + psum [w] over ici
            out["dcn_bytes"] += (
                (hostsz - 1) / hostsz * hostsz * per_group_elems * elem_bytes
            )
            add_psum(per_group_elems, elem_bytes, axes=ici_axes)

    layout = resolve_topology_layout(layout)
    hbm_desc = 0.0
    hbm_fetch = 0.0
    for l, k in enumerate(sizes):
        if has_host:
            add_all_gather_host(widths[l], id_bytes + 1)  # frontier ids + valid
        add_grouped(widths[l] * k, id_bytes + 4)  # nbrs + int32 valid return
        if feature_dim:
            add_grouped(widths[l] * k * feature_dim, feat_bytes)
        # shard-local fetch: every chip resolves the all_gathered frontier
        w = widths[l] * hostsz
        hbm_desc += w + w * k  # degree/base lookup + k-split position fetch
        per_fetch = LANE * id_bytes if layout == "tiled" else id_bytes
        hbm_fetch += w * 8 + w * k * per_fetch
    if feature_dim:
        add_grouped(widths[0] * feature_dim, feat_bytes)  # seed rows
    out["hbm_descriptors"] = hbm_desc
    out["hbm_fetch_bytes"] = hbm_fetch
    out["total_bytes"] = out["ici_bytes"] + out["dcn_bytes"]
    return out

"""Sharded end-to-end training step over a device mesh.

The reference's scaling story is torch DDP (gradient allreduce over NCCL,
examples/multi_gpu/pyg/ogb-products/dist_sampling_ogb_products_quiver.py:85-117)
around per-GPU sampling + the tiered feature cache. The TPU-native story is a
single jitted step over a 2-D mesh:

- ``dp`` axis: data parallelism — per-shard seed batches, gradient ``psum``
  (replacing DDP/NCCL allreduce);
- ``ici`` axis: the hot feature table is row-sharded across chips
  (``p2p_clique_replicate`` analog, reference feature.py:225-265), assembled
  per batch with one collective gather (`sharded_gather`).

Sampling, reindex, gather, forward, backward, and the optimizer update all
trace into ONE XLA program — the compiler overlaps the collectives with
compute, which is the ICI analog of the reference overlapping NVLink peer
reads inside its gather kernel.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# jax >= 0.6 top-level shard_map vs older experimental spelling: one
# compat wrapper (utils.shard_map_compat) absorbs both the location and
# the check_vma/check_rep rename
from ..utils import axis_size_compat, shard_map_compat as _shard_map_fn

from ..pyg.sage_sampler import (
    sample_and_gather_dedup,
    sample_and_gather_fused,
)
from .collectives import (
    sharded_gather,
    sharded_gather_grouped,
    sharded_gather_hot_cold,
)


def make_mesh(
    n_devices: Optional[int] = None,
    dp: Optional[int] = None,
    hosts: Optional[int] = None,
) -> Mesh:
    """Build a (dp, ici) mesh over the first n local devices; ici gets the
    largest power-of-two factor so the feature shard spans chips.

    ``hosts`` adds a leading DCN axis: a (host, dp, ici) mesh where the
    feature table stripes over (host, ici) and gradients psum over
    (host, dp) — the papers100M-scale multi-host layout in one program
    (on a real pod ``host`` maps to the inter-host dimension of
    ``jax.devices()``; hermetically it is just more virtual devices).
    """
    import numpy as np

    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(
            f"make_mesh: requested {n} devices but only {len(devs)} are "
            f"visible ({devs}); for a virtual mesh set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n} and "
            f'jax.config.update("jax_platforms", "cpu") before first jax use'
        )
    devs = np.array(devs[:n])
    if hosts is not None:
        if hosts <= 0 or n % hosts != 0:
            raise ValueError(f"make_mesh: hosts={hosts} does not divide {n}")
        per_host = n // hosts
        inner = make_mesh_shape(per_host, dp)
        return Mesh(devs.reshape(hosts, *inner), ("host", "dp", "ici"))
    return Mesh(devs.reshape(make_mesh_shape(n, dp)), ("dp", "ici"))


def make_mesh_shape(n: int, dp: Optional[int] = None) -> Tuple[int, int]:
    """(dp, ici) factorization: ici takes the largest power-of-two factor."""
    if dp is None:
        dp = 1
        m = n
        while m % 2 == 0 and dp < m // 2:
            dp *= 2
            m //= 2
    if dp <= 0 or n % dp != 0:
        raise ValueError(f"make_mesh: dp={dp} does not divide device count {n}")
    return dp, n // dp


def mesh_axes(mesh: Mesh) -> Tuple[Tuple[str, ...], Tuple[str, ...], int]:
    """(data_axes, feature_axes, n_data_groups) for a quiver mesh — the ONE
    place the (host?, dp, ici) layout conventions live: seeds/gradients span
    ``data_axes``, the feature table stripes over ``feature_axes``."""
    has_host = "host" in mesh.axis_names
    data_axes = ("host", "dp") if has_host else ("dp",)
    feat_axes = ("host", "ici") if has_host else ("ici",)
    n_groups = 1
    for a in data_axes:
        n_groups *= mesh.shape[a]
    return data_axes, feat_axes, n_groups


def _validate_step_config(mesh, pipeline, caps, hot_rows, cold_budget):
    """Shared precondition checks + layout facts for both step factories.
    Returns (has_host, data_axes, feat_axes, hot_cold)."""
    if pipeline not in ("dedup", "fused"):
        raise ValueError(f"unknown pipeline: {pipeline!r}")
    if pipeline == "fused" and caps is not None:
        raise ValueError(
            "caps only apply to the dedup pipeline: the fused layout is "
            "structural (width is exactly B*prod(1+k), not cappable)"
        )
    has_host = "host" in mesh.axis_names
    data_axes, feat_axes, _ = mesh_axes(mesh)
    hot_cold = hot_rows is not None
    if hot_cold and not has_host:
        raise ValueError(
            "hot_rows/cold_budget need a multi-host mesh: on a single host "
            "the plain ici-sharded gather already pays no DCN cost"
        )
    if hot_cold and cold_budget is None:
        raise ValueError("hot_rows set but cold_budget missing")
    return has_host, data_axes, feat_axes, hot_cold


def _make_gather_rows(has_host, hot_cold, feat_axes, hot_rows, cold_budget,
                      overflow_acc):
    """The per-step feature gather closure both factories share: plain
    ici-sharded, host-grouped, or replicated-hot/cold (appending each
    call's overflow to ``overflow_acc``)."""
    def gather_rows(tab, ids):
        # hosts sample DIFFERENT seeds, so the host axis needs the grouped
        # gather (see sharded_gather_grouped: all_gather ids over host,
        # gather once, slice own answer)
        if hot_cold:
            hot_block, cold_block = tab
            rows, overflow = sharded_gather_hot_cold(
                hot_block, cold_block, ids, feat_axes, "host",
                hot_rows, cold_budget,
            )
            overflow_acc.append(overflow)
            return rows
        if not has_host:
            return sharded_gather(tab, ids, feat_axes)
        return sharded_gather_grouped(tab, ids, feat_axes, "host")

    return gather_rows


def _fold_group_key(key, has_host):
    """Distinct sample stream per data-parallel group, identical within an
    ici group."""
    dp_idx = lax.axis_index("dp")
    if has_host:
        dp_idx = lax.axis_index("host") * axis_size_compat("dp") + dp_idx
    return jax.random.fold_in(key, dp_idx)


def _loss_and_update(model, tx, train, data_axes, hot_cold, overflow_acc,
                     params, opt_state, dropout_key, ds, x, labels, seeds):
    """Shared tail of both step functions: objective, grad pmean over the
    data axes (the DDP-analog allreduce), optimizer update — plus, on
    hot/cold layouts, the worst cold-budget overflow across groups as a
    FOURTH output (persistently nonzero means the budget needs raising,
    see `sharded_gather_hot_cold`)."""
    y = jnp.take(labels, jnp.clip(ds.n_id[: seeds.shape[0]], 0, labels.shape[0] - 1))

    def objective(p):
        logits = model.apply(
            p, x, ds.adjs, train=train,
            rngs={"dropout": dropout_key} if train else None,
        )
        ll = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(ll, y[:, None].astype(jnp.int32), axis=1)[:, 0]
        return nll.mean()

    loss, grads = jax.value_and_grad(objective)(params)
    grads = lax.pmean(grads, data_axes)
    loss = lax.pmean(loss, data_axes)
    updates, opt_state = tx.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    if hot_cold:
        overflow = lax.pmax(sum(overflow_acc), data_axes)
        return params, opt_state, loss, overflow
    return params, opt_state, loss


def _step_specs(hot_cold, feat_axes):
    """(feat_spec, out_specs) for shard_map: hot block replicated over host
    (striped over ici) + cold block striped over every feature axis on
    hot/cold layouts; a single striped table otherwise."""
    if hot_cold:
        ici_axes = tuple(a for a in feat_axes if a != "host")
        feat_spec = (P(ici_axes, None), P(feat_axes, None))
        return feat_spec, (P(), P(), P(), P())
    return P(feat_axes, None), (P(), P(), P())


def make_sharded_train_step(
    mesh: Mesh,
    model,
    tx,
    sizes: Sequence[int],
    caps: Optional[Sequence[Optional[int]]] = None,
    train: bool = True,
    pipeline: str = "dedup",
    hot_rows: Optional[int] = None,
    cold_budget=None,
):
    """Build ``step(params, opt_state, key, indptr, indices, feat_block,
    labels, seeds) -> (params, opt_state, loss)``.

    Sharding contract (the full tp/dp layout of this framework):
      - indptr/indices/labels: replicated (graph topology in every HBM; use
        `make_sharded_topo_train_step` to row-shard the CSR instead);
      - feat_block: hot rows striped over the ici axis, replicated over dp
        (the p2p_clique_replicate layout, reference feature.py:225-265);
      - seeds: sharded over dp, replicated over ici;
      - params/opt_state: replicated; grads psum over dp.

    ``pipeline``: "dedup" (reference-parity per-hop reindex) or "fused"
    (no-dedup structural layout; per-hop ICI gathers interleave with
    sampling — the fastest path, same tradeoff as the single-chip
    pipelines, PERF_NOTES.md).

    ``hot_rows``/``cold_budget`` (multi-host meshes only) switch the feature
    gather to the replicated-hot layout (`sharded_gather_hot_cold`): the
    heat-ordered table's first ``hot_rows`` rows are replicated per host
    (striped over ici) and only up to ``cold_budget`` cold lanes per gather
    ride the DCN grouped path. ``feat_block`` must then be the
    ``(hot_block, cold_block)`` pair from `shard_feature_hot_cold`;
    ``cold_budget`` may be a float fraction of each gather's width.
    Overflowing cold ids come back as zero rows, and the step returns a
    FOURTH output — the worst summed overflow across data groups this
    step; persistently nonzero means the budget needs raising
    (`calibrate_cold_budget` produces a float budget with margin).
    """
    # with a "host" DCN axis (make_mesh(hosts=...)), the feature table
    # stripes over (host, ici) and gradients sync over (host, dp)
    has_host, data_axes, feat_axes, hot_cold = _validate_step_config(
        mesh, pipeline, caps, hot_rows, cold_budget
    )

    def step_local(params, opt_state, key, indptr, indices, feat_block, labels, seeds):
        overflow_acc = []
        gather_rows = _make_gather_rows(
            has_host, hot_cold, feat_axes, hot_rows, cold_budget, overflow_acc
        )
        key, dropout_key = jax.random.split(_fold_group_key(key, has_host))
        if pipeline == "fused":
            ds, x = sample_and_gather_fused(
                indptr, indices, feat_block, key, seeds, tuple(sizes),
                gather_fn=gather_rows,
            )
        else:
            # struct-leaf dedup (same formulation as the single-chip e2e):
            # reference-parity sampling DAG, last hop's features gathered
            # straight through the sharded gather in structural layout
            ds, x = sample_and_gather_dedup(
                indptr, indices, feat_block, key, seeds, tuple(sizes), caps,
                gather_fn=gather_rows,
            )
        return _loss_and_update(
            model, tx, train, data_axes, hot_cold, overflow_acc,
            params, opt_state, dropout_key, ds, x, labels, seeds,
        )

    feat_spec, out_specs = _step_specs(hot_cold, feat_axes)
    sharded = _shard_map_fn(
        step_local,
        mesh=mesh,
        in_specs=(
            P(),            # params (replicated)
            P(),            # opt_state
            P(),            # rng key
            P(),            # indptr
            P(),            # indices
            feat_spec,      # feature rows (see docstring)
            P(),            # labels
            P(data_axes),   # seeds sharded over (host?,) dp
        ),
        out_specs=out_specs,
        check_vma=False,
    )
    return jax.jit(sharded)


def make_sharded_topo_train_step(
    mesh: Mesh,
    model,
    tx,
    sizes: Sequence[int],
    caps: Optional[Sequence[Optional[int]]] = None,
    train: bool = True,
    pipeline: str = "dedup",
    hot_rows: Optional[int] = None,
    cold_budget=None,
    layout: Optional[str] = None,
):
    """`make_sharded_train_step` with the GRAPH row-sharded across the mesh.

    Build ``step(params, opt_state, key, stopo, feat_block,
    labels, seeds) -> (params, opt_state, loss)``. Unlike
    `make_sharded_train_step` — which replicates indptr/indices in every
    HBM — each device holds only its contiguous CSR block
    (`topology.shard_topology_rows`), so total graph capacity scales with
    chip count: the papers100M axis the reference reaches with UVA
    (quiver_sample.cu:361-421, train_quiver_multi_node.py). Each hop's
    neighbor draw becomes a psum collective over the topology axes
    (`topology.sharded_sample_layer`); with a "host" axis the frontier is
    first all_gathered over it (hosts sample different seeds), mirroring the
    grouped feature gather.

    ``layout`` selects the shard block format ``stopo`` must carry —
    "flat" (`ShardedTopology`) or "tiled" (`TiledShardedTopology`, the
    128-lane tile layout whose row-gather fetch shape won the single-chip
    2.58x fused-SEPS round). ``None`` resolves per backend
    (`topology.resolve_topology_layout`: tiled on TPU, matching the
    single-chip `GraphSageSampler` default). Build ``stopo`` with the SAME
    ``layout`` on `shard_topology_rows`; collective payloads and sampling
    draws are identical between layouts (same key -> same neighbors).

    ``hot_rows``/``cold_budget`` compose the replicated-hot feature tier
    with the sharded topology (multi-host meshes; same contract as
    `make_sharded_train_step`): pass ``(hot_block, cold_block)`` from
    `shard_feature_hot_cold` as ``feat_block``.

    Per-step collective traffic for this layout is statically modeled by
    `topology.sampling_comm_bytes` — log it next to any multichip artifact.
    """
    from .topology import (
        resolve_topology_layout,
        sharded_sample_layer,
        sharded_sample_layer_grouped,
        tiled_sharded_sample_layer,
        tiled_sharded_sample_layer_grouped,
    )

    layout = resolve_topology_layout(layout)
    has_host, data_axes, feat_axes, hot_cold = _validate_step_config(
        mesh, pipeline, caps, hot_rows, cold_budget
    )

    def step_local(params, opt_state, key, stopo, feat_block, labels, seeds):
        overflow_acc = []
        gather_rows = _make_gather_rows(
            has_host, hot_cold, feat_axes, hot_rows, cold_budget, overflow_acc
        )

        row_start = stopo.row_start     # [P+1] replicated boundaries
        if layout == "tiled":
            bd_blk = stopo.bd[0]        # [R_max, 2] this shard's (base, deg)
            tiles_blk = stopo.tiles[0]  # [M_max, 128] this shard's tile table

            def sample_fn(cur, cur_valid, k, sub):
                if not has_host:
                    return tiled_sharded_sample_layer(
                        bd_blk, tiles_blk, row_start, cur, cur_valid, k,
                        sub, feat_axes,
                    )
                return tiled_sharded_sample_layer_grouped(
                    bd_blk, tiles_blk, row_start, cur, cur_valid, k, sub,
                    feat_axes, "host",
                )
        else:
            indptr_blk = stopo.indptr[0]    # [R_max+1] shard-local indptr
            indices_blk = stopo.indices[0]  # [E_pad] this shard's edge block

            def sample_fn(cur, cur_valid, k, sub):
                if not has_host:
                    return sharded_sample_layer(
                        indptr_blk, indices_blk, row_start, cur, cur_valid, k,
                        sub, feat_axes,
                    )
                return sharded_sample_layer_grouped(
                    indptr_blk, indices_blk, row_start, cur, cur_valid, k, sub,
                    feat_axes, "host",
                )

        key, dropout_key = jax.random.split(_fold_group_key(key, has_host))
        if pipeline == "fused":
            ds, x = sample_and_gather_fused(
                None, None, feat_block, key, seeds, tuple(sizes),
                gather_fn=gather_rows, sample_fn=sample_fn,
            )
        else:
            ds, x = sample_and_gather_dedup(
                None, None, feat_block, key, seeds, tuple(sizes), caps,
                gather_fn=gather_rows, sample_fn=sample_fn,
            )
        return _loss_and_update(
            model, tx, train, data_axes, hot_cold, overflow_acc,
            params, opt_state, dropout_key, ds, x, labels, seeds,
        )

    from .topology import tiled_topology_specs, topology_specs

    topo_specs = (
        tiled_topology_specs(feat_axes) if layout == "tiled"
        else topology_specs(feat_axes)
    )
    feat_spec, out_specs = _step_specs(hot_cold, feat_axes)
    sharded = _shard_map_fn(
        step_local,
        mesh=mesh,
        in_specs=(
            P(),            # params (replicated)
            P(),            # opt_state
            P(),            # rng key
            topo_specs,     # row-sharded CSR blocks + replicated boundaries
            feat_spec,      # feature rows (see docstring)
            P(),            # labels
            P(data_axes),   # seeds sharded over (host?,) dp
        ),
        out_specs=out_specs,
        check_vma=False,
    )
    return jax.jit(sharded)


def shard_feature_rows(mesh: Mesh, table) -> jax.Array:
    """Place a [N, D] host table row-striped over the feature axes — ici,
    plus host when the mesh has the DCN axis (replicated over dp); pads N
    to a multiple of the shard count."""
    from .collectives import pad_to_multiple

    _, feat_axes, _ = mesh_axes(mesh)
    shards = 1
    for a in feat_axes:
        shards *= mesh.shape[a]
    padded = pad_to_multiple(table, shards)
    sharding = NamedSharding(mesh, P(feat_axes, None))
    return jax.device_put(jnp.asarray(padded), sharding)


def shard_feature_hot_cold(
    mesh: Mesh, table, hot_rows: int
) -> Tuple[jax.Array, jax.Array]:
    """Split a heat-ordered [N, D] table for `sharded_gather_hot_cold`:
    rows ``< hot_rows`` replicated per host (striped over ici), the cold
    remainder striped over every feature axis. Zero-pads both blocks to
    their shard multiples (hot padding rows MUST be zero — cold ids landing
    in the padded hot range rely on it). Order the table by heat first
    (``Feature`` degree order / `utils.reindex_by_config`) — the analog of
    the reference's replicate-hottest preprocessing
    (mag240m preprocess.py:117-179)."""
    import numpy as np

    from .collectives import pad_to_multiple

    _, feat_axes, _ = mesh_axes(mesh)
    ici_axes = tuple(a for a in feat_axes if a != "host")
    if ici_axes == feat_axes:
        raise ValueError("hot/cold placement needs a multi-host mesh")
    ici = 1
    for a in ici_axes:
        ici *= mesh.shape[a]
    shards = ici
    for a in feat_axes:
        if a == "host":
            shards *= mesh.shape[a]
    table = np.asarray(table)
    if not 0 < hot_rows < table.shape[0]:
        raise ValueError(f"hot_rows {hot_rows} out of range for {table.shape}")
    hot = pad_to_multiple(table[:hot_rows], ici)
    cold = pad_to_multiple(table[hot_rows:], shards)
    hot_dev = jax.device_put(jnp.asarray(hot), NamedSharding(mesh, P(ici_axes, None)))
    cold_dev = jax.device_put(jnp.asarray(cold), NamedSharding(mesh, P(feat_axes, None)))
    return hot_dev, cold_dev


def calibrate_cold_budget(
    sampler,
    probe_seeds,
    hot_rows: int,
    margin: float = 1.3,
) -> float:
    """Cold-lane budget FRACTION for `sharded_gather_hot_cold`, calibrated
    like the sampler caps: max observed cold share of the sampled id space
    over probe batches x ``margin`` (capped at 1.0).

    A fraction — not a lane count — because the train steps gather at
    several static widths per step (frontier block, structural leaf block);
    `sharded_gather_hot_cold` scales a float budget to each call's width
    with a 256-lane granule. The id space must be heat-ordered (rows <
    ``hot_rows`` are the replicated tier) — the convention the gather
    itself assumes."""
    import numpy as np

    shares = []
    for seeds in probe_seeds:
        ds = sampler.sample_dense(np.asarray(seeds))
        n_id = np.asarray(ds.n_id)
        # prefix-valid (dedup) samples: count real lanes only; structural
        # samples interleave invalid lanes that carry real sampled ids, so
        # counting every lane is the conservative choice there
        if all(a.cols is not None for a in ds.adjs):
            n_id = n_id[: int(ds.count)]
        if n_id.shape[0]:
            shares.append(float((n_id >= hot_rows).mean()))
    if not shares:
        raise ValueError("calibrate_cold_budget needs at least one probe batch")
    return float(min(max(shares) * margin, 1.0))


def replicate(mesh: Mesh, x):
    """Place an array or pytree fully replicated on the mesh."""
    x = jax.tree_util.tree_map(jnp.asarray, x)
    return jax.device_put(x, NamedSharding(mesh, P()))

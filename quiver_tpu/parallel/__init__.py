"""Multi-chip / multi-host parallelism over jax.sharding meshes."""

from .collectives import (
    pad_to_multiple,
    sharded_gather,
    sharded_gather_a2a,
    sharded_gather_grouped,
)
from .topology import (
    ShardedTopology,
    TiledShardedTopology,
    build_tiled_topology_shards,
    resolve_topology_layout,
    sampling_comm_bytes,
    shard_topology_rows,
    sharded_sample_layer,
    sharded_sample_layer_grouped,
    tiled_sharded_sample_layer,
    tiled_sharded_sample_layer_grouped,
)
from .collectives import sharded_gather_hot_cold
from .scaling import (
    collective_payload_bytes,
    predict_layout,
    products_scaling_table,
)
from .train import (
    calibrate_cold_budget,
    make_mesh,
    make_sharded_topo_train_step,
    make_sharded_train_step,
    mesh_axes,
    replicate,
    shard_feature_hot_cold,
    shard_feature_rows,
)

__all__ = [
    "ShardedTopology",
    "TiledShardedTopology",
    "build_tiled_topology_shards",
    "calibrate_cold_budget",
    "resolve_topology_layout",
    "tiled_sharded_sample_layer",
    "tiled_sharded_sample_layer_grouped",
    "collective_payload_bytes",
    "predict_layout",
    "products_scaling_table",
    "make_mesh",
    "make_sharded_topo_train_step",
    "make_sharded_train_step",
    "mesh_axes",
    "pad_to_multiple",
    "replicate",
    "sampling_comm_bytes",
    "shard_feature_hot_cold",
    "shard_feature_rows",
    "sharded_gather_hot_cold",
    "shard_topology_rows",
    "sharded_gather",
    "sharded_gather_a2a",
    "sharded_gather_grouped",
    "sharded_sample_layer",
    "sharded_sample_layer_grouped",
]

"""Multi-chip / multi-host parallelism over jax.sharding meshes."""

from .collectives import (
    pad_to_multiple,
    sharded_gather,
    sharded_gather_a2a,
    sharded_gather_grouped,
)
from .train import (
    make_mesh,
    make_sharded_train_step,
    mesh_axes,
    replicate,
    shard_feature_rows,
)

__all__ = [
    "make_mesh",
    "make_sharded_train_step",
    "mesh_axes",
    "pad_to_multiple",
    "replicate",
    "shard_feature_rows",
    "sharded_gather",
    "sharded_gather_a2a",
    "sharded_gather_grouped",
]

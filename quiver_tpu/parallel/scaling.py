"""Analytic multi-chip scaling model for the sharded train steps.

The reference publishes MEASURED 1-to-4-GPU scaling tables for its sampling
and e2e benchmarks (docs/Introduction_en.md:123-126 sampling, :144-158 e2e
epochs). This environment exposes a single tunneled TPU chip, so the
framework's multichip evidence is split: hermetic correctness on the virtual
CPU mesh (tests/test_parallel.py, `__graft_entry__.dryrun_multichip`) plus
THIS static cost model, which predicts step/epoch time on N chips from

- the single-chip measured step time (BENCH context, PERF_NOTES.md), and
- per-step collective bytes counted statically from the same layout the
  jitted programs use (`topology.sampling_comm_bytes` ring model), divided
  by explicit, overridable link-bandwidth assumptions.

Every number the model emits is tagged with the assumptions; on real
multi-chip hardware `scripts/scaling_model.py --measured ...` rows can be
replaced by measurements one at a time without touching the model.

Model shape
-----------
A data-parallel epoch at ``N`` chips runs ``ceil(steps_1 / N)`` steps whose
duration is bounded below by ``max(t_compute, t_comm)`` (perfect overlap)
and above by ``t_compute + t_comm`` (no overlap). XLA overlaps collectives
with compute inside one program, so reality sits between; the table reports
the pessimistic (additive) bound plus the optimistic bound, and scaling
efficiency against ideal linear speedup. ``t_compute`` is the measured
single-chip step time: per-chip batch work is constant under dp scaling
(each dp group samples its own seed batch).
"""

from __future__ import annotations

import math
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple


class ShapeMesh(NamedTuple):
    """Duck-typed stand-in for `jax.sharding.Mesh` carrying only what the
    byte model reads (`mesh_axes` / `sampling_comm_bytes` touch
    ``axis_names`` and ``shape[axis]`` exclusively), so layouts larger than
    the visible device count can be modeled without devices."""

    axis_names: Tuple[str, ...]
    shape: Dict[str, int]


# Link-rate assumptions (bytes/s, per chip or per host). Deliberately
# conservative public-ballpark figures — the point is relative layout cost,
# and each is a named knob the caller can override.
DEFAULT_BANDWIDTHS = {
    # v5e inter-chip interconnect, usable per-chip ring bandwidth
    "ici_bytes_per_s": 9.0e10,
    # data-center network per host (200 Gbps NIC class)
    "dcn_bytes_per_s": 2.5e10,
}


class LayoutPrediction(NamedTuple):
    layout: str
    n_devices: int
    mesh_shape: Dict[str, int]
    step_comm_s: float
    step_s_optimistic: float   # max(compute, comm): perfect overlap
    step_s_pessimistic: float  # compute + comm: zero overlap
    epoch_s_optimistic: float
    epoch_s_pessimistic: float
    efficiency_pessimistic: float  # vs ideal linear scaling of the epoch
    ici_bytes: float
    dcn_bytes: float


import re as _re

# sync collectives are counted by their RESULT shape; async pairs by the
# `-done` op's result only (a `-start` result tuple carries BOTH operand
# and result buffers, which would double-count the payload)
_HLO_COLLECTIVE_LINE_RE = _re.compile(
    r"=\s*(\([^)]*\)|\S+)\s+"
    r"(all-reduce|all-gather|all-to-all|collective-permute|reduce-scatter)"
    r"(-start|-done)?\("
)
_HLO_SHAPE_RE = _re.compile(
    r"(f64|f32|f16|bf16|s64|s32|s16|s8|u64|u32|u16|u8|pred)\[([0-9,]*)\]"
)
_HLO_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2, "s64": 8, "s32": 4,
    "s16": 2, "s8": 1, "u64": 8, "u32": 4, "u16": 2, "u8": 1, "pred": 1,
}


def collective_payload_bytes(
    hlo_text: str, expected: Optional[Sequence[str]] = None
) -> Dict[str, int]:
    """Measured counterpart of the ring model: parse a COMPILED program's
    HLO and sum the payload bytes of every collective, per op kind.

    Returns e.g. ``{"all-reduce": 123456, "all-gather": 789}`` — payloads
    are the per-device program's result shapes (tuples summed), i.e. the
    quantity the ring model multiplies by ``2(P-1)/P`` per axis. Feed it
    ``jax.jit(step).lower(*args).compile().as_text()``; pairing these
    measured bytes with `sampling_comm_bytes`' predictions turns the
    scaling table's traffic column from arithmetic into evidence (see
    tests/test_scaling_model.py::test_model_matches_compiled_step).

    Matched spellings: sync (``all-gather(...)``) and async pairs
    (``all-gather-start``/``-done`` — counted once, on the ``-done``).
    Generic ``async-start``/``async-done`` wrappers print the wrapped
    collective inside their called computation, whose body line matches the
    sync form, so those are counted too. Because a future XLA spelling
    could still slip through silently, pass ``expected`` (op-kind names)
    and the parser raises if any expected kind shows ZERO bytes — callers
    validating a program they *know* contains a psum should always use it
    (round-3 ADVICE.md item 3).
    """
    out: Dict[str, int] = {}
    for line in hlo_text.splitlines():
        m = _HLO_COLLECTIVE_LINE_RE.search(line)
        if not m or m.group(3) == "-start":
            continue
        total = 0
        for dt, dims in _HLO_SHAPE_RE.findall(m.group(1)):
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            total += n * _HLO_DTYPE_BYTES[dt]
        out[m.group(2)] = out.get(m.group(2), 0) + total
    if expected:
        missing = [k for k in expected if not out.get(k)]
        if missing:
            raise ValueError(
                f"expected collective kinds {missing} not found in HLO — "
                "either the program lost its collectives or XLA emits a "
                "spelling this parser does not match"
            )
    return out


def comm_seconds(
    ici_bytes: float,
    dcn_bytes: float,
    bandwidths: Optional[Dict[str, float]] = None,
) -> float:
    bw = dict(DEFAULT_BANDWIDTHS)
    if bandwidths:
        bw.update(bandwidths)
    return ici_bytes / bw["ici_bytes_per_s"] + dcn_bytes / bw["dcn_bytes_per_s"]


def grad_psum_bytes(param_bytes: int, mesh: ShapeMesh) -> Dict[str, float]:
    """Gradient allreduce cost over the data axes (ring model, per chip):
    the DDP-analog `lax.pmean` in the train steps (train.py:218)."""
    out = {"ici_bytes": 0.0, "dcn_bytes": 0.0}
    for axis in ("dp", "host"):
        if axis in mesh.axis_names and mesh.shape[axis] > 1:
            a = mesh.shape[axis]
            key = "dcn_bytes" if axis == "host" else "ici_bytes"
            out[key] += 2.0 * (a - 1) / a * param_bytes
    return out


def predict_layout(
    layout: str,
    mesh: ShapeMesh,
    step_s_1chip: float,
    steps_per_epoch_1chip: int,
    sizes: Sequence[int],
    batch_per_group: int,
    feature_dim: int,
    param_bytes: int,
    caps: Optional[Sequence[Optional[int]]] = None,
    bandwidths: Optional[Dict[str, float]] = None,
) -> LayoutPrediction:
    """One row of the scaling table.

    ``layout``:
      - "dp_replicated": graph + features replicated per chip; the only
        collective is the gradient psum (the reference's DDP layout,
        dist_sampling_ogb_products_quiver.py:85-117).
      - "dp_ici_features": features row-striped over ici
        (p2p_clique_replicate analog); adds the per-hop sharded-gather
        psums of the fused pipeline.
      - "sharded_topology": CSR row-sharded too (papers100M layout); adds
        the per-hop neighbor psums of `sharded_sample_layer`.
      - "sharded_topology_hot_cold": same, with the replicated-hot feature
        tier (`sharded_gather_hot_cold`): only ``cold_frac`` of the feature
        payload rides the host (DCN) axis — the model face of
        tests/test_hot_cold.py's measured lane reduction.

    Note on ``efficiency_pessimistic``: it divides by IDEAL linear speedup
    over ALL chips. Layouts that spend the ici axis on *capacity* (feature
    or graph rows beyond one HBM) parallelize batches only over the data
    groups, so their efficiency is bounded by dp_groups/n by construction —
    read their rows as "what capacity costs", not as a defect.
    """
    from .topology import sampling_comm_bytes

    cold_frac = 1.0
    kind = layout
    if layout == "sharded_topology_hot_cold":
        kind, cold_frac = "sharded_topology", 0.2  # calibrated-budget scale

    n = 1
    for a in mesh.axis_names:
        n *= mesh.shape[a]
    comm = grad_psum_bytes(param_bytes, mesh)
    if kind == "dp_replicated":
        pass  # feature + topology local: gradient psum only
    elif kind == "dp_ici_features":
        # sampling is LOCAL in this layout; the only sharded traffic is the
        # per-hop feature gathers, modeled directly by gather_comm_bytes
        # (grouped id all-gather + row return — including the DCN legs on
        # (host, ...) meshes, which round 3 modeled as free: ADVICE item 2)
        from ..ops.sample import pad_widths
        from .topology import gather_comm_bytes

        widths = pad_widths(batch_per_group, sizes, caps)
        gather_widths = [widths[0]] + [w * k for w, k in zip(widths, sizes)]
        for gw in gather_widths:
            g = gather_comm_bytes(mesh, gw, feature_dim)
            comm["ici_bytes"] += g["ici_bytes"]
            comm["dcn_bytes"] += g["dcn_bytes"]
    elif kind == "sharded_topology":
        c = sampling_comm_bytes(
            mesh, sizes, batch_per_group, feature_dim=feature_dim, caps=caps
        )
        c_ids = sampling_comm_bytes(mesh, sizes, batch_per_group, caps=caps)
        comm["ici_bytes"] += c["ici_bytes"]
        # id exchange always pays DCN in full; the feature payload's DCN leg
        # shrinks to the cold fraction under the replicated-hot tier
        comm["dcn_bytes"] += (
            c_ids["dcn_bytes"]
            + (c["dcn_bytes"] - c_ids["dcn_bytes"]) * cold_frac
        )
    else:
        raise ValueError(f"unknown layout {kind!r}")

    t_comm = comm_seconds(comm["ici_bytes"], comm["dcn_bytes"], bandwidths)
    opt = max(step_s_1chip, t_comm)
    pess = step_s_1chip + t_comm
    dp_groups = 1
    for a in ("host", "dp"):
        if a in mesh.axis_names:
            dp_groups *= mesh.shape[a]
    steps = math.ceil(steps_per_epoch_1chip / dp_groups)
    ideal = step_s_1chip * steps_per_epoch_1chip / n
    return LayoutPrediction(
        layout=layout,
        n_devices=n,
        mesh_shape=dict(mesh.shape),
        step_comm_s=t_comm,
        step_s_optimistic=opt,
        step_s_pessimistic=pess,
        epoch_s_optimistic=opt * steps,
        epoch_s_pessimistic=pess * steps,
        efficiency_pessimistic=ideal / (pess * steps) if steps else 0.0,
        ici_bytes=comm["ici_bytes"],
        dcn_bytes=comm["dcn_bytes"],
    )


def products_scaling_table(
    step_s_1chip: float,
    steps_per_epoch_1chip: int = 193,
    sizes: Sequence[int] = (15, 10, 5),
    batch_per_group: int = 1024,
    feature_dim: int = 100,
    param_bytes: int = 1_650_000,
    caps: Optional[Sequence[Optional[int]]] = None,
    bandwidths: Optional[Dict[str, float]] = None,
) -> List[LayoutPrediction]:
    """The products-config scaling table the reference publishes measured
    (Introduction_en.md:144-158: 11.1s/6.0s/4.0s/3.2s at 1/2/3/4 GPUs),
    predicted for this framework's three layouts at 1..8 chips plus one
    2-host DCN row."""
    rows: List[LayoutPrediction] = []
    for n in (1, 2, 4, 8):
        dp = n  # all-dp: the DDP-analog scaling axis
        rows.append(
            predict_layout(
                "dp_replicated",
                ShapeMesh(("dp", "ici"), {"dp": dp, "ici": 1}),
                step_s_1chip, steps_per_epoch_1chip, sizes, batch_per_group,
                feature_dim, param_bytes, caps, bandwidths,
            )
        )
    for n in (4, 8):
        rows.append(
            predict_layout(
                "dp_ici_features",
                ShapeMesh(("dp", "ici"), {"dp": n // 2, "ici": 2}),
                step_s_1chip, steps_per_epoch_1chip, sizes, batch_per_group,
                feature_dim, param_bytes, caps, bandwidths,
            )
        )
        rows.append(
            predict_layout(
                "sharded_topology",
                ShapeMesh(("dp", "ici"), {"dp": n // 2, "ici": 2}),
                step_s_1chip, steps_per_epoch_1chip, sizes, batch_per_group,
                feature_dim, param_bytes, caps, bandwidths,
            )
        )
    for layout in ("sharded_topology", "sharded_topology_hot_cold"):
        rows.append(
            predict_layout(
                layout,
                ShapeMesh(("host", "dp", "ici"), {"host": 2, "dp": 2, "ici": 2}),
                step_s_1chip, steps_per_epoch_1chip, sizes, batch_per_group,
                feature_dim, param_bytes, caps, bandwidths,
            )
        )
    return rows


# Measured single-chip HBM gather rates (PERF_NOTES.md "ROUND-5", v5e): at
# the hop-3 probe shape (W=135168 rows, k=5 -> 811,008 descriptors/hop,
# scripts/probe_fetch_final.py) the flat element fetch ran 8.95 ms/hop and
# the 128-lane tile fetch 6.48 ms/hop. Expressed as descriptor issue rates
# so the model scales to other hop shapes; both are descriptor-rate-bound
# regimes, not bandwidth-bound, which is why tiled wins despite fetching
# 128x the bytes per position descriptor.
MEASURED_FETCH_DESC_PER_S = {
    "flat": 811_008 / 8.95e-3,   # ~90.6M element-gather descriptors/s
    "tiled": 811_008 / 6.48e-3,  # ~125.2M 128-lane row-gather descriptors/s
}


class FetchPrediction(NamedTuple):
    layout: str
    hbm_descriptors: float
    hbm_fetch_bytes: float
    fetch_s: float


def sharded_fetch_table(
    mesh: ShapeMesh,
    sizes: Sequence[int],
    batch_per_group: int,
    caps: Optional[Sequence[Optional[int]]] = None,
    rates: Optional[Dict[str, float]] = None,
) -> List[FetchPrediction]:
    """Flat-vs-tiled shard-LOCAL fetch cost for the sharded-topology step.

    The collective payloads are layout-invariant (same ``[W, k]`` return
    trip — `sampling_comm_bytes` and the dryrun LAYOUT-TABLE both show it),
    so the layouts differ ONLY in this per-chip HBM fetch term: descriptor
    counts from `sampling_comm_bytes(layout=...)` divided by the measured
    single-chip issue rates (`MEASURED_FETCH_DESC_PER_S`). This is the row
    that makes the flat-vs-tiled sharded choice comparable without a pod;
    ``rates`` overrides the measured constants for other hardware.
    """
    from .topology import sampling_comm_bytes

    r = dict(MEASURED_FETCH_DESC_PER_S)
    if rates:
        r.update(rates)
    rows = []
    for layout in ("flat", "tiled"):
        c = sampling_comm_bytes(
            mesh, sizes, batch_per_group, caps=caps, layout=layout
        )
        rows.append(
            FetchPrediction(
                layout=layout,
                hbm_descriptors=c["hbm_descriptors"],
                hbm_fetch_bytes=c["hbm_fetch_bytes"],
                fetch_s=c["hbm_descriptors"] / r[layout],
            )
        )
    return rows


def format_fetch_markdown(rows: Sequence[FetchPrediction]) -> str:
    lines = [
        "| shard layout | HBM descriptors/step | HBM bytes/step | fetch ms/step (measured rates) |",
        "|---|---|---|---|",
    ]
    for row in rows:
        lines.append(
            f"| {row.layout} | {row.hbm_descriptors:.0f} "
            f"| {row.hbm_fetch_bytes:.0f} | {row.fetch_s*1e3:.2f} |"
        )
    lines.append("")
    lines.append(
        "Rates: flat ~90.6M element-gather desc/s, tiled ~125.2M 128-lane "
        "row-gather desc/s (PERF_NOTES.md ROUND-5 hop-3 probe; both "
        "descriptor-rate-bound, so tiled wins despite moving more bytes)."
    )
    return "\n".join(lines)


class QuantPrediction(NamedTuple):
    codec: str
    bytes_per_elem: float
    row_bytes: float           # payload + per-row side-table bytes
    hot_capacity_multiplier: float  # rows hot per HBM byte, vs fp32
    gather_gb_per_step: float  # HBM bytes the step's row gathers touch
    h2d_gb_per_step: float     # cold wire bytes (side tables stay on device)
    gather_reduction: float    # fraction of the fp32 gather bytes
    h2d_reduction: float       # fraction of the fp32 H2D bytes


def quant_fetch_table(
    sizes: Sequence[int],
    batch_per_group: int,
    feature_dim: int,
    caps: Optional[Sequence[Optional[int]]] = None,
    cold_frac: float = 0.2,
    codecs: Sequence[str] = ("fp32", "bf16", "int8"),
) -> List[QuantPrediction]:
    """Per-codec fetch/byte rows for the quantized feature store
    (`quiver_tpu.quant`): what each codec does to the three byte walls the
    tiered step pays —

    - hot capacity: ``4*D / row_bytes`` more rows fit the same HBM budget
      (int8 at D=100: 3.70x — the 20% fp32 hot tier becomes ~74%, i.e.
      most cold host-gathers become hot HBM hits before any wire speedup).
      This is the amortized full-residency figure: ``QuantizedFeature``
      charges the full-N side tables at ingest, so realized hot rows are
      ``(budget - side_bytes_per_row*N) / payload_row_bytes``;
    - gather bytes: the step's final padded n_id width (`pad_widths`, the
      dedup/tiered pipelines' single full-row gather) times row bytes;
    - H2D bytes: ``cold_frac`` of that width crosses the host link at
      PAYLOAD width (per-row side tables are device-replicated,
      quant/feature.py) — the wire leg `trace.gbps(bytes_per_elem=...)`
      measures.

    Codec byte shapes come from the live `quant.codecs` registry, so a
    registered custom codec shows up by adding its name to ``codecs``.
    """
    from ..ops.sample import pad_widths
    from ..quant.codecs import get_codec

    widths = pad_widths(batch_per_group, sizes, caps)
    w = widths[-1]
    base_row = 4.0 * feature_dim
    base_gather = w * base_row
    base_h2d = cold_frac * w * base_row
    rows: List[QuantPrediction] = []
    for name in codecs:
        c = get_codec(name)
        row_b = c.row_bytes(feature_dim)
        gather = w * row_b
        h2d = cold_frac * w * c.bytes_per_elem * feature_dim
        rows.append(
            QuantPrediction(
                codec=c.name,
                bytes_per_elem=c.bytes_per_elem,
                row_bytes=row_b,
                hot_capacity_multiplier=base_row / row_b,
                gather_gb_per_step=gather / 1e9,
                h2d_gb_per_step=h2d / 1e9,
                gather_reduction=gather / base_gather,
                # cold_frac=0 (fully HBM-resident): no H2D leg, reduction
                # is vacuously 1.0 rather than 0/0
                h2d_reduction=h2d / base_h2d if base_h2d else 1.0,
            )
        )
    return rows


def format_quant_markdown(rows: Sequence[QuantPrediction]) -> str:
    lines = [
        "| codec | B/elem | row B | hot capacity x | gather GB/step | H2D GB/step | gather vs f32 | H2D vs f32 |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        lines.append(
            f"| {r.codec} | {r.bytes_per_elem:g} | {r.row_bytes:g} "
            f"| {r.hot_capacity_multiplier:.2f} | {r.gather_gb_per_step:.4f} "
            f"| {r.h2d_gb_per_step:.4f} | {r.gather_reduction:.0%} "
            f"| {r.h2d_reduction:.0%} |"
        )
    lines.append("")
    lines.append(
        "Rows gathered/step = final padded n_id width (pad_widths); side "
        "tables (int8 fp32 scale+zero, 8 B/row) are device-replicated so "
        "they count against hot capacity but never the H2D wire "
        "(quiver_tpu/quant). The capacity multiplier compounds with the "
        "byte shrink: more rows hot means FEWER cold H2D rows on top of "
        "each row being cheaper."
    )
    return "\n".join(lines)


class ServePrediction(NamedTuple):
    bucket: int            # dispatched batch shape (GLOBAL, pre-split)
    hit_rate: float        # embedding-cache hit rate
    unique_frac: float     # unique seeds / requests among cache misses
    dispatch_s: float      # per-shard sample + gather + forward (shard_bucket wide)
    requests_per_dispatch: float
    qps: float             # sustainable device-bound AGGREGATE throughput
    device_us_per_request: float
    floor_p50_ms: float    # latency floor: half the flush window + dispatch (+ exchange)
    # -- H-host fields (defaults keep the hosts=1 rows and older callers
    # byte-identical to the round-9 model) --
    hosts: int = 1
    shard_bucket: int = 0          # per-shard batch width, ceil(bucket/H)
    exchange_bytes: float = 0.0    # router exchange bytes per routed dispatch
    exchange_s: float = 0.0        # that payload over the DCN link
    # -- one-vs-two-dispatch fields (round 11; defaults keep older rows
    # value-identical: zero overhead makes the call count irrelevant) --
    dispatches_per_flush: int = 1  # 1 = fused serve_step, 2 = split path
    overhead_s: float = 0.0        # fixed per-execute overhead paid each call
    # -- host-path fields (round 20; default 0 = no host term, rows
    # byte-identical to the round-11 model) --
    host_submit_us: float = 0.0    # measured submit->seal host cost/request
    host_qps_cap: float = math.inf # serial host ceiling, 1e6/(submit+resolve)
    # -- drain-side host field (round 22; default 0 keeps round-20 rows
    # byte-identical: the cap reduces to 1e6/host_submit_us) --
    host_resolve_us: float = 0.0   # measured drain (assemble→resolve)/request
    # -- routed fan-out fields (round 23; default 0 = collective pricing,
    # rows byte-identical to the round-22 model) --
    owner_fanout: int = 0          # host-mode legs running concurrently (F)
    leg_merge_us: float = 0.0      # per-flush join/merge host cost (us)


def serve_table(
    t_sample_s: float,
    t_gather_s: float,
    t_forward_s: float,
    ref_batch: int,
    buckets: Sequence[int] = (8, 32, 64),
    hit_rates: Sequence[float] = (0.0, 0.5, 0.9),
    unique_frac: float = 0.8,
    max_delay_ms: float = 2.0,
    hosts: int = 1,
    out_dim: int = 47,
    bandwidths: Optional[Dict[str, float]] = None,
    dispatches_per_flush: int = 1,
    dispatch_overhead_s: float = 0.0,
    host_submit_us: float = 0.0,
    host_resolve_us: float = 0.0,
    owner_fanout: Optional[int] = None,
    leg_merge_us: float = 0.0,
) -> List[ServePrediction]:
    """Analytic QPS model for the online serving engine
    (`quiver_tpu.serve.ServeEngine`) from MEASURED per-batch costs.

    The engine's device work per dispatch is exactly one offline eval step
    (`inference.batch_logits`): sample + gather + forward at the bucket
    shape. Feed the three measured costs at a reference batch ``ref_batch``
    (bench.py's sampling/feature/e2e sections, or scripts/serve_probe.py on
    CPU); they are scaled to each bucket linearly in batch rows — honest at
    large shapes because all three paths are descriptor/row-count bound,
    not occupancy bound (PERF_NOTES.md), but OPTIMISTIC for tiny buckets:
    the linear model omits the fixed per-dispatch overhead (kernel launch,
    host sync — and in this tunneled setup the 0.06-0.13 s RPC floor,
    `bench.py` context ``rpc_floor_s``), which does not shrink with batch
    and dominates small dispatches. Read small-bucket rows as ceilings on
    dispatch speed, large-bucket rows as floors when the cost input is a
    train step (which additionally pays backward + update).

    Request algebra: of R incoming requests/s, ``(1-hit_rate)`` miss the
    embedding cache and ``unique_frac`` of those survive coalescing, so one
    bucket-B dispatch retires ``B / ((1-hit_rate) * unique_frac)`` requests.
    Sustainable QPS is that over the dispatch time; the p50 latency floor
    is half the flush window plus one dispatch (a request arrives mid-
    window on average, then rides the next flush).

    ``hosts > 1`` prices the distributed engine
    (`quiver_tpu.serve.DistServeEngine`): the router splits each bucket-B
    flush by seed ownership, so every shard samples/forwards a
    ``ceil(B/hosts)``-wide sub-batch (the 1/H width shrink the serve probe
    measures) and the shards run CONCURRENTLY — one routed dispatch takes
    one shard-width dispatch plus the exchange hop. Exchange bytes per
    routed flush are the serve-shaped collective's actual payloads
    (`comm.exchange_serve_all`): ``H*H*L`` int32 seed ids out plus
    ``H*H*L*out_dim`` float32 logits back, with ``L`` the STATIC per-owner
    lane budget ``round_up_pow2(bucket)`` — the engine's default, sized
    for worst-case skew (a whole flush owned by one host), so these rows
    match the engine's measured ``exchange_id_bytes``/
    ``exchange_logit_bytes`` counters byte for byte — priced against
    ``dcn_bytes_per_s`` exactly like `sampling_comm_bytes` prices the
    training-side exchange. Aggregate QPS then scales ~H-fold until the
    exchange term catches the shrinking dispatch — the crossover this
    table exists to locate before hardware does.

    ``dispatches_per_flush`` x ``dispatch_overhead_s`` is the
    ONE-vs-TWO-dispatch cost model (round 11): every device execute call
    pays a fixed overhead that does not shrink with batch (kernel launch,
    host sync — the measured ~0.06–0.13 s RPC floor through the tunnel).
    The round-9 split path pays it twice per flush (sample + forward,
    ``dispatches_per_flush=2``); the fused `inference.serve_step` path
    pays it once (``=1``, the engine default). With the default zero
    overhead the rows reduce to the round-10 model exactly; feed the
    measured floor (or the probe's measured split-minus-fused delta) to
    price what the 2→1 cut buys at each bucket — the smaller the bucket,
    the more of its flush time was overhead, so the win concentrates
    exactly where latency-bound serving lives.

    ``host_submit_us`` is the HOST-side submit→seal cost per request
    (round 20): admission — cache/coalesce probe, shed decision, queue
    insert, journal append — runs serially on the submit path, so it
    caps sustainable throughput at ``1e6 / host_submit_us`` requests/s
    no matter how fast the device retires dispatches. Feed the measured
    batch-path number from ``scripts/bench_frontend.py``
    (FRONTEND_r01.json ``host_submit_us``, or via ``scripts/
    scaling_model.py --frontend``); the default 0 keeps every row
    byte-identical to the round-11 model. Rows where the cap binds
    (``qps == host_qps_cap`` below the device-bound ceiling) are
    exactly the regimes the vectorized `submit_many` path exists for —
    the scalar-path cost typically binds at high cache-hit rates, where
    one dispatch retires many requests.

    ``host_resolve_us`` (round 22) is the drain-side twin: the
    assemble→seal→resolve host work per request (block resolution,
    `put_many` cache fill, batched delivery), measured as
    FRONTEND_r02.json's ``host_resolve_us``. The two host phases run on
    the same serial admission/drain path, so the cap becomes
    ``1e6 / (host_submit_us + host_resolve_us)``; the default 0 keeps
    every row byte-identical to the round-20 model.

    ``owner_fanout`` (round 23) prices the HOST-mode router instead of
    the collective: direct owner legs over loopback (no DCN collective
    payload — exchange bytes drop to zero) with ``F = owner_fanout``
    legs running concurrently, so the routed dispatch term is
    ``ceil(H / F) * t_dispatch + leg_merge_us`` — ``F=1`` is the
    pre-round-23 SEQUENTIAL router (the implicit Σ(legs) =
    ``H * t_dispatch`` this model silently assumed away), ``F >= H``
    the concurrent fan-out's max(legs) + merge. ``leg_merge_us`` is the
    measured per-FLUSH join/merge host cost (FRONTEND_r03.json's
    ``leg_merge_us``; via ``scripts/scaling_model.py --frontend``). The
    default ``owner_fanout=None`` keeps every row byte-identical to
    the round-22 collective pricing.
    """
    bw = dict(DEFAULT_BANDWIDTHS)
    if bandwidths:
        bw.update(bandwidths)
    if hosts < 1:
        raise ValueError("hosts must be >= 1")
    if dispatches_per_flush < 1:
        raise ValueError("dispatches_per_flush must be >= 1")
    rows: List[ServePrediction] = []
    per_seed = (t_sample_s + t_gather_s + t_forward_s) / max(ref_batch, 1)
    for b in buckets:
        shard_b = -(-b // hosts)
        t_dispatch = (
            per_seed * shard_b + dispatches_per_flush * dispatch_overhead_s
        )
        if owner_fanout is not None and hosts > 1:
            # host-mode routed dispatch (round 23): F legs at a time,
            # direct owner calls — no collective payload to price
            fan = max(1, int(owner_fanout))
            xbytes = 0.0
            x_s = 0.0
            t_routed = (
                -(-hosts // fan) * t_dispatch + leg_merge_us * 1e-6
            )
        elif hosts > 1:
            from ..comm import round_up_pow2

            lanes = round_up_pow2(b)  # the engine's default static budget
            xbytes = hosts * hosts * lanes * (4 + 4 * out_dim)
            x_s = xbytes / bw["dcn_bytes_per_s"]
            t_routed = t_dispatch + x_s
        else:
            xbytes = 0.0
            x_s = 0.0
            t_routed = t_dispatch + x_s
        host_us = host_submit_us + host_resolve_us
        host_cap = 1e6 / host_us if host_us > 0 else math.inf
        for h in hit_rates:
            miss = (1.0 - h) * unique_frac
            rpd = b / miss if miss > 0 else math.inf
            qps = min(rpd / t_routed, host_cap)
            rows.append(
                ServePrediction(
                    bucket=b,
                    hit_rate=h,
                    unique_frac=unique_frac,
                    dispatch_s=t_dispatch,
                    requests_per_dispatch=rpd,
                    qps=qps,
                    device_us_per_request=(
                        0.0 if math.isinf(rpd) else t_dispatch / rpd * 1e6
                    ),
                    floor_p50_ms=max_delay_ms / 2 + t_routed * 1e3,
                    hosts=hosts,
                    shard_bucket=shard_b,
                    exchange_bytes=xbytes,
                    exchange_s=x_s,
                    dispatches_per_flush=dispatches_per_flush,
                    overhead_s=dispatch_overhead_s,
                    host_submit_us=host_submit_us,
                    host_qps_cap=host_cap,
                    host_resolve_us=host_resolve_us,
                    owner_fanout=(
                        0 if owner_fanout is None or hosts <= 1
                        else max(1, int(owner_fanout))
                    ),
                    leg_merge_us=(
                        leg_merge_us
                        if owner_fanout is not None and hosts > 1
                        else 0.0
                    ),
                )
            )
    return rows


def format_serve_markdown(rows: Sequence[ServePrediction]) -> str:
    multi = any(getattr(r, "hosts", 1) > 1 for r in rows)
    if multi:
        lines = [
            "| bucket | hosts | shard bucket | cache hit | req/dispatch | shard dispatch ms | exchange KB | exchange ms | agg QPS | p50 floor ms |",
            "|---|---|---|---|---|---|---|---|---|---|",
        ]
    else:
        lines = [
            "| bucket | cache hit | req/dispatch | dispatch ms | QPS | device us/req | p50 floor ms |",
            "|---|---|---|---|---|---|---|",
        ]
    for r in rows:
        rpd = "inf" if math.isinf(r.requests_per_dispatch) else f"{r.requests_per_dispatch:.0f}"
        qps = "inf" if math.isinf(r.qps) else f"{r.qps:.0f}"
        if multi:
            lines.append(
                f"| {r.bucket} | {r.hosts} | {r.shard_bucket} | {r.hit_rate:.0%} "
                f"| {rpd} | {r.dispatch_s*1e3:.2f} | {r.exchange_bytes/1e3:.1f} "
                f"| {r.exchange_s*1e3:.3f} | {qps} | {r.floor_p50_ms:.2f} |"
            )
        else:
            lines.append(
                f"| {r.bucket} | {r.hit_rate:.0%} | {rpd} "
                f"| {r.dispatch_s*1e3:.2f} | {qps} "
                f"| {r.device_us_per_request:.1f} | {r.floor_p50_ms:.2f} |"
            )
    lines.append("")
    if multi:
        lines.append(
            "Aggregate QPS = bucket / ((1-hit)*unique_frac) / (shard "
            "dispatch + exchange): the router splits each flush by seed "
            "owner, shards run ~bucket/H-wide dispatches concurrently, and "
            "the exchange ships H*H*L ids out + H*H*L*out_dim f32 logits "
            "back over DCN (comm.exchange_serve payloads). Measured "
            "counterpart: scripts/serve_probe.py --hosts."
        )
        fanned = [r for r in rows if getattr(r, "owner_fanout", 0) > 0]
        if fanned:
            f0 = fanned[0]
            lines.append(
                f"Host-mode routed dispatch (round 23): legs priced at "
                f"ceil(H/{f0.owner_fanout}) shard dispatches + "
                f"{f0.leg_merge_us:.2f} us join/merge per flush, no "
                "collective payload — owner_fanout=1 is the sequential "
                "router's Σ(legs); fan-out >= H is max(legs) + merge "
                "(scripts/bench_frontend.py --r03, FRONTEND_r03.json)."
            )
    else:
        lines.append(
            "QPS = bucket / ((1-hit)*unique_frac) / dispatch_s — device-bound "
            "ceiling, ignores host queueing; p50 floor = max_delay_ms/2 + one "
            "dispatch. Costs scale linearly from the measured reference batch "
            "(row-count-bound regime, PERF_NOTES.md); the serving engine's "
            "measured counterpart is scripts/serve_probe.py / bench.py serve."
        )
    hosted = [
        r for r in rows
        if getattr(r, "host_submit_us", 0.0) > 0
        or getattr(r, "host_resolve_us", 0.0) > 0
    ]
    if hosted:
        hs = hosted[0].host_submit_us
        hr = getattr(hosted[0], "host_resolve_us", 0.0)
        if hr > 0:
            lines.append(
                f"Host path (round 22): {hs:.2f} us/request submit + "
                f"{hr:.2f} us/request drain (assemble→resolve, scripts/"
                f"bench_frontend.py) cap QPS at {1e6 / (hs + hr):.0f}/s "
                "per admission path; rows at that value are host-bound, "
                "not device-bound."
            )
        else:
            lines.append(
                f"Host submit path (round 20): {hs:.2f} us/request "
                f"(submit→seal, scripts/bench_frontend.py) caps QPS at "
                f"{1e6 / hs:.0f}/s per admission path; rows at that value "
                "are host-bound, not device-bound."
            )
    return "\n".join(lines)


class SkewPrediction(NamedTuple):
    top_k: int                 # rows replicated on every host
    coverage: float            # measured request share of those rows
    replica_bytes_per_host: float  # feature bytes the replica set costs
    exchange_seed_frac: float  # seeds still crossing the exchange
    exchange_bytes_frac: float # collective payload vs no replication
    exchange_s: float          # exchange time per routed flush, replicated
    routed_flush_s: float      # shard dispatch + exchange, replicated
    qps_uplift: float          # aggregate QPS multiplier vs no replication


def skew_table(
    coverage: Sequence[Tuple[int, float]],
    hosts: int,
    bucket: int,
    out_dim: int,
    dispatch_s: float,
    feature_dim: int = 100,
    feature_bytes_per_elem: float = 4.0,
    bandwidths: Optional[Dict[str, float]] = None,
) -> List[SkewPrediction]:
    """Predicted hot-shard REPLICATION benefit from a MEASURED
    head-concentration curve — the `scaling` face of the round-13
    frequency sketch, feeding ROADMAP item 3a before it is built.

    ``coverage`` is [(k, frac)]: the request share of the hottest ``k``
    rows, straight from ``WorkloadMonitor.skew_report()['top_coverage']``
    (or an analytic Zipf curve for what-if rows). Replicating those ``k``
    rows' results on every host means that share of seeds is served
    locally and never crosses the serve exchange; a routed bucket-B flush
    then ships only ``(1-frac)*B`` seeds, so the static per-owner lane
    budget shrinks from ``pow2(B)`` to ``pow2(ceil((1-frac)*B))`` and the
    exchange term of `serve_table`'s routed-flush model shrinks with it
    (ids out + logits back, priced against ``dcn_bytes_per_s``; the
    model matches the engine's measured ``exchange_id_bytes`` /
    ``exchange_logit_bytes`` counters shape for shape). Aggregate device
    work is unchanged — hot seeds still compute somewhere — so
    ``qps_uplift`` isolates what replication buys on the WIRE and at the
    straggler boundary: (dispatch + exchange_full) / (dispatch +
    exchange_replicated). ``replica_bytes_per_host`` prices what it
    costs: k feature rows per host at the stated width.

    ``dispatch_s`` is the per-shard dispatch time at ``bucket/hosts``
    width (measure it: bench.py ``serve_fused_step_s`` scaled, or the
    probe's measured costs); ``hosts=1`` rows are legal and show uplift
    1.0 — replication buys nothing without an exchange to avoid.
    """
    if hosts < 1:
        raise ValueError("hosts must be >= 1")
    bw = dict(DEFAULT_BANDWIDTHS)
    if bandwidths:
        bw.update(bandwidths)

    def pow2(n: int) -> int:
        p = 1
        while p < n:
            p *= 2
        return p

    def exchange_s_for(lanes: int) -> float:
        if hosts == 1:
            return 0.0
        xbytes = hosts * hosts * lanes * (4 + 4 * out_dim)
        return xbytes / bw["dcn_bytes_per_s"]

    base_lanes = pow2(bucket)
    base_x = exchange_s_for(base_lanes)
    base_t = dispatch_s + base_x
    rows: List[SkewPrediction] = []
    for k, frac in coverage:
        frac = min(max(float(frac), 0.0), 1.0)
        routed = max(int(math.ceil((1.0 - frac) * bucket)), 0)
        lanes = pow2(routed) if routed else 0
        x_s = exchange_s_for(lanes) if routed else 0.0
        t = dispatch_s + x_s
        rows.append(
            SkewPrediction(
                top_k=int(k),
                coverage=frac,
                replica_bytes_per_host=(
                    float(k) * feature_dim * feature_bytes_per_elem
                ),
                exchange_seed_frac=routed / bucket if bucket else 0.0,
                # zero baseline (hosts=1: no exchange exists) -> nothing
                # is paid, so the honest fraction is 0, not 100%
                exchange_bytes_frac=(
                    exchange_s_for(lanes) / base_x if base_x else 0.0
                ),
                exchange_s=x_s,
                routed_flush_s=t,
                qps_uplift=base_t / t if t > 0 else 1.0,
            )
        )
    return rows


def pick_replication_k(
    rows: Sequence[SkewPrediction],
    min_uplift: float = 1.0,
    replica_budget_bytes: Optional[float] = None,
) -> Optional[SkewPrediction]:
    """The CHEAPEST `skew_table` row worth replicating: the smallest
    top-k whose predicted ``qps_uplift`` strictly beats ``min_uplift``
    within the per-host replica byte budget (None = unbounded). Returns
    None when no row qualifies — replication buys nothing at this skew /
    budget, don't pay for it. This is how the round-15 serve stack sizes
    ``DistServeConfig.replicate_top_k`` from a MEASURED head-concentration
    curve instead of a guess (serve_probe --faults closes the loop:
    measured uplift vs this row's prediction)."""
    best: Optional[SkewPrediction] = None
    for r in sorted(rows, key=lambda r: r.top_k):
        if r.qps_uplift <= min_uplift:
            continue
        if (replica_budget_bytes is not None
                and r.replica_bytes_per_host > replica_budget_bytes):
            continue
        best = r
        break
    return best


class FleetPrediction(NamedTuple):
    action: str                # "baseline" | "replicate top-k" | "add host"
    hosts: int                 # fleet size under this action
    top_k: int                 # replicated head size (0 for host actions)
    dispatch_s: float          # per-owner shard dispatch at this size
    exchange_s: float          # serve-exchange wire time per routed flush
    routed_flush_s: float      # dispatch + exchange
    agg_qps: float             # bucket / routed_flush_s
    qps_uplift: float          # vs the baseline row
    added_bytes_per_host: float  # replica rows, or the new host's shard


def fleet_table(
    coverage: Sequence[Tuple[int, float]],
    hosts: int,
    bucket: int,
    out_dim: int,
    dispatch_s: float,
    table_rows: int,
    feature_dim: int = 100,
    add_hosts: Sequence[int] = (1, 2),
    feature_bytes_per_elem: float = 4.0,
    bandwidths: Optional[Dict[str, float]] = None,
) -> List[FleetPrediction]:
    """Price ADD-A-HOST against REPLICATE-THE-HEAD on one table — the
    round-16 elastic-fleet planning face (`DistServeEngine.scale` vs
    `refresh_replicas`), from the same measured inputs the round-13/15
    models ride: the sketch's head-concentration ``coverage`` [(k, frac)]
    and the measured per-owner ``dispatch_s`` at the CURRENT ``hosts``
    (bench.py ``serve_fused_step_s`` scaled, or the probe's in-run
    timing).

    Replication rows reuse `skew_table`'s wire model exactly (device
    work unchanged, exchange term shrinks with the head share; cost = k
    feature rows ON EVERY host). Add-host rows scale the per-owner
    dispatch with the sub-batch width (``ceil(bucket/H')`` vs
    ``ceil(bucket/H)`` — row-count-bound regime, PERF_NOTES.md) and
    re-price the exchange at the larger ``H'^2 * L`` payload (the
    all_to_all grows quadratically in hosts — adding hosts buys device
    width but PAYS wire); cost = the new host's resident shard,
    ``table_rows/H'`` feature rows (closure halo excluded — label it
    when the partition isn't k-hop closed). The two costs land in one
    ``added_bytes_per_host`` column so `pick_fleet_action` can choose
    the cheapest uplift within a byte budget. Replication attacks the
    wire and the head; a host attacks device width and capacity — at
    high skew the table shows replication winning long before a host
    pays for itself, which is the round-15 measured story."""
    if hosts < 1:
        raise ValueError("hosts must be >= 1")
    bw = dict(DEFAULT_BANDWIDTHS)
    if bandwidths:
        bw.update(bandwidths)

    def pow2(n: int) -> int:
        p = 1
        while p < n:
            p *= 2
        return p

    def exchange_s_at(h: int, lanes: int) -> float:
        if h == 1 or lanes == 0:
            return 0.0
        return h * h * lanes * (4 + 4 * out_dim) / bw["dcn_bytes_per_s"]

    base_width = max(-(-bucket // hosts), 1)
    base_x = exchange_s_at(hosts, pow2(bucket))
    base_t = dispatch_s + base_x
    rows = [FleetPrediction(
        action="baseline", hosts=hosts, top_k=0, dispatch_s=dispatch_s,
        exchange_s=base_x, routed_flush_s=base_t,
        agg_qps=bucket / base_t if base_t > 0 else 0.0,
        qps_uplift=1.0, added_bytes_per_host=0.0,
    )]
    for k, frac in coverage:
        frac = min(max(float(frac), 0.0), 1.0)
        routed = max(int(math.ceil((1.0 - frac) * bucket)), 0)
        x_s = exchange_s_at(hosts, pow2(routed) if routed else 0)
        t = dispatch_s + x_s
        rows.append(FleetPrediction(
            action="replicate top-k", hosts=hosts, top_k=int(k),
            dispatch_s=dispatch_s, exchange_s=x_s, routed_flush_s=t,
            agg_qps=bucket / t if t > 0 else 0.0,
            qps_uplift=base_t / t if t > 0 else 1.0,
            added_bytes_per_host=(
                float(k) * feature_dim * feature_bytes_per_elem
            ),
        ))
    for dh in add_hosts:
        h2 = hosts + int(dh)
        if h2 <= hosts:
            continue
        width2 = max(-(-bucket // h2), 1)
        d_s = dispatch_s * width2 / base_width
        x_s = exchange_s_at(h2, pow2(bucket))
        t = d_s + x_s
        rows.append(FleetPrediction(
            action="add host", hosts=h2, top_k=0, dispatch_s=d_s,
            exchange_s=x_s, routed_flush_s=t,
            agg_qps=bucket / t if t > 0 else 0.0,
            qps_uplift=base_t / t if t > 0 else 1.0,
            added_bytes_per_host=(
                float(table_rows) / h2 * feature_dim
                * feature_bytes_per_elem
            ),
        ))
    return rows


def pick_fleet_action(
    rows: Sequence[FleetPrediction],
    min_uplift: float = 1.0,
    budget_bytes_per_host: Optional[float] = None,
) -> Optional[FleetPrediction]:
    """The cheapest `fleet_table` row whose predicted uplift strictly
    beats ``min_uplift`` within the per-host byte budget (None =
    unbounded): rows sort by added bytes, first qualifying wins — the
    same shape as `pick_replication_k`, now choosing BETWEEN replication
    and a new host. None = nothing qualifies; keep the fleet as is."""
    best: Optional[FleetPrediction] = None
    for r in sorted(rows, key=lambda r: (r.added_bytes_per_host, r.hosts)):
        if r.action == "baseline" or r.qps_uplift <= min_uplift:
            continue
        if (budget_bytes_per_host is not None
                and r.added_bytes_per_host > budget_bytes_per_host):
            continue
        best = r
        break
    return best


def format_fleet_markdown(rows: Sequence[FleetPrediction]) -> str:
    lines = [
        "| action | hosts | top-k | dispatch ms | exchange ms | flush ms | agg QPS | uplift | added KB/host |",
        "|---|---|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        lines.append(
            f"| {r.action} | {r.hosts} | {r.top_k} "
            f"| {r.dispatch_s*1e3:.3f} | {r.exchange_s*1e3:.3f} "
            f"| {r.routed_flush_s*1e3:.3f} | {r.agg_qps:.0f} "
            f"| {r.qps_uplift:.2f}x | {r.added_bytes_per_host/1e3:.1f} |"
        )
    lines.append("")
    lines.append(
        "Add-a-host vs replicate-the-head priced from the same measured "
        "coverage curve + per-owner dispatch cost: replication shrinks "
        "the exchange term (device work unchanged), a new host shrinks "
        "per-owner width but grows the H^2 all_to_all payload. "
        "added_bytes = k replica rows per host, or the new host's 1/H' "
        "shard (closure halo excluded). Measured counterpart: "
        "scripts/serve_probe.py --scale."
    )
    return "\n".join(lines)


class TierPrediction(NamedTuple):
    mix: str
    hbm_frac: float
    host_frac: float
    disk_frac: float
    gather_s: float        # host-side tiered gather per flush
    h2d_bytes: float       # cold rows shipped per flush (host + disk)
    flush_s: float         # gather + device dispatch (split path: serial)
    qps: float             # bucket / flush_s
    slowdown_vs_hbm: float # flush_s over the all-HBM flush_s
    prefetch_hit_rate: float = 0.0  # disk rows already staged at gather


def tier_table(
    mixes: Sequence[Tuple[str, float, float, float]],
    bucket: int,
    dispatch_s: float,
    hbm_row_s: float,
    host_row_s: float,
    disk_row_s: float,
    feature_dim: int = 100,
    bytes_per_elem: float = 4.0,
    read_workers: int = 4,
    prefetch_hit_rate: float = 0.0,
) -> List[TierPrediction]:
    """Price disk/DRAM/HBM HIT MIXES for the round-14 tiered serve path
    — the `scaling` face of the disk tier, answering "what does a
    placement (or a predicted hit-rate curve) cost per flush" BEFORE a
    run commits to it.

    ``mixes`` is ``[(name, f_hbm, f_host, f_disk)]`` — fractions of a
    bucket-``B`` flush's feature rows resolving in each tier. Feed it
    MEASURED attribution (``WorkloadMonitor.skew_report()['tiers']``
    normalized, or `Feature.tier_bytes` ratios) for placement-vs-
    placement comparisons, or the Che-predicted hit rate at a candidate
    DRAM capacity (``predicted_hit_rate``) for what-if rows.

    Per-row tier costs are MEASURED inputs (bench.py legs or the
    probe's in-run timings — this model invents no constants):
    ``hbm_row_s`` the amortized jitted-take cost, ``host_row_s`` the
    native DRAM gather + H2D share, and ``disk_row_s`` the
    SINGLE-THREAD flat-file read per row (bench.py
    ``tier_disk_row_single_s``; NOT the pooled ``tier_disk_row_s``,
    which already amortizes the workers — feeding it here would
    double-discount the disk term). Disk reads fan out over the
    `AsyncReadPool`'s ``read_workers``, so the model divides the
    single-thread cost by the pool width. The tiered
    gather is host-mediated (split dispatch path), so a flush costs
    ``gather + dispatch`` serially — the honest upper bound the probe's
    measured p99 is compared against.

    ``prefetch_hit_rate`` (round 18): the measured fraction of disk rows
    a flush-ahead prefetch already staged in DRAM when the gather ran
    (``tier_prefetch_hit / tier_prefetch_issued``-weighted attribution,
    or the probe's `disk_prefetched` gather share over the disk total).
    A staged row costs the DRAM-staging consume (priced at
    ``host_row_s``) instead of the pooled backing read — the column this
    knob adds is how the table prices "hide the read" against "shorten
    the read".
    """
    if bucket < 1:
        raise ValueError("bucket must be >= 1")
    if read_workers < 1:
        raise ValueError("read_workers must be >= 1")
    p = float(prefetch_hit_rate)
    if not 0.0 <= p <= 1.0:
        raise ValueError(f"prefetch_hit_rate must be in [0, 1]: {p}")
    base = dispatch_s + bucket * hbm_row_s
    # a staged disk row is consumed from DRAM at gather time; the
    # remainder pays the pooled backing read
    disk_eff_s = (1.0 - p) * disk_row_s / read_workers + p * host_row_s
    rows: List[TierPrediction] = []
    for name, f_hbm, f_host, f_disk in mixes:
        fracs = (float(f_hbm), float(f_host), float(f_disk))
        if any(f < 0 for f in fracs) or abs(sum(fracs) - 1.0) > 1e-6:
            raise ValueError(
                f"mix {name!r} fractions must be >= 0 and sum to 1: {fracs}"
            )
        f_hbm, f_host, f_disk = fracs
        gather_s = bucket * (
            f_hbm * hbm_row_s
            + f_host * host_row_s
            + f_disk * disk_eff_s
        )
        h2d = bucket * (f_host + f_disk) * feature_dim * bytes_per_elem
        flush_s = dispatch_s + gather_s
        rows.append(
            TierPrediction(
                mix=str(name),
                hbm_frac=f_hbm,
                host_frac=f_host,
                disk_frac=f_disk,
                gather_s=gather_s,
                h2d_bytes=h2d,
                flush_s=flush_s,
                qps=bucket / flush_s if flush_s > 0 else 0.0,
                slowdown_vs_hbm=flush_s / base if base > 0 else 0.0,
                prefetch_hit_rate=p,
            )
        )
    return rows


def format_tier_markdown(rows: Sequence[TierPrediction]) -> str:
    lines = [
        "| mix | hbm | dram | disk | pf hit | gather ms | H2D KB | flush ms | QPS bound | vs all-HBM |",
        "|---|---|---|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        lines.append(
            f"| {r.mix} | {r.hbm_frac:.0%} | {r.host_frac:.0%} "
            f"| {r.disk_frac:.0%} | {r.prefetch_hit_rate:.0%} "
            f"| {r.gather_s*1e3:.3f} "
            f"| {r.h2d_bytes/1e3:.1f} | {r.flush_s*1e3:.2f} "
            f"| {r.qps:.0f} | {r.slowdown_vs_hbm:.2f}x |"
        )
    lines.append("")
    lines.append(
        "Hit mixes priced with MEASURED per-row tier costs (bench/probe "
        "inputs; disk term divided by the read pool width). Feed measured "
        "attribution (skew_report tiers) or Che-predicted hit rates at a "
        "candidate capacity — the round-14 placement planning table. "
        "`pf hit` (round 18) is the measured flush-ahead prefetch hit "
        "rate: that fraction of disk rows is priced at the DRAM-staging "
        "consume instead of the pooled backing read."
    )
    return "\n".join(lines)


class DeltaPrediction(NamedTuple):
    name: str
    edges_per_s: float       # offered edge-arrival rate
    edges_per_commit: float  # arrivals accumulated per fenced commit
    commit_s: float          # host appends + batched device tile swap
    duty_frac: float         # commit wall over the commit period
    fence_stall_s: float     # serving stall per commit (the fenced part)
    sustainable: bool        # duty < 1 (the stream keeps up)
    # round-21 lifecycle terms (default 0: the round-17 table unchanged)
    churn_s: float = 0.0         # per-commit delete/expiry lane rewrites
    compact_amort_s: float = 0.0  # compaction wall amortized per commit
    # round-24: which commit discipline priced the stall column
    fence_mode: str = "fenced"   # "fenced" (drain) | "zerostall" (flip)


def delta_table(
    cases: Sequence[Tuple[str, float]],
    append_s_per_edge: float,
    swap_s_per_commit: float,
    commit_period_s: float = 1.0,
    delete_frac: float = 0.0,
    delete_s_per_edge: float = 0.0,
    compact_s_per_pass: float = 0.0,
    compact_every_commits: float = 0.0,
    commit_stall_us: Optional[float] = None,
    fence_mode: str = "fenced",
) -> List[DeltaPrediction]:
    """Price streaming-graph ingest (round 17) from MEASURED per-edge
    costs: "at edge rate R with a commit every ``commit_period_s``, what
    does `update_graph` cost and does the stream keep up?"

    ``cases`` is ``[(name, edges_per_s)]``. ``append_s_per_edge`` is the
    host pad-lane apply cost per edge and ``swap_s_per_commit`` the
    batched device tile-swap cost per commit — both measured by bench.py
    (``stream_append_s`` / ``stream_swap_s``); this model invents no
    constants. The whole commit runs under the update_params-style fence,
    so ``fence_stall_s`` IS the per-commit serving stall — ``duty_frac``
    (commit wall over period) is the fraction of wall the engine spends
    fenced, and a case is ``sustainable`` only while that stays below 1.
    Batching is the lever the table makes visible: the swap cost
    amortizes over ``edges_per_commit``, so longer periods trade delta
    visibility lag for lower duty.

    Round-21 lifecycle terms (all default 0 — the round-17 table is
    unchanged without them): a ``delete_frac`` of arrivals also pay
    ``delete_s_per_edge`` (the measured lane-rewrite cost of a removal
    or TTL expiry, bench ``stream_delete_s``) per commit, and a
    background compaction pass costing ``compact_s_per_pass`` (bench
    ``stream_compact_s``) every ``compact_every_commits`` commits is
    amortized into the duty — the steady-state price of a stream that
    lives forever instead of only growing.

    Round-24 zero-stall pricing: ``fence_mode="zerostall"`` decouples
    the DUTY (the commit work still costs the same host/device wall,
    it just runs off-fence) from the SERVING STALL, which collapses to
    the measured flip hold — pass it as ``commit_stall_us`` (the
    engine's ``commit_stall`` histogram mean, serve_probe
    ``--stream-stall``). With ``fence_mode="fenced"`` (default) the
    stall stays equal to the whole commit wall and ``commit_stall_us``
    is ignored — the drain-vs-flip comparison the Round-24 SCALING.md
    section tabulates.
    """
    if append_s_per_edge < 0 or swap_s_per_commit < 0:
        raise ValueError("per-edge/per-commit costs must be >= 0")
    if commit_period_s <= 0:
        raise ValueError("commit_period_s must be > 0")
    if delete_frac < 0 or delete_s_per_edge < 0 or compact_s_per_pass < 0:
        raise ValueError("lifecycle costs must be >= 0")
    if fence_mode not in ("fenced", "zerostall"):
        raise ValueError(
            f"fence_mode must be 'fenced' or 'zerostall', got {fence_mode!r}"
        )
    if fence_mode == "zerostall" and commit_stall_us is None:
        raise ValueError(
            "zerostall pricing needs the measured flip hold: pass "
            "commit_stall_us (serve_probe --stream-stall measures it)"
        )
    if commit_stall_us is not None and commit_stall_us < 0:
        raise ValueError("commit_stall_us must be >= 0")
    compact_amort = (compact_s_per_pass / compact_every_commits
                     if compact_every_commits > 0 else 0.0)
    rows: List[DeltaPrediction] = []
    for name, rate in cases:
        rate = float(rate)
        if rate < 0:
            raise ValueError(f"edge rate must be >= 0 for case {name!r}")
        per_commit = rate * commit_period_s
        churn = per_commit * delete_frac * delete_s_per_edge
        commit_s = per_commit * append_s_per_edge + swap_s_per_commit + churn
        duty = (commit_s + compact_amort) / commit_period_s
        # zero-stall: the commit WORK is unchanged (duty identical) but
        # the serving stall is the measured flip hold, not the wall
        stall_s = (commit_stall_us * 1e-6 if fence_mode == "zerostall"
                   else commit_s)
        rows.append(
            DeltaPrediction(
                name=str(name),
                edges_per_s=rate,
                edges_per_commit=per_commit,
                commit_s=commit_s,
                duty_frac=duty,
                fence_stall_s=stall_s,
                sustainable=duty < 1.0,
                churn_s=churn,
                compact_amort_s=compact_amort,
                fence_mode=fence_mode,
            )
        )
    return rows


def format_delta_markdown(rows: Sequence[DeltaPrediction]) -> str:
    lifecycle = any(r.churn_s or r.compact_amort_s for r in rows)
    zerostall = any(r.fence_mode == "zerostall" for r in rows)
    stall_col = "commit stall ms" if zerostall else "fence stall ms"
    if lifecycle:
        lines = [
            "| case | edges/s | edges/commit | commit ms | churn ms "
            f"| compact ms | {stall_col} | duty | sustainable |",
            "|---|---|---|---|---|---|---|---|---|",
        ]
    else:
        lines = [
            f"| case | edges/s | edges/commit | commit ms | {stall_col} "
            "| duty | sustainable |",
            "|---|---|---|---|---|---|---|",
        ]
    for r in rows:
        mid = (f"| {r.churn_s*1e3:.2f} | {r.compact_amort_s*1e3:.2f} "
               if lifecycle else "")
        stall = (f"{r.fence_stall_s*1e3:.4f}" if r.fence_mode == "zerostall"
                 else f"{r.fence_stall_s*1e3:.2f}")
        lines.append(
            f"| {r.name} | {r.edges_per_s:.0f} | {r.edges_per_commit:.0f} "
            f"| {r.commit_s*1e3:.2f} {mid}"
            f"| {stall} "
            f"| {r.duty_frac:.1%} | {'yes' if r.sustainable else 'NO'} |"
        )
    lines.append("")
    lines.append(
        "Streaming-graph ingest priced from MEASURED bench legs "
        "(stream_append_s per edge, stream_swap_s per batched commit"
        + (", stream_delete_s per lane rewrite, stream_compact_s per "
           "background pass" if lifecycle else "")
        + "). "
        + ("Zero-stall commits: the commit WORK still costs the same "
           "wall (duty unchanged) but builds off-fence, so the serving "
           "stall collapses to the measured flip hold "
           "(serve_probe --stream-stall commit_stall_us). "
           if zerostall else
           "The commit runs fenced, so its wall is the per-commit "
           "serving stall; ")
        + "longer commit periods amortize the swap at the cost of "
        "delta visibility lag — the round-17 ingest planning table"
        + (" with the round-21 lifecycle churn/compaction terms."
           if lifecycle else ".")
    )
    return "\n".join(lines)


class LPPrediction(NamedTuple):
    bucket: int
    hit_rate: float            # endpoint embedding-cache hit rate
    unique_frac: float         # endpoint seeds surviving coalescing
    dispatch_s: float          # one bucket-B endpoint dispatch
    node_qps: float            # node-classification requests/s ceiling
    pairs_per_dispatch: float  # pairs retired per endpoint dispatch
    head_s: float              # pair-head cost per retired dispatch
    pair_qps: float            # LP pairs/s ceiling
    qps_ratio: float           # pair_qps / node_qps


def lp_table(
    t_node_step_s: float,
    ref_batch: int,
    head_s_per_pair: float = 0.0,
    buckets: Sequence[int] = (8, 32, 64),
    hit_rates: Sequence[float] = (0.0, 0.5, 0.9),
    unique_frac: float = 0.8,
) -> List[LPPrediction]:
    """Price PAIR-QPS against node-QPS from measured step costs (round
    19): a link-prediction request is TWO endpoint computations through
    the same serve path plus a head.

    ``t_node_step_s`` is the measured fused serve-step cost at
    ``ref_batch`` (bench.py ``serve_fused_step_s``, or the temporal leg's
    ``temporal_step_s``), scaled linearly per seed like `serve_table`;
    ``head_s_per_pair`` the measured scoring-head cost per pair (bench
    ``lp_head_s`` — one jitted dispatch per scored batch, so per pair
    it is tiny and amortized). Request algebra: of P pairs/s, each
    submits 2 endpoint requests; ``(1-hit)*unique_frac`` of those reach
    the device (endpoints of a hot candidate set hit the embedding cache
    and coalesce EXACTLY like node requests — the sharing is the whole
    design, see workloads/linkpred.py), so one bucket-B dispatch retires
    ``B / (2*(1-hit)*unique_frac)`` pairs. Temporal serving shrinks the
    effective hit rate (cache keys gain the t_bucket dimension: only
    same-window repeats hit) — feed the MEASURED temporal hit rate in,
    the table stays honest.

    The ratio column is the planning number: pair traffic costs ~2x node
    traffic at equal cache behavior, less when candidate endpoints are
    hotter than classification seeds (their hit rate is what you buy
    with a bigger cache)."""
    if t_node_step_s < 0 or head_s_per_pair < 0:
        raise ValueError("step/head costs must be >= 0")
    rows: List[LPPrediction] = []
    per_seed = t_node_step_s / max(ref_batch, 1)
    for b in buckets:
        t_dispatch = per_seed * b
        for h in hit_rates:
            miss = (1.0 - h) * unique_frac
            node_rpd = b / miss if miss > 0 else math.inf
            node_qps = node_rpd / t_dispatch if t_dispatch > 0 else math.inf
            pairs_pd = node_rpd / 2.0
            head_s = (
                0.0 if math.isinf(pairs_pd) else pairs_pd * head_s_per_pair
            )
            t_pair = t_dispatch + head_s
            pair_qps = pairs_pd / t_pair if t_pair > 0 else math.inf
            ratio = (
                0.5 if math.isinf(node_qps) and math.isinf(pair_qps)
                else pair_qps / node_qps
            )
            rows.append(
                LPPrediction(
                    bucket=b, hit_rate=h, unique_frac=unique_frac,
                    dispatch_s=t_dispatch, node_qps=node_qps,
                    pairs_per_dispatch=pairs_pd, head_s=head_s,
                    pair_qps=pair_qps, qps_ratio=ratio,
                )
            )
    return rows


def format_lp_markdown(rows: Sequence[LPPrediction]) -> str:
    lines = [
        "| bucket | cache hit | dispatch ms | node QPS | pairs/dispatch "
        "| head ms | pair QPS | pair/node |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        nq = "inf" if math.isinf(r.node_qps) else f"{r.node_qps:.0f}"
        pq = "inf" if math.isinf(r.pair_qps) else f"{r.pair_qps:.0f}"
        ppd = ("inf" if math.isinf(r.pairs_per_dispatch)
               else f"{r.pairs_per_dispatch:.0f}")
        lines.append(
            f"| {r.bucket} | {r.hit_rate:.0%} | {r.dispatch_s*1e3:.2f} "
            f"| {nq} | {ppd} | {r.head_s*1e3:.3f} | {pq} "
            f"| {r.qps_ratio:.2f}x |"
        )
    lines.append("")
    lines.append(
        "Link-prediction pricing from measured step costs (round 19): a "
        "pair = 2 endpoint lookups through the shared serve path + a "
        "batched scoring head. The pair/node ratio sits near 0.5x at "
        "equal cache behavior; hotter candidate endpoints (higher hit "
        "rate) close the gap. Measured counterpart: bench.py workloads "
        "leg + scripts/serve_probe.py --temporal."
    )
    return "\n".join(lines)


def format_skew_markdown(rows: Sequence[SkewPrediction]) -> str:
    lines = [
        "| replicated top-k | coverage | replica KB/host | exchange seeds | exchange bytes | exchange ms | routed flush ms | QPS uplift |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        lines.append(
            f"| {r.top_k} | {r.coverage:.0%} "
            f"| {r.replica_bytes_per_host/1e3:.1f} "
            f"| {r.exchange_seed_frac:.0%} | {r.exchange_bytes_frac:.0%} "
            f"| {r.exchange_s*1e3:.3f} | {r.routed_flush_s*1e3:.2f} "
            f"| {r.qps_uplift:.2f}x |"
        )
    lines.append("")
    lines.append(
        "Coverage from a measured head-concentration curve "
        "(WorkloadMonitor.skew_report — the round-13 frequency sketch); "
        "replicating the top-k keeps that request share off the serve "
        "exchange, shrinking the static lane budget pow2(bucket) -> "
        "pow2((1-coverage)*bucket). Device work is unchanged — the uplift "
        "is the wire term only (ROADMAP item 3a's predicted benefit)."
    )
    return "\n".join(lines)


def format_markdown(rows: Sequence[LayoutPrediction], step_s_1chip: float,
                    bandwidths: Optional[Dict[str, float]] = None) -> str:
    bw = dict(DEFAULT_BANDWIDTHS)
    if bandwidths:
        bw.update(bandwidths)
    lines = [
        "| layout | mesh | chips | comm ms/step | epoch s (overlap..none) | eff |",
        "|---|---|---|---|---|---|",
    ]
    for r in rows:
        mesh = ",".join(f"{k}={v}" for k, v in r.mesh_shape.items() if v > 1) or "1"
        lines.append(
            f"| {r.layout} | {mesh} | {r.n_devices} | {r.step_comm_s*1e3:.2f} "
            f"| {r.epoch_s_optimistic:.2f}..{r.epoch_s_pessimistic:.2f} "
            f"| {r.efficiency_pessimistic:.0%} |"
        )
    lines.append("")
    lines.append(
        f"Assumptions: single-chip step {step_s_1chip*1e3:.1f} ms (measured); "
        f"ICI {bw['ici_bytes_per_s']/1e9:.0f} GB/s/chip, "
        f"DCN {bw['dcn_bytes_per_s']/1e9:.0f} GB/s/host (ring model, "
        "see quiver_tpu/parallel/scaling.py docstring)."
    )
    return "\n".join(lines)

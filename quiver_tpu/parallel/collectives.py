"""Mesh collectives for sharded feature access.

TPU-native replacement for the reference's three transports (SURVEY.md 5):

- NVLink peer-pointer reads inside one kernel (shard_tensor.cu.hpp:44-55)
  -> `sharded_gather`: the hot feature table is row-sharded across an ICI
  mesh axis; every chip gathers its in-range rows and a `psum` over the axis
  assembles full rows. One collective rides ICI instead of per-row peer loads.
- NCCL send/recv pairwise exchange (quiver_comm.cu:38-64, comm.py:42-75)
  -> `all_to_all` based exchange in `quiver_tpu.comm` over a DCN axis.
- CUDA IPC handles -> nothing: one process drives all local chips.

Everything here runs *inside* ``shard_map`` — callers wrap with
`jax.experimental.shard_map.shard_map` (see `quiver_tpu.parallel.train`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..utils import axis_size_compat


def sharded_gather(table_block: jax.Array, ids: jax.Array, axis_name) -> jax.Array:
    """Gather rows by *global* id from a row-sharded table.

    table_block: this chip's ``[rows_per_shard, D]`` contiguous block.
    ids: global row ids, any shape; identical across the axis (replicated).
    axis_name: one mesh axis name, or a TUPLE of names when the table is
    striped over several axes (e.g. ``("host", "ici")`` for a multi-host
    shard — matching a ``P(("host", "ici"), None)`` sharding, whose dim-0
    blocks are ordered major-to-minor across the named axes). The psum then
    rides ICI within a host and DCN across hosts.

    Returns full rows, replicated across the axis/axes. Out-of-range ids
    (e.g. padding sentinels) return zero rows.
    """
    if isinstance(axis_name, str):
        axes = (axis_name,)
    else:
        axes = tuple(axis_name)
    return lax.psum(_partial_rows(table_block, ids, axes), axes)


def _partial_rows(table_block: jax.Array, ids: jax.Array, axes) -> jax.Array:
    """This shard's un-reduced contribution to a row gather: its in-range
    rows, zeros elsewhere. Callers choose the reduction (psum, psum_scatter,
    or a scatter/psum mix). Shard index is flat major-to-minor over ``axes``
    — the block order of ``P((a, b), ...)``. int64 ids stay wide (>2^31-row
    global tables, x64 mode); everything else runs int32 (cheaper TPU
    gathers)."""
    rows_per_shard = table_block.shape[0]
    idx = lax.axis_index(axes[0])
    for a in axes[1:]:
        idx = idx * axis_size_compat(a) + lax.axis_index(a)
    id_dt = ids.dtype if ids.dtype == jnp.int64 else jnp.int32
    local = ids.astype(id_dt) - idx.astype(id_dt) * rows_per_shard
    in_range = (local >= 0) & (local < rows_per_shard)
    rows = jnp.take(table_block, jnp.clip(local, 0, rows_per_shard - 1), axis=0)
    return jnp.where(in_range[..., None], rows, jnp.zeros_like(rows))


def sharded_gather_grouped(
    table_block: jax.Array, ids: jax.Array, feat_axes, group_axis: str,
    via: str = "scatter",
) -> jax.Array:
    """`sharded_gather` for id lists that DIFFER across ``group_axis`` (one
    of the table's striping axes, typically "host").

    `sharded_gather` requires ids identical across every psum axis; when
    data-parallel groups span the host axis, each host samples different
    seeds, so the lists are first all_gathered over ``group_axis`` and
    gathered once for all groups. The return trip has two spellings:

    - ``via="scatter"`` (default): `psum_scatter` the ``[G, W, D]`` partial
      rows over ``group_axis`` (each group receives only ITS slice, reduced
      on the way — ring cost (G-1)/G of the payload), then psum the ``[W,
      D]`` remainder over the other striping axes. DCN row-bytes: (G-1)*W*D.
    - ``via="psum"``: full psum over every striping axis, slice own answer
      (round-3 layout). DCN row-bytes: 2*(G-1)*W*D, and the non-group axes
      carry the G-fold width too — G x the ICI payload of "scatter".

    Both produce identical rows; "scatter" strictly dominates the byte
    model and the hermetic 8-device measurement (SCALING.md round-4 table,
    tests/test_parallel.py::test_grouped_gather_scatter_matches_psum), so
    "psum" remains only as the reference spelling for that comparison.
    """
    if via == "psum":
        all_ids = lax.all_gather(ids, group_axis)  # identical across group_axis
        rows = sharded_gather(table_block, all_ids, feat_axes)
        return rows[lax.axis_index(group_axis)]
    if via != "scatter":
        raise ValueError(f"unknown via {via!r}")
    if isinstance(feat_axes, str):
        axes = (feat_axes,)
    else:
        axes = tuple(feat_axes)
    if group_axis not in axes:
        # table not striped over the group axis: every group participant
        # holds identical partials, so a scatter-reduce would G-fold-count
        # them; the psum+slice spelling is the correct (and equally cheap,
        # no reduction rides group_axis at all) form there
        all_ids = lax.all_gather(ids, group_axis)
        rows = sharded_gather(table_block, all_ids, axes)
        return rows[lax.axis_index(group_axis)]
    all_ids = lax.all_gather(ids, group_axis)  # [G, ...]
    rows = _partial_rows(table_block, all_ids, axes)  # [G, W, D]
    own = lax.psum_scatter(rows, group_axis, scatter_dimension=0, tiled=False)
    other = tuple(a for a in axes if a != group_axis)
    if other:
        own = lax.psum(own, other)
    return own


def sharded_gather_a2a(
    table_block: jax.Array, ids: jax.Array, axis_name: str, axis_size: int
) -> jax.Array:
    """Per-chip-request gather: each chip requests only its own ``ids``
    (sharded over the axis) and receives only its own rows.

    ids: [B_local] this chip's request list (global ids).
    Returns [B_local, D]: rows for this chip's ids.

    This is exactly `sharded_gather_grouped(via="scatter")` specialized to
    one axis that is both the striping and the group axis, so it DELEGATES
    there (one return-trip implementation; the reference's id/feature
    exchange pattern, comm.py:127-182, collapsed into two XLA collectives).

    When to use which (measured compiled-HLO payloads at W=512, D=32,
    P=8 — scripts/compare_grouped_return.py a2a section + SCALING.md
    round-5 table): with a SHARDED consumer, a2a moves 10240 B/chip
    (2048 request all-gather + 8192 reduce-scatter) vs the
    replicated-request `sharded_gather`'s 65536 B all-reduce — 6.4x
    cheaper. But if the consumer needs the FULL row set (every train step
    in this library does: the model eats all of x), the re-assembly
    all_gather brings it to 75776 B — WORSE than the all-reduce — so the
    train steps stay on `sharded_gather`/`sharded_gather_grouped`. a2a is
    the right spelling only when downstream consumption is sharded over
    the same axis (e.g. an embedding-table exchange feeding per-chip
    partitions).
    """
    return sharded_gather_grouped(
        table_block, ids, feat_axes=axis_name, group_axis=axis_name,
        via="scatter",
    )


def sharded_gather_hot_cold(
    hot_block: jax.Array,
    cold_block: jax.Array,
    ids: jax.Array,
    feat_axes,
    group_axis: str,
    hot_rows: int,
    cold_budget: int,
    cold_via: str = "scatter",
):
    """Grouped gather with a per-host REPLICATED hot prefix — the in-jit
    analog of the reference's `PartitionInfo.replicate` hot set
    (feature.py:461-526; mag240m preprocess.py:117-179 replicates the hot
    rows on every host for exactly this reason).

    The plain `sharded_gather_grouped` pays ``axis_size(group_axis)`` x the
    full gather width over the DCN axis for EVERY row. Here the table is
    heat-ordered (reindex_by_config / Feature degree order) and split:

    - rows ``< hot_rows``: replicated per host, striped over the non-group
      axes — served by an ICI-only psum at full width;
    - rows ``>= hot_rows``: striped over ALL ``feat_axes`` — the cold ids
      are compacted (one cheap sort) into a static ``cold_budget``-lane
      buffer and only THAT rides the grouped DCN path.

    DCN row-volume drops from W to ``cold_budget`` — i.e. by the hot-tier
    hit rate; calibrate the budget like the sampler caps (observed max cold
    count x margin, `pyg.sage_sampler.caps_from_counts` policy). Returns
    ``(rows [W, D], overflow)`` where ``overflow`` counts cold ids beyond
    the budget this call (their rows come back ZERO — monitor it; a
    persistent nonzero overflow means the budget needs recalibrating).

    Inside shard_map only. ``ids`` identical across every non-group feat
    axis; may differ across ``group_axis``.
    """
    ici_axes = tuple(a for a in feat_axes if a != group_axis)
    if not ici_axes:
        raise ValueError("hot/cold gather needs a non-group striping axis")
    # same int64 treatment as sharded_gather/_a2a: this is the layout built
    # for the LARGEST tables, so >2^31-row global id spaces must not wrap
    ids = ids.astype(ids.dtype if ids.dtype == jnp.int64 else jnp.int32)
    w = ids.shape[0]
    if isinstance(cold_budget, float):
        # fraction of the gather width (handy when one policy must serve
        # calls of several static widths, e.g. the fused per-hop gathers);
        # 256-lane granule, never above the width itself
        cold_budget = min(w, -(-int(w * cold_budget) // 256) * 256)
    if cold_budget > w:
        raise ValueError(f"cold_budget {cold_budget} exceeds gather width {w}")
    # hot side: ids >= hot_rows fall out of the hot shards' range -> zeros
    # (hot padding rows are zero, so cold ids landing in [hot_rows, padded)
    # contribute nothing either)
    hot_part = sharded_gather(hot_block, ids, ici_axes)
    # cold side: compact the cold ids to the front (argsort of the hot flag
    # is stable and costs ~0.5 ms/M lanes — sorts are the cheap primitive,
    # PERF_NOTES.md), slice the static budget, gather grouped, scatter back.
    # Out-of-range ids (padding sentinels: reindex pads with intmax) are
    # NEITHER hot nor cold — they must not consume budget lanes
    n_cold_global = cold_block.shape[0]
    for a in feat_axes:
        n_cold_global = n_cold_global * axis_size_compat(a)
    is_cold = (ids >= hot_rows) & (ids < hot_rows + n_cold_global)
    n_cold = is_cold.sum().astype(jnp.int32)
    order = jnp.argsort(jnp.where(is_cold, 0, 1), stable=True)
    sel = order[:cold_budget]
    lane_ok = jnp.arange(cold_budget, dtype=jnp.int32) < n_cold
    cold_local = jnp.where(lane_ok, jnp.take(ids, sel) - hot_rows, -1)
    cold_rows = sharded_gather_grouped(
        cold_block, cold_local, feat_axes, group_axis, via=cold_via
    )
    cold_rows = jnp.where(lane_ok[:, None], cold_rows, jnp.zeros_like(cold_rows))
    out = hot_part.at[sel].add(cold_rows, mode="drop")
    overflow = jnp.maximum(n_cold - cold_budget, 0)
    return out, overflow


def replicated_psum(x, axis_name: str):
    return lax.psum(x, axis_name)


def pad_to_multiple(arr, multiple: int, axis: int = 0):
    """Pad rows so a table splits evenly across shards (host-side helper)."""
    import numpy as np

    n = arr.shape[axis]
    target = ((n + multiple - 1) // multiple) * multiple
    if target == n:
        return np.asarray(arr)
    pad_width = [(0, 0)] * arr.ndim
    pad_width[axis] = (0, target - n)
    return np.pad(np.asarray(arr), pad_width)

"""Disk-backed cold tier + sketch-driven adaptive placement (round 14).

The tier stack so far stopped at host DRAM — the capacity wall "millions
of users" hits first. The reference spanned its hierarchy to mmap'd disk
(PAPER.md L2/L4: ``quiver<T,CPU>`` + the ShardTensor CPU slice); the
PAPERS.md entries "GPU Initiated Direct Storage Accesses" (2306.16384)
and PyTorch-Direct (2101.07956) are the same lever. This module is the
TPU-native version, in two halves:

1. **A fourth storage tier**: :class:`DiskShard` — a flat-file ``.npy``
   row shard read through ``np.memmap`` (page-cache-friendly) and an
   optional :class:`quiver_tpu.pipeline.AsyncReadPool` (the same
   one-worker-per-stage thread machinery the train pipeline runs on,
   widened to a bounded pool: disk reads are the one stage that scales
   with parallel outstanding requests). `ShardTensor.append_disk` hangs
   it under the existing shard book as a static tail; rows are stored at
   the STORE's dtype, so a `QuantizedFeature`'s disk tier holds int8 —
   cold rows are encoded on disk AND on the wire.

2. **Adaptive placement**: :class:`TierStore` — HBM cache table + host
   DRAM cache + full disk backing, with a host-side
   :class:`TierPlacement` map (stored row -> tier, slot). Gathers stay
   GATHER-ONLY (the placement map is computed on host; per-tier gathers
   scatter-merge into the output exactly like `ShardTensor.__getitem__`
   — no scatter builds of big arrays per gather, PERF_NOTES).
   :func:`plan_adaptive` turns the round-13 frequency sketch
   (`WorkloadMonitor.promotion_candidates`) into a bounded
   :class:`PlacementPlan`; `TierStore.apply` executes it in batches
   (demotions free slots, promotions batch-read the backing file and
   land as ONE device row-scatter per batch — the "stage host-side, swap
   device tiles in batches" discipline). The serve engines fence the
   apply exactly like ``update_params`` (drain in-flight flushes, bump a
   placement version, invalidate moved rows' embedding-cache entries).

Bit-parity contract: every row's bytes live on disk permanently (the
backing file is the full table), so placement NEVER changes a gathered
byte — promotion copies, demotion just edits the map. A frozen placement
replays bit-identically, and a run straddling a promotion batch still
serves bit-identical logits (pinned in tests/test_tiers.py).

Module imports: `shard_tensor` only (leaf-ward); the read pool and the
serve engines import lazily, so `feature`/`pipeline`/`serve` can all
reach this module without a cycle.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .shard_tensor import _bucket, _device_of, _gather_local, _scatter_rows

TIER_HBM = 0
TIER_HOST = 1
TIER_DISK = 2
TIER_NAMES = ("hbm", "host", "disk")


class DiskShard:
    """Flat-file ``[R, D]`` row shard on disk (``.npy`` format, read
    through ``np.memmap``).

    ``read_rows`` is the only read surface: local row ids in, a fresh
    C-contiguous array out. With a pool the read is split into chunks
    that run on the pool's workers concurrently — each chunk is an
    independent page-cache/disk read, which is where parallelism
    actually pays (a single thread serializes the page faults).
    Out-of-range ids raise loudly: unlike lookup padding (which the
    callers mask BEFORE reaching the disk tier), a bad local id here
    means a corrupt placement map, not padding.
    """

    def __init__(self, path: str):
        self.path = path
        # mmap_mode='r': reads hit the page cache; nothing is resident
        # until touched, which is the whole point of the tier
        self._mm = np.load(path, mmap_mode="r")
        if self._mm.ndim != 2:
            raise ValueError(f"disk shard {path} must be [R, D]")

    @classmethod
    def create(cls, path: str, rows: np.ndarray) -> "DiskShard":
        """Write ``rows`` as a ``.npy`` flat file and open it mmap'd.
        The array is written at ITS dtype — an int8 store spills int8."""
        rows = np.ascontiguousarray(rows)
        if rows.ndim != 2:
            raise ValueError("disk shard rows must be [R, D]")
        if not path.endswith(".npy"):
            path = path + ".npy"
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        np.save(path, rows)
        return cls(path)

    @property
    def shape(self) -> Tuple[int, int]:
        return self._mm.shape

    @property
    def dtype(self) -> np.dtype:
        return self._mm.dtype

    @property
    def nbytes(self) -> int:
        """Payload bytes (rows * row_bytes; the npy header is noise)."""
        return int(self._mm.shape[0]) * self.row_bytes

    @property
    def row_bytes(self) -> int:
        return int(self._mm.shape[1]) * self._mm.dtype.itemsize

    def read_block(self, local_ids: np.ndarray) -> np.ndarray:
        """One synchronous gather (the unit of work a read pool chunks)."""
        ids = np.asarray(local_ids, np.int64).reshape(-1)
        if ids.size and (ids.min() < 0 or ids.max() >= self._mm.shape[0]):
            raise ValueError(
                f"disk read ids outside [0, {self._mm.shape[0]}): "
                "corrupt placement map (callers mask padding before the "
                "disk tier)"
            )
        return np.ascontiguousarray(self._mm[ids])

    def read_rows(self, local_ids: np.ndarray, pool=None) -> np.ndarray:
        ids = np.asarray(local_ids, np.int64).reshape(-1)
        if pool is None or ids.size == 0:
            return self.read_block(ids)
        return pool.gather(self.read_block, ids)


@jax.jit
def _set_rows(table: jax.Array, slots: jax.Array, rows: jax.Array) -> jax.Array:
    # padded slots point past the table; 'drop' discards them — one
    # bounded batched row-scatter per PROMOTION batch (a placement
    # update, not a per-gather build)
    return table.at[slots].set(rows, mode="drop")


class TierPlacement:
    """Host-side placement book for a 3-tier adaptive store.

    ``tier_of[stored_row]`` in {TIER_HBM, TIER_HOST, TIER_DISK};
    ``slot_of[stored_row]`` is the row's slot within its tier's cache
    table (-1 on disk — disk rows are addressed by stored id against the
    full backing file). ``hbm_slots``/``host_slots`` are the inverse
    (slot -> stored id, -1 free). Pure numpy, mutated only under the
    owner's placement fence; ``version`` bumps once per applied batch.
    """

    def __init__(self, n: int, hbm_rows: int, host_rows: int):
        if hbm_rows < 0 or host_rows < 0:
            raise ValueError("tier capacities must be >= 0")
        hbm_rows = min(hbm_rows, n)
        host_rows = min(host_rows, n - hbm_rows)
        self.n = int(n)
        self.hbm_rows = int(hbm_rows)
        self.host_rows = int(host_rows)
        self.tier_of = np.full(n, TIER_DISK, np.int8)
        self.slot_of = np.full(n, -1, np.int64)
        # prefix init: the degree/id-ordered head fills the fast tiers —
        # exactly the static placement, so a frozen adaptive store and a
        # static store start bit-and-placement identical
        self.tier_of[:hbm_rows] = TIER_HBM
        self.slot_of[:hbm_rows] = np.arange(hbm_rows)
        self.tier_of[hbm_rows : hbm_rows + host_rows] = TIER_HOST
        self.slot_of[hbm_rows : hbm_rows + host_rows] = np.arange(host_rows)
        self.hbm_slots = np.full(hbm_rows, -1, np.int64)
        self.hbm_slots[:hbm_rows] = np.arange(hbm_rows)
        self.host_slots = np.full(host_rows, -1, np.int64)
        self.host_slots[:host_rows] = np.arange(
            hbm_rows, hbm_rows + host_rows
        )
        self.version = 0

    def counts(self) -> Dict[str, int]:
        return {
            "hbm": int((self.tier_of == TIER_HBM).sum()),
            "host": int((self.tier_of == TIER_HOST).sum()),
            "disk": int((self.tier_of == TIER_DISK).sum()),
        }

    def residents(self, tier: int) -> np.ndarray:
        """Stored ids currently resident in ``tier`` (disk = everything
        not in a faster tier)."""
        return np.nonzero(self.tier_of == tier)[0]

    def _slot_table(self, tier: int) -> np.ndarray:
        return self.hbm_slots if tier == TIER_HBM else self.host_slots

    def free_slots(self, tier: int) -> np.ndarray:
        return np.nonzero(self._slot_table(tier) < 0)[0]

    def release(self, stored: int) -> None:
        """Free ``stored``'s slot (no-op on disk)."""
        t = int(self.tier_of[stored])
        if t == TIER_DISK:
            return
        self._slot_table(t)[self.slot_of[stored]] = -1
        self.tier_of[stored] = TIER_DISK
        self.slot_of[stored] = -1

    def occupy(self, stored: int, tier: int, slot: int) -> None:
        self._slot_table(tier)[slot] = stored
        self.tier_of[stored] = tier
        self.slot_of[stored] = slot

    def check(self) -> None:
        """Invariant sweep (tests; O(N))."""
        for tier in (TIER_HBM, TIER_HOST):
            tab = self._slot_table(tier)
            res = self.residents(tier)
            assert res.size == int((tab >= 0).sum()), "slot table drift"
            assert np.array_equal(
                np.sort(tab[tab >= 0]), np.sort(res)
            ), "slot table contents drift"
            slots = self.slot_of[res]
            assert np.array_equal(tab[slots], res), "inverse map drift"
        assert np.all(self.slot_of[self.tier_of == TIER_DISK] == -1)


@dataclass
class PlacementPlan:
    """An ordered batch of tier moves: ``(stored_row, dst_tier)``.
    Demotions are listed before the promotions whose slots they free;
    `TierStore.apply` executes in order and batches the data movement."""

    moves: List[Tuple[int, int]] = field(default_factory=list)

    def __len__(self) -> int:
        return len(self.moves)

    def demote(self, stored: int, dst: int = TIER_DISK) -> None:
        self.moves.append((int(stored), int(dst)))

    def promote(self, stored: int, dst: int) -> None:
        self.moves.append((int(stored), int(dst)))


def plan_adaptive(
    placement: TierPlacement,
    hot_stored: np.ndarray,
    hot_weight: np.ndarray,
    resident_weight: Callable[[np.ndarray], np.ndarray],
    max_moves: int = 64,
    min_weight: float = 2.0,
    hysteresis: float = 1.25,
) -> PlacementPlan:
    """Greedy bounded promote/demote plan from a measured hot set.

    ``hot_stored``/``hot_weight`` are the sketch's err-corrected heavy
    hitters mapped into stored-row space (unmapped entries already
    dropped); ``resident_weight(stored_ids)`` prices CURRENT residents
    (the engine answers it from the Count-Min sketch). Two passes:

    - HBM pass: hottest non-HBM candidates displace the coldest HBM
      residents, but only when ``cand_w >= max(victim_w * hysteresis,
      min_weight)`` — the hysteresis band is what keeps near-tied rows
      from ping-ponging between windows. A displaced HBM victim cascades
      to host DRAM when host has a free slot or a colder resident
      (which then drops to disk); otherwise it drops to disk.
    - Host pass: remaining disk candidates displace the coldest host
      residents under the same band.

    Each promotion costs at most 2 moves (victim out, candidate in) plus
    at most 1 cascade move; ``max_moves`` bounds the TOTAL move count,
    so an apply batch's device scatter and disk read are bounded too.
    """
    plan = PlacementPlan()
    hot_stored = np.asarray(hot_stored, np.int64).reshape(-1)
    hot_weight = np.asarray(hot_weight, np.float64).reshape(-1)
    keep = hot_weight >= min_weight
    hot_stored, hot_weight = hot_stored[keep], hot_weight[keep]
    if hot_stored.size == 0:
        return plan
    order = np.argsort(-hot_weight, kind="stable")
    hot_stored, hot_weight = hot_stored[order], hot_weight[order]
    hot_w_of = dict(zip(hot_stored.tolist(), hot_weight.tolist()))

    # victim books: (weight asc) heaps per fast tier, weights from the
    # sketch for every CURRENT resident — bounded by the tier capacities
    def victim_list(tier: int) -> List[Tuple[float, int]]:
        res = placement.residents(tier)
        if res.size == 0:
            return []
        w = np.asarray(resident_weight(res), np.float64)
        # a resident that is itself a tracked hot row keeps its (larger)
        # head estimate — never victimize a row hotter than the candidate
        for i, sid in enumerate(res.tolist()):
            if sid in hot_w_of:
                w[i] = max(w[i], hot_w_of[sid])
        order = np.argsort(w, kind="stable")
        return [(float(w[i]), int(res[i])) for i in order]

    moved: set = set()
    free_host = placement.free_slots(TIER_HOST).size
    host_victims = victim_list(TIER_HOST)
    hv_i = 0  # next coldest host victim

    def spill_to_host(victim_sid: int, victim_w: float) -> None:
        """Cascade an HBM victim: host free slot, else displace a colder
        host resident to disk, else straight to disk."""
        nonlocal free_host, hv_i
        if placement.host_rows == 0:
            plan.demote(victim_sid, TIER_DISK)
            return
        if free_host > 0:
            free_host -= 1
            plan.demote(victim_sid, TIER_HOST)
            return
        while hv_i < len(host_victims) and host_victims[hv_i][1] in moved:
            hv_i += 1
        if hv_i < len(host_victims) and host_victims[hv_i][0] < victim_w:
            w, sid = host_victims[hv_i]
            hv_i += 1
            moved.add(sid)
            plan.demote(sid, TIER_DISK)
            plan.demote(victim_sid, TIER_HOST)
        else:
            plan.demote(victim_sid, TIER_DISK)

    # -- HBM pass ---------------------------------------------------------
    if placement.hbm_rows > 0:
        hbm_victims = victim_list(TIER_HBM)
        free_hbm = placement.free_slots(TIER_HBM).size
        vi = 0
        for sid, w in zip(hot_stored.tolist(), hot_weight.tolist()):
            if len(plan) + 3 > max_moves:
                break
            if placement.tier_of[sid] == TIER_HBM or sid in moved:
                continue
            if free_hbm > 0:
                free_hbm -= 1
            else:
                while vi < len(hbm_victims) and hbm_victims[vi][1] in moved:
                    vi += 1
                if vi >= len(hbm_victims):
                    break
                vw, vsid = hbm_victims[vi]
                if w < max(vw * hysteresis, min_weight):
                    break  # victims only get hotter from here
                vi += 1
                moved.add(vsid)
                spill_to_host(vsid, vw)
            moved.add(sid)
            plan.promote(sid, TIER_HBM)

    # -- host pass --------------------------------------------------------
    if placement.host_rows > 0:
        host_victims2 = [
            (w, sid) for w, sid in victim_list(TIER_HOST) if sid not in moved
        ]
        vi = 0
        for sid, w in zip(hot_stored.tolist(), hot_weight.tolist()):
            if len(plan) + 2 > max_moves:
                break
            if sid in moved or placement.tier_of[sid] != TIER_DISK:
                continue
            if free_host > 0:
                free_host -= 1
            else:
                while vi < len(host_victims2) and host_victims2[vi][1] in moved:
                    vi += 1
                if vi >= len(host_victims2):
                    break
                vw, vsid = host_victims2[vi]
                if w < max(vw * hysteresis, min_weight):
                    break
                vi += 1
                moved.add(vsid)
                plan.demote(vsid, TIER_DISK)
            moved.add(sid)
            plan.promote(sid, TIER_HOST)
    return plan


class TierStore:
    """Adaptive 3-tier row store: HBM cache table + host DRAM cache +
    full flat-file disk backing, placed by a :class:`TierPlacement`.

    The backing file holds EVERY stored row (at the store dtype), so a
    placement move never moves truth — promotion copies disk bytes into
    a cache slot, demotion frees the slot. That is what makes placement
    bit-neutral: ``gather(ids)`` returns identical bytes under any
    placement (the parity pin in tests/test_tiers.py), and a promotion
    batch can never corrupt an in-flight gather that the engine fence
    already excluded.

    Gathers are gather-only: the per-tier split is host-computed from
    the placement map; HBM rows ride one jitted take + scatter-merge
    (the `ShardTensor.__getitem__` pattern), host+disk rows assemble
    host-side and ship as ONE padded H2D copy.
    """

    def __init__(
        self,
        backing: DiskShard,
        placement: TierPlacement,
        hbm_table: Optional[jax.Array],
        host_cache: Optional[np.ndarray],
        rank: int = 0,
        read_pool=None,
    ):
        self.backing = backing
        self.placement = placement
        self.hbm_table = hbm_table  # [hbm_rows, D] device, or None
        self.host_cache = host_cache  # [host_rows, D] numpy, or None
        self.rank = rank
        self.read_pool = read_pool
        self.dtype = np.dtype(backing.dtype)
        self.dim = int(backing.shape[1])
        # orders concurrent apply() calls ONLY. Gathers are deliberately
        # lock-free (serializing them would kill the engines' in-flight
        # overlap), so a gather racing a bare apply() can see new maps
        # over old cache bytes — callers must fence gathers against
        # placement moves, which is exactly what the serve engines'
        # `apply_placement` does (drain in-flight flushes under _seq).
        # Bare stores: treat apply() like the engines treat it — no
        # concurrent gathers.
        self._lock = threading.Lock()
        self.rows_promoted = 0
        self.rows_demoted = 0

    @classmethod
    def build(
        cls,
        arr: np.ndarray,
        path: str,
        hbm_rows: int,
        host_rows: int,
        rank: int = 0,
        read_pool=None,
    ) -> "TierStore":
        """Spill the FULL stored table to ``path`` and seed the fast
        tiers with the prefix placement (rows [0, hbm) in HBM,
        [hbm, hbm+host) in DRAM — identical to the static split)."""
        arr = np.ascontiguousarray(arr)
        n, d = arr.shape
        backing = DiskShard.create(path, arr)
        placement = TierPlacement(n, hbm_rows, host_rows)
        hbm_rows, host_rows = placement.hbm_rows, placement.host_rows
        hbm_table = None
        if hbm_rows > 0:
            hbm_table = jax.device_put(
                jnp.asarray(arr[:hbm_rows]), _device_of(rank)
            )
        host_cache = None
        if host_rows > 0:
            # an owned COPY, never a view: promotions write into these
            # slots, and a view would silently mutate the caller's table
            host_cache = np.array(
                arr[hbm_rows : hbm_rows + host_rows], copy=True, order="C"
            )
        return cls(backing, placement, hbm_table, host_cache,
                   rank=rank, read_pool=read_pool)

    # ------------------------------------------------------------------ reads
    @property
    def n_rows(self) -> int:
        return self.placement.n

    @property
    def placement_version(self) -> int:
        return self.placement.version

    def tier_bytes(self) -> Dict[str, int]:
        """LIVE byte footprint per tier at the stored dtype — reflects
        the current placement, so a demotion batch shrinks the device
        row immediately (the honest-accounting satellite: ``device`` is
        occupied rows, never the cache capacity)."""
        row = self.dim * self.dtype.itemsize
        c = self.placement.counts()
        return {
            "device": c["hbm"] * row,
            "host": c["host"] * row,
            "disk": self.backing.nbytes,
            "device_capacity": self.placement.hbm_rows * row,
            "host_capacity": self.placement.host_rows * row,
            "row": row,
        }

    def tier_split(self, stored_ids: np.ndarray) -> Dict[str, int]:
        """Host-side per-tier row counts for a gather batch (the
        attribution the workload monitor records)."""
        t = self.placement.tier_of[np.asarray(stored_ids, np.int64)]
        return {
            "hbm": int((t == TIER_HBM).sum()),
            "host": int((t == TIER_HOST).sum()),
            "disk": int((t == TIER_DISK).sum()),
        }

    def gather_np(self, stored_ids: np.ndarray) -> np.ndarray:
        """Host-side oracle gather straight from the backing file — the
        bit-parity reference every placement-routed gather is tested
        against (placement cannot change these bytes)."""
        return self.backing.read_rows(
            np.asarray(stored_ids, np.int64), pool=self.read_pool
        )

    def gather(self, stored_ids) -> jax.Array:
        """Tiered gather by STORED row id onto this rank's device.

        Placement-routed: HBM slots via one jitted take (+ scatter-merge
        into the output), host-cache and disk rows assembled host-side
        (disk through the read pool) and shipped as ONE padded H2D copy.
        Caller passes pre-sanitized ids (the Feature masks invalid lanes
        before and after)."""
        ids = np.asarray(stored_ids, np.int64).reshape(-1)
        n = ids.shape[0]
        target = _device_of(self.rank)
        out = jnp.zeros((n, self.dim), self.dtype, device=target)
        if n == 0:
            return out
        pl = self.placement
        tiers = pl.tier_of[ids]
        hbm_sel = np.nonzero(tiers == TIER_HBM)[0]
        if hbm_sel.size and self.hbm_table is not None:
            b = _bucket(hbm_sel.shape[0])
            pos = np.full(b, n, np.int32)
            pos[: hbm_sel.shape[0]] = hbm_sel
            slots = np.zeros(b, np.int64)
            slots[: hbm_sel.shape[0]] = pl.slot_of[ids[hbm_sel]]
            rows = _gather_local(self.hbm_table, jnp.asarray(slots))
            out = _scatter_rows(out, jnp.asarray(pos), rows)
        cold_sel = np.nonzero(tiers != TIER_HBM)[0]
        if cold_sel.size:
            from .ops import cpu_kernels

            b = _bucket(cold_sel.shape[0])
            pos = np.full(b, n, np.int32)
            pos[: cold_sel.shape[0]] = cold_sel
            rows_np = np.zeros((b, self.dim), self.dtype)
            host_sel = np.nonzero(tiers == TIER_HOST)[0]
            if host_sel.size and self.host_cache is not None:
                # cold_sel is sorted and host/disk partition it, so the
                # searchsorted below recovers each row's lane in rows_np
                lanes = np.searchsorted(cold_sel, host_sel)
                rows_np[lanes] = cpu_kernels.gather_rows(
                    self.host_cache, pl.slot_of[ids[host_sel]]
                )
            disk_sel = np.nonzero(tiers == TIER_DISK)[0]
            if disk_sel.size:
                lanes = np.searchsorted(cold_sel, disk_sel)
                rows_np[lanes] = self.backing.read_rows(
                    ids[disk_sel], pool=self.read_pool
                )
            rows = jax.device_put(jnp.asarray(rows_np), target)
            out = _scatter_rows(out, jnp.asarray(pos), rows)
        return out

    # ------------------------------------------------------------ placement
    def apply(self, plan: PlacementPlan) -> Dict[str, object]:
        """Execute a :class:`PlacementPlan` as one batch: map updates in
        plan order (demotions free the slots promotions take), then the
        data movement batched per destination — one pooled backing read
        + numpy write for host promotions, one pooled backing read + ONE
        jitted row-scatter for HBM promotions. Callers running a serve
        engine go through ``engine.apply_placement`` (which fences
        in-flight flushes first); the store's own lock only orders bare
        concurrent callers."""
        with self._lock:
            pl = self.placement
            promote_hbm: List[Tuple[int, int]] = []   # (stored, slot)
            promote_host: List[Tuple[int, int]] = []
            promoted = demoted = 0
            for sid, dst in plan.moves:
                cur = int(pl.tier_of[sid])
                if dst == cur:
                    continue
                pl.release(sid)
                if dst == TIER_DISK:
                    demoted += 1
                    continue
                free = pl.free_slots(dst)
                if free.size == 0:
                    # over-full plan (stale weights): leave the row on
                    # disk rather than evict outside the plan
                    if cur != TIER_DISK:
                        demoted += 1
                    continue
                slot = int(free[0])
                pl.occupy(sid, dst, slot)
                (promote_hbm if dst == TIER_HBM else promote_host).append(
                    (sid, slot)
                )
                if dst < cur:
                    promoted += 1
                else:
                    demoted += 1  # an hbm->host demotion lands in DRAM
            moved_stored = np.asarray(
                sorted({sid for sid, _ in plan.moves}), np.int64
            )
            if promote_host and self.host_cache is not None:
                sids = np.asarray([s for s, _ in promote_host], np.int64)
                slots = np.asarray([sl for _, sl in promote_host], np.int64)
                self.host_cache[slots] = self.backing.read_rows(
                    sids, pool=self.read_pool
                )
            if promote_hbm and self.hbm_table is not None:
                sids = np.asarray([s for s, _ in promote_hbm], np.int64)
                slots_np = np.asarray([sl for _, sl in promote_hbm], np.int64)
                rows_np = self.backing.read_rows(sids, pool=self.read_pool)
                b = _bucket(slots_np.shape[0])
                slots = np.full(b, self.placement.hbm_rows, np.int64)
                slots[: slots_np.shape[0]] = slots_np
                rows = np.zeros((b, self.dim), self.dtype)
                rows[: rows_np.shape[0]] = rows_np
                self.hbm_table = _set_rows(
                    self.hbm_table, jnp.asarray(slots), jnp.asarray(rows)
                )
            pl.version += 1
            self.rows_promoted += promoted
            self.rows_demoted += demoted
            return {
                "moves": len(plan.moves),
                "promoted_rows": promoted,
                "demoted_rows": demoted,
                "promoted_hbm": len(promote_hbm),
                "promoted_host": len(promote_host),
                "moved_stored": moved_stored,
                "version": pl.version,
                "counts": pl.counts(),
            }


def tier_daemon_loop(engine) -> None:
    """Body of the background promote/demote consumer, shared by
    `ServeEngine` and `DistServeEngine` (both expose ``_running``,
    ``config.tier_adapt_every_s``, ``adapt_tiers`` and a
    ``tier_adapt_errors`` counter). Sleeps in small slices so ``stop()``
    never waits a full period; a failing pass increments the error
    counter (exposed as a gauge) instead of killing serving — a counter
    stuck rising is how operators tell "adaptation crashing every
    period" from "nothing hot to move"."""
    period = engine.config.tier_adapt_every_s
    while engine._running:
        deadline = time.monotonic() + period
        while engine._running and time.monotonic() < deadline:
            time.sleep(min(0.05, period))
        if not engine._running:
            return
        try:
            engine.adapt_tiers()
        except Exception:
            engine.tier_adapt_errors += 1


def find_tiered_feature(feature):
    """The feature object owning an adaptive :class:`TierStore` under
    the serve-feature wrappers (`QuantizedFeature.inner`, the dist
    engine's ``_ShardFeature`` -> `DistFeature` chain). Returns the
    feature that can map stored rows <-> node ids (``tier_store`` +
    ``node_ids_of_stored``), or None when the engine's feature has no
    adaptive store — static placements have nothing to adapt."""
    seen = set()
    obj = feature
    while obj is not None and id(obj) not in seen:
        seen.add(id(obj))
        if (
            getattr(obj, "tier_store", None) is not None
            and hasattr(obj, "node_ids_of_stored")
        ):
            return obj
        nxt = None
        for attr in ("inner", "_dist", "feature"):
            n = getattr(obj, attr, None)
            if n is not None:
                nxt = n
                break
        obj = nxt
    return None

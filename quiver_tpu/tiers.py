"""Disk-backed cold tier + sketch-driven adaptive placement (round 14).

The tier stack so far stopped at host DRAM — the capacity wall "millions
of users" hits first. The reference spanned its hierarchy to mmap'd disk
(PAPER.md L2/L4: ``quiver<T,CPU>`` + the ShardTensor CPU slice); the
PAPERS.md entries "GPU Initiated Direct Storage Accesses" (2306.16384)
and PyTorch-Direct (2101.07956) are the same lever. This module is the
TPU-native version, in two halves:

1. **A fourth storage tier**: :class:`DiskShard` — a flat-file ``.npy``
   row shard read through ``np.memmap`` (page-cache-friendly) and an
   optional :class:`quiver_tpu.pipeline.AsyncReadPool` (the same
   one-worker-per-stage thread machinery the train pipeline runs on,
   widened to a bounded pool: disk reads are the one stage that scales
   with parallel outstanding requests). `ShardTensor.append_disk` hangs
   it under the existing shard book as a static tail; rows are stored at
   the STORE's dtype, so a `QuantizedFeature`'s disk tier holds int8 —
   cold rows are encoded on disk AND on the wire.

2. **Adaptive placement**: :class:`TierStore` — HBM cache table + host
   DRAM cache + full disk backing, with a host-side
   :class:`TierPlacement` map (stored row -> tier, slot). Gathers stay
   GATHER-ONLY (the placement map is computed on host; per-tier gathers
   scatter-merge into the output exactly like `ShardTensor.__getitem__`
   — no scatter builds of big arrays per gather, PERF_NOTES).
   :func:`plan_adaptive` turns the round-13 frequency sketch
   (`WorkloadMonitor.promotion_candidates`) into a bounded
   :class:`PlacementPlan`; `TierStore.apply` executes it in batches
   (demotions free slots, promotions batch-read the backing file and
   land as ONE device row-scatter per batch — the "stage host-side, swap
   device tiles in batches" discipline). The serve engines fence the
   apply exactly like ``update_params`` (drain in-flight flushes, bump a
   placement version, invalidate moved rows' embedding-cache entries).

Bit-parity contract: every row's bytes live on disk permanently (the
backing file is the full table), so placement NEVER changes a gathered
byte — promotion copies, demotion just edits the map. A frozen placement
replays bit-identically, and a run straddling a promotion batch still
serves bit-identical logits (pinned in tests/test_tiers.py).

Module imports: `shard_tensor` only (leaf-ward); the read pool and the
serve engines import lazily, so `feature`/`pipeline`/`serve` can all
reach this module without a cycle.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .shard_tensor import _bucket, _device_of, _gather_local, _scatter_rows

TIER_HBM = 0
TIER_HOST = 1
TIER_DISK = 2
TIER_NAMES = ("hbm", "host", "disk")

# O_DIRECT reads must be aligned to the device's logical block size in
# offset, length AND buffer address; 4096 covers every common device
# (512e drives accept it too). Anonymous mmap buffers are page-aligned,
# which is what makes the direct path possible from Python at all.
DIRECT_ALIGN = 4096


def drop_page_cache(path: str) -> bool:
    """Ask the kernel to evict ``path``'s pages from the page cache
    (``posix_fadvise(DONTNEED)`` over the whole file) — the portable
    page-cache defeat for real-disk measurement when the filesystem
    refuses O_DIRECT. Best-effort: returns False (instead of raising)
    on platforms without the syscall, so probes can record WHICH method
    actually ran."""
    if not hasattr(os, "posix_fadvise"):
        return False
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return False
    try:
        os.posix_fadvise(fd, 0, 0, os.POSIX_FADV_DONTNEED)
        return True
    except OSError:
        return False
    finally:
        os.close(fd)


def o_direct_supported(path: str) -> bool:
    """Whether ``path``'s filesystem accepts an O_DIRECT aligned read —
    probed by actually doing one (overlayfs/tmpfs commonly refuse with
    EINVAL; the only honest answer is empirical). The probe reads the
    first aligned block into a page-aligned anonymous mmap buffer."""
    if not hasattr(os, "O_DIRECT"):
        return False
    import mmap as _mmap

    try:
        fd = os.open(path, os.O_RDONLY | os.O_DIRECT)
    except OSError:
        return False
    try:
        buf = _mmap.mmap(-1, DIRECT_ALIGN)
        try:
            return os.preadv(fd, [buf], 0) >= 0
        finally:
            buf.close()
    except OSError:
        return False
    finally:
        os.close(fd)


class DiskShard:
    """Flat-file ``[R, D]`` row shard on disk (``.npy`` format, read
    through ``np.memmap``).

    ``read_rows`` is the only read surface: local row ids in, a fresh
    C-contiguous array out. With a pool the read is split into chunks
    that run on the pool's workers concurrently — each chunk is an
    independent page-cache/disk read, which is where parallelism
    actually pays (a single thread serializes the page faults).
    Out-of-range ids raise loudly: unlike lookup padding (which the
    callers mask BEFORE reaching the disk tier), a bad local id here
    means a corrupt placement map, not padding.

    ``direct=True`` (round 18, real-disk measurement) reads through an
    ``O_DIRECT`` descriptor instead of the memmap: every ``read_block``
    is an aligned pread into a page-aligned buffer, bypassing the page
    cache entirely — the honest cold-read path a 10x-DRAM claim must be
    measured on. Bytes are identical to the memmap path by construction
    (same file, same offsets); only the cache behavior differs. Raises
    at open when the filesystem refuses O_DIRECT (probe with
    :func:`o_direct_supported` first; fall back to
    :func:`drop_page_cache` between measurement legs).
    """

    def __init__(self, path: str, direct: bool = False):
        self.path = path
        # mmap_mode='r': reads hit the page cache; nothing is resident
        # until touched, which is the whole point of the tier
        self._mm = np.load(path, mmap_mode="r")
        if self._mm.ndim != 2:
            raise ValueError(f"disk shard {path} must be [R, D]")
        self.direct = bool(direct)
        self._fd = None
        if self.direct:
            if not hasattr(os, "O_DIRECT"):
                raise OSError("platform has no O_DIRECT")
            # raises OSError where the filesystem refuses — callers that
            # want a fallback probe o_direct_supported() first
            self._fd = os.open(path, os.O_RDONLY | os.O_DIRECT)
            if not o_direct_supported(path):
                os.close(self._fd)
                self._fd = None
                raise OSError(f"filesystem refuses O_DIRECT reads: {path}")
            # the npy payload offset: np.load's memmap records where the
            # header ends — direct preads address rows relative to it
            self._data_off = int(self._mm.offset)
            # PER-THREAD descriptors for pooled reads: concurrent preads
            # on one shared fd serialize in the kernel (measured SLOWER
            # than single-threaded on this box's filesystem), so each
            # pool worker reads through its own fd. _fd above stays the
            # probe/owner descriptor; _all_fds tracks every lazy open
            # for close.
            self._tls = threading.local()
            self._all_fds: List[int] = [self._fd]
            self._fd_lock = threading.Lock()

    def _direct_fd(self) -> int:
        fd = getattr(self._tls, "fd", None)
        if fd is None:
            fd = os.open(self.path, os.O_RDONLY | os.O_DIRECT)
            self._tls.fd = fd
            with self._fd_lock:
                self._all_fds.append(fd)
        return fd

    def _direct_buf(self, nbytes: int) -> np.ndarray:
        """This thread's persistent block-address-aligned read buffer,
        grown (never shrunk) to ``nbytes``."""
        buf = getattr(self._tls, "buf", None)
        if buf is None or buf.shape[0] < nbytes:
            base = np.empty(nbytes + DIRECT_ALIGN, np.uint8)
            shift = (-base.ctypes.data) % DIRECT_ALIGN
            buf = base[shift: shift + nbytes]
            self._tls.buf_base = base  # keeps the allocation alive
            self._tls.buf = buf
        return buf

    def __del__(self):
        fds = getattr(self, "_all_fds", None)
        if fds is None:
            fds = [f for f in (getattr(self, "_fd", None),)
                   if f is not None]
        for fd in fds:
            try:
                os.close(fd)
            except OSError:
                pass

    # contiguous aligned spans merge into one pread up to this many
    # bytes: amortizes the per-syscall cost (and the Python dispatch
    # around it, which holds the GIL) without unbounded buffer growth
    DIRECT_RUN_BYTES = 1 << 20

    def _read_block_direct(self, ids: np.ndarray) -> np.ndarray:
        """Aligned O_DIRECT gather, span-grouped: rows are bucketed by
        the aligned block span enclosing them, spans dedup (rows smaller
        than a block share one read), and CONTIGUOUS spans merge into a
        single pread up to ``DIRECT_RUN_BYTES``. A naive per-row pread
        loop is GIL-bound from Python — per-row slicing serializes pool
        workers and 128-byte rows re-read the same 4 KiB block 32 times
        — so grouping is what makes the direct path pool-parallel at
        all. Reads land in a PERSISTENT per-thread block-aligned buffer
        (O_DIRECT requires the buffer ADDRESS aligned too): a fresh
        anonymous mmap per call would serialize pool workers on the
        process mmap lock and pay a TLB shootdown at every munmap —
        measured 4x slower across 4 workers than one thread. Never
        touches the page cache; bytes equal the memmap path (same file
        region)."""
        rb = self.row_bytes
        out = np.empty((ids.shape[0], self._mm.shape[1]), self._mm.dtype)
        row_u8 = out.view(np.uint8).reshape(ids.shape[0], rb)
        offs = self._data_off + ids.astype(np.int64) * rb
        a0 = (offs // DIRECT_ALIGN) * DIRECT_ALIGN           # span start
        a1 = (-(-(offs + rb) // DIRECT_ALIGN)) * DIRECT_ALIGN  # span end
        # merge the sorted spans into contiguous runs, recording which
        # run each row landed in (a span near the cap boundary may start
        # inside run i yet belong to run i+1 — membership must be
        # tracked, not re-derived from positions)
        order = np.argsort(a0, kind="stable")
        runs: List[Tuple[int, int]] = []        # (run_start, run_end)
        rows_of: List[List[int]] = []           # run -> row indices
        for j in order.tolist():
            s, e = int(a0[j]), int(a1[j])
            if (runs and s <= runs[-1][1]
                    and e - runs[-1][0] <= self.DIRECT_RUN_BYTES):
                if e > runs[-1][1]:
                    runs[-1] = (runs[-1][0], e)
            else:
                # new run; when the cap split a contiguous stretch the
                # boundary block re-reads, which is correct just not free
                runs.append((s, e))
                rows_of.append([])
            rows_of[-1].append(j)
        buf_bytes = max((e - s for s, e in runs), default=DIRECT_ALIGN)
        buf_np = self._direct_buf(buf_bytes)
        mv = memoryview(buf_np)
        fd = self._direct_fd()  # this thread's own descriptor
        for (s, e), members in zip(runs, rows_of):
            got = os.preadv(fd, [mv[: e - s]], s)
            for j in members:
                # the DATA extent is what must be covered: the last
                # row's aligned span may exceed EOF, where pread
                # honestly returns only what exists
                lo = int(offs[j]) - s
                if lo + rb > got:
                    raise OSError(
                        f"short O_DIRECT read at row {int(ids[j])}: "
                        f"run [{s}, {e}) got {got}"
                    )
                row_u8[j] = buf_np[lo: lo + rb]
        return out

    @classmethod
    def create(cls, path: str, rows: np.ndarray) -> "DiskShard":
        """Write ``rows`` as a ``.npy`` flat file and open it mmap'd.
        The array is written at ITS dtype — an int8 store spills int8."""
        rows = np.ascontiguousarray(rows)
        if rows.ndim != 2:
            raise ValueError("disk shard rows must be [R, D]")
        if not path.endswith(".npy"):
            path = path + ".npy"
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        np.save(path, rows)
        return cls(path)

    @property
    def shape(self) -> Tuple[int, int]:
        return self._mm.shape

    @property
    def dtype(self) -> np.dtype:
        return self._mm.dtype

    @property
    def nbytes(self) -> int:
        """Payload bytes (rows * row_bytes; the npy header is noise)."""
        return int(self._mm.shape[0]) * self.row_bytes

    @property
    def row_bytes(self) -> int:
        return int(self._mm.shape[1]) * self._mm.dtype.itemsize

    def read_block(self, local_ids: np.ndarray) -> np.ndarray:
        """One synchronous gather (the unit of work a read pool chunks)."""
        ids = np.asarray(local_ids, np.int64).reshape(-1)
        if ids.size and (ids.min() < 0 or ids.max() >= self._mm.shape[0]):
            raise ValueError(
                f"disk read ids outside [0, {self._mm.shape[0]}): "
                "corrupt placement map (callers mask padding before the "
                "disk tier)"
            )
        if self._fd is not None:
            return self._read_block_direct(ids)
        return np.ascontiguousarray(self._mm[ids])

    def drop_cache(self) -> bool:
        """Evict this shard's pages from the page cache (see
        :func:`drop_page_cache`); the measurement-leg reset for real-disk
        probes on filesystems without O_DIRECT."""
        return drop_page_cache(self.path)

    def read_rows(self, local_ids: np.ndarray, pool=None) -> np.ndarray:
        ids = np.asarray(local_ids, np.int64).reshape(-1)
        if pool is None or ids.size == 0:
            return self.read_block(ids)
        return pool.gather(self.read_block, ids)


@jax.jit
def _set_rows(table: jax.Array, slots: jax.Array, rows: jax.Array) -> jax.Array:
    # padded slots point past the table; 'drop' discards them — one
    # bounded batched row-scatter per PROMOTION batch (a placement
    # update, not a per-gather build)
    return table.at[slots].set(rows, mode="drop")


class PrefetchBuffer:
    """Flush-ahead staging for disk-tier reads (round 18, ROADMAP item
    3a): the serve/train engines know a gather's row set one stage
    before the gather runs, so they ``issue()`` `AsyncReadPool` reads
    then and the gather ``take()``s the landed rows out of DRAM instead
    of waiting on the device path's critical section.

    STRICTLY OBSERVE-ONLY ON BITS: staged rows are read by the SAME
    ``read_fn`` the direct path uses (resolved at call time, so probe
    wrappers and simulated latencies apply identically), so a taken row
    is byte-identical to an unstaged read — prefetch can change WHEN a
    byte is read, never WHICH byte. A staged read that failed is simply
    not a hit: the gather falls back to the direct read and surfaces the
    same error the prefetch-off run would (error parity).

    Accounting: ``issued`` counts rows submitted to the pool (after
    dedup against in-flight stages and the ``max_rows`` bound),
    ``hits`` rows a gather consumed from staging, ``wasted`` rows
    staged but never consumed (cleared by ``cancel()`` — the fence
    hook). An optional ``listener(kind, n)`` mirrors hit/wasted counts
    into engine stats without a second source of truth.

    Thread safety: the map mutates under one small lock; futures are
    observed on cancel so a fenced-away prefetch never logs "exception
    was never retrieved" at GC (the r7/r14 error-contract discipline).
    """

    def __init__(self, read_fn: Callable[[np.ndarray], np.ndarray],
                 pool, max_rows: int = 8192):
        if pool is None:
            raise ValueError("PrefetchBuffer needs an AsyncReadPool")
        self._read_fn = read_fn
        self._pool = pool
        self.max_rows = int(max_rows)
        # local row id -> (chunk future, lane within the chunk's rows)
        self._staged: Dict[int, Tuple[object, int]] = {}
        self._lock = threading.Lock()
        self.issued = 0
        self.hits = 0
        self.wasted = 0
        self.errors = 0
        self.listener: Optional[Callable[[str, int], None]] = None

    def __len__(self) -> int:
        with self._lock:
            return len(self._staged)

    def _emit(self, kind: str, n: int) -> None:
        if n and self.listener is not None:
            try:
                self.listener(kind, n)
            except Exception:
                pass  # observe-only: a broken tap never breaks reads

    def issue(self, local_ids: np.ndarray) -> int:
        """Submit pool reads for the not-yet-staged subset of
        ``local_ids`` (bounded by ``max_rows`` total staged); returns
        rows actually issued. Duplicate/in-flight ids are free — the
        router and its owner engines may both prefetch the same rows."""
        ids = np.asarray(local_ids, np.int64).reshape(-1)
        if ids.size == 0:
            return 0
        # dedup WITHOUT sorting: callers pass BFS-ordered closures, and
        # when max_rows bites the truncation below must keep the nearest
        # (most-certainly-gathered) rows, not the lowest ids
        _, first = np.unique(ids, return_index=True)
        ids = ids[np.sort(first)]
        chunk = max(int(getattr(self._pool, "chunk_rows", 1024)), 1)
        read = self._read_fn
        with self._lock:
            fresh = [int(i) for i in ids if int(i) not in self._staged]
            room = self.max_rows - len(self._staged)
            if room <= 0 or not fresh:
                return 0
            fresh = fresh[:room]
            arr = np.asarray(fresh, np.int64)
            for lo in range(0, arr.shape[0], chunk):
                part = arr[lo : lo + chunk]
                fut = self._pool.submit(read, part)
                for lane, sid in enumerate(part.tolist()):
                    self._staged[sid] = (fut, lane)
            self.issued += len(fresh)
        return len(fresh)

    def staged_mask(self, local_ids: np.ndarray) -> np.ndarray:
        """Bool mask of ``local_ids`` currently staged (peek, no
        consume) — the `disk_prefetched` attribution input."""
        ids = np.asarray(local_ids, np.int64).reshape(-1)
        with self._lock:
            staged = self._staged
            return np.fromiter(
                (int(i) in staged for i in ids), bool, ids.shape[0]
            )

    def take(self, local_ids: np.ndarray
             ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """Consume the staged subset of ``local_ids``: returns
        ``(positions, rows)`` where ``positions`` indexes into
        ``local_ids`` and ``rows`` are the staged bytes (None when no
        position hit). A staged read still in flight is waited on (the
        bytes must be right; most of its latency is already hidden); a
        staged read that FAILED is dropped from the result so the caller
        re-reads directly and surfaces the prefetch-off error."""
        ids = np.asarray(local_ids, np.int64).reshape(-1)
        with self._lock:
            if not self._staged:
                return np.empty(0, np.int64), None
            entries = []
            for j, i in enumerate(ids.tolist()):
                e = self._staged.pop(int(i), None)
                if e is not None:
                    entries.append((j, e))
        # group by chunk future: one wait + one fancy-index per CHUNK
        # (a per-row python loop here costs more than the rows at batch
        # scale — this runs inside the gather's critical section)
        by_fut: Dict[int, Tuple[object, List[int], List[int]]] = {}
        for j, (fut, lane) in entries:
            g = by_fut.get(id(fut))
            if g is None:
                g = by_fut[id(fut)] = (fut, [], [])
            g[1].append(j)
            g[2].append(lane)
        pos_parts, row_parts = [], []
        failed = 0
        for fut, js, lanes in by_fut.values():
            try:
                chunk_rows = fut.result()
            except BaseException:
                failed += len(js)
                continue
            pos_parts.append(np.asarray(js, np.int64))
            row_parts.append(chunk_rows[np.asarray(lanes)])
        hits = sum(p.shape[0] for p in pos_parts)
        self.hits += hits
        # a failed staged read is BOTH an error (diagnostic) and waste
        # (the issue bought nothing) — keeping the two ledgers in step
        # with the listener mirror, which reports it as wasted
        self.errors += failed
        self.wasted += failed
        self._emit("hit", hits)
        self._emit("wasted", failed)
        if not pos_parts:
            return np.empty(0, np.int64), None
        return np.concatenate(pos_parts), np.concatenate(row_parts)

    def take_or_read(self, local_ids: np.ndarray,
                     read_fn: Callable[[np.ndarray], np.ndarray]
                     ) -> np.ndarray:
        """Assemble ``[n, D]`` rows for ``local_ids``: staged bytes for
        the rows a prefetch landed, ``read_fn(rest)`` for the remainder
        — byte-identical either way (staged rows came through the same
        read path, earlier). THE single consume-side helper: every
        gather that can hit staging routes here, so the hit/fallback
        semantics live in one place."""
        ids = np.asarray(local_ids, np.int64).reshape(-1)
        if not len(self):
            return read_fn(ids)
        hit_pos, hit_rows = self.take(ids)
        if hit_pos.size == 0:
            return read_fn(ids)
        out = np.empty((ids.shape[0], hit_rows.shape[1]), hit_rows.dtype)
        out[hit_pos] = hit_rows
        rest = np.ones(ids.shape[0], bool)
        rest[hit_pos] = False
        if rest.any():
            out[rest] = read_fn(ids[rest])
        return out

    def cancel(self) -> int:
        """Drop every staged row (the FENCE hook — update_params /
        apply_placement / update_graph / stop all route here): cancel
        what the pool has not started, observe every future so nothing
        logs at GC, count the unconsumed rows as wasted. Returns the
        rows dropped. Never blocks on an in-flight read."""
        with self._lock:
            staged, self._staged = self._staged, {}
        if not staged:
            return 0
        seen = set()
        for fut, _ in staged.values():
            if id(fut) in seen:
                continue
            seen.add(id(fut))
            fut.cancel()
            fut.add_done_callback(
                lambda f: f.cancelled() or f.exception()
            )
        n = len(staged)
        self.wasted += n
        self._emit("wasted", n)
        return n

    def stats(self) -> Dict[str, int]:
        with self._lock:
            staged = len(self._staged)
        return {"issued": self.issued, "hits": self.hits,
                "wasted": self.wasted, "errors": self.errors,
                "staged": staged, "max_rows": self.max_rows}


def expected_closure(sampler, seeds, hops: int,
                     max_nodes: Optional[int] = None) -> np.ndarray:
    """The rows a ``hops``-layer sample of ``seeds`` can GATHER: the
    forward k-hop closure over the sampler's CURRENT graph (the
    streamed adjacency when the sampler is stream-bound, the frozen CSR
    otherwise), in BFS order so a ``max_nodes`` truncation keeps the
    nearest — most-certainly-gathered — rows. A sampled draw touches a
    SUBSET of this closure (fanouts cap each hop), which is exactly why
    prefetching it is observe-only: a superset staged early costs wasted
    reads, never wrong bytes.

    ``hops`` for an L-layer sampler is ``len(sizes)`` — one MORE than
    the cache-invalidation depth, because the final hop's frontier is
    gathered even though it is never expanded (the round-11
    closure-hops rule)."""
    seeds = np.unique(np.asarray(seeds, np.int64).reshape(-1))
    stream = getattr(sampler, "stream", None)
    if stream is not None:
        adj = stream.adj
        n = adj.n

        def expand(frontier):
            # forward expansion must honor round-21 lifecycle rewrites:
            # a node with deletions/updates answers from its override
            # list, not the base CSR slice
            return adj._expand(frontier, adj.indptr, adj.indices,
                               adj._extra, adj._override)
    else:
        topo = getattr(sampler, "csr_topo", None)
        if topo is None:
            return seeds
        indptr = np.asarray(topo.indptr)
        indices = np.asarray(topo.indices)
        n = indptr.shape[0] - 1

        def expand(frontier):
            parts = [indices[s:e] for s, e in
                     zip(indptr[frontier], indptr[frontier + 1]) if e > s]
            if not parts:
                return np.array([], np.int64)
            return np.unique(np.concatenate(parts))

    seeds = seeds[(seeds >= 0) & (seeds < n)]
    if seeds.size == 0:
        return seeds
    mask = np.zeros(n, bool)
    mask[seeds] = True
    order = [seeds]
    frontier = seeds
    for _ in range(max(int(hops), 0)):
        if frontier.size == 0:
            break
        if max_nodes is not None and sum(p.size for p in order) >= max_nodes:
            break
        nxt = expand(frontier)
        nxt = nxt[~mask[nxt]]
        if nxt.size == 0:
            break
        mask[nxt] = True
        order.append(nxt)
        frontier = nxt
    out = np.concatenate(order)
    if max_nodes is not None and out.shape[0] > max_nodes:
        out = out[:max_nodes]
    return out


class TierPlacement:
    """Host-side placement book for a 3-tier adaptive store.

    ``tier_of[stored_row]`` in {TIER_HBM, TIER_HOST, TIER_DISK};
    ``slot_of[stored_row]`` is the row's slot within its tier's cache
    table (-1 on disk — disk rows are addressed by stored id against the
    full backing file). ``hbm_slots``/``host_slots`` are the inverse
    (slot -> stored id, -1 free). Pure numpy, mutated only under the
    owner's placement fence; ``version`` bumps once per applied batch.
    """

    def __init__(self, n: int, hbm_rows: int, host_rows: int):
        if hbm_rows < 0 or host_rows < 0:
            raise ValueError("tier capacities must be >= 0")
        hbm_rows = min(hbm_rows, n)
        host_rows = min(host_rows, n - hbm_rows)
        self.n = int(n)
        self.hbm_rows = int(hbm_rows)
        self.host_rows = int(host_rows)
        self.tier_of = np.full(n, TIER_DISK, np.int8)
        self.slot_of = np.full(n, -1, np.int64)
        # prefix init: the degree/id-ordered head fills the fast tiers —
        # exactly the static placement, so a frozen adaptive store and a
        # static store start bit-and-placement identical
        self.tier_of[:hbm_rows] = TIER_HBM
        self.slot_of[:hbm_rows] = np.arange(hbm_rows)
        self.tier_of[hbm_rows : hbm_rows + host_rows] = TIER_HOST
        self.slot_of[hbm_rows : hbm_rows + host_rows] = np.arange(host_rows)
        self.hbm_slots = np.full(hbm_rows, -1, np.int64)
        self.hbm_slots[:hbm_rows] = np.arange(hbm_rows)
        self.host_slots = np.full(host_rows, -1, np.int64)
        self.host_slots[:host_rows] = np.arange(
            hbm_rows, hbm_rows + host_rows
        )
        self.version = 0

    def counts(self) -> Dict[str, int]:
        return {
            "hbm": int((self.tier_of == TIER_HBM).sum()),
            "host": int((self.tier_of == TIER_HOST).sum()),
            "disk": int((self.tier_of == TIER_DISK).sum()),
        }

    def residents(self, tier: int) -> np.ndarray:
        """Stored ids currently resident in ``tier`` (disk = everything
        not in a faster tier)."""
        return np.nonzero(self.tier_of == tier)[0]

    def _slot_table(self, tier: int) -> np.ndarray:
        return self.hbm_slots if tier == TIER_HBM else self.host_slots

    def free_slots(self, tier: int) -> np.ndarray:
        return np.nonzero(self._slot_table(tier) < 0)[0]

    def release(self, stored: int) -> None:
        """Free ``stored``'s slot (no-op on disk)."""
        t = int(self.tier_of[stored])
        if t == TIER_DISK:
            return
        self._slot_table(t)[self.slot_of[stored]] = -1
        self.tier_of[stored] = TIER_DISK
        self.slot_of[stored] = -1

    def occupy(self, stored: int, tier: int, slot: int) -> None:
        self._slot_table(tier)[slot] = stored
        self.tier_of[stored] = tier
        self.slot_of[stored] = slot

    def check(self) -> None:
        """Invariant sweep (tests; O(N))."""
        for tier in (TIER_HBM, TIER_HOST):
            tab = self._slot_table(tier)
            res = self.residents(tier)
            assert res.size == int((tab >= 0).sum()), "slot table drift"
            assert np.array_equal(
                np.sort(tab[tab >= 0]), np.sort(res)
            ), "slot table contents drift"
            slots = self.slot_of[res]
            assert np.array_equal(tab[slots], res), "inverse map drift"
        assert np.all(self.slot_of[self.tier_of == TIER_DISK] == -1)


@dataclass
class PlacementPlan:
    """An ordered batch of tier moves: ``(stored_row, dst_tier)``.
    Demotions are listed before the promotions whose slots they free;
    `TierStore.apply` executes in order and batches the data movement."""

    moves: List[Tuple[int, int]] = field(default_factory=list)

    def __len__(self) -> int:
        return len(self.moves)

    def demote(self, stored: int, dst: int = TIER_DISK) -> None:
        self.moves.append((int(stored), int(dst)))

    def promote(self, stored: int, dst: int) -> None:
        self.moves.append((int(stored), int(dst)))


def plan_adaptive(
    placement: TierPlacement,
    hot_stored: np.ndarray,
    hot_weight: np.ndarray,
    resident_weight: Callable[[np.ndarray], np.ndarray],
    max_moves: int = 64,
    min_weight: float = 2.0,
    hysteresis: float = 1.25,
) -> PlacementPlan:
    """Greedy bounded promote/demote plan from a measured hot set.

    ``hot_stored``/``hot_weight`` are the sketch's err-corrected heavy
    hitters mapped into stored-row space (unmapped entries already
    dropped); ``resident_weight(stored_ids)`` prices CURRENT residents
    (the engine answers it from the Count-Min sketch). Two passes:

    - HBM pass: hottest non-HBM candidates displace the coldest HBM
      residents, but only when ``cand_w >= max(victim_w * hysteresis,
      min_weight)`` — the hysteresis band is what keeps near-tied rows
      from ping-ponging between windows. A displaced HBM victim cascades
      to host DRAM when host has a free slot or a colder resident
      (which then drops to disk); otherwise it drops to disk.
    - Host pass: remaining disk candidates displace the coldest host
      residents under the same band.

    Each promotion costs at most 2 moves (victim out, candidate in) plus
    at most 1 cascade move; ``max_moves`` bounds the TOTAL move count,
    so an apply batch's device scatter and disk read are bounded too.
    """
    plan = PlacementPlan()
    hot_stored = np.asarray(hot_stored, np.int64).reshape(-1)
    hot_weight = np.asarray(hot_weight, np.float64).reshape(-1)
    keep = hot_weight >= min_weight
    hot_stored, hot_weight = hot_stored[keep], hot_weight[keep]
    if hot_stored.size == 0:
        return plan
    order = np.argsort(-hot_weight, kind="stable")
    hot_stored, hot_weight = hot_stored[order], hot_weight[order]
    hot_w_of = dict(zip(hot_stored.tolist(), hot_weight.tolist()))

    # victim books: (weight asc) heaps per fast tier, weights from the
    # sketch for every CURRENT resident — bounded by the tier capacities
    def victim_list(tier: int) -> List[Tuple[float, int]]:
        res = placement.residents(tier)
        if res.size == 0:
            return []
        w = np.asarray(resident_weight(res), np.float64)
        # a resident that is itself a tracked hot row keeps its (larger)
        # head estimate — never victimize a row hotter than the candidate
        for i, sid in enumerate(res.tolist()):
            if sid in hot_w_of:
                w[i] = max(w[i], hot_w_of[sid])
        order = np.argsort(w, kind="stable")
        return [(float(w[i]), int(res[i])) for i in order]

    moved: set = set()
    free_host = placement.free_slots(TIER_HOST).size
    host_victims = victim_list(TIER_HOST)
    hv_i = 0  # next coldest host victim

    def spill_to_host(victim_sid: int, victim_w: float) -> None:
        """Cascade an HBM victim: host free slot, else displace a colder
        host resident to disk, else straight to disk."""
        nonlocal free_host, hv_i
        if placement.host_rows == 0:
            plan.demote(victim_sid, TIER_DISK)
            return
        if free_host > 0:
            free_host -= 1
            plan.demote(victim_sid, TIER_HOST)
            return
        while hv_i < len(host_victims) and host_victims[hv_i][1] in moved:
            hv_i += 1
        if hv_i < len(host_victims) and host_victims[hv_i][0] < victim_w:
            w, sid = host_victims[hv_i]
            hv_i += 1
            moved.add(sid)
            plan.demote(sid, TIER_DISK)
            plan.demote(victim_sid, TIER_HOST)
        else:
            plan.demote(victim_sid, TIER_DISK)

    # -- HBM pass ---------------------------------------------------------
    if placement.hbm_rows > 0:
        hbm_victims = victim_list(TIER_HBM)
        free_hbm = placement.free_slots(TIER_HBM).size
        vi = 0
        for sid, w in zip(hot_stored.tolist(), hot_weight.tolist()):
            if len(plan) + 3 > max_moves:
                break
            if placement.tier_of[sid] == TIER_HBM or sid in moved:
                continue
            if free_hbm > 0:
                free_hbm -= 1
            else:
                while vi < len(hbm_victims) and hbm_victims[vi][1] in moved:
                    vi += 1
                if vi >= len(hbm_victims):
                    break
                vw, vsid = hbm_victims[vi]
                if w < max(vw * hysteresis, min_weight):
                    break  # victims only get hotter from here
                vi += 1
                moved.add(vsid)
                spill_to_host(vsid, vw)
            moved.add(sid)
            plan.promote(sid, TIER_HBM)

    # -- host pass --------------------------------------------------------
    if placement.host_rows > 0:
        host_victims2 = [
            (w, sid) for w, sid in victim_list(TIER_HOST) if sid not in moved
        ]
        vi = 0
        for sid, w in zip(hot_stored.tolist(), hot_weight.tolist()):
            if len(plan) + 2 > max_moves:
                break
            if sid in moved or placement.tier_of[sid] != TIER_DISK:
                continue
            if free_host > 0:
                free_host -= 1
            else:
                while vi < len(host_victims2) and host_victims2[vi][1] in moved:
                    vi += 1
                if vi >= len(host_victims2):
                    break
                vw, vsid = host_victims2[vi]
                if w < max(vw * hysteresis, min_weight):
                    break
                vi += 1
                moved.add(vsid)
                plan.demote(vsid, TIER_DISK)
            moved.add(sid)
            plan.promote(sid, TIER_HOST)
    return plan


class TierStore:
    """Adaptive 3-tier row store: HBM cache table + host DRAM cache +
    full flat-file disk backing, placed by a :class:`TierPlacement`.

    The backing file holds EVERY stored row (at the store dtype), so a
    placement move never moves truth — promotion copies disk bytes into
    a cache slot, demotion frees the slot. That is what makes placement
    bit-neutral: ``gather(ids)`` returns identical bytes under any
    placement (the parity pin in tests/test_tiers.py), and a promotion
    batch can never corrupt an in-flight gather that the engine fence
    already excluded.

    Gathers are gather-only: the per-tier split is host-computed from
    the placement map; HBM rows ride one jitted take + scatter-merge
    (the `ShardTensor.__getitem__` pattern), host+disk rows assemble
    host-side and ship as ONE padded H2D copy.
    """

    def __init__(
        self,
        backing: DiskShard,
        placement: TierPlacement,
        hbm_table: Optional[jax.Array],
        host_cache: Optional[np.ndarray],
        rank: int = 0,
        read_pool=None,
    ):
        self.backing = backing
        self.placement = placement
        self.hbm_table = hbm_table  # [hbm_rows, D] device, or None
        self.host_cache = host_cache  # [host_rows, D] numpy, or None
        self.rank = rank
        self.read_pool = read_pool
        self.dtype = np.dtype(backing.dtype)
        self.dim = int(backing.shape[1])
        # orders concurrent apply() calls ONLY. Gathers are deliberately
        # lock-free (serializing them would kill the engines' in-flight
        # overlap), so a gather racing a bare apply() can see new maps
        # over old cache bytes — callers must fence gathers against
        # placement moves, which is exactly what the serve engines'
        # `apply_placement` does (drain in-flight flushes under _seq).
        # Bare stores: treat apply() like the engines treat it — no
        # concurrent gathers.
        self._lock = threading.Lock()
        self.rows_promoted = 0
        self.rows_demoted = 0
        # round-18 flush-ahead prefetch staging (enable_prefetch);
        # strictly observe-only on bits — see PrefetchBuffer
        self.prefetch: Optional[PrefetchBuffer] = None

    @classmethod
    def build(
        cls,
        arr: np.ndarray,
        path: str,
        hbm_rows: int,
        host_rows: int,
        rank: int = 0,
        read_pool=None,
    ) -> "TierStore":
        """Spill the FULL stored table to ``path`` and seed the fast
        tiers with the prefix placement (rows [0, hbm) in HBM,
        [hbm, hbm+host) in DRAM — identical to the static split)."""
        arr = np.ascontiguousarray(arr)
        n, d = arr.shape
        backing = DiskShard.create(path, arr)
        placement = TierPlacement(n, hbm_rows, host_rows)
        hbm_rows, host_rows = placement.hbm_rows, placement.host_rows
        hbm_table = None
        if hbm_rows > 0:
            hbm_table = jax.device_put(
                jnp.asarray(arr[:hbm_rows]), _device_of(rank)
            )
        host_cache = None
        if host_rows > 0:
            # an owned COPY, never a view: promotions write into these
            # slots, and a view would silently mutate the caller's table
            host_cache = np.array(
                arr[hbm_rows : hbm_rows + host_rows], copy=True, order="C"
            )
        return cls(backing, placement, hbm_table, host_cache,
                   rank=rank, read_pool=read_pool)

    # ------------------------------------------------------------------ reads
    @property
    def n_rows(self) -> int:
        return self.placement.n

    @property
    def placement_version(self) -> int:
        return self.placement.version

    def tier_bytes(self) -> Dict[str, int]:
        """LIVE byte footprint per tier at the stored dtype — reflects
        the current placement, so a demotion batch shrinks the device
        row immediately (the honest-accounting satellite: ``device`` is
        occupied rows, never the cache capacity)."""
        row = self.dim * self.dtype.itemsize
        c = self.placement.counts()
        return {
            "device": c["hbm"] * row,
            "host": c["host"] * row,
            "disk": self.backing.nbytes,
            "device_capacity": self.placement.hbm_rows * row,
            "host_capacity": self.placement.host_rows * row,
            "row": row,
        }

    def tier_split(self, stored_ids: np.ndarray) -> Dict[str, int]:
        """Host-side per-tier row counts for a gather batch (the
        attribution the workload monitor records). Disk rows a prefetch
        already STAGED in DRAM report as ``disk_prefetched`` — the tier
        labels tell the truth about where the bytes actually come from
        (round-18 satellite), while the placement itself is unchanged."""
        ids = np.asarray(stored_ids, np.int64)
        t = self.placement.tier_of[ids]
        disk = int((t == TIER_DISK).sum())
        staged = 0
        pf = self.prefetch
        if pf is not None and disk and len(pf):
            staged = int(pf.staged_mask(ids[t == TIER_DISK]).sum())
        out = {
            "hbm": int((t == TIER_HBM).sum()),
            "host": int((t == TIER_HOST).sum()),
            "disk": disk - staged,
        }
        if staged:
            out["disk_prefetched"] = staged
        return out

    # ----------------------------------------------------------- prefetch
    def enable_prefetch(self, max_rows: int = 8192,
                        listener: Optional[Callable[[str, int], None]] = None,
                        ) -> PrefetchBuffer:
        """Attach (or retune) the flush-ahead staging buffer. Requires a
        read pool (the reads must land off the caller's thread to hide
        anything). Idempotent: a second call updates the bound/listener
        on the existing buffer so router + owner engines can share."""
        if self.read_pool is None:
            raise ValueError(
                "prefetch needs an AsyncReadPool (build the Feature with "
                "read_pool=/disk_read_workers=)"
            )
        if self.prefetch is None:
            self.prefetch = PrefetchBuffer(
                lambda ids: self.backing.read_block(ids),
                self.read_pool, max_rows=max_rows,
            )
        else:
            self.prefetch.max_rows = int(max_rows)
        if listener is not None:
            self.prefetch.listener = listener
        return self.prefetch

    def prefetch_rows(self, stored_ids) -> int:
        """Issue flush-ahead reads for the DISK-resident subset of
        ``stored_ids`` (no-op rows already in a fast tier or already
        staged). Returns rows issued. Call `enable_prefetch` first."""
        if self.prefetch is None:
            return 0
        ids = np.asarray(stored_ids, np.int64).reshape(-1)
        ids = ids[(ids >= 0) & (ids < self.placement.n)]
        if ids.size == 0:
            return 0
        disk = ids[self.placement.tier_of[ids] == TIER_DISK]
        if disk.size == 0:
            return 0
        return self.prefetch.issue(disk)

    def cancel_prefetch(self) -> int:
        """Drop staged prefetch rows (fence hook); see
        `PrefetchBuffer.cancel`."""
        return self.prefetch.cancel() if self.prefetch is not None else 0

    def gather_np(self, stored_ids: np.ndarray) -> np.ndarray:
        """Host-side oracle gather straight from the backing file — the
        bit-parity reference every placement-routed gather is tested
        against (placement cannot change these bytes)."""
        return self.backing.read_rows(
            np.asarray(stored_ids, np.int64), pool=self.read_pool
        )

    def gather(self, stored_ids) -> jax.Array:
        """Tiered gather by STORED row id onto this rank's device.

        Placement-routed: HBM slots via one jitted take (+ scatter-merge
        into the output), host-cache and disk rows assembled host-side
        (disk through the read pool) and shipped as ONE padded H2D copy.
        Caller passes pre-sanitized ids (the Feature masks invalid lanes
        before and after)."""
        ids = np.asarray(stored_ids, np.int64).reshape(-1)
        n = ids.shape[0]
        target = _device_of(self.rank)
        out = jnp.zeros((n, self.dim), self.dtype, device=target)
        if n == 0:
            return out
        pl = self.placement
        tiers = pl.tier_of[ids]
        hbm_sel = np.nonzero(tiers == TIER_HBM)[0]
        if hbm_sel.size and self.hbm_table is not None:
            b = _bucket(hbm_sel.shape[0])
            pos = np.full(b, n, np.int32)
            pos[: hbm_sel.shape[0]] = hbm_sel
            slots = np.zeros(b, np.int64)
            slots[: hbm_sel.shape[0]] = pl.slot_of[ids[hbm_sel]]
            rows = _gather_local(self.hbm_table, jnp.asarray(slots))
            out = _scatter_rows(out, jnp.asarray(pos), rows)
        cold_sel = np.nonzero(tiers != TIER_HBM)[0]
        if cold_sel.size:
            from .ops import cpu_kernels

            b = _bucket(cold_sel.shape[0])
            pos = np.full(b, n, np.int32)
            pos[: cold_sel.shape[0]] = cold_sel
            rows_np = np.zeros((b, self.dim), self.dtype)
            host_sel = np.nonzero(tiers == TIER_HOST)[0]
            if host_sel.size and self.host_cache is not None:
                # cold_sel is sorted and host/disk partition it, so the
                # searchsorted below recovers each row's lane in rows_np
                lanes = np.searchsorted(cold_sel, host_sel)
                rows_np[lanes] = cpu_kernels.gather_rows(
                    self.host_cache, pl.slot_of[ids[host_sel]]
                )
            disk_sel = np.nonzero(tiers == TIER_DISK)[0]
            if disk_sel.size:
                lanes = np.searchsorted(cold_sel, disk_sel)
                disk_ids = ids[disk_sel]
                pf = self.prefetch

                def read(i):
                    return self.backing.read_rows(i, pool=self.read_pool)

                # flush-ahead staging: rows a prefetch landed in DRAM
                # skip the backing read — SAME bytes (the buffer read
                # them through the same read path), earlier
                rows_np[lanes] = (read(disk_ids) if pf is None
                                  else pf.take_or_read(disk_ids, read))
            rows = jax.device_put(jnp.asarray(rows_np), target)
            out = _scatter_rows(out, jnp.asarray(pos), rows)
        return out

    # ------------------------------------------------------------ placement
    def apply(self, plan: PlacementPlan) -> Dict[str, object]:
        """Execute a :class:`PlacementPlan` as one batch: map updates in
        plan order (demotions free the slots promotions take), then the
        data movement batched per destination — one pooled backing read
        + numpy write for host promotions, one pooled backing read + ONE
        jitted row-scatter for HBM promotions. Callers running a serve
        engine go through ``engine.apply_placement`` (which fences
        in-flight flushes first); the store's own lock only orders bare
        concurrent callers."""
        with self._lock:
            # staged prefetch rows predate this placement: a promoted row
            # would stop being consumed (wasted forever) and attribution
            # would lie — drop the staging at every placement batch (the
            # engine fence calls apply under its drain, so nothing is
            # mid-gather here)
            self.cancel_prefetch()
            pl = self.placement
            promote_hbm: List[Tuple[int, int]] = []   # (stored, slot)
            promote_host: List[Tuple[int, int]] = []
            promoted = demoted = 0
            for sid, dst in plan.moves:
                cur = int(pl.tier_of[sid])
                if dst == cur:
                    continue
                pl.release(sid)
                if dst == TIER_DISK:
                    demoted += 1
                    continue
                free = pl.free_slots(dst)
                if free.size == 0:
                    # over-full plan (stale weights): leave the row on
                    # disk rather than evict outside the plan
                    if cur != TIER_DISK:
                        demoted += 1
                    continue
                slot = int(free[0])
                pl.occupy(sid, dst, slot)
                (promote_hbm if dst == TIER_HBM else promote_host).append(
                    (sid, slot)
                )
                if dst < cur:
                    promoted += 1
                else:
                    demoted += 1  # an hbm->host demotion lands in DRAM
            moved_stored = np.asarray(
                sorted({sid for sid, _ in plan.moves}), np.int64
            )
            if promote_host and self.host_cache is not None:
                sids = np.asarray([s for s, _ in promote_host], np.int64)
                slots = np.asarray([sl for _, sl in promote_host], np.int64)
                self.host_cache[slots] = self.backing.read_rows(
                    sids, pool=self.read_pool
                )
            if promote_hbm and self.hbm_table is not None:
                sids = np.asarray([s for s, _ in promote_hbm], np.int64)
                slots_np = np.asarray([sl for _, sl in promote_hbm], np.int64)
                rows_np = self.backing.read_rows(sids, pool=self.read_pool)
                b = _bucket(slots_np.shape[0])
                slots = np.full(b, self.placement.hbm_rows, np.int64)
                slots[: slots_np.shape[0]] = slots_np
                rows = np.zeros((b, self.dim), self.dtype)
                rows[: rows_np.shape[0]] = rows_np
                self.hbm_table = _set_rows(
                    self.hbm_table, jnp.asarray(slots), jnp.asarray(rows)
                )
            pl.version += 1
            self.rows_promoted += promoted
            self.rows_demoted += demoted
            return {
                "moves": len(plan.moves),
                "promoted_rows": promoted,
                "demoted_rows": demoted,
                "promoted_hbm": len(promote_hbm),
                "promoted_host": len(promote_host),
                "moved_stored": moved_stored,
                "version": pl.version,
                "counts": pl.counts(),
            }


def tier_daemon_loop(engine) -> None:
    """Body of the background promote/demote consumer, shared by
    `ServeEngine` and `DistServeEngine` (both expose ``_running``,
    ``config.tier_adapt_every_s``, ``adapt_tiers`` and a
    ``tier_adapt_errors`` counter). Sleeps in small slices so ``stop()``
    never waits a full period; a failing pass increments the error
    counter (exposed as a gauge) instead of killing serving — a counter
    stuck rising is how operators tell "adaptation crashing every
    period" from "nothing hot to move"."""
    period = engine.config.tier_adapt_every_s
    while engine._running:
        deadline = time.monotonic() + period
        while engine._running and time.monotonic() < deadline:
            time.sleep(min(0.05, period))
        if not engine._running:
            return
        try:
            engine.adapt_tiers()
        except Exception:
            engine.tier_adapt_errors += 1


def find_tiered_feature(feature):
    """The feature object owning an adaptive :class:`TierStore` under
    the serve-feature wrappers (`QuantizedFeature.inner`, the dist
    engine's ``_ShardFeature`` -> `DistFeature` chain). Returns the
    feature that can map stored rows <-> node ids (``tier_store`` +
    ``node_ids_of_stored``), or None when the engine's feature has no
    adaptive store — static placements have nothing to adapt."""
    seen = set()
    obj = feature
    while obj is not None and id(obj) not in seen:
        seen.add(id(obj))
        if (
            getattr(obj, "tier_store", None) is not None
            and hasattr(obj, "node_ids_of_stored")
        ):
            return obj
        nxt = None
        for attr in ("inner", "_dist", "feature"):
            n = getattr(obj, attr, None)
            if n is not None:
                nxt = n
                break
        obj = nxt
    return None

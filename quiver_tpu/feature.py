"""Feature — tiered feature cache with power-law-aware placement.

TPU-native re-design of the reference's ``srcs/python/quiver/feature.py``:
``Feature`` (feature.py:17-458), ``DeviceConfig`` (feature.py:11-14),
``PartitionInfo`` (feature.py:461-526), ``DistFeature`` (feature.py:529-567).

Cache policies (reference feature.py:43-45, docs/Introduction_en.md:104-119):

- ``device_replicate``: the hot (high-degree) prefix is replicated into every
  chip's HBM; the cold tail lives once in host DRAM.  On TPU the "every GPU"
  replication becomes "every local chip" — one jax.Array per chip.
- ``p2p_clique_replicate`` (alias ``ici_replicate``): the hot set is striped
  across all chips of an ICI clique (a TPU slice is one all-to-all clique, so
  the NVLink-clique detection degenerates — see utils.IciTopo); reads off-chip
  rows over ICI.  The eager path ships rows with device_put; the jit path
  uses ``quiver_tpu.parallel.collectives.sharded_gather`` inside shard_map.

The degree-descending hot ordering comes from ``reindex_feature``
(reference utils.py:230-248) when a ``csr_topo`` is attached; lookups remap
through ``feature_order`` exactly like reference feature.py:296-333.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

import jax
import jax.numpy as jnp

from .shard_tensor import (
    CPU_DEVICE,
    ShardTensor,
    ShardTensorConfig,
    _device_of,
    normalize_dtype,
)
from .utils import CSRTopo, IciTopo, parse_size, reindex_feature


@dataclass
class DeviceConfig:
    """Reference feature.py:11-14."""

    device_list: List[int]
    device_cache_size: Union[int, str] = 0


def validate_lookup_ids(
    node_idx, n: int, feature_order: Optional[np.ndarray] = None,
    local_order_applied: bool = False,
) -> np.ndarray:
    """Opt-in STRICT id validation for feature lookups (host-side, not
    jittable). The jit gather paths (`lookup_padded`, `tiered_lookup`)
    deliberately ``jnp.clip`` out-of-range ids into the table — negative
    ids land on row 0, ids ``>= N`` on the last row — because a data-
    dependent raise cannot exist inside an XLA program; the eager paths
    zero-fill instead. Both are silent by design (sampler sentinel padding
    must flow through). Call this at ingest boundaries where an
    out-of-range id means corrupt input, not padding.

    Returns the flattened int64 ids; raises ValueError naming the bad
    count and examples. With ``local_order_applied`` (distributed path),
    ids whose remap entry is negative — globals this host does not own —
    are invalid too.
    """
    ids = np.asarray(node_idx).astype(np.int64).reshape(-1)
    if local_order_applied:
        if feature_order is None:
            raise ValueError("local-order validation needs the feature_order map")
        oob = (ids < 0) | (ids >= feature_order.shape[0])
        bad = oob | (feature_order[np.where(oob, 0, ids)] < 0)
        domain = f"owned global ids (map size {feature_order.shape[0]})"
    else:
        bad = (ids < 0) | (ids >= n)
        domain = f"[0, {n})"
    if bad.any():
        examples = ids[bad][:8].tolist()
        raise ValueError(
            f"{int(bad.sum())} of {ids.size} lookup ids outside {domain}; "
            f"examples: {examples} (jit lookups would clip these, eager "
            "lookups would zero-fill — see Feature.validate_ids)"
        )
    return ids


def attribute_gather_tiers(shard_tensor, rank, stored_ids, counter,
                           valid=None, staged=None) -> None:
    """OBSERVE-ONLY per-tier attribution of a tiered gather (round-13
    workload telemetry): count how many of ``stored_ids`` resolve in each
    tier — ``hbm`` (this rank's own device shard), ``ici`` (another
    chip's shard in the clique stripe), ``host`` (the DRAM tail) — into a
    tier-aware `trace.HitRateCounter` (``counter.hit(n, tier=...)``).

    Pure counting over the shard book's offsets (one vectorized compare
    per shard); never touches the gather itself, so attaching a counter
    changes no gathered byte. ``valid`` masks out pad/invalid lanes —
    those gather row 0 physically but are not real feature requests, and
    counting them would inflate the hot tier.

    ``staged`` (round 18): a callable ``stored_ids -> bool mask`` naming
    disk-tier rows a flush-ahead prefetch already landed in DRAM (e.g.
    ``PrefetchBuffer.staged_mask`` over the disk shard's LOCAL ids) —
    those count as ``disk_prefetched`` instead of ``disk``, so the tier
    labels report where bytes actually come from, not just where the
    placement says they live."""
    if counter is None or shard_tensor is None:
        return
    ids = np.asarray(stored_ids).reshape(-1)
    if valid is not None:
        ids = ids[np.asarray(valid).reshape(-1)]
    if ids.size == 0:
        return
    for dev_rank, _, off in shard_tensor.device_shards:
        n = int(((ids >= off.start) & (ids < off.end)).sum())
        if n:
            counter.hit(n, tier="hbm" if dev_rank == rank else "ici")
    off = shard_tensor.cpu_offset
    if shard_tensor.cpu_tensor is not None and off is not None:
        n = int(((ids >= off.start) & (ids < off.end)).sum())
        if n:
            counter.hit(n, tier="host")
    off = getattr(shard_tensor, "disk_offset", None)
    if getattr(shard_tensor, "disk_shard", None) is not None and off is not None:
        # the round-14 flat-file tail: REAL disk-hit counts (the "disk"
        # label register_hit_rate has carried since round 13, now fed)
        sel = (ids >= off.start) & (ids < off.end)
        n = int(sel.sum())
        pre = 0
        if n and staged is not None:
            pre = int(np.asarray(staged(ids[sel] - off.start)).sum())
            if pre:
                counter.hit(pre, tier="disk_prefetched")
        if n - pre:
            counter.hit(n - pre, tier="disk")


@jax.jit
def _padded_gather(table: jax.Array, ids: jax.Array) -> jax.Array:
    return jnp.take(table, jnp.clip(ids, 0, table.shape[0] - 1), axis=0)


@jax.jit
def _padded_gather_ordered(table: jax.Array, order: jax.Array, ids: jax.Array) -> jax.Array:
    ids = jnp.take(order, jnp.clip(ids, 0, order.shape[0] - 1))
    return jnp.take(table, jnp.clip(ids, 0, table.shape[0] - 1), axis=0)


class Feature:
    """Tiered [N, D] float feature store (reference feature.py:17).

    Parameters mirror the reference constructor (feature.py:25-45):

    rank : local chip index whose HBM serves this handle's gathers
    device_list : chips participating in caching
    device_cache_size : per-chip hot bytes (int or "200M"/"4G" strings)
    cache_policy : "device_replicate" | "p2p_clique_replicate" | "ici_replicate"
    csr_topo : optional CSRTopo — enables degree-ordered hot placement

    Round 14 (disk tier — docs/api.md "Tiered storage"):

    host_memory_budget : host-DRAM byte budget for the middle tier when a
        disk tier is configured (int or "200M" strings; 0 = no DRAM tier
        — HBM misses go straight to disk). WITHOUT ``disk_path`` this
        knob is ignored and the host tail is unbounded (the legacy
        3-tier layout).
    disk_path : flat-file ``.npy`` path for the 4th tier. Static mode
        spills rows beyond ``device_cache_size + host_memory_budget``
        there; adaptive mode writes the FULL stored table (the backing
        file placement moves never have to rewrite).
    adaptive_tiers : overlay a `tiers.TierStore` placement map instead
        of the static shard book — rows then promote/demote between
        disk <-> DRAM <-> HBM in fenced batches (the serve engines'
        ``adapt_tiers``/``apply_placement``). Placement is bit-neutral:
        gathers return identical bytes under any placement.
    disk_read_workers : `pipeline.AsyncReadPool` width for disk reads
        (used when no ``read_pool`` is passed).
    read_pool : share an existing `AsyncReadPool` across features.
    """

    def __init__(
        self,
        rank: int = 0,
        device_list: Optional[Sequence[int]] = None,
        device_cache_size: Union[int, str] = 0,
        cache_policy: str = "device_replicate",
        csr_topo: Optional[CSRTopo] = None,
        dtype=np.float32,
        host_memory_budget: Union[int, str] = 0,
        disk_path: Optional[str] = None,
        adaptive_tiers: bool = False,
        disk_read_workers: int = 4,
        read_pool=None,
    ):
        if cache_policy == "ici_replicate":
            cache_policy = "p2p_clique_replicate"
        if cache_policy not in ("device_replicate", "p2p_clique_replicate"):
            raise ValueError(f"unknown cache_policy: {cache_policy}")
        if adaptive_tiers and disk_path is None:
            raise ValueError(
                "adaptive_tiers needs a disk_path (the full-table backing "
                "file is what makes placement moves bit-neutral)"
            )
        if disk_path is not None and cache_policy != "device_replicate":
            raise ValueError(
                "disk tiers support cache_policy='device_replicate' only "
                "(the clique stripe has no per-rank disk story yet)"
            )
        # dtype of the in-memory tiers: bfloat16 doubles the rows every HBM
        # byte buys (the reference is float32-only, quiver_feature.cu:65-69).
        # The mmap disk tier keeps its on-disk dtype.
        self.dtype = normalize_dtype(dtype)
        self.rank = rank
        self.device_list = list(device_list) if device_list else [rank]
        self.device_cache_size = parse_size(device_cache_size)
        self.cache_policy = cache_policy
        self.csr_topo = csr_topo
        self.feature_order: Optional[np.ndarray] = None  # old id -> stored row
        self._order_dev: Optional[jax.Array] = None
        self.shard_tensor: Optional[ShardTensor] = None
        self.topo = IciTopo.detect()
        self._dim: Optional[int] = None
        self._n: int = 0
        self._local_order_applied = False
        self.mmap_handle_ = None  # disk tier (reference feature.py:84-93)
        self.disk_map: Optional[np.ndarray] = None
        # round-14 disk tier + adaptive placement
        self.host_memory_budget = parse_size(host_memory_budget)
        self.disk_path = disk_path
        self.adaptive_tiers = bool(adaptive_tiers)
        self.disk_read_workers = int(disk_read_workers)
        self.read_pool = read_pool
        self.tier_store = None  # tiers.TierStore when adaptive
        self._inv_order: Optional[np.ndarray] = None
        # observe-only workload tap (round 13): when a tier-aware
        # HitRateCounter is attached, every eager gather attributes its
        # rows per tier (attribute_gather_tiers) — placement telemetry,
        # never control flow
        self.tier_counter = None
        # round-14 row-access tap: a callable fed every VALID gathered
        # STORED row id (`WorkloadMonitor.observe_rows`) — the gather-
        # frequency sketch the tier planner reads. Observe-only too.
        self.row_tap = None
        # round-18: a callable (disk-LOCAL ids -> bool mask) naming rows
        # a flush-ahead prefetch staged in DRAM — installed by whoever
        # runs the prefetch (the train pipeline for static disk tails;
        # adaptive stores carry their own PrefetchBuffer) so attribution
        # can report `disk_prefetched` honestly. Observe-only.
        self.disk_staged = None

    # ------------------------------------------------------------------ build
    def from_cpu_tensor(self, cpu_tensor) -> None:
        """Ingest the full feature table and tier it (reference
        feature.py:195-281)."""
        arr = np.asarray(cpu_tensor)
        if arr.ndim != 2:
            raise ValueError("features must be [N, D]")
        arr = arr.astype(self.dtype, copy=False)
        self._n, self._dim = arr.shape
        row_bytes = self._dim * self.dtype.itemsize
        cache_rows = min(self.device_cache_size // row_bytes, self._n)

        if self.csr_topo is not None and not self._local_order_applied:
            # degree-descending reorder so the cache prefix is hot
            # (reference feature.py:211-215)
            if self.cache_policy == "p2p_clique_replicate":
                clique = self.topo.get_clique(self.rank)
                ratio = min(cache_rows * len(clique), self._n) / max(self._n, 1)
            else:
                ratio = cache_rows / max(self._n, 1)
            arr, order = reindex_feature(self.csr_topo, arr, ratio)
            self.feature_order = order
            self.csr_topo.feature_order = order
            self._inv_order = None

        if self.disk_path is not None:
            self._build_disk_tiers(arr, cache_rows)
            return

        st = ShardTensor(self.rank, ShardTensorConfig({}), dtype=self.dtype)
        if self.cache_policy == "device_replicate":
            # hot prefix replicated per chip: each rank's Feature handle is
            # built with its own `rank` and stores its own replica, so this
            # handle's shard book holds one device shard + the shared host
            # tail (reference feature.py:219-223,268-274)
            if cache_rows > 0:
                st.append(arr[:cache_rows], self.rank)
            if cache_rows < self._n:
                st.append(arr[cache_rows:], CPU_DEVICE)
        else:
            # hot set striped across the ICI clique (reference feature.py:225-265)
            clique = [d for d in self.topo.get_clique(self.rank)]
            hot_total = min(cache_rows * len(clique), self._n)
            per = hot_total // max(len(clique), 1)
            cursor = 0
            for dev in clique:
                rows = min(per, hot_total - cursor)
                if rows <= 0:
                    break
                st.append(arr[cursor : cursor + rows], dev)
                cursor += rows
            if cursor < self._n:
                st.append(arr[cursor:], CPU_DEVICE)
        self.shard_tensor = st

    def _build_disk_tiers(self, arr: np.ndarray, cache_rows: int) -> None:
        """4-tier build (round 14): HBM prefix -> DRAM middle (bounded by
        ``host_memory_budget``) -> flat-file disk tail. ``arr`` is the
        STORED order (degree-reordered when a csr_topo is attached), so
        the prefix placement is the hot head either way. Adaptive mode
        overlays a `tiers.TierStore` with the IDENTICAL initial
        placement — a frozen adaptive store and a static one serve
        bit-identical bytes from the same tiers."""
        row_bytes = self._dim * self.dtype.itemsize
        host_rows = 0
        if self.host_memory_budget > 0:
            host_rows = min(
                self.host_memory_budget // row_bytes, self._n - cache_rows
            )
        if self.read_pool is None:
            from .pipeline import AsyncReadPool

            self.read_pool = AsyncReadPool(self.disk_read_workers)
        if self.adaptive_tiers:
            from .tiers import TierStore

            self.tier_store = TierStore.build(
                arr, self.disk_path, hbm_rows=cache_rows,
                host_rows=host_rows, rank=self.rank,
                read_pool=self.read_pool,
            )
            self.shard_tensor = None
            return
        st = ShardTensor(self.rank, ShardTensorConfig({}), dtype=self.dtype)
        if cache_rows > 0:
            st.append(arr[:cache_rows], self.rank)
        if host_rows > 0:
            st.append(arr[cache_rows : cache_rows + host_rows], CPU_DEVICE)
        if cache_rows + host_rows < self._n:
            st.append_disk(
                arr[cache_rows + host_rows :], self.disk_path,
                read_pool=self.read_pool,
            )
        self.shard_tensor = st

    @classmethod
    def from_mmap(cls, mmap_array, device_config: DeviceConfig, **kwargs) -> "Feature":
        """Build from an np.memmap without materialising it (reference
        from_mmap feature.py:84-192 — the disk tier). The hot prefix is read
        into HBM; the cold tail stays mmap-backed (reads hit page cache/disk)."""
        self = cls(
            rank=device_config.device_list[0] if device_config.device_list else 0,
            device_list=device_config.device_list,
            device_cache_size=device_config.device_cache_size,
            **kwargs,
        )
        n, d = mmap_array.shape
        self._n, self._dim = n, d
        cache_rows = min(
            parse_size(device_config.device_cache_size) // (d * self.dtype.itemsize), n
        )
        st = ShardTensor(self.rank, ShardTensorConfig({}), dtype=self.dtype)
        if cache_rows > 0:
            # cast on host BEFORE the device_put: uploading f32 then casting
            # on device would double the bytes over the tunnel
            st.append(np.asarray(mmap_array[:cache_rows]).astype(self.dtype), self.rank)
        if cache_rows < n:
            cold = mmap_array[cache_rows:]
            if isinstance(cold, np.memmap) or cold.dtype != np.float32:
                # keep the memmap as the cold tier without copying when possible
                cold = cold if isinstance(cold, np.memmap) else np.asarray(cold, np.float32)
            st.cpu_tensor = cold
            from .shard_tensor import Offset

            st.cpu_offset = Offset(cache_rows, n)
            st._n_rows = n
            st._dim = d
        self.shard_tensor = st
        return self

    def set_mmap_file(self, path: str, disk_map) -> None:
        """Attach a disk tier (reference feature.py:84-88): ``path`` is an
        ``np.save``'d [N_total, D] array opened with ``mmap_mode='r'``;
        ``disk_map[global_id]`` is the in-memory row for cached ids and
        ``< 0`` for ids resident only on disk."""
        self.mmap_handle_ = np.load(path, mmap_mode="r")
        self.disk_map = np.asarray(disk_map).astype(np.int64).reshape(-1)
        if self._dim is None:
            self._dim = int(self.mmap_handle_.shape[1])

    def read_mmap(self, ids) -> jax.Array:
        """Read rows from the disk tier by GLOBAL node id (reference
        feature.py:89-93); one page-cache-friendly host read + one H2D.
        Out-of-range ids (sampler sentinel padding) yield zero rows, same
        as every other lookup path (numpy would silently wrap negatives)."""
        ids = np.asarray(ids).astype(np.int64).reshape(-1)
        oob = (ids < 0) | (ids >= self.mmap_handle_.shape[0])
        rows = np.asarray(self.mmap_handle_[np.where(oob, 0, ids)], dtype=np.float32)
        if oob.any():
            rows[oob] = 0.0
        return jnp.asarray(rows)

    # ----------------------------------------------------------------- lookup
    def __getitem__(self, node_idx) -> jax.Array:
        """Gather features for (original) node ids; remaps through
        feature_order then hits the tiered ShardTensor (reference
        feature.py:296-333). Out-of-range ids (e.g. the sampler's
        sentinel padding) yield zero rows. With a disk tier attached
        (:meth:`set_mmap_file`), ids whose ``disk_map`` entry is negative
        are read from the mmap and merged (reference feature.py:309-333)."""
        if self.mmap_handle_ is not None:
            return self._getitem_with_disk(node_idx)
        ids, invalid = self._map_ids(node_idx)
        if self.tier_counter is not None:
            self._attribute(ids, valid=~invalid)
        if self.row_tap is not None:
            self.row_tap(ids[~invalid])
        rows = self.gather_stored(ids)
        if invalid.any():
            rows = rows * jnp.asarray(~invalid, rows.dtype)[:, None]
        return rows

    def _map_ids(self, node_idx):
        """(stored_rows, invalid_mask) for a lookup batch — the id remap
        every gather path shares. Invalid lanes map to stored row 0 and
        are zeroed by the caller."""
        ids = np.asarray(node_idx).astype(np.int64).reshape(-1)
        if self._local_order_applied:
            # distributed path: ids are GLOBAL but self._n is the LOCAL row
            # count, so validity must come from the remap itself —
            # feature_order[gid] < 0 means this host does not own gid
            oob = (ids < 0) | (ids >= self.feature_order.shape[0])
            mapped = self.feature_order[np.where(oob, 0, ids)]
            invalid = oob | (mapped < 0)
            ids = np.where(invalid, 0, mapped)
        else:
            invalid = (ids < 0) | (ids >= self._n)
            if invalid.any():
                ids = np.where(invalid, 0, ids)
            if self.feature_order is not None:
                ids = self.feature_order[ids]
        return ids, invalid

    def _attribute(self, stored: np.ndarray, valid: np.ndarray) -> None:
        """Observe-only per-tier attribution of a gather (round 13/14):
        static shard books count by offset range; adaptive stores by the
        LIVE placement map (hbm/host/disk as placed right now)."""
        tc = self.tier_counter
        if self.tier_store is not None:
            split = self.tier_store.tier_split(stored[valid])
            for tier, n in split.items():
                if n:
                    tc.hit(n, tier=tier)
            return
        attribute_gather_tiers(
            self.shard_tensor, self.rank, stored, tc, valid=valid,
            staged=self.disk_staged,
        )

    def gather_stored(self, stored) -> jax.Array:
        """Gather by STORED row id through whichever store backs this
        feature (static shard book or adaptive tier store) — the surface
        `QuantizedFeature` and the tests' oracles share."""
        if self.tier_store is not None:
            return self.tier_store.gather(stored)
        return self.shard_tensor[stored]

    def tier_bytes(self) -> Dict[str, int]:
        """Live per-tier byte footprint (adaptive stores report the
        CURRENT placement — a demotion batch shrinks ``device``
        immediately; the honest-accounting pin in tests/test_tiers.py)."""
        if self.tier_store is not None:
            return self.tier_store.tier_bytes()
        if self.shard_tensor is not None:
            return self.shard_tensor.tier_bytes()
        return {}

    def stored_rows_of(self, node_ids) -> np.ndarray:
        """Node id -> stored row (-1 for out-of-range / unowned ids) —
        how the tier planner maps sketch keys into placement space."""
        ids = np.asarray(node_ids).astype(np.int64).reshape(-1)
        stored, invalid = self._map_ids(ids)
        return np.where(invalid, -1, stored)

    def node_ids_of_stored(self, stored) -> np.ndarray:
        """Stored row -> node id (inverse of ``feature_order``; identity
        without a reorder) — how a placement batch names the embedding-
        cache entries it must invalidate."""
        stored = np.asarray(stored, np.int64).reshape(-1)
        if self.feature_order is None:
            return stored
        if self._inv_order is None:
            order = self.feature_order
            valid = order >= 0
            size = int(order[valid].max()) + 1 if valid.any() else 0
            inv = np.full(size, -1, np.int64)
            inv[order[valid]] = np.nonzero(valid)[0]
            self._inv_order = inv
        return self._inv_order[stored]

    def _getitem_with_disk(self, node_idx) -> jax.Array:
        """Disk-mask merge (reference feature.py:309-333): ``disk_map`` splits
        the batch into mmap reads (entry < 0, read by global id) and
        in-memory rows (entry = local row into the shard book)."""
        ids = np.asarray(node_idx).astype(np.int64).reshape(-1)
        oob = (ids < 0) | (ids >= self.disk_map.shape[0])
        safe = np.where(oob, 0, ids)
        disk_index = self.disk_map[safe]
        disk_mask = (disk_index < 0) & ~oob
        mem_mask = (disk_index >= 0) & ~oob
        out = np.zeros((ids.shape[0], self.dim), np.float32)
        tc = self.tier_counter
        if disk_mask.any():
            if tc is not None:
                tc.hit(int(disk_mask.sum()), tier="disk")
            out[disk_mask] = np.asarray(self.mmap_handle_[ids[disk_mask]], np.float32)
        if mem_mask.any():
            if tc is not None:
                attribute_gather_tiers(
                    self.shard_tensor, self.rank, disk_index[mem_mask], tc
                )
            out[mem_mask] = np.asarray(self.shard_tensor[disk_index[mem_mask]])
        return jnp.asarray(out)

    def lookup_padded(self, node_idx: jax.Array, valid: Optional[jax.Array] = None) -> jax.Array:
        """Jit-friendly gather for padded id arrays; already jitted
        internally (the table is passed as an ARGUMENT to the jitted
        program — never ``jax.jit`` a bound method of this class, or the
        table becomes a baked-in compile-time constant).

        Requires the feature to be fully device-resident (single hot shard on
        this chip covering all rows); multi-tier padded lookup goes through
        `quiver_tpu.parallel.collectives.sharded_gather` on a mesh.
        """
        st = self.shard_tensor
        if st is None or st.cpu_tensor is not None or len(st.device_shards) != 1:
            raise ValueError(
                "lookup_padded needs a fully HBM-resident feature; "
                "use __getitem__ (tiered) or the mesh-sharded gather"
            )
        table = st.device_shards[0][1]
        if self.feature_order is not None:
            if self._order_dev is None:
                self._order_dev = jnp.asarray(self.feature_order)
            rows = _padded_gather_ordered(table, self._order_dev, node_idx)
        else:
            rows = _padded_gather(table, node_idx)
        if valid is not None:
            rows = rows * valid[:, None].astype(rows.dtype)
        return rows

    def validate_ids(self, node_idx) -> np.ndarray:
        """Strict opt-in id check: raise instead of the lookup paths'
        silent clip/zero-fill. See :func:`validate_lookup_ids`."""
        return validate_lookup_ids(
            node_idx, self._n, self.feature_order, self._local_order_applied
        )

    # ------------------------------------------------------------------ misc
    @property
    def shape(self):
        return (self._n, self._dim)

    @property
    def dim(self) -> int:
        return self._dim or 0

    def size(self, axis: int) -> int:
        return self.shape[axis]

    def set_local_order(self, local_order) -> None:
        """Distributed local remap (reference feature.py:283-294): after
        cross-host partitioning, this host stores only its rows; map
        global id -> local row."""
        local_order = np.asarray(local_order, dtype=np.int64)
        order = np.full(int(local_order.max()) + 1 if local_order.size else 0, -1, np.int64)
        order[local_order] = np.arange(local_order.shape[0], dtype=np.int64)
        self.feature_order = order
        self._order_dev = None
        self._inv_order = None
        self._local_order_applied = True

    # ------------------------------------------------------- ipc-compat shims
    def share_ipc(self):
        """Reference feature.py:383-445; a pickleable handle."""
        return dict(
            rank=self.rank,
            device_list=self.device_list,
            device_cache_size=self.device_cache_size,
            cache_policy=self.cache_policy,
            shard_ipc=None if self.shard_tensor is None else self.shard_tensor.share_ipc(),
            feature_order=self.feature_order,
            shape=(self._n, self._dim),
            dtype=str(self.dtype),
        )

    @classmethod
    def new_from_ipc_handle(cls, rank: int, ipc_handle) -> "Feature":
        self = cls(
            rank=rank,
            device_list=ipc_handle["device_list"],
            device_cache_size=ipc_handle["device_cache_size"],
            cache_policy=ipc_handle["cache_policy"],
            dtype=ipc_handle.get("dtype", np.float32),
        )
        self._n, self._dim = ipc_handle["shape"]
        self.feature_order = ipc_handle["feature_order"]
        if ipc_handle["shard_ipc"] is not None:
            self.shard_tensor = ShardTensor.new_from_share_ipc(ipc_handle["shard_ipc"], rank)
        return self

    lazy_from_ipc_handle = new_from_ipc_handle


class PartitionInfo:
    """Cross-host partition metadata (reference feature.py:461-526).

    global2host maps node id -> owning host; an optional replicate set marks
    ids this host also holds locally.
    """

    def __init__(self, device, host: int, hosts: int, global2host, replicate=None):
        self.device = device
        self.host = host
        self.hosts = hosts
        self.global2host = np.asarray(global2host, dtype=np.int32)
        self.replicate = None if replicate is None else np.asarray(replicate, dtype=np.int64)
        self._build_global2local()

    def _build_global2local(self):
        """global id -> owner-local row, for EVERY host (reference
        feature.py:484-508 ranks each host's owned ids 0..n_h-1)."""
        n = self.global2host.shape[0]
        self.global2local = np.zeros(n, dtype=np.int64)
        for h in range(self.hosts):
            owned = np.nonzero(self.global2host == h)[0]
            self.global2local[owned] = np.arange(owned.shape[0])
        local_mask = self.global2host == self.host
        if self.replicate is not None:
            # replicated ids live after this host's owned rows, in the order
            # given (reference feature.py:497-505)
            local_mask = local_mask.copy()
            owned_count = int(local_mask.sum())
            rep = self.replicate[~local_mask[self.replicate]]
            self.global2local[rep] = owned_count + np.arange(rep.shape[0])
            local_mask[rep] = True
        local_ids = np.nonzero(local_mask)[0]
        self.local_ids = local_ids
        self.local_mask = local_mask

    def dispatch(self, ids: np.ndarray):
        """Split a request batch by owning host (reference feature.py:510-526).
        Returns (per_host_ids list, local_ids, orig_pos_per_host, local_pos)."""
        ids = np.asarray(ids).astype(np.int64)
        local = self.local_mask[ids]
        local_pos = np.nonzero(local)[0]
        remote_pos = np.nonzero(~local)[0]
        owner = self.global2host[ids[remote_pos]]
        per_host, per_pos = [], []
        for h in range(self.hosts):
            sel = remote_pos[owner == h]
            per_host.append(ids[sel])
            per_pos.append(sel)
        return per_host, ids[local_pos], per_pos, local_pos


class DistFeature:
    """Multi-host feature collection (reference feature.py:529-567): dispatch
    ids by owner, exchange over the communication backend, merge with the
    local gather. Synchronous/collective across hosts — every host must call
    ``__getitem__`` together (reference docstring feature.py:530-535)."""

    def __init__(self, feature: Feature, info: PartitionInfo, comm):
        self.feature = feature
        self.info = info
        self.comm = comm

    def __getitem__(self, ids) -> jax.Array:
        ids = np.asarray(ids).astype(np.int64)
        per_host, local_ids, per_pos, local_pos = self.info.dispatch(ids)
        # owners answer in their local row space (reference set_local_order
        # remap, feature.py:283-294 + comm.py:165-168 local gather)
        per_host_local = [self.info.global2local[h_ids] for h_ids in per_host]
        if jax.process_count() == 1 and not any(len(h) for h in per_host_local):
            # fully shard-local lookup: nothing to exchange, skip the
            # collective. Single-controller ONLY — in multi-process mode
            # every host must enter the collective together, so a
            # data-dependent skip would desync it (the serve engines hit
            # this path on every flush when the partition is k-hop closed,
            # e.g. community-partitioned serving shards)
            remote_feats: List[Optional[jax.Array]] = [None] * self.info.hosts
        else:
            remote_feats = self.comm.exchange(per_host_local)
        out = np.zeros((ids.shape[0], self.feature.dim), np.float32)
        if local_ids.size:
            # a Feature with set_local_order applied remaps global ids itself
            # (reference feature.py:283-294); otherwise localize here
            if self.feature._local_order_applied:
                out[local_pos] = np.asarray(self.feature[local_ids])
            else:
                out[local_pos] = np.asarray(self.feature[self.info.global2local[local_ids]])
        for h, feats in enumerate(remote_feats):
            if feats is not None and per_pos[h].size:
                out[per_pos[h]] = np.asarray(feats)
        return jnp.asarray(out)
